"""Multi-chip frontier search: the BFS sharded over a device mesh.

The distributed half of :mod:`jepsen_tpu.lin.bfs` — the capability the
reference gets from a 32GB JVM heap on one control node
(jepsen/project.clj:22-25), re-designed as SPMD over a
``jax.sharding.Mesh``:

- The frontier's capacity axis is sharded: each device owns
  ``cap_local = cap/D`` configs in its HBM, so total frontier capacity
  scales linearly with chip count.
- Expansion (config x pending-op step kernels) is embarrassingly parallel
  and stays local.
- Dedup is the collective: candidate (bits, state) keys are
  ``all_gather``-ed over the mesh axis (ICI within a slice), every device
  runs the identical lexicographic sort + unique-mask + cumsum compaction,
  and keeps the slice of the packed result it owns: a deterministic
  balanced re-shard with no host round-trips. All control decisions
  (fixpoint, death, overflow) derive from replicated reductions, so every
  device takes the same `lax.while_loop` branches.

The whole search — outer return-event loop included — is one
``shard_map``-ped program: a single XLA computation per (R-bucket, W, cap)
with collectives inlined where the dedup needs them.
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from jepsen_tpu import util
from jepsen_tpu.lin import supervise
from jepsen_tpu.lin.bfs import (KEY_FILL, _dedup_keys, _dedup_keys2,
                                _dedup_keys2_dom, _dedup_keys_dom,
                                _expand_keys, _expand_keys_compact,
                                _pad_rows, expansion_tables)
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace

# The sparse sharded MULTIWORD frontier keeps single-word bitsets (its
# all_gather dedup keys stay u32); past 32 the read-value-match register
# band rides the pair-key (lo, hi) compact path to window+b <= 60, and
# only shapes outside BOTH bands fall back to the single-chip engine.
MAX_DEVICE_WINDOW = 32
# Whole-history single-program bound for the MULTIWORD mesh path (no
# chunking there). The packed-key mesh path chunks like bfs.check_packed
# and has no length bound; the dense hypercube engine likewise.
MAX_SHARDED_ROWS = 8192
from jepsen_tpu.lin.prepare import PackedHistory


def _global_dedup_keys(keys, valid, cap_local, axis):
    """Packed-u32-key collective dedup: ONE all_gather of u32 keys over
    the mesh axis (vs bits+state columns — this is the "bitset-hash
    dedup allreduced over ICI" axis of the north star, at a fraction of
    the collective bytes), a global sort, duplicate masking, and a
    second sort for compaction (no scatter — `.at[idx].set` serializes
    on TPU; no searchsorted — it kernel-faults this runtime at scale,
    see bfs._dedup_keys). Every device keeps its deterministic slice.
    Returns (keys[cap_local], count_local, total, overflow); total and
    overflow are replicated."""
    d = lax.axis_index(axis)
    n_dev = util.axis_size(axis)

    key = keys | ((~valid).astype(jnp.uint32) << 31)
    key_all = lax.all_gather(key, axis, tiled=True)
    n = key_all.shape[0]
    key_s = lax.sort(key_all)
    inv_s = key_s >> 31

    prev_differs = key_s != jnp.roll(key_s, 1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap_local * n_dev
    packed = lax.sort(jnp.where(mask, key_s, KEY_FILL))
    mine = lax.dynamic_slice(packed, (d * cap_local,), (cap_local,))
    count_local = jnp.clip(total - d * cap_local, 0, cap_local)
    return mine, count_local, total, overflow


def _global_dedup_keys_dom(lo, hi, valid, cap_local, axis, *, key_hi,
                           crash_dom, masks, dom_iters=1,
                           preprune=True):
    """The compact band's collective dedup, both key widths: per-shard
    pre-prune, ONE all_gather of the (lo[, hi]) key words, a GLOBAL
    sort-dedup, and the deterministic balanced re-shard of
    _global_dedup_keys.

    With ``crash_dom`` both the local and the global passes run the
    EXACT crashed-subset/read-bit dominance prune — always on the
    FORCED-LAX path (bfs._dedup_keys_dom / _dedup_keys2_dom with
    ``dom_force=True``), never the psort dom kernels: the round-5
    stability rule holds on the mesh too, and inside shard_map the
    pallas kernels are off the table anyway. ``masks`` is this row's
    (crash_lo, crash_hi, read_lo, read_hi) key-space mask quadruple
    (hi words ignored for single keys).

    The global pass runs at cap = gathered length, so it NEVER
    truncates: on the same candidate multiset it is bit-identical to
    the single-chip dedup (the sort canonicalizes shard order), which
    is what the mesh/single-chip prune-parity test pins down. The
    per-shard pre-prune (``preprune``, the default) bounds the
    collective bytes at 2*cap_local words per device instead of
    cap_local*(1+M); it can only REMOVE dominated/duplicate
    candidates the global pass would also remove, so the surviving
    SET is unchanged — only its pre-gather layout. A shard whose
    survivors exceed its 2*cap_local pre-prune bound reports
    overflow (psum'd, so every device escalates together).

    Returns (lo[cap_local], hi[cap_local] | None, count_local, total,
    overflow) — total/overflow replicated."""
    d = lax.axis_index(axis)
    n_dev = util.axis_size(axis)
    c_lo, c_hi, r_lo, r_hi = masks
    ovf_pre = None
    if crash_dom and preprune:
        pcap = min(lo.shape[0], 2 * cap_local)
        if key_hi:
            hi, lo, pcnt, ovf_pre = _dedup_keys2_dom(
                hi, lo, valid, pcap, c_hi, c_lo, r_hi, r_lo,
                use_psort=False, dom_force=True, dom_iters=dom_iters)
        else:
            lo, pcnt, ovf_pre = _dedup_keys_dom(
                lo, valid, pcap, c_lo, r_lo, use_psort=False,
                dom_force=True, dom_iters=dom_iters)
        valid = jnp.arange(pcap) < pcnt
    lo_all = lax.all_gather(lo, axis, tiled=True)
    val_all = lax.all_gather(valid, axis, tiled=True)
    n = lo_all.shape[0]
    if key_hi:
        hi_all = lax.all_gather(hi, axis, tiled=True)
        if crash_dom:
            hi_p, lo_p, total, _ = _dedup_keys2_dom(
                hi_all, lo_all, val_all, n, c_hi, c_lo, r_hi, r_lo,
                use_psort=False, dom_force=True, dom_iters=dom_iters)
        else:
            hi_p, lo_p, total, _ = _dedup_keys2(hi_all, lo_all,
                                                val_all, n)
        mine_hi = lax.dynamic_slice(hi_p, (d * cap_local,),
                                    (cap_local,))
    else:
        if crash_dom:
            lo_p, total, _ = _dedup_keys_dom(
                lo_all, val_all, n, c_lo, r_lo, use_psort=False,
                dom_force=True, dom_iters=dom_iters)
        else:
            lo_p, total, _ = _dedup_keys(lo_all, val_all, n)
        mine_hi = None
    mine_lo = lax.dynamic_slice(lo_p, (d * cap_local,), (cap_local,))
    overflow = total > cap_local * n_dev
    if ovf_pre is not None:
        overflow = overflow | \
            (lax.psum(ovf_pre.astype(jnp.int32), axis) > 0)
    count_local = jnp.clip(total - d * cap_local, 0, cap_local)
    return mine_lo, mine_hi, count_local, total, overflow


def _global_dedup(bits, state, valid, cap_local, axis):
    """All-gather candidates, globally sort-dedup, keep this device's
    slice. Returns (bits[cap_local], state[cap_local,S], count_local,
    total, overflow) — total/overflow are replicated."""
    d = lax.axis_index(axis)
    n_dev = util.axis_size(axis)
    s_width = state.shape[1]

    bits_all = lax.all_gather(bits, axis, tiled=True)
    state_all = lax.all_gather(state, axis, tiled=True)
    valid_all = lax.all_gather(valid, axis, tiled=True)
    n = bits_all.shape[0]

    inv = (~valid_all).astype(jnp.uint32)
    operands = (inv, bits_all) + tuple(state_all[:, k]
                                       for k in range(s_width))
    sorted_ops = lax.sort(operands, num_keys=len(operands))
    inv_s, bits_s = sorted_ops[0], sorted_ops[1]
    state_s = jnp.stack(sorted_ops[2:], axis=1)

    prev_differs = (bits_s != jnp.roll(bits_s, 1)) | \
        jnp.any(state_s != jnp.roll(state_s, 1, axis=0), axis=1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    cap_global = cap_local * n_dev
    overflow = total > cap_global

    # Global packed position; this device keeps [d*cap_local, (d+1)*cap).
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    lo = d * cap_local
    mine = mask & (pos >= lo) & (pos < lo + cap_local)
    idx = jnp.where(mine, pos - lo, n)

    out_n = max(n, cap_local) + 1
    out_bits = jnp.zeros(out_n, jnp.uint32).at[idx].set(bits_s)[:cap_local]
    out_state = jnp.zeros((out_n, s_width), jnp.int32) \
        .at[idx].set(state_s)[:cap_local]
    count_local = jnp.clip(total - lo, 0, cap_local)
    return out_bits, out_state, count_local, total, overflow


@partial(jax.jit, static_argnames=("cap_local", "step_fn", "mesh",
                                   "axis"))
def _search_sharded(ret_slot, active, slot_f, slot_v, pure, pred_mask,
                    init_state, *, cap_local, step_fn, mesh, axis="d"):
    """shard_map-ped full search. Frontier sharded over `axis`; row tables
    replicated — including the reduction tables (prepare.reduction_tables):
    pure[R,W] slots saturate instead of branching, pred_mask[R,W] gates
    canonical-chain expansion. Returns replicated
    (ok, dead_row, overflow, total)."""
    R, W = active.shape
    S = init_state.shape[0]

    def shard_body(ret_slot, active, slot_f, slot_v, pure, pred_mask,
                   init_state):
        d = lax.axis_index(axis)
        slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

        bits0 = jnp.zeros(cap_local, jnp.uint32)
        state0 = jnp.zeros((cap_local, S), jnp.int32).at[0].set(init_state)
        # Only device 0 starts with the initial config.
        count0 = jnp.where(d == 0, jnp.int32(1), jnp.int32(0))

        step_cfg_slot = jax.vmap(
            jax.vmap(step_fn, in_axes=(None, 0, 0)),
            in_axes=(0, None, None))

        # Closure pass ceiling: the mesh closures are MONOTONE (no
        # content-sensitive dominance prune on these two paths;
        # candidates include the current frontier), so they terminate
        # in O(W) passes and the ceiling cannot bind — it exists so a
        # regression that breaks monotonicity becomes an honest
        # overflow instead of the round-5 orbit (an in-program
        # infinite loop the runtime watchdog kills, presenting as a
        # kernel fault).
        it_max = jnp.int32(4 * W + 16)

        def closure_cond(c):
            _, _, _, _, changed, ovf, it = c
            return changed & ~ovf & (it < it_max)

        def row_body(carry):
            r, bits, state, count, total, dead, ovf = carry
            act = active[r]
            f_row = slot_f[r]
            v_row = slot_v[r]
            pure_row = pure[r]
            pred_row = pred_mask[r]
            s = ret_slot[r]

            def closure_body(c):
                bits_in, state, count, total, _, ovf, it = c
                cfg_valid = jnp.arange(cap_local) < count
                ok, new_state = step_cfg_slot(state, f_row, v_row)
                already = (bits_in[:, None] & slot_bit[None, :]) != 0
                fresh = ok & act[None, :] & ~already & cfg_valid[:, None]
                # Saturation: absorb legal pure bits in place (local —
                # the config's slice assignment may move at dedup, but
                # the global multiset is what matters). Statically
                # unrolled OR, not a vector reduce (TPU-runtime hazard,
                # see bfs.py).
                sat = jnp.zeros_like(bits_in)
                for j in range(W):
                    sat = sat | jnp.where(fresh[:, j] & pure_row[j],
                                          slot_bit[j], jnp.uint32(0))
                bits = jnp.where(cfg_valid, bits_in | sat, bits_in)
                chain_ok = (bits[:, None] & pred_row[None, :]) == \
                    pred_row[None, :]
                legal = fresh & ~pure_row[None, :] & chain_ok
                new_bits = bits[:, None] | slot_bit[None, :]

                cand_bits = jnp.concatenate([bits, new_bits.reshape(-1)])
                cand_state = jnp.concatenate(
                    [state, new_state.reshape(-1, S)], axis=0)
                cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

                b2, s2, n2, tot2, o2 = _global_dedup(
                    cand_bits, cand_state, cand_valid, cap_local, axis)
                # Fixpoint test is against the pass INPUT (the stable set
                # keeps both a config and its saturated twin; see
                # bfs._search_chunk_keys.closure_body).
                changed = jnp.any(b2 != bits_in) | jnp.any(s2 != state) | \
                    (tot2 != total)
                changed = lax.psum(changed.astype(jnp.int32), axis) > 0
                return (b2, s2, n2, tot2, changed, ovf | o2, it + 1)

            init = (bits, state, count, total, jnp.bool_(True), ovf,
                    jnp.int32(0))
            bits, state, count, total, changed, ovf = lax.while_loop(
                closure_cond, closure_body, init)[:6]
            # Ceiling exhaustion (still `changed` at exit) folds into
            # overflow — an honest unknown, never a hang.
            ovf = ovf | changed

            s_bit = jnp.uint32(1) << s.astype(jnp.uint32)
            cfg_valid = jnp.arange(cap_local) < count
            keep = cfg_valid & ((bits & s_bit) != 0)
            bits = bits & ~s_bit
            bits, state, count, total, o2 = _global_dedup(
                bits, state, keep, cap_local, axis)
            dead = total == 0
            return (r + 1, bits, state, count, total, dead, ovf | o2)

        def row_cond(carry):
            r, _, _, _, _, dead, ovf = carry
            return (r < R) & ~dead & ~ovf

        r, bits, state, count, total, dead, ovf = lax.while_loop(
            row_cond, row_body,
            (jnp.int32(0), bits0, state0, count0, jnp.int32(1),
             False, False))
        return (~dead & ~ovf)[None], (r - 1)[None], ovf[None], total[None]

    shard_map = util.get_shard_map()

    # check_vma off: the carry deliberately mixes axis-varying values (the
    # frontier shard, via axis_index) with replicated control scalars
    # (total/dead/overflow from all_gather'ed reductions).
    fn = shard_map(shard_body, mesh=mesh,
                   in_specs=(P(), P(), P(), P(), P(), P(), P()),
                   out_specs=(P(axis), P(axis), P(axis), P(axis)),
                   check_vma=False)
    ok, dead_row, ovf, total = fn(ret_slot, active, slot_f, slot_v,
                                  pure, pred_mask, init_state)
    return ok[0], dead_row[0], ovf[0], total[0]


@partial(jax.jit, static_argnames=("cap_local", "step_fn", "mesh", "axis",
                                   "b", "nil_id", "read_value_match"))
def _search_sharded_keys(ret_slot, active, slot_f, slot_v, pure, pred_mask,
                         keys, counts, n_rows, *, cap_local, step_fn,
                         mesh, b, nil_id, read_value_match, axis="d"):
    """ONE chunk of the packed-u32-key mesh search: each device owns
    cap_local keys (bits << b | state id, the bfs._pack_frontier_keys
    layout) of the globally [n_dev*cap_local]-shaped ``keys``; ``counts``
    is the per-device live count [n_dev]. Dedup is the single-array
    collective of _global_dedup_keys; candidate generation is
    bfs._expand_keys, so the pass semantics (saturation, canonical
    chains, the register read fast table) are byte-identical to the
    single-chip engine. The frontier carries between chunk dispatches
    exactly like bfs.check_packed, so history length is unbounded.
    Returns (keys', counts', rows_done, dead, overflow, total) — the
    last four replicated scalars."""
    C, W = active.shape

    def shard_body(n_rows, ret_slot, active, slot_f, slot_v, pure,
                   pred_mask, keys, counts):
        count = counts[0]
        total0 = lax.psum(count, axis)
        # Same monotone-closure ceiling as the multiword body above.
        it_max = jnp.int32(4 * W + 16)

        def closure_cond(c):
            _, _, _, changed, ovf, it = c
            return changed & ~ovf & (it < it_max)

        def row_body(carry):
            r, keys, count, total, dead, ovf = carry
            act = active[r]
            f_row = slot_f[r]
            v_row = slot_v[r]
            pure_row = pure[r]
            pred_row = pred_mask[r]
            s = ret_slot[r]

            def closure_body(c):
                keys_in, count, total, _, ovf, it = c
                cand, cand_valid = _expand_keys(
                    keys_in, count, act, f_row, v_row, pure_row,
                    pred_row, cap=cap_local, W=W, b=b, nil_id=nil_id,
                    step_fn=step_fn, read_value_match=read_value_match)
                k2, n2, tot2, o2 = _global_dedup_keys(
                    cand, cand_valid, cap_local, axis)
                changed = jnp.any(k2 != keys_in) | (tot2 != total)
                changed = lax.psum(changed.astype(jnp.int32), axis) > 0
                return (k2, n2, tot2, changed, ovf | o2, it + 1)

            init = (keys, count, total, jnp.bool_(True), ovf,
                    jnp.int32(0))
            keys, count, total, changed, ovf = lax.while_loop(
                closure_cond, closure_body, init)[:5]
            ovf = ovf | changed

            s_key_bit = jnp.uint32(1) << (b + s).astype(jnp.uint32)
            cfg_valid = jnp.arange(cap_local) < count
            keep = cfg_valid & ((keys & s_key_bit) != 0)
            keys, count, total, o2 = _global_dedup_keys(
                jnp.where(keep, keys & ~s_key_bit, KEY_FILL), keep,
                cap_local, axis)
            dead = total == 0
            return (r + 1, keys, count, total, dead, ovf | o2)

        def row_cond(carry):
            r, _, _, _, dead, ovf = carry
            return (r < n_rows) & ~dead & ~ovf

        r, keys, count, total, dead, ovf = lax.while_loop(
            row_cond, row_body,
            (jnp.int32(0), keys, count, total0, False, False))
        return (keys, count[None], r[None], dead[None], ovf[None],
                total[None])

    fn = util.get_shard_map()(
        shard_body, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(),
                  P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis), P(axis),
                   P(axis), P(axis)),
        check_vma=False)
    keys, counts, r, dead, ovf, total = fn(
        n_rows, ret_slot, active, slot_f, slot_v, pure, pred_mask,
        keys, counts)
    return keys, counts, r[0], dead[0], ovf[0], total[0]


@partial(jax.jit, static_argnames=("cap_local", "step_fn", "mesh",
                                   "axis", "b", "nil_id", "key_hi",
                                   "crash_dom", "it_max", "dom_iters",
                                   "preprune"))
def _search_sharded_sched(n_rows, dropback, min_left, ret_slot, active,
                          slot_v, pure, exp, lo, hi, counts, *,
                          cap_local, step_fn, mesh, b, nil_id, key_hi,
                          crash_dom, it_max, dom_iters, preprune,
                          axis="d"):
    """The compact-band mesh scheduler: ONE SPMD program that walks
    return rows with a COMMITTED-frontier carry — the sharded analogue
    of bfs._host_sched_rows, covering both the single-u32 and the
    pair-key (lo, hi) crash-dom bands.

    Per row: the shared bfs._expand_keys_compact candidate generation
    (saturation tables + M mutator columns + chain/JIT gates —
    identical pass semantics to the single-chip engine by
    construction), then the _global_dedup_keys_dom collective. The
    closure fixpoint is UNGROUPED (G=1: every device evaluates its
    whole shard each pass — the round-5 orbit needs expansion groups)
    and carries an in-program iteration ceiling, so a non-converging
    prune surfaces as an honest ``trip=budget`` instead of a
    watchdog-killed hang.

    A row that converges COMMITS (frontier arrays, per-device counts,
    committed-row counter); a row that overflows or exhausts its
    budget leaves the commit untouched, so the host re-enters at the
    committed row with the committed frontier — escalation re-runs
    ONE row, not the chunk. ``dropback``/``min_left`` mirror the
    host-row scheduler: after ``min_left`` rows the program returns
    early once the GLOBAL frontier fits ``dropback`` (the episode
    hands narrow waves back to the cheap chunk caps).

    Returns (lo', hi'|None, counts'[n_dev], peaks[n_dev],
    flags[7]) — flags = [committed_rows, trip(0 none/1 capacity/
    2 budget), dead, closure_passes, peak_total, committed_total,
    attempted_rows]; committed arrays are the balanced re-shard of the
    last committed frontier."""
    C, W = active.shape

    def shard_body(n_rows, dropback, min_left, ret_slot, active,
                   slot_v, pure, *rest):
        exp_t = rest[:14]
        if key_hi:
            lo, hi, counts = rest[14], rest[15], rest[16]
        else:
            lo, counts = rest[14], rest[15]
            hi = None
        cnt0 = counts[0]
        tot0 = lax.psum(cnt0, axis)
        zero = jnp.int32(0)

        def row_body(carry):
            (r, lo, hi, cnt, clo, chi, ccnt, crow, tot, ctot, peak,
             pk_loc, it_tot, _trip, _dead) = carry
            act_r = active[r]
            v_row = slot_v[r]
            pure_r = pure[r]
            exp_r = tuple(t[r] for t in exp_t)
            # (crash_lo, crash_hi, read_lo, read_hi) — this row's
            # dominance masks (expansion_tables indices 7-10).
            masks = (exp_r[7], exp_r[8], exp_r[9], exp_r[10])

            def cl_cond(c):
                _, _, _, _, changed, ovf, it = c
                return changed & ~ovf & (it < it_max)

            def cl_body(c):
                lo_in, hi_in, n_in, t_in, _, ovf, it = c
                cand_lo, cand_hi, cand_valid = _expand_keys_compact(
                    lo_in, hi_in, n_in, act_r, v_row, pure_r, exp_r,
                    cap=cap_local, W=W, b=b, nil_id=nil_id,
                    step_fn=step_fn)
                l2, h2, n2, t2, o2 = _global_dedup_keys_dom(
                    cand_lo, cand_hi, cand_valid, cap_local, axis,
                    key_hi=key_hi, crash_dom=crash_dom, masks=masks,
                    dom_iters=dom_iters, preprune=preprune)
                changed = jnp.any(l2 != lo_in) | (t2 != t_in)
                if key_hi:
                    changed = changed | jnp.any(h2 != hi_in)
                changed = lax.psum(changed.astype(jnp.int32), axis) > 0
                return (l2, h2, n2, t2, changed, ovf | o2, it + 1)

            lo2, hi2, n2, tot2, changed, ovf, it = lax.while_loop(
                cl_cond, cl_body,
                (lo, hi, cnt, tot, jnp.bool_(True), jnp.bool_(False),
                 zero))
            # Ceiling exhaustion is a budget trip, not convergence.
            budget_hit = changed & ~ovf

            # Return filter (bfs._filter_pass_keys semantics): keep
            # configs holding the returner's key bit, drop the bit
            # (injective on survivors: the bit is constant-1 across
            # them), compact + re-shard through the PLAIN collective.
            s = ret_slot[r]
            pos = (b + s).astype(jnp.uint32)
            live = jnp.arange(cap_local) < n2
            if key_hi:
                in_lo = pos < jnp.uint32(32)
                bit_lo = jnp.where(in_lo, jnp.uint32(1) << (pos & 31),
                                   jnp.uint32(0))
                bit_hi = jnp.where(in_lo, jnp.uint32(0),
                                   jnp.uint32(1) << (pos & 31))
                keep = live & \
                    (((lo2 & bit_lo) | (hi2 & bit_hi)) != 0)
                f_lo = jnp.where(keep, lo2 & ~bit_lo, KEY_FILL)
                f_hi = jnp.where(keep, hi2 & ~bit_hi, KEY_FILL)
            else:
                bit_lo = jnp.uint32(1) << pos
                keep = live & ((lo2 & bit_lo) != 0)
                f_lo = jnp.where(keep, lo2 & ~bit_lo, KEY_FILL)
                f_hi = None
            lo3, hi3, n3, tot3, _ = _global_dedup_keys_dom(
                f_lo, f_hi, keep, cap_local, axis, key_hi=key_hi,
                crash_dom=False, masks=masks, preprune=False)

            converged = ~ovf & ~budget_hit
            dead = converged & (tot3 == 0)
            commit = converged & ~dead
            trip = jnp.where(converged, zero,
                             jnp.where(ovf, jnp.int32(1),
                                       jnp.int32(2)))
            clo2 = jnp.where(commit, lo3, clo)
            chi2 = jnp.where(commit, hi3, chi) if key_hi else None
            ccnt2 = jnp.where(commit, n3, ccnt)
            crow2 = jnp.where(commit, r + 1, crow)
            ctot2 = jnp.where(commit, tot3, ctot)
            return (r + 1, lo3, hi3, n3, clo2, chi2, ccnt2, crow2,
                    tot3, ctot2, jnp.maximum(peak, tot2),
                    jnp.maximum(pk_loc, jnp.maximum(n2, n3)),
                    it_tot + it, trip, dead)

        def row_cond(carry):
            (r, _, _, _, _, _, _, _, _, ctot, _, _, _, trip,
             dead) = carry
            return (r < n_rows) & (trip == 0) & ~dead & \
                ((r < min_left) | (ctot > dropback))

        init = (zero, lo, hi, cnt0, lo, hi, cnt0, zero, tot0, tot0,
                tot0, cnt0, zero, zero, jnp.bool_(False))
        (r, _, _, _, clo, chi, ccnt, crow, _, ctot, peak, pk_loc,
         it_tot, trip, dead) = lax.while_loop(row_cond, row_body, init)
        flags = jnp.stack([crow, trip, dead.astype(jnp.int32), it_tot,
                           peak, ctot, r])
        outs = (clo,) + ((chi,) if key_hi else ()) + \
            (ccnt[None], pk_loc[None], flags[None, :])
        return outs

    n_rep = 7 + 14
    args = [n_rows, dropback, min_left, ret_slot, active, slot_v,
            pure, *exp]
    spec_in = (P(),) * n_rep
    if key_hi:
        args += [lo, hi, counts]
        spec_in += (P(axis), P(axis), P(axis))
        spec_out = (P(axis),) * 5
    else:
        args += [lo, counts]
        spec_in += (P(axis), P(axis))
        spec_out = (P(axis),) * 4
    fn = util.get_shard_map()(shard_body, mesh=mesh, in_specs=spec_in,
                              out_specs=spec_out, check_vma=False)
    out = fn(*args)
    if key_hi:
        clo, chi, ccnt, pk, flags = out
    else:
        clo, ccnt, pk, flags = out
        chi = None
    return clo, chi, ccnt, pk, flags[0]


DEFAULT_CAP_PER_DEVICE = (64, 1024, 16384)

# Episode cap ladder for the compact band: when a row overflows the
# top CHUNK cap the host re-enters THAT row at these per-device caps
# (the mesh twin of the host-row executor's cap ladder) — the 8-device
# global capacity at the top rung matches the single-chip max-cap the
# config-5 history needs (8 * 262144 = 2M > 524288 with margin for
# shard imbalance transients).
MESH_CAPS_DEFAULT = (16384, 65536, 262144)


def _mesh_caps():
    raw = os.environ.get("JEPSEN_TPU_MESH_CAPS", "")
    if raw:
        try:
            caps = tuple(int(x) for x in raw.split(",") if x.strip())
        except ValueError:
            caps = ()
        if caps:
            return caps
    return MESH_CAPS_DEFAULT


def _mesh_queue():
    return max(1, util.env_int("JEPSEN_TPU_MESH_QUEUE", 8))


def _mesh_it_max(W):
    v = util.env_int("JEPSEN_TPU_MESH_IT_MAX", 0)
    return v if v > 0 else 4 * W + 16


def _mesh_preprune():
    return bool(util.env_int("JEPSEN_TPU_MESH_PREPRUNE", 1))


def _mesh_stats_none(n_dev, **extra):
    """The no-dispatch mesh-stats shape: EVERY verdict this module
    returns carries a ``mesh-stats`` dict with at least these keys, so
    bench/driver artifacts never branch on its presence (routing
    errors and empty histories included)."""
    out = {"devices": int(n_dev), "chunks": 0, "escalations": 0,
           "episodes": 0, "dispatches": 0, "sched-rows": 0,
           "dispatch-wall-s": 0.0, "peak-frontier": 0,
           "cap-per-device": 0}
    out.update(extra)
    return out


def check_packed(p: PackedHistory, mesh: Mesh | None = None,
                 cap_schedule=DEFAULT_CAP_PER_DEVICE,
                 engine: str = "auto", cancel=None,
                 explain: bool = False) -> dict:
    """Decide linearizability with the frontier sharded over a mesh. With
    no mesh, shards over all visible devices on axis 'd'.

    ``engine="auto"`` routes to the hypercube-sharded dense bitmap engine
    (:mod:`jepsen_tpu.lin.sharded_dense`) whenever the history fits its
    bounds — chunked, crash-proof, no capacity escalation — and falls back
    to the sparse all_gather-dedup frontier here otherwise;
    ``engine="sparse"`` forces the sparse path."""
    if engine not in ("auto", "sparse"):
        raise ValueError(f"unknown engine {engine!r}; use 'auto'/'sparse'")
    if mesh is None:
        mesh = Mesh(np.array(jax.devices()), ("d",))

    if engine == "auto":
        from jepsen_tpu.lin import sharded_dense

        n_dev = int(np.prod(mesh.devices.shape))
        if sharded_dense.plan(p, n_dev) is not None:
            return sharded_dense.check_packed(p, mesh=mesh, cancel=cancel,
                                              explain=explain)

    n_dev = int(np.prod(mesh.devices.shape))
    if p.kernel is None:
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "mesh-stats": _mesh_stats_none(n_dev),
                "error": f"no device kernel for {type(p.model).__name__}"}

    axis = mesh.axis_names[0]

    from jepsen_tpu.lin.bfs import reduction_bit_tables
    from jepsen_tpu.models.kernels import (PACKED_STATE_KERNELS,
                                           READ_VALUE_MATCH_KERNELS,
                                           packed_state_bound)

    # Packed-u32 keys when the window plus state id fit 31 bits; past
    # that the read-value-match register band (b <= 6) packs the
    # 64-bit config as a PAIR of u32 words to window+b <= 60 — the
    # bfs.check_packed gate, mirrored exactly so the mesh and the
    # single-chip engine route the same shapes to the same key
    # widths. The packed path chunks (static 512-row table slices),
    # so it needs neither the R-bucketing identity rows nor the pad
    # slot of _pad_rows and runs exactly p.R rows on the raw tables.
    read_value_match = p.kernel.name in READ_VALUE_MATCH_KERNELS
    state_bits = nil_id = None
    key_hi = False
    if p.init_state.shape[0] == 1 \
            and p.kernel.name in PACKED_STATE_KERNELS:
        nid = packed_state_bound(p.kernel, len(p.unintern))
        bb = nid.bit_length()
        if p.window + bb <= 31:
            state_bits, nil_id = bb, nid
        elif read_value_match and bb <= 6 and p.window + bb <= 60:
            state_bits, nil_id, key_hi = bb, nid, True

    if p.window > MAX_DEVICE_WINDOW and not key_hi:
        # Explicit routing error, not a silent ceiling: the MULTIWORD
        # mesh frontier keeps single-word u32 dedup keys, and this
        # shape is outside the pair-key compact band too (not a
        # read-value-match register family, or window+b > 60). The
        # single-chip engine covers it — lin.device_check_packed
        # routes wide multiword windows through the sparse engine.
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "mesh-stats": _mesh_stats_none(n_dev),
                "error": (f"concurrency window {p.window} exceeds the "
                          f"sharded engine's single-word key limit "
                          f"{MAX_DEVICE_WINDOW} and the shape is "
                          "outside the pair-key compact band "
                          "(read-value-match registers, window+b <= "
                          "60); re-check on the single-chip engine "
                          "(lin.device_check_packed)")}
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-bfs-sharded",
                "mesh-stats": _mesh_stats_none(n_dev)}

    if state_bits is not None:
        # Mutator-compacted expansion columns (the crash-dom band's
        # program shape): same engagement rule as bfs.check_packed —
        # read-value-match registers with b <= 6.
        exp_h = None
        crash_dom = False
        if read_value_match and state_bits <= 6:
            exp_h = expansion_tables(p, state_bits, lazy=True)
            crash_dom = bool(np.asarray(p.crashed).any())
        if exp_h is not None:
            # nw sized to the window (pair band reaches past 32); only
            # the pure table is consumed — the compact program's chain
            # masks live in the expansion tables.
            pure_k, _ = reduction_bit_tables(p, (p.window + 31) // 32)
            tables_h = (np.asarray(p.ret_slot), np.asarray(p.active),
                        np.asarray(p.slot_v), pure_k)
            return _run_compact_chunks(
                p, mesh, axis, tables_h, exp_h, cap_schedule,
                b=state_bits, nil_id=nil_id, key_hi=key_hi,
                crash_dom=crash_dom, cancel=cancel, explain=explain)
        pure_k, pred_bit_k = reduction_bit_tables(p, 1)
        tables_h = (np.asarray(p.ret_slot), np.asarray(p.active),
                    np.asarray(p.slot_f), np.asarray(p.slot_v),
                    pure_k, pred_bit_k[:, :, 0])
        return _run_packed_chunks(
            p, mesh, axis, tables_h, cap_schedule,
            b=state_bits, nil_id=nil_id,
            read_value_match=read_value_match,
            cancel=cancel, explain=explain)

    ret_slot_h, active_h, slot_f_h, slot_v_h = _pad_rows(p)
    pure_k, pred_bit_k = reduction_bit_tables(p, 1)
    R, W = p.active.shape
    pure_h = np.zeros(active_h.shape, bool)
    pure_h[:R, :W] = pure_k
    pred_mask_h = np.zeros(active_h.shape, np.uint32)
    pred_mask_h[:R, :W] = pred_bit_k[:, :, 0]
    args = (jnp.asarray(ret_slot_h), jnp.asarray(active_h),
            jnp.asarray(slot_f_h), jnp.asarray(slot_v_h),
            jnp.asarray(pure_h), jnp.asarray(pred_mask_h),
            jnp.asarray(p.init_state))

    # Multiword mesh path: the whole history is ONE device program (no
    # chunking); past this bound a single dispatch risks watchdog kills.
    if p.R > MAX_SHARDED_ROWS:
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "mesh-stats": _mesh_stats_none(n_dev),
                "error": f"history length {p.R} exceeds the unchunked "
                         f"multiword mesh bound {MAX_SHARDED_ROWS}; "
                         f"use the single-chip engine"}
    dispatches = 0
    for cap in cap_schedule:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                    "mesh-stats": _mesh_stats_none(
                        n_dev, dispatches=dispatches),
                    "error": "cancelled"}
        ok, dead_row, overflow, total = _search_sharded(
            *args, cap_local=cap, step_fn=p.kernel.step, mesh=mesh,
            axis=axis)
        dispatches += 1
        if not bool(overflow):
            break
    ms = _mesh_stats_none(n_dev, chunks=1, dispatches=dispatches,
                          escalations=dispatches - 1)
    ms["cap-per-device"] = int(cap)
    if bool(overflow):
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "overflow": "capacity", "mesh-stats": ms,
                "error": f"frontier exceeded {cap_schedule[-1]} per device"}
    ms["peak-frontier"] = int(total)
    if bool(ok):
        return {"valid?": True, "analyzer": "tpu-bfs-sharded",
                "dedup": "multiword", "mesh-stats": ms,
                "final-frontier-size": int(total)}
    r = int(dead_row)
    ret = p.ops[int(p.ret_op[r])]
    out = {"valid?": False, "analyzer": "tpu-bfs-sharded",
           "dedup": "multiword", "mesh-stats": ms,
           "op": {"process": ret.process, "f": ret.f, "value": ret.value,
                  "index": ret.op_index, "ok": ret.ok},
           "configs": [], "final-paths": []}
    if explain:
        from jepsen_tpu.lin import witness

        if r < SHARDED_CHUNK:
            # The multiword mesh search runs the whole history as one
            # program, so there is no chunk snapshot. Replay from the
            # initial config ONLY within the bounded-replay contract
            # (witness.py: one chunk of return events); past that the
            # host replay of a device-scale frontier could DNF.
            init = (0, tuple(int(x) for x in p.init_state))
            out.update(witness.replay_configs(p, {init}, 0, r,
                                              cancel=cancel))
        else:
            out["explain-error"] = (
                f"dead row {r} is beyond the bounded replay window "
                f"({SHARDED_CHUNK} rows); the unchunked multiword mesh "
                f"path keeps no chunk snapshots — re-check on the "
                f"single-chip engine for a counterexample")
    return out


SHARDED_CHUNK = 512


def _run_packed_chunks(p, mesh, axis, tables_h, cap_schedule, *, b,
                       nil_id, read_value_match, cancel=None,
                       explain=False):
    """Host loop over SHARDED_CHUNK-row dispatches of the packed-key
    mesh search: the frontier (global [n_dev*cap] keys + per-device
    counts) carries device-resident between chunks, so history length is
    unbounded — the mesh twin of bfs.check_packed's chunk loop, with
    per-chunk capacity escalation from the chunk-entry snapshot."""
    from jepsen_tpu.lin.bfs import _chunk_slice
    from jepsen_tpu.models.kernels import NIL

    n_dev = int(np.prod(mesh.devices.shape))
    step_fn = p.kernel.step

    sv0 = int(p.init_state[0])
    init_key = np.uint32(nil_id if sv0 == int(NIL) else sv0)
    level = 0
    cap = cap_schedule[level]
    keys = jnp.full(n_dev * cap, KEY_FILL, jnp.uint32).at[0].set(init_key)
    counts = jnp.zeros(n_dev, jnp.int32).at[0].set(1)

    def resize(keys, old_cap, new_cap):
        k = keys.reshape(n_dev, old_cap)
        k = jnp.pad(k, ((0, 0), (0, new_cap - old_cap)),
                    constant_values=KEY_FILL)
        return k.reshape(-1)

    snapshots = [] if explain else None
    base = 0
    n_chunks = 0
    n_escalations = 0
    peak_total = 1
    sup_stats: dict = {"watchdog_trips": 0, "faults": 0}
    # mesh-stats as a live registry view (the host-stats precedent):
    # the snapshot shows the dispatch/escalation profile of a running
    # mesh decide next to the run gauges web.py /run renders.
    _mesh_view = obs_metrics.REGISTRY.view("mesh-stats", {})
    obs_metrics.REGISTRY.start_run("lin-sharded", total=int(p.R),
                                   window=int(p.window))

    n_dispatches = 0
    wall = [0.0]

    def mesh_stats():
        # Observability twin of the single-chip engine's host-stats:
        # attached to EVERY verdict shape (success, death, overflow)
        # so bench/driver artifacts can read the dispatch and
        # escalation profile without re-running. Key set is uniform
        # with the compact band's (see _mesh_stats_none).
        out = {"devices": n_dev, "chunks": n_chunks,
               "escalations": n_escalations, "episodes": 0,
               "dispatches": n_dispatches, "sched-rows": 0,
               "dispatch-wall-s": round(wall[0], 3),
               "peak-frontier": peak_total,
               "cap-per-device": cap_schedule[level]}
        if sup_stats["watchdog_trips"] or sup_stats["faults"]:
            out.update(sup_stats)
        return out

    while base < p.R:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                    "mesh-stats": mesh_stats(), "error": "cancelled"}
        if snapshots is not None:
            # Only the last snapshot is replayed (the dead row is inside
            # the current chunk).
            snapshots[:] = [(base, keys, counts)]
        n = min(SHARDED_CHUNK, p.R - base)
        tbl = tuple(jnp.asarray(_chunk_slice(a, base, SHARDED_CHUNK))
                    for a in tables_h)
        while True:
            util.progress_tick()   # liveness: one tick per chunk dispatch

            def _mesh_chunk_prog(keys=keys, counts=counts,
                                 level=level):
                return _search_sharded_keys(
                    *tbl, keys, counts, jnp.int32(n),
                    cap_local=cap_schedule[level], step_fn=step_fn,
                    mesh=mesh, b=b, nil_id=nil_id,
                    read_value_match=read_value_match, axis=axis)

            def _mesh_chunk():
                out = _mesh_chunk_prog()
                return out, bool(out[4])

            mesh_key = supervise.shape_key(
                "mesh-chunk", rows=SHARDED_CHUNK,
                cap=cap_schedule[level], window=p.window,
                kernel=p.kernel.name)
            t0 = time.monotonic()
            outcome, val = supervise.run_guarded(
                "mesh-chunk", mesh_key, _mesh_chunk, stats=sup_stats,
                traceable=_mesh_chunk_prog)
            wall[0] += time.monotonic() - t0
            n_dispatches += 1
            if outcome == "wedge":
                return {"valid?": "unknown",
                        "analyzer": "tpu-bfs-sharded",
                        "overflow": "wedge",
                        "mesh-stats": mesh_stats(), "error": str(val)}
            if outcome == "fault":
                return {"valid?": "unknown",
                        "analyzer": "tpu-bfs-sharded",
                        "overflow": "fault",
                        "mesh-stats": mesh_stats(),
                        "error": f"dispatch fault near row {base}: "
                                 f"{val!r}"}
            (k2, c2, r_done, dead, ovf, total), ovf_b = val
            if not ovf_b:
                break
            if level + 1 >= len(cap_schedule):
                return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                        "overflow": "capacity",
                        "mesh-stats": mesh_stats(),
                        "error": (f"frontier exceeded {cap_schedule[-1]} "
                                  f"per device")}
            # Retry this chunk from its entry frontier at the next cap.
            level += 1
            n_escalations += 1
            keys = resize(keys, cap, cap_schedule[level])
            cap = cap_schedule[level]
        if bool(dead):
            r = base + int(r_done) - 1
            ret = p.ops[int(p.ret_op[r])]
            out = {"valid?": False, "analyzer": "tpu-bfs-sharded",
                   "dedup": "packed-keys",
                   "mesh-stats": mesh_stats(),
                   "op": {"process": ret.process, "f": ret.f,
                          "value": ret.value, "index": ret.op_index,
                          "ok": ret.ok},
                   "configs": [], "final-paths": []}
            if snapshots:
                # Global keys are front-packed in global index order, so
                # the single-chip unpack applies to the gathered array.
                from jepsen_tpu.lin import witness
                from jepsen_tpu.lin.bfs import _unpack_frontier_keys

                s_base, s_keys, s_counts = snapshots[-1]
                tot = int(np.asarray(s_counts).sum())
                kb, ks = _unpack_frontier_keys(
                    jnp.asarray(np.asarray(s_keys)), tot,
                    s_keys.shape[0], b, nil_id)
                out.update(witness.tail_replay_sparse(
                    p, [(s_base, kb, ks, tot)], r, cancel=cancel))
            return out
        keys, counts = k2, c2
        base += n
        n_chunks += 1
        peak_total = max(peak_total, int(total))
        _mesh_view.clear()
        _mesh_view.update(mesh_stats())
        obs_metrics.REGISTRY.progress(row=base, frontier=int(total))
        # Shrink back to a smaller (faster) program when the global
        # frontier has room to spare; survivors are globally packed to
        # the front, so slicing each device's prefix keeps them all.
        while level > 0 and int(total) * 4 <= cap_schedule[level - 1]:
            new_cap = cap_schedule[level - 1]
            keys = keys.reshape(n_dev, cap)[:, :new_cap].reshape(-1)
            level -= 1
            cap = new_cap
    ms = mesh_stats()
    return {"valid?": True, "analyzer": "tpu-bfs-sharded",
            "dedup": "packed-keys", "final-frontier-size": int(total),
            # Shard observability (the multi-chip speedup evidence the
            # day real hardware exists): the collective dedup packs
            # survivors to the global front, so occupancy is the
            # balanced prefix-fill of cap_local per device. The
            # top-level chunks/peak/cap keys predate mesh-stats and
            # are kept for consumers (__graft_entry__ asserts them);
            # both spellings read the SAME mesh_stats() values.
            "chunks": ms["chunks"], "peak-frontier": ms["peak-frontier"],
            "cap-per-device": ms["cap-per-device"], "mesh-stats": ms,
            "shard-occupancy": [int(x) for x in np.asarray(counts)]}


def _run_compact_chunks(p, mesh, axis, tables_h, exp_h, cap_schedule,
                        *, b, nil_id, key_hi, crash_dom, cancel=None,
                        explain=False):
    """Host scheduler for the COMPACT mesh band (both key widths,
    crash-dom included): SHARDED_CHUNK-row dispatches of
    _search_sharded_sched with a committed-frontier carry, per-ROW
    capacity escalation (the program returns committed progress on a
    trip, so escalation re-enters at the tripped row, never re-runs
    the chunk), and — past the top chunk cap — EPISODES: the mesh
    analogue of the single-chip host-row executor. An episode
    re-shards the frontier across the JEPSEN_TPU_MESH_CAPS ladder,
    walks rows in JEPSEN_TPU_MESH_QUEUE-row dispatches with deeper
    dominance iterations (dom_iters=6, the host-row setting), and
    drops back to the cheap chunk caps once the global frontier
    narrows below a quarter of the top chunk capacity. A row that
    exhausts the top mesh cap (or its closure budget there) returns
    an honest ``overflow: capacity`` / ``overflow: budget`` unknown.

    Frontier state between dispatches is the globally-packed key
    array (+ per-device counts); _reshard's host repack preserves the
    balanced prefix-fill invariant, and supervise checkpoints
    (kind "mesh") make a killed long decide resumable at the last
    committed row."""
    from jepsen_tpu.lin import witness
    from jepsen_tpu.lin.bfs import (_chunk_slice, _unpack_frontier_keys,
                                    _unpack_frontier_keys2)
    from jepsen_tpu.models.kernels import NIL

    n_dev = int(np.prod(mesh.devices.shape))
    step_fn = p.kernel.step
    W = int(p.active.shape[1])
    nw = (p.window + 31) // 32
    it_max = _mesh_it_max(W)
    preprune = _mesh_preprune()
    mesh_caps = _mesh_caps()
    queue_rows = _mesh_queue()
    kernel_name = p.kernel.name
    band = "pair" if key_hi else "single"

    sv0 = int(p.init_state[0])
    init_sid = np.uint32(nil_id if sv0 == int(NIL) else sv0)

    def _reshard(lo_a, hi_a, total, new_cap):
        """Host repack at a new per-device cap. The carried global
        array is front-packed (the collective dedup sorts survivors
        to the global front), so the repack is one prefix copy; the
        per-device counts become the balanced prefix-fill."""
        ln = np.full(n_dev * new_cap, KEY_FILL, np.uint32)
        ln[:total] = np.asarray(lo_a)[:total]
        hn = None
        if key_hi:
            hn = np.full(n_dev * new_cap, KEY_FILL, np.uint32)
            hn[:total] = np.asarray(hi_a)[:total]
        cnts = np.clip(total - np.arange(n_dev) * new_cap, 0,
                       new_cap).astype(np.int32)
        return (jnp.asarray(ln),
                jnp.asarray(hn) if key_hi else None,
                jnp.asarray(cnts))

    level = 0
    mlvl = 0
    episode_mode = False
    cap_now = cap_schedule[level]
    base = 0
    total = 1
    lo_h = np.full(n_dev * cap_now, KEY_FILL, np.uint32)
    lo_h[0] = init_sid
    lo = jnp.asarray(lo_h)
    hi = None
    if key_hi:
        hi_h = np.full(n_dev * cap_now, KEY_FILL, np.uint32)
        hi_h[0] = np.uint32(0)
        hi = jnp.asarray(hi_h)
    counts = jnp.zeros(n_dev, jnp.int32).at[0].set(1)

    # --- checkpoint/resume (supervise module docstring) -------------
    ck = None
    ck_path = supervise.ckpt_path()
    if ck_path:
        ck = supervise.Checkpointer(
            ck_path, supervise.history_fingerprint(p))
        rd = supervise.load_checkpoint(ck_path, ck.fingerprint)
        if rd is not None and rd["kind"] == "mesh" \
                and rd["meta"].get("b") == b \
                and rd["meta"].get("key_hi") == key_hi:
            base = rd["row"]
            total = rd["count"]
            if total <= n_dev * cap_schedule[-1]:
                level = next(i for i, c in enumerate(cap_schedule)
                             if total <= n_dev * c)
                cap_now = cap_schedule[level]
            else:
                episode_mode = True
                level = len(cap_schedule) - 1
                mlvl = next((i for i, c in enumerate(mesh_caps)
                             if total <= n_dev * c),
                            len(mesh_caps) - 1)
                cap_now = mesh_caps[mlvl]
            lo, hi, counts = _reshard(rd["lo"][:total],
                                      rd.get("hi"), total, cap_now)

    n_chunks = 0
    n_escalations = 0
    n_episodes = 0
    n_dispatches = 0
    sched_rows = 0
    peak_total = int(total)
    wall = [0.0]
    pk_dev = np.zeros(n_dev, np.int64)
    sup_stats: dict = {"watchdog_trips": 0, "faults": 0}
    _mesh_view = obs_metrics.REGISTRY.view("mesh-stats", {})
    obs_metrics.REGISTRY.start_run("lin-sharded", total=int(p.R),
                                   window=int(p.window))

    def mesh_stats():
        # The uniform verdict-attached stats shape (_mesh_stats_none
        # keys) plus the compact band's per-device counters: every
        # device's peak shard occupancy across all dispatches, the
        # episode/scheduler row profile, and the accumulated guarded
        # dispatch wall — the evidence bench.py's mesh probe and the
        # perf ledger read.
        out = {"devices": n_dev, "band": band, "crash-dom": crash_dom,
               "chunks": n_chunks, "escalations": n_escalations,
               "episodes": n_episodes, "sched-rows": sched_rows,
               "dispatches": n_dispatches,
               "dispatch-wall-s": round(wall[0], 3),
               "peak-frontier": peak_total, "cap-per-device": cap_now,
               "peak-occupancy": [int(x) for x in pk_dev]}
        if sup_stats["watchdog_trips"] or sup_stats["faults"]:
            out.update(sup_stats)
        return out

    def _unknown(kind, err):
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "overflow": kind, "mesh-stats": mesh_stats(),
                "error": err}

    while base < p.R:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                    "mesh-stats": mesh_stats(), "error": "cancelled"}
        if episode_mode:
            C = queue_rows
            cap_now = mesh_caps[mlvl]
            dropback = n_dev * cap_schedule[-1] // 4
            min_left = 1
            d_iters = 6
            band_key = f"{band}-sched"
        else:
            C = SHARDED_CHUNK
            cap_now = cap_schedule[level]
            dropback = 0
            min_left = C
            d_iters = 2
            band_key = band
        n = min(C, p.R - base)
        tbl = tuple(jnp.asarray(_chunk_slice(a, base, C))
                    for a in tables_h)
        exp_j = tuple(jnp.asarray(_chunk_slice(np.asarray(a), base, C))
                      for a in exp_h)
        util.progress_tick()   # liveness: one tick per dispatch

        def _mesh_sched_prog(lo=lo, hi=hi, counts=counts, n=n,
                             tbl=tbl, exp_j=exp_j, cap_now=cap_now,
                             dropback=dropback, min_left=min_left,
                             d_iters=d_iters):
            return _search_sharded_sched(
                jnp.int32(n), jnp.int32(dropback), jnp.int32(min_left),
                *tbl, exp_j, lo, hi, counts, cap_local=cap_now,
                step_fn=step_fn, mesh=mesh, b=b, nil_id=nil_id,
                key_hi=key_hi, crash_dom=crash_dom, it_max=it_max,
                dom_iters=d_iters, preprune=preprune, axis=axis)

        def _mesh_sched():
            out = _mesh_sched_prog()
            return out, np.asarray(out[4])   # flags fetch = sync

        mesh_key = supervise.shape_key(
            "mesh-chunk", rows=C, cap=cap_now, window=p.window,
            kernel=kernel_name, band=band_key)
        t0 = time.monotonic()
        outcome, val = supervise.run_guarded(
            "mesh-chunk", mesh_key, _mesh_sched, stats=sup_stats,
            traceable=_mesh_sched_prog)
        wall[0] += time.monotonic() - t0
        n_dispatches += 1
        if outcome == "wedge":
            return _unknown("wedge", str(val))
        if outcome == "fault":
            return _unknown("fault",
                            f"dispatch fault near row {base}: {val!r}")
        (clo, chi, ccnt, pk, _), flags = val
        crow, trip, dead_f, it_tot, peak_d, ctot, attempted = \
            (int(x) for x in flags)
        # Commit the program's progress (trip or not — the committed
        # carry is the last CONVERGED row's frontier).
        base += crow
        lo, hi, counts = clo, chi, ccnt
        total = ctot
        peak_total = max(peak_total, peak_d)
        pk_dev = np.maximum(pk_dev, np.asarray(pk))
        if episode_mode:
            sched_rows += crow
        obs_trace.tail_note(row=base, rows=crow, passes=it_tot,
                            frontier=total, cap=cap_now)
        _mesh_view.clear()
        _mesh_view.update(mesh_stats())
        obs_metrics.REGISTRY.progress(row=base, frontier=total)
        if ck is not None and crow > 0 and ck.due():
            arrays = {"lo": np.asarray(lo)}
            if key_hi:
                arrays["hi"] = np.asarray(hi)
            ck.save("mesh", base, total, arrays,
                    {"b": b, "key_hi": key_hi})
        if dead_f:
            # The dead row is the first uncommitted one; the carried
            # frontier is exactly its ENTRY, so the counterexample
            # replay spans ONE row.
            r = base
            ret = p.ops[int(p.ret_op[r])]
            out = {"valid?": False, "analyzer": "tpu-bfs-sharded",
                   "dedup": "packed-keys2" if key_hi else "packed-keys",
                   "mesh-stats": mesh_stats(),
                   "op": {"process": ret.process, "f": ret.f,
                          "value": ret.value, "index": ret.op_index,
                          "ok": ret.ok},
                   "configs": [], "final-paths": []}
            if explain:
                tot = int(total)
                cap_g = n_dev * cap_now
                if key_hi:
                    kb, ks = _unpack_frontier_keys2(
                        jnp.asarray(np.asarray(lo)),
                        jnp.asarray(np.asarray(hi)), tot, cap_g, b,
                        nil_id, nw)
                else:
                    kb, ks = _unpack_frontier_keys(
                        jnp.asarray(np.asarray(lo)), tot, cap_g, b,
                        nil_id)
                out.update(witness.tail_replay_sparse(
                    p, [(r, kb, ks, tot)], r, cancel=cancel))
            if ck is not None:
                ck.clear()
            return out
        if trip:
            if not episode_mode:
                if level + 1 < len(cap_schedule):
                    level += 1
                    n_escalations += 1
                    lo, hi, counts = _reshard(lo, hi, total,
                                              cap_schedule[level])
                    continue
                episode_mode = True
                n_episodes += 1
                mlvl = next((i for i, c in enumerate(mesh_caps)
                             if c > cap_now and total <= n_dev * c),
                            len(mesh_caps) - 1)
            else:
                if mlvl + 1 >= len(mesh_caps):
                    if trip == 1:
                        return _unknown(
                            "capacity",
                            f"row {base} frontier exceeded the top "
                            f"mesh cap {mesh_caps[-1]} per device "
                            f"({n_dev} devices)")
                    return _unknown(
                        "budget",
                        f"row {base} closure passed {it_max} "
                        f"iterations without converging at the top "
                        f"mesh cap (suspected non-terminating prune "
                        f"orbit; see round-5 lore)")
                mlvl += 1
                n_escalations += 1
            if total > n_dev * mesh_caps[mlvl]:
                return _unknown(
                    "capacity",
                    f"row {base} frontier {total} exceeds mesh cap "
                    f"{mesh_caps[mlvl]} x {n_dev} devices")
            lo, hi, counts = _reshard(lo, hi, total, mesh_caps[mlvl])
            obs_trace.instant("mesh-episode", row=base, total=total,
                              cap=mesh_caps[mlvl])
            continue
        # Clean return: a finished chunk, or an episode that ran out
        # of rows / narrowed below the dropback threshold.
        if episode_mode:
            if base >= p.R:
                break
            if total <= dropback:
                episode_mode = False
                level = len(cap_schedule) - 1
                lo, hi, counts = _reshard(lo, hi, total,
                                          cap_schedule[level])
            continue
        n_chunks += 1
        # Shrink back to a smaller (faster) chunk program when the
        # global frontier has room to spare (generic-loop precedent).
        while level > 0 and total * 4 <= cap_schedule[level - 1]:
            level -= 1
            lo, hi, counts = _reshard(lo, hi, total,
                                      cap_schedule[level])
    cap_now = mesh_caps[mlvl] if episode_mode else cap_schedule[level]
    if ck is not None:
        ck.clear()
    ms = mesh_stats()
    return {"valid?": True, "analyzer": "tpu-bfs-sharded",
            "dedup": "packed-keys2" if key_hi else "packed-keys",
            "final-frontier-size": int(total),
            # Same top-level compatibility keys as the generic loop
            # (__graft_entry__ asserts them on mesh verdicts).
            "chunks": ms["chunks"], "peak-frontier": ms["peak-frontier"],
            "cap-per-device": ms["cap-per-device"], "mesh-stats": ms,
            "shard-occupancy": [int(x) for x in np.asarray(counts)]}
