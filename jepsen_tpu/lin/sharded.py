"""Multi-chip frontier search: the BFS sharded over a device mesh.

The distributed half of :mod:`jepsen_tpu.lin.bfs` — the capability the
reference gets from a 32GB JVM heap on one control node
(jepsen/project.clj:22-25), re-designed as SPMD over a
``jax.sharding.Mesh``:

- The frontier's capacity axis is sharded: each device owns
  ``cap_local = cap/D`` configs in its HBM, so total frontier capacity
  scales linearly with chip count.
- Expansion (config x pending-op step kernels) is embarrassingly parallel
  and stays local.
- Dedup is the collective: candidate (bits, state) keys are
  ``all_gather``-ed over the mesh axis (ICI within a slice), every device
  runs the identical lexicographic sort + unique-mask + cumsum compaction,
  and keeps the slice of the packed result it owns: a deterministic
  balanced re-shard with no host round-trips. All control decisions
  (fixpoint, death, overflow) derive from replicated reductions, so every
  device takes the same `lax.while_loop` branches.

The whole search — outer return-event loop included — is one
``shard_map``-ped program: a single XLA computation per (R-bucket, W, cap)
with collectives inlined where the dedup needs them.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from jepsen_tpu import util
from jepsen_tpu.lin import supervise
from jepsen_tpu.lin.bfs import KEY_FILL, _expand_keys, _pad_rows
from jepsen_tpu.obs import metrics as obs_metrics

# The sparse sharded frontier keeps single-word bitsets (the all_gather
# dedup keys stay u32); wider windows fall back to the single-chip engine.
MAX_DEVICE_WINDOW = 32
# Whole-history single-program bound for the MULTIWORD mesh path (no
# chunking there). The packed-key mesh path chunks like bfs.check_packed
# and has no length bound; the dense hypercube engine likewise.
MAX_SHARDED_ROWS = 8192
from jepsen_tpu.lin.prepare import PackedHistory


def _global_dedup_keys(keys, valid, cap_local, axis):
    """Packed-u32-key collective dedup: ONE all_gather of u32 keys over
    the mesh axis (vs bits+state columns — this is the "bitset-hash
    dedup allreduced over ICI" axis of the north star, at a fraction of
    the collective bytes), a global sort, duplicate masking, and a
    second sort for compaction (no scatter — `.at[idx].set` serializes
    on TPU; no searchsorted — it kernel-faults this runtime at scale,
    see bfs._dedup_keys). Every device keeps its deterministic slice.
    Returns (keys[cap_local], count_local, total, overflow); total and
    overflow are replicated."""
    d = lax.axis_index(axis)
    n_dev = util.axis_size(axis)

    key = keys | ((~valid).astype(jnp.uint32) << 31)
    key_all = lax.all_gather(key, axis, tiled=True)
    n = key_all.shape[0]
    key_s = lax.sort(key_all)
    inv_s = key_s >> 31

    prev_differs = key_s != jnp.roll(key_s, 1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap_local * n_dev
    packed = lax.sort(jnp.where(mask, key_s, KEY_FILL))
    mine = lax.dynamic_slice(packed, (d * cap_local,), (cap_local,))
    count_local = jnp.clip(total - d * cap_local, 0, cap_local)
    return mine, count_local, total, overflow


def _global_dedup(bits, state, valid, cap_local, axis):
    """All-gather candidates, globally sort-dedup, keep this device's
    slice. Returns (bits[cap_local], state[cap_local,S], count_local,
    total, overflow) — total/overflow are replicated."""
    d = lax.axis_index(axis)
    n_dev = util.axis_size(axis)
    s_width = state.shape[1]

    bits_all = lax.all_gather(bits, axis, tiled=True)
    state_all = lax.all_gather(state, axis, tiled=True)
    valid_all = lax.all_gather(valid, axis, tiled=True)
    n = bits_all.shape[0]

    inv = (~valid_all).astype(jnp.uint32)
    operands = (inv, bits_all) + tuple(state_all[:, k]
                                       for k in range(s_width))
    sorted_ops = lax.sort(operands, num_keys=len(operands))
    inv_s, bits_s = sorted_ops[0], sorted_ops[1]
    state_s = jnp.stack(sorted_ops[2:], axis=1)

    prev_differs = (bits_s != jnp.roll(bits_s, 1)) | \
        jnp.any(state_s != jnp.roll(state_s, 1, axis=0), axis=1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    cap_global = cap_local * n_dev
    overflow = total > cap_global

    # Global packed position; this device keeps [d*cap_local, (d+1)*cap).
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    lo = d * cap_local
    mine = mask & (pos >= lo) & (pos < lo + cap_local)
    idx = jnp.where(mine, pos - lo, n)

    out_n = max(n, cap_local) + 1
    out_bits = jnp.zeros(out_n, jnp.uint32).at[idx].set(bits_s)[:cap_local]
    out_state = jnp.zeros((out_n, s_width), jnp.int32) \
        .at[idx].set(state_s)[:cap_local]
    count_local = jnp.clip(total - lo, 0, cap_local)
    return out_bits, out_state, count_local, total, overflow


@partial(jax.jit, static_argnames=("cap_local", "step_fn", "mesh",
                                   "axis"))
def _search_sharded(ret_slot, active, slot_f, slot_v, pure, pred_mask,
                    init_state, *, cap_local, step_fn, mesh, axis="d"):
    """shard_map-ped full search. Frontier sharded over `axis`; row tables
    replicated — including the reduction tables (prepare.reduction_tables):
    pure[R,W] slots saturate instead of branching, pred_mask[R,W] gates
    canonical-chain expansion. Returns replicated
    (ok, dead_row, overflow, total)."""
    R, W = active.shape
    S = init_state.shape[0]

    def shard_body(ret_slot, active, slot_f, slot_v, pure, pred_mask,
                   init_state):
        d = lax.axis_index(axis)
        slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

        bits0 = jnp.zeros(cap_local, jnp.uint32)
        state0 = jnp.zeros((cap_local, S), jnp.int32).at[0].set(init_state)
        # Only device 0 starts with the initial config.
        count0 = jnp.where(d == 0, jnp.int32(1), jnp.int32(0))

        step_cfg_slot = jax.vmap(
            jax.vmap(step_fn, in_axes=(None, 0, 0)),
            in_axes=(0, None, None))

        def closure_cond(c):
            _, _, _, _, changed, ovf = c
            return changed & ~ovf

        def row_body(carry):
            r, bits, state, count, total, dead, ovf = carry
            act = active[r]
            f_row = slot_f[r]
            v_row = slot_v[r]
            pure_row = pure[r]
            pred_row = pred_mask[r]
            s = ret_slot[r]

            def closure_body(c):
                bits_in, state, count, total, _, ovf = c
                cfg_valid = jnp.arange(cap_local) < count
                ok, new_state = step_cfg_slot(state, f_row, v_row)
                already = (bits_in[:, None] & slot_bit[None, :]) != 0
                fresh = ok & act[None, :] & ~already & cfg_valid[:, None]
                # Saturation: absorb legal pure bits in place (local —
                # the config's slice assignment may move at dedup, but
                # the global multiset is what matters). Statically
                # unrolled OR, not a vector reduce (TPU-runtime hazard,
                # see bfs.py).
                sat = jnp.zeros_like(bits_in)
                for j in range(W):
                    sat = sat | jnp.where(fresh[:, j] & pure_row[j],
                                          slot_bit[j], jnp.uint32(0))
                bits = jnp.where(cfg_valid, bits_in | sat, bits_in)
                chain_ok = (bits[:, None] & pred_row[None, :]) == \
                    pred_row[None, :]
                legal = fresh & ~pure_row[None, :] & chain_ok
                new_bits = bits[:, None] | slot_bit[None, :]

                cand_bits = jnp.concatenate([bits, new_bits.reshape(-1)])
                cand_state = jnp.concatenate(
                    [state, new_state.reshape(-1, S)], axis=0)
                cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

                b2, s2, n2, tot2, o2 = _global_dedup(
                    cand_bits, cand_state, cand_valid, cap_local, axis)
                # Fixpoint test is against the pass INPUT (the stable set
                # keeps both a config and its saturated twin; see
                # bfs._search_chunk_keys.closure_body).
                changed = jnp.any(b2 != bits_in) | jnp.any(s2 != state) | \
                    (tot2 != total)
                changed = lax.psum(changed.astype(jnp.int32), axis) > 0
                return (b2, s2, n2, tot2, changed, ovf | o2)

            init = (bits, state, count, total, jnp.bool_(True), ovf)
            # lint: unbounded-ok — monotone closure fixpoint (no
            # content-sensitive dominance prune on the mesh path;
            # candidates include the current frontier) so it
            # terminates in O(W) passes; an in-carry ceiling rides
            # with the crash-dom mesh work (ROADMAP mesh item).
            bits, state, count, total, _, ovf = lax.while_loop(
                closure_cond, closure_body, init)

            s_bit = jnp.uint32(1) << s.astype(jnp.uint32)
            cfg_valid = jnp.arange(cap_local) < count
            keep = cfg_valid & ((bits & s_bit) != 0)
            bits = bits & ~s_bit
            bits, state, count, total, o2 = _global_dedup(
                bits, state, keep, cap_local, axis)
            dead = total == 0
            return (r + 1, bits, state, count, total, dead, ovf | o2)

        def row_cond(carry):
            r, _, _, _, _, dead, ovf = carry
            return (r < R) & ~dead & ~ovf

        r, bits, state, count, total, dead, ovf = lax.while_loop(
            row_cond, row_body,
            (jnp.int32(0), bits0, state0, count0, jnp.int32(1),
             False, False))
        return (~dead & ~ovf)[None], (r - 1)[None], ovf[None], total[None]

    shard_map = util.get_shard_map()

    # check_vma off: the carry deliberately mixes axis-varying values (the
    # frontier shard, via axis_index) with replicated control scalars
    # (total/dead/overflow from all_gather'ed reductions).
    fn = shard_map(shard_body, mesh=mesh,
                   in_specs=(P(), P(), P(), P(), P(), P(), P()),
                   out_specs=(P(axis), P(axis), P(axis), P(axis)),
                   check_vma=False)
    ok, dead_row, ovf, total = fn(ret_slot, active, slot_f, slot_v,
                                  pure, pred_mask, init_state)
    return ok[0], dead_row[0], ovf[0], total[0]


@partial(jax.jit, static_argnames=("cap_local", "step_fn", "mesh", "axis",
                                   "b", "nil_id", "read_value_match"))
def _search_sharded_keys(ret_slot, active, slot_f, slot_v, pure, pred_mask,
                         keys, counts, n_rows, *, cap_local, step_fn,
                         mesh, b, nil_id, read_value_match, axis="d"):
    """ONE chunk of the packed-u32-key mesh search: each device owns
    cap_local keys (bits << b | state id, the bfs._pack_frontier_keys
    layout) of the globally [n_dev*cap_local]-shaped ``keys``; ``counts``
    is the per-device live count [n_dev]. Dedup is the single-array
    collective of _global_dedup_keys; candidate generation is
    bfs._expand_keys, so the pass semantics (saturation, canonical
    chains, the register read fast table) are byte-identical to the
    single-chip engine. The frontier carries between chunk dispatches
    exactly like bfs.check_packed, so history length is unbounded.
    Returns (keys', counts', rows_done, dead, overflow, total) — the
    last four replicated scalars."""
    C, W = active.shape

    def shard_body(n_rows, ret_slot, active, slot_f, slot_v, pure,
                   pred_mask, keys, counts):
        count = counts[0]
        total0 = lax.psum(count, axis)

        def closure_cond(c):
            _, _, _, changed, ovf = c
            return changed & ~ovf

        def row_body(carry):
            r, keys, count, total, dead, ovf = carry
            act = active[r]
            f_row = slot_f[r]
            v_row = slot_v[r]
            pure_row = pure[r]
            pred_row = pred_mask[r]
            s = ret_slot[r]

            def closure_body(c):
                keys_in, count, total, _, ovf = c
                cand, cand_valid = _expand_keys(
                    keys_in, count, act, f_row, v_row, pure_row,
                    pred_row, cap=cap_local, W=W, b=b, nil_id=nil_id,
                    step_fn=step_fn, read_value_match=read_value_match)
                k2, n2, tot2, o2 = _global_dedup_keys(
                    cand, cand_valid, cap_local, axis)
                changed = jnp.any(k2 != keys_in) | (tot2 != total)
                changed = lax.psum(changed.astype(jnp.int32), axis) > 0
                return (k2, n2, tot2, changed, ovf | o2)

            init = (keys, count, total, jnp.bool_(True), ovf)
            # lint: unbounded-ok — monotone closure fixpoint (same
            # termination argument as the multiword body above).
            keys, count, total, _, ovf = lax.while_loop(
                closure_cond, closure_body, init)

            s_key_bit = jnp.uint32(1) << (b + s).astype(jnp.uint32)
            cfg_valid = jnp.arange(cap_local) < count
            keep = cfg_valid & ((keys & s_key_bit) != 0)
            keys, count, total, o2 = _global_dedup_keys(
                jnp.where(keep, keys & ~s_key_bit, KEY_FILL), keep,
                cap_local, axis)
            dead = total == 0
            return (r + 1, keys, count, total, dead, ovf | o2)

        def row_cond(carry):
            r, _, _, _, dead, ovf = carry
            return (r < n_rows) & ~dead & ~ovf

        r, keys, count, total, dead, ovf = lax.while_loop(
            row_cond, row_body,
            (jnp.int32(0), keys, count, total0, False, False))
        return (keys, count[None], r[None], dead[None], ovf[None],
                total[None])

    fn = util.get_shard_map()(
        shard_body, mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(),
                  P(axis), P(axis)),
        out_specs=(P(axis), P(axis), P(axis), P(axis),
                   P(axis), P(axis)),
        check_vma=False)
    keys, counts, r, dead, ovf, total = fn(
        n_rows, ret_slot, active, slot_f, slot_v, pure, pred_mask,
        keys, counts)
    return keys, counts, r[0], dead[0], ovf[0], total[0]


DEFAULT_CAP_PER_DEVICE = (64, 1024, 16384)


def check_packed(p: PackedHistory, mesh: Mesh | None = None,
                 cap_schedule=DEFAULT_CAP_PER_DEVICE,
                 engine: str = "auto", cancel=None,
                 explain: bool = False) -> dict:
    """Decide linearizability with the frontier sharded over a mesh. With
    no mesh, shards over all visible devices on axis 'd'.

    ``engine="auto"`` routes to the hypercube-sharded dense bitmap engine
    (:mod:`jepsen_tpu.lin.sharded_dense`) whenever the history fits its
    bounds — chunked, crash-proof, no capacity escalation — and falls back
    to the sparse all_gather-dedup frontier here otherwise;
    ``engine="sparse"`` forces the sparse path."""
    if engine not in ("auto", "sparse"):
        raise ValueError(f"unknown engine {engine!r}; use 'auto'/'sparse'")
    if mesh is None:
        mesh = Mesh(np.array(jax.devices()), ("d",))

    if engine == "auto":
        from jepsen_tpu.lin import sharded_dense

        n_dev = int(np.prod(mesh.devices.shape))
        if sharded_dense.plan(p, n_dev) is not None:
            return sharded_dense.check_packed(p, mesh=mesh, cancel=cancel,
                                              explain=explain)

    if p.kernel is None:
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "error": f"no device kernel for {type(p.model).__name__}"}
    if p.window > MAX_DEVICE_WINDOW:
        # Explicit routing error, not a silent ceiling: the sparse
        # mesh frontier keeps single-word u32 dedup keys, so windows
        # past 32 have no multi-chip path yet (the crash-dom mesh gap
        # is a ROADMAP open item). The single-chip engine DOES cover
        # this band — lin.device_check_packed routes windows up to 64
        # through the pair-key crash-dom band + host-row executor.
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "error": (f"concurrency window {p.window} exceeds the "
                          f"sharded engine's single-word key limit "
                          f"{MAX_DEVICE_WINDOW}; re-check on the "
                          "single-chip engine (lin.device_check_packed"
                          ": pair-key crash-dom band, windows to 64) — "
                          "no crash-dom mesh path exists yet")}
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-bfs-sharded"}

    axis = mesh.axis_names[0]

    from jepsen_tpu.lin.bfs import reduction_bit_tables
    from jepsen_tpu.models.kernels import (PACKED_STATE_KERNELS,
                                           READ_VALUE_MATCH_KERNELS,
                                           packed_state_bound)

    # Packed-u32 keys when the window plus state id fit 31 bits: the
    # collective dedup then all_gathers ONE u32 array instead of bits +
    # state columns — far fewer ICI bytes per dedup. The packed path
    # chunks (static 512-row table slices), so it needs neither the
    # R-bucketing identity rows nor the pad slot of _pad_rows and runs
    # exactly p.R rows on the raw tables.
    state_bits = nil_id = None
    if p.init_state.shape[0] == 1 \
            and p.kernel.name in PACKED_STATE_KERNELS:
        nid = packed_state_bound(p.kernel, len(p.unintern))
        bb = nid.bit_length()
        if p.window + bb <= 31:
            state_bits, nil_id = bb, nid
    dedup_kind = "packed-keys" if state_bits is not None else "multiword"

    if state_bits is not None:
        pure_k, pred_bit_k = reduction_bit_tables(p, 1)
        tables_h = (np.asarray(p.ret_slot), np.asarray(p.active),
                    np.asarray(p.slot_f), np.asarray(p.slot_v),
                    pure_k, pred_bit_k[:, :, 0])
        return _run_packed_chunks(
            p, mesh, axis, tables_h, cap_schedule,
            b=state_bits, nil_id=nil_id,
            read_value_match=p.kernel.name in READ_VALUE_MATCH_KERNELS,
            cancel=cancel, explain=explain)

    ret_slot_h, active_h, slot_f_h, slot_v_h = _pad_rows(p)
    pure_k, pred_bit_k = reduction_bit_tables(p, 1)
    R, W = p.active.shape
    pure_h = np.zeros(active_h.shape, bool)
    pure_h[:R, :W] = pure_k
    pred_mask_h = np.zeros(active_h.shape, np.uint32)
    pred_mask_h[:R, :W] = pred_bit_k[:, :, 0]
    args = (jnp.asarray(ret_slot_h), jnp.asarray(active_h),
            jnp.asarray(slot_f_h), jnp.asarray(slot_v_h),
            jnp.asarray(pure_h), jnp.asarray(pred_mask_h),
            jnp.asarray(p.init_state))

    # Multiword mesh path: the whole history is ONE device program (no
    # chunking); past this bound a single dispatch risks watchdog kills.
    if p.R > MAX_SHARDED_ROWS:
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "error": f"history length {p.R} exceeds the unchunked "
                         f"multiword mesh bound {MAX_SHARDED_ROWS}; "
                         f"use the single-chip engine"}
    for cap in cap_schedule:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                    "error": "cancelled"}
        ok, dead_row, overflow, total = _search_sharded(
            *args, cap_local=cap, step_fn=p.kernel.step, mesh=mesh,
            axis=axis)
        if not bool(overflow):
            break
    if bool(overflow):
        return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                "overflow": "capacity",
                "error": f"frontier exceeded {cap_schedule[-1]} per device"}
    if bool(ok):
        return {"valid?": True, "analyzer": "tpu-bfs-sharded",
                "dedup": dedup_kind, "final-frontier-size": int(total)}
    r = int(dead_row)
    ret = p.ops[int(p.ret_op[r])]
    out = {"valid?": False, "analyzer": "tpu-bfs-sharded",
           "dedup": dedup_kind,
           "op": {"process": ret.process, "f": ret.f, "value": ret.value,
                  "index": ret.op_index, "ok": ret.ok},
           "configs": [], "final-paths": []}
    if explain:
        from jepsen_tpu.lin import witness

        if r < SHARDED_CHUNK:
            # The multiword mesh search runs the whole history as one
            # program, so there is no chunk snapshot. Replay from the
            # initial config ONLY within the bounded-replay contract
            # (witness.py: one chunk of return events); past that the
            # host replay of a device-scale frontier could DNF.
            init = (0, tuple(int(x) for x in p.init_state))
            out.update(witness.replay_configs(p, {init}, 0, r,
                                              cancel=cancel))
        else:
            out["explain-error"] = (
                f"dead row {r} is beyond the bounded replay window "
                f"({SHARDED_CHUNK} rows); the unchunked multiword mesh "
                f"path keeps no chunk snapshots — re-check on the "
                f"single-chip engine for a counterexample")
    return out


SHARDED_CHUNK = 512


def _run_packed_chunks(p, mesh, axis, tables_h, cap_schedule, *, b,
                       nil_id, read_value_match, cancel=None,
                       explain=False):
    """Host loop over SHARDED_CHUNK-row dispatches of the packed-key
    mesh search: the frontier (global [n_dev*cap] keys + per-device
    counts) carries device-resident between chunks, so history length is
    unbounded — the mesh twin of bfs.check_packed's chunk loop, with
    per-chunk capacity escalation from the chunk-entry snapshot."""
    from jepsen_tpu.lin.bfs import _chunk_slice
    from jepsen_tpu.models.kernels import NIL

    n_dev = int(np.prod(mesh.devices.shape))
    step_fn = p.kernel.step

    sv0 = int(p.init_state[0])
    init_key = np.uint32(nil_id if sv0 == int(NIL) else sv0)
    level = 0
    cap = cap_schedule[level]
    keys = jnp.full(n_dev * cap, KEY_FILL, jnp.uint32).at[0].set(init_key)
    counts = jnp.zeros(n_dev, jnp.int32).at[0].set(1)

    def resize(keys, old_cap, new_cap):
        k = keys.reshape(n_dev, old_cap)
        k = jnp.pad(k, ((0, 0), (0, new_cap - old_cap)),
                    constant_values=KEY_FILL)
        return k.reshape(-1)

    snapshots = [] if explain else None
    base = 0
    n_chunks = 0
    n_escalations = 0
    peak_total = 1
    sup_stats: dict = {"watchdog_trips": 0, "faults": 0}
    # mesh-stats as a live registry view (the host-stats precedent):
    # the snapshot shows the dispatch/escalation profile of a running
    # mesh decide next to the run gauges web.py /run renders.
    _mesh_view = obs_metrics.REGISTRY.view("mesh-stats", {})
    obs_metrics.REGISTRY.start_run("lin-sharded", total=int(p.R),
                                   window=int(p.window))

    def mesh_stats():
        # Observability twin of the single-chip engine's host-stats:
        # attached to EVERY verdict shape (success, death, overflow)
        # so bench/driver artifacts can read the dispatch and
        # escalation profile without re-running.
        out = {"chunks": n_chunks, "escalations": n_escalations,
               "peak-frontier": peak_total,
               "cap-per-device": cap_schedule[level]}
        if sup_stats["watchdog_trips"] or sup_stats["faults"]:
            out.update(sup_stats)
        return out

    while base < p.R:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                    "error": "cancelled"}
        if snapshots is not None:
            # Only the last snapshot is replayed (the dead row is inside
            # the current chunk).
            snapshots[:] = [(base, keys, counts)]
        n = min(SHARDED_CHUNK, p.R - base)
        tbl = tuple(jnp.asarray(_chunk_slice(a, base, SHARDED_CHUNK))
                    for a in tables_h)
        while True:
            util.progress_tick()   # liveness: one tick per chunk dispatch

            def _mesh_chunk_prog(keys=keys, counts=counts,
                                 level=level):
                return _search_sharded_keys(
                    *tbl, keys, counts, jnp.int32(n),
                    cap_local=cap_schedule[level], step_fn=step_fn,
                    mesh=mesh, b=b, nil_id=nil_id,
                    read_value_match=read_value_match, axis=axis)

            def _mesh_chunk():
                out = _mesh_chunk_prog()
                return out, bool(out[4])

            mesh_key = supervise.shape_key(
                "mesh-chunk", rows=SHARDED_CHUNK,
                cap=cap_schedule[level], window=p.window,
                kernel=p.kernel.name)
            outcome, val = supervise.run_guarded(
                "mesh-chunk", mesh_key, _mesh_chunk, stats=sup_stats,
                traceable=_mesh_chunk_prog)
            if outcome == "wedge":
                return {"valid?": "unknown",
                        "analyzer": "tpu-bfs-sharded",
                        "overflow": "wedge",
                        "mesh-stats": mesh_stats(), "error": str(val)}
            if outcome == "fault":
                return {"valid?": "unknown",
                        "analyzer": "tpu-bfs-sharded",
                        "overflow": "fault",
                        "mesh-stats": mesh_stats(),
                        "error": f"dispatch fault near row {base}: "
                                 f"{val!r}"}
            (k2, c2, r_done, dead, ovf, total), ovf_b = val
            if not ovf_b:
                break
            if level + 1 >= len(cap_schedule):
                return {"valid?": "unknown", "analyzer": "tpu-bfs-sharded",
                        "overflow": "capacity",
                        "mesh-stats": mesh_stats(),
                        "error": (f"frontier exceeded {cap_schedule[-1]} "
                                  f"per device")}
            # Retry this chunk from its entry frontier at the next cap.
            level += 1
            n_escalations += 1
            keys = resize(keys, cap, cap_schedule[level])
            cap = cap_schedule[level]
        if bool(dead):
            r = base + int(r_done) - 1
            ret = p.ops[int(p.ret_op[r])]
            out = {"valid?": False, "analyzer": "tpu-bfs-sharded",
                   "dedup": "packed-keys",
                   "mesh-stats": mesh_stats(),
                   "op": {"process": ret.process, "f": ret.f,
                          "value": ret.value, "index": ret.op_index,
                          "ok": ret.ok},
                   "configs": [], "final-paths": []}
            if snapshots:
                # Global keys are front-packed in global index order, so
                # the single-chip unpack applies to the gathered array.
                from jepsen_tpu.lin import witness
                from jepsen_tpu.lin.bfs import _unpack_frontier_keys

                s_base, s_keys, s_counts = snapshots[-1]
                tot = int(np.asarray(s_counts).sum())
                kb, ks = _unpack_frontier_keys(
                    jnp.asarray(np.asarray(s_keys)), tot,
                    s_keys.shape[0], b, nil_id)
                out.update(witness.tail_replay_sparse(
                    p, [(s_base, kb, ks, tot)], r, cancel=cancel))
            return out
        keys, counts = k2, c2
        base += n
        n_chunks += 1
        peak_total = max(peak_total, int(total))
        _mesh_view.clear()
        _mesh_view.update(mesh_stats())
        obs_metrics.REGISTRY.progress(row=base, frontier=int(total))
        # Shrink back to a smaller (faster) program when the global
        # frontier has room to spare; survivors are globally packed to
        # the front, so slicing each device's prefix keeps them all.
        while level > 0 and int(total) * 4 <= cap_schedule[level - 1]:
            new_cap = cap_schedule[level - 1]
            keys = keys.reshape(n_dev, cap)[:, :new_cap].reshape(-1)
            level -= 1
            cap = new_cap
    ms = mesh_stats()
    return {"valid?": True, "analyzer": "tpu-bfs-sharded",
            "dedup": "packed-keys", "final-frontier-size": int(total),
            # Shard observability (the multi-chip speedup evidence the
            # day real hardware exists): the collective dedup packs
            # survivors to the global front, so occupancy is the
            # balanced prefix-fill of cap_local per device. The
            # top-level chunks/peak/cap keys predate mesh-stats and
            # are kept for consumers (__graft_entry__ asserts them);
            # both spellings read the SAME mesh_stats() values.
            "chunks": ms["chunks"], "peak-frontier": ms["peak-frontier"],
            "cap-per-device": ms["cap-per-device"], "mesh-stats": ms,
            "shard-occupancy": [int(x) for x in np.asarray(counts)]}
