"""Device-resident history packer: the slot walk on the accelerator.

The host packer (``prepare``) runs pairing + interning (Python-object
work by nature) and then three numeric column passes — the endpoint
slot walk, the R x W snapshot paint, and the canonical-chain tables.
Under FAST_PACK those passes are already pure integer sort / cumsum /
searchsorted / scatter algebra (prepare._pack_events_vec,
prepare._chain_tables_vec), i.e. exactly the shapes XLA runs well.
This module splits the pack at that boundary:

- :func:`prepack` does the host-only half — pairing, kernelizing,
  interning, and the O(E log E) window/overflow scan — producing a
  :class:`PrePacked` column bundle that already answers everything the
  service admission tier needs (shape bin, fingerprint, window/R)
  WITHOUT painting the R x W grids. It raises the exact
  ``UnsupportedHistory`` errors ``prepare.prepare`` would.
- :func:`materialize` / :func:`materialize_batch` finish the pack on
  the DEVICE: one jitted program runs the event sort, the
  running-minimum fresh-slot detection, the level-sorted bracket
  pairing, pointer-doubling slot propagation, the interval paint, the
  snapshot gathers, the crashed table, and the chain tables — the
  whole O(R x W) tail — and the batched entry vmaps K same-shape
  histories through it as ONE dispatch. Output is BIT-IDENTICAL to
  the spec walk (fuzzed in tests/test_pack_dev.py, gated in
  ``make pack-smoke``).

Padding (static shapes, one compile per shape bucket): ops pad to a
power-of-two ``n_pad`` with inert synthetic ops at positions past
every real event — the first ``R_pad - R`` pads invoke and return as
sequential non-overlapping pairs (filling the return-event axis; their
rows land in ``[R, R_pad)`` and are sliced off), the rest invoke and
never return (crashed pads; their paint interval ``[r0, r1)`` is
empty). Pad events sort AFTER every real event, so the real prefix of
every scan (depth, running min, bracket levels) is untouched; a pad
that bracket-matches a real return merely reuses its slot for rows
that are sliced off. Pads that go past the real window paint into a
dump column that is also sliced off.

Every device dispatch rides the supervision stack as site ``pack-dev``
(watchdog -> quarantine -> honest fallback to the proven FAST_PACK
numpy path — a pack fallback can never cost a verdict), is
static-gate analyzed (the traceable is the pure program), span-traced
(``pack-dev`` spans), and feeds the pack meter. Knobs:
``JEPSEN_TPU_PACK_DEV`` (default on), ``JEPSEN_TPU_PACK_DEV_MIN_K``,
``JEPSEN_TPU_PACK_DEV_STREAM_ROWS`` — tabled in doc/env.md.
"""

from __future__ import annotations

import hashlib
import os
import time
from dataclasses import dataclass
from typing import Any

import numpy as np

from jepsen_tpu.lin import prepare
from jepsen_tpu.lin.prepare import PackedHistory, UnsupportedHistory
from jepsen_tpu.models.kernels import F_IDS, NIL


def pack_dev_enabled() -> bool:
    """``JEPSEN_TPU_PACK_DEV``: the device packer (default on; ``=0``
    keeps every materialization on the host FAST_PACK path). Re-read
    per call (the env-knob convention, doc/env.md)."""
    return os.environ.get("JEPSEN_TPU_PACK_DEV", "") != "0"


def min_batch_k() -> int:
    """``JEPSEN_TPU_PACK_DEV_MIN_K``: bin-wave occupancy below which
    the daemon materializes on the host instead of dispatching the
    batched device pack (per-dispatch tunnel overhead dominates small
    waves; the bench pack rung's device leg is the evidence)."""
    from jepsen_tpu import util

    return util.env_int("JEPSEN_TPU_PACK_DEV_MIN_K", 4)


def stream_min_rows() -> int:
    """``JEPSEN_TPU_PACK_DEV_STREAM_ROWS``: settled-row count below
    which a stream increment paints on the host (the device paint is
    one more dispatch between frontier dispatches — only worth it for
    big settle batches)."""
    from jepsen_tpu import util

    return util.env_int("JEPSEN_TPU_PACK_DEV_STREAM_ROWS", 512)


# Device-pack accounting (pack-smoke, the service stats block, and the
# bench pack rung's device leg read this; reset per process).
_dev_stats = {"dev_packs": 0, "dev_lanes": 0, "dev_pack_s": 0.0,
              "host_fallbacks": 0, "quarantine_skips": 0,
              "wedges": 0, "faults": 0, "static_skips": 0}


def dev_stats() -> dict:
    return dict(_dev_stats)


def reset_dev_stats() -> None:
    for k in _dev_stats:
        _dev_stats[k] = 0.0 if k.endswith("_s") else 0


@dataclass
class PrePacked:
    """The host half of a pack: pairing + interning done, numeric
    columns ready, grids NOT painted. Exposes the attributes
    ``service.daemon.bin_key`` / ``dense.plan`` read (kernel, window,
    R, state_width, unintern, init_state), so admission can bin and
    fingerprint a request without the R x W paint."""

    model: Any
    kernel: Any                  # KernelModel | None
    ops: list                    # LinOp list (reporting / witnesses)
    window: int                  # W_used (exact, from the depth scan)
    R: int
    n: int
    invoke_pos: np.ndarray       # i32[n]
    return_pos: np.ndarray       # i32[n]  (-1 = crashed)
    op_f: np.ndarray             # i32[n]
    op_v: np.ndarray             # i32[n, vw]
    ok_col: np.ndarray | None    # bool[n] (None on the spec pairing)
    init_state: np.ndarray
    intern: dict
    unintern: list
    crashed_ops: list

    @property
    def state_width(self) -> int:
        return len(self.init_state)


def prepack(model, history,
            max_window: int = prepare.MAX_WINDOW) -> PrePacked:
    """Pairing + kernelize + the O(E log E) window scan — everything
    ``prepare.prepare`` does BEFORE the grid paint, raising the same
    ``UnsupportedHistory`` errors (double-invoke, unknown f, cas pair,
    window overflow) at admission time."""
    from jepsen_tpu.obs import trace as obs_trace

    t0 = time.perf_counter()
    history = list(history)
    fast = prepare.fast_pack_enabled()
    with obs_trace.span("prepack", events=len(history)) as sp:
        ok_col = None
        if fast:
            ops, invoke_pos, return_pos, ok_col = \
                prepare._pair_ops_vec_arrays(history)
        else:
            ops = prepare.pair_ops(history)
        intern = prepare._Interner()
        kv = prepare._kernelize_vec(model, ops, intern) if fast else None
        if kv is None:
            kernel, init_state, op_f, op_v = prepare._kernelize(
                model, ops, intern)
        else:
            kernel, init_state, op_f, op_v = kv
        n = len(ops)
        if ok_col is not None:
            R = int(ok_col.sum())
        else:
            R = sum(1 for o in ops if o.ok)
            invoke_pos = np.fromiter(
                (o.invoke_pos for o in ops), np.int32, n)
            return_pos = np.fromiter(
                (-1 if o.return_pos is None else o.return_pos
                 for o in ops), np.int32, n)
        W_used = _window_scan(invoke_pos, return_pos, max_window)
        if ok_col is not None:
            crashed = [ops[i] for i in np.flatnonzero(~ok_col).tolist()]
        else:
            crashed = [o for o in ops if o.return_pos is None]
        sp.note(n_ops=n, R=R, W=W_used)
    st = prepare._pack_stats
    st["prepare_s"] += time.perf_counter() - t0
    return PrePacked(
        model=model, kernel=kernel, ops=ops, window=max(1, W_used),
        R=R, n=n,
        invoke_pos=np.asarray(invoke_pos, np.int32),
        return_pos=np.asarray(return_pos, np.int32),
        op_f=np.asarray(op_f, np.int32),
        op_v=np.asarray(op_v, np.int32),
        ok_col=ok_col, init_state=init_state, intern=intern.ids,
        unintern=intern.values, crashed_ops=crashed)


def _window_scan(invoke_pos, return_pos, max_window: int) -> int:
    """Exact W_used + the overflow check — prepare._pack_events_vec's
    depth scan, standalone (the device program never sees an
    overflowing history)."""
    n = len(invoke_pos)
    if n == 0:
        return 0
    ret_ids = np.flatnonzero(np.asarray(return_pos) >= 0)
    ev_pos = np.concatenate([np.asarray(invoke_pos, np.int64),
                             np.asarray(return_pos, np.int64)[ret_ids]])
    order = np.argsort(ev_pos, kind="stable")
    delta = np.where(order >= n, -1, 1)
    depth = np.cumsum(delta)
    W_used = int(depth.max(initial=0))
    if W_used > max_window:
        t = int(np.flatnonzero(depth > max_window)[0])
        raise UnsupportedHistory(
            f"concurrency window exceeds {max_window} pending ops "
            f"at history position {int(ev_pos[order[t]])}",
            kind="window")
    return W_used


def prepack_fingerprint(pre: PrePacked) -> str:
    """History identity over the PRE-pack columns: the admission tier
    needs the fingerprint before the grids exist, and the grids are a
    pure function of these columns — so hashing the columns identifies
    at least as finely as ``supervise.history_fingerprint`` over the
    painted tables. This is the service-wire fingerprint (journal
    admits, ``result-fetch``, the chaos oracle audits):
    ``protocol.request_fingerprint`` computes the SAME function
    client-side, bit for bit. The checkpoint/resume identity
    (``supervise.history_fingerprint``) is a separate contract over
    packed tables and is unchanged."""
    h = hashlib.sha256()
    h.update(
        f"{pre.kernel.name if pre.kernel else None}|{pre.window}|"
        f"{pre.R}|{len(pre.unintern)}".encode())
    for a in (pre.invoke_pos, pre.return_pos, pre.op_f, pre.op_v,
              pre.init_state):
        arr = np.ascontiguousarray(np.asarray(a))
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


# --- the device program ------------------------------------------------------


def _pow2(x: int, floor: int = 1) -> int:
    return max(floor, 1 << max(0, (int(x) - 1).bit_length()))


def pad_shape(n: int, R: int, W: int, vw: int) -> tuple:
    """The static shape bucket one history compiles into: pow2 R and
    W axes, and an op axis with room for the ``R_pad - R`` returning
    pads (n_pad - n >= R_pad - R by construction)."""
    r_pad = _pow2(R, 4)
    w_pad = _pow2(W, 4)
    n_pad = _pow2(n + r_pad - R, 8)
    return n_pad, r_pad, w_pad, vw


def _pad_columns(pre: PrePacked, shape: tuple):
    """Host-side pad to the static bucket: synthetic ops at sequential
    positions past every real event (module docstring). Returns the
    per-lane device inputs as numpy arrays."""
    n_pad, r_pad, w_pad, vw = shape
    n, R = pre.n, pre.R
    inv = np.zeros(n_pad, np.int32)
    ret = np.full(n_pad, -1, np.int32)
    inv[:n] = pre.invoke_pos
    ret[:n] = pre.return_pos
    big = np.int32(0)
    if n:
        big = max(int(pre.invoke_pos.max(initial=0)),
                  int(pre.return_pos.max(initial=0))) + 1
    p_ret = r_pad - R                    # returning pads (fill R axis)
    j = np.arange(n_pad - n, dtype=np.int32)
    inv[n:] = big + 2 * j
    ret[n:n + p_ret] = big + 2 * j[:p_ret] + 1
    op_f = np.zeros(n_pad + 1, np.int32)
    op_v = np.full((n_pad + 1, vw), int(NIL), np.int32)
    op_f[:n] = pre.op_f
    op_v[:n] = pre.op_v
    # Return-event column (static R_pad returns): real returns in op
    # order, then the returning pads.
    ret_ids = np.flatnonzero(pre.return_pos >= 0).astype(np.int32)
    ev_rop = np.concatenate([ret_ids,
                             n + j[:p_ret]]).astype(np.int32)
    ev_rpos = ret[ev_rop]
    # Per-op chain ranks (prepare._chain_tables_vec's host half): class
    # rank lexicographic over (f<<1|crashed, value words), ordkey rank
    # over (return row | R+2+invoke position) — both O(n log n) host
    # sorts; the per-row stable sort happens on device.
    cls_rank = np.zeros(n_pad + 1, np.int32)
    ord_rank = np.zeros(n_pad + 1, np.int32)
    if n:
        ret_row = np.full(n, -1, np.int64)
        order_r = np.argsort(pre.return_pos[ret_ids], kind="stable")
        ret_row[ret_ids[order_r]] = np.arange(R)
        crashed_op = ret_row < 0
        ordkey = np.where(crashed_op,
                          np.int64(R + 2)
                          + pre.invoke_pos.astype(np.int64), ret_row)
        cls_cols = [pre.op_v[:, k].astype(np.int64)
                    for k in range(vw - 1, -1, -1)]
        cls_cols.append((pre.op_f.astype(np.int64) << 1) | crashed_op)
        o_ops = np.lexsort(tuple(cls_cols))
        chg = np.zeros(n, bool)
        if n > 1:
            for c in cls_cols:
                cs = c[o_ops]
                chg[1:] |= cs[1:] != cs[:-1]
        cls_rank[:n][o_ops] = np.cumsum(chg, dtype=np.int32)
        ord_rank[:n][np.argsort(ordkey, kind="stable")] = \
            np.arange(n, dtype=np.int32)
    return (inv, ret, ev_rop, ev_rpos, op_f, op_v, cls_rank, ord_rank)


def _pack_program(shape: tuple, f_read: int):
    """The single-lane jitted pack: event sort -> fresh detection ->
    bracket pairing -> pointer-doubling slot propagation -> interval
    paint -> snapshot gathers -> crashed table -> chain tables, all
    static-shape jax. Cached per shape bucket."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n_pad, r_pad, w_pad, vw = shape
    e_tot = n_pad + r_pad
    doublings = max(1, (n_pad - 1).bit_length())

    def pack(inv, ret, ev_rop, ev_rpos, op_f, op_v, cls_rank,
             ord_rank):
        # Endpoint events: invokes [0, n_pad) + returns [n_pad, e_tot),
        # sorted by position (positions are unique, so the plain sort
        # is the spec's stable argsort).
        ev_pos = jnp.concatenate([inv, ev_rpos])
        ev_op = jnp.concatenate(
            [jnp.arange(n_pad, dtype=jnp.int32), ev_rop])
        ev_isret = jnp.concatenate(
            [jnp.zeros(n_pad, jnp.int32), jnp.ones(r_pad, jnp.int32)])
        pos_s, op_s, kind_i = lax.sort(
            (ev_pos, ev_op, ev_isret), num_keys=1)
        kind_ret = kind_i == 1
        # Fresh invokes: new running minima of the return-minus-invoke
        # sum take virgin slots 0,1,2... in order.
        delta = jnp.where(kind_ret, -1, 1).astype(jnp.int32)
        sigma = jnp.cumsum(-delta)
        runmin = lax.cummin(jnp.minimum(sigma, 0))
        prev_runmin = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), runmin[:-1]])
        fresh = (~kind_ret) & (sigma < prev_runmin)
        fresh_rank = (jnp.cumsum(fresh.astype(jnp.int32)) - 1)
        slot_root = jnp.full(n_pad + 1, -1, jnp.int32)
        slot_root = slot_root.at[
            jnp.where(fresh, op_s, n_pad)].set(
            jnp.where(fresh, fresh_rank, -1), mode="drop")
        # Bracket-match recycled invokes (closes) to the return whose
        # slot they reuse (opens) — stable level sort, odd ranks match
        # their predecessor within the level run.
        sub = kind_ret | ((~kind_ret) & ~fresh)
        lev = sigma - runmin
        lv = jnp.where(kind_ret, lev, lev + 1)
        big_lv = jnp.int32(e_tot + w_pad + 2)
        lv_key = jnp.where(sub, lv, big_lv)
        idx = jnp.arange(e_tot, dtype=jnp.int32)
        lvs, ss, subs_s = lax.sort(
            (lv_key, idx, sub.astype(jnp.int32)), num_keys=2)
        run_first = jnp.concatenate(
            [jnp.ones(1, bool), lvs[1:] != lvs[:-1]])
        base = lax.cummax(jnp.where(run_first, idx, 0))
        rank = idx - base
        prev_op = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), op_s[ss][:-1]])
        mpair = (rank % 2 == 1) & (subs_s == 1)
        parent = jnp.arange(n_pad + 1, dtype=jnp.int32)
        parent = parent.at[
            jnp.where(mpair, op_s[ss], n_pad)].set(
            jnp.where(mpair, prev_op, n_pad), mode="drop")
        for _ in range(doublings):        # fixed-trip pointer doubling
            parent = parent[parent]
        slot = slot_root[parent[:n_pad]]
        # Return-event tables, in sorted event order.
        ret_rank = jnp.cumsum(kind_ret.astype(jnp.int32)) - 1
        ret_op = jnp.zeros(r_pad, jnp.int32).at[
            jnp.where(kind_ret, ret_rank, r_pad)].set(
            op_s, mode="drop")
        ret_pos_sorted = jnp.zeros(r_pad, jnp.int32).at[
            jnp.where(kind_ret, ret_rank, r_pad)].set(
            pos_s, mode="drop")
        ret_slot = slot[ret_op]
        # Row intervals: op i is active in rows [r0, r1) at column
        # slot[i]; paint op id + 1 by endpoint deltas + cumsum.
        r0 = jnp.searchsorted(ret_pos_sorted, inv)
        r1 = jnp.full(n_pad, r_pad, jnp.int32).at[ret_op].set(
            jnp.arange(1, r_pad + 1, dtype=jnp.int32))
        col = jnp.where((slot < 0) | (slot >= w_pad), w_pad, slot)
        ids1 = jnp.arange(1, n_pad + 1, dtype=jnp.int32)
        occ = jnp.zeros((w_pad + 1) * (r_pad + 1), jnp.int32)
        occ = occ.at[col * (r_pad + 1) + r0].add(ids1, mode="drop")
        occ = occ.at[col * (r_pad + 1) + r1].add(-ids1, mode="drop")
        occ = jnp.cumsum(occ.reshape(w_pad + 1, r_pad + 1), axis=1)
        grid = occ[:w_pad, :r_pad].T
        active = grid != 0
        slot_op = grid - 1                    # -1 at inactive cells
        gidx = jnp.where(active, slot_op, n_pad)
        slot_f = op_f[gidx]
        slot_v = op_v[gidx]
        ret_ext = jnp.concatenate(
            [ret[:n_pad], jnp.zeros(1, jnp.int32)])
        crashed = (ret_ext[gidx] < 0) & active
        # Chain tables (prepare._chain_tables_vec's per-row half): the
        # class/ordkey ranks came from the host sorts; the row-wise
        # canonical sort runs here. Key order is identical to the spec
        # (lexicographic (class, ordkey-rank); sentinels per column
        # below every chainable class), so the pred table's real
        # region matches bit for bit after the slice.
        pure = active & (slot_f == f_read)
        chainable = active & (~pure) & (slot_op >= 0)
        cls_slot = cls_rank[gidx] + jnp.int32(w_pad)
        ord_slot = ord_rank[gidx]
        sent = (w_pad - 1
                - jnp.arange(w_pad, dtype=jnp.int32))[None, :]
        sent = jnp.broadcast_to(sent, (r_pad, w_pad))
        key_hi = jnp.where(chainable, cls_slot, sent)
        key_lo = jnp.where(chainable, ord_slot, 0)
        cols = jnp.broadcast_to(
            jnp.arange(w_pad, dtype=jnp.int32)[None, :],
            (r_pad, w_pad))
        _, _, order = lax.sort((key_hi, key_lo, cols), num_keys=2,
                               dimension=1)
        rows_off = (jnp.arange(r_pad, dtype=jnp.int32)
                    * jnp.int32(w_pad))[:, None]
        cs = key_hi.reshape(-1)[order + rows_off]
        same = cs[:, 1:] == cs[:, :-1]
        pred = jnp.full(r_pad * w_pad, -1, jnp.int32)
        pred = pred.at[(order[:, 1:] + rows_off).reshape(-1)].set(
            jnp.where(same, order[:, :-1], -1).reshape(-1),
            mode="drop")
        pred = pred.reshape(r_pad, w_pad)
        return (ret_slot, ret_op, active, slot_f, slot_v, slot_op,
                crashed, pure, pred)

    return pack


_program_cache: dict = {}


def _compiled(shape: tuple, batched: bool):
    """jit(program) / jit(vmap(program)) per static shape bucket."""
    import jax

    key = (shape, batched)
    fn = _program_cache.get(key)
    if fn is None:
        f_read = int(F_IDS["read"])
        prog = _pack_program(shape, f_read)
        fn = jax.jit(jax.vmap(prog) if batched else prog)
        _program_cache[key] = fn
    return fn


def pack_traceable(shape: tuple, lanes: int = 0):
    """A no-arg pure-jax callable of the pack program at ``shape``
    (vmapped over ``lanes`` when > 0) over zero inputs — what the
    static gate traces and tests/test_analysis.py lints."""
    import jax.numpy as jnp

    n_pad, r_pad, w_pad, vw = shape
    prog = _pack_program(shape, int(F_IDS["read"]))

    def args():
        z = lambda *s: jnp.zeros(s, jnp.int32)  # noqa: E731
        one = (z(n_pad), z(n_pad), z(r_pad), z(r_pad), z(n_pad + 1),
               z(n_pad + 1, vw), z(n_pad + 1), z(n_pad + 1))
        if not lanes:
            return one
        return tuple(jnp.broadcast_to(a, (lanes,) + a.shape)
                     for a in one)

    if not lanes:
        return lambda: prog(*args())
    import jax

    vprog = jax.vmap(prog)
    return lambda: vprog(*args())


# --- materialization ---------------------------------------------------------


def _host_materialize(pre: PrePacked) -> PackedHistory:
    """The proven FAST_PACK numpy path over the prepack columns —
    prepare.prepare's grid half, byte-identical (the honest fallback
    rung under the pack-dev site, and the non-device default)."""
    t0 = time.perf_counter()
    fill_fv = pre.kernel is not None
    packed = None
    if prepare.fast_pack_enabled():
        packed = prepare._pack_events_vec(
            pre.invoke_pos, pre.return_pos, pre.op_f, pre.op_v,
            prepare.MAX_WINDOW, fill_fv, pre.R)
    if packed is None and pre.op_v.shape[1] == 2:
        packed = prepare._pack_events_native(
            pre.invoke_pos, pre.return_pos, pre.op_f, pre.op_v,
            prepare.MAX_WINDOW, fill_fv, pre.R)
    if packed is None:
        packed = prepare._pack_events_py(
            pre.invoke_pos, pre.return_pos, pre.op_f, pre.op_v,
            prepare.MAX_WINDOW, fill_fv, pre.R)
    out = _assemble(pre, *packed[:6])
    st = prepare._pack_stats
    st["prepare_s"] += time.perf_counter() - t0
    st["prepare_calls"] += 1
    st["mode"] = "vec" if prepare.fast_pack_enabled() else "py"
    return out


def _assemble(pre: PrePacked, ret_slot, ret_op, active, slot_f,
              slot_v, slot_op) -> PackedHistory:
    """PackedHistory from grid tables at (>=W) width — the same
    construction (crashed sentinel trick included) as
    prepare.prepare."""
    W = pre.window
    ret_ext = np.concatenate(
        [pre.return_pos.astype(np.int32, copy=False),
         np.zeros(1, np.int32)])
    crashed_tbl = (ret_ext[slot_op] < 0) & active
    out = PackedHistory(
        model=pre.model, kernel=pre.kernel, ops=pre.ops, window=W,
        R=pre.R, ret_slot=ret_slot, ret_op=ret_op,
        active=active[:, :W], slot_f=slot_f[:, :W],
        slot_v=slot_v[:, :W], slot_op=slot_op[:, :W],
        crashed=crashed_tbl[:, :W], init_state=pre.init_state,
        intern=pre.intern, unintern=pre.unintern,
        crashed_ops=pre.crashed_ops)
    out._op_fv = (pre.op_f, pre.op_v, pre.invoke_pos)
    return out


def _assemble_dev(pre: PrePacked, lane) -> PackedHistory:
    """PackedHistory from one device lane's fetched outputs (sliced
    to the real R x W region, spec dtypes)."""
    R, W = pre.R, pre.window
    (ret_slot, ret_op, active, slot_f, slot_v, slot_op, crashed,
     pure, pred) = lane
    out = PackedHistory(
        model=pre.model, kernel=pre.kernel, ops=pre.ops, window=W,
        R=R,
        ret_slot=np.ascontiguousarray(ret_slot[:R], np.int32),
        ret_op=np.ascontiguousarray(ret_op[:R], np.int32),
        active=np.ascontiguousarray(active[:R, :W]),
        slot_f=np.ascontiguousarray(slot_f[:R, :W], np.int32),
        slot_v=np.ascontiguousarray(slot_v[:R, :W], np.int32),
        slot_op=np.ascontiguousarray(slot_op[:R, :W], np.int32),
        crashed=np.ascontiguousarray(crashed[:R, :W]),
        init_state=pre.init_state, intern=pre.intern,
        unintern=pre.unintern, crashed_ops=pre.crashed_ops)
    out._op_fv = (pre.op_f, pre.op_v, pre.invoke_pos)
    out._reduction_tables = (
        np.ascontiguousarray(pure[:R, :W]),
        np.ascontiguousarray(pred[:R, :W], np.int32))
    return out


def _device_eligible(pre: PrePacked) -> bool:
    # kernel-less histories never bin (generic CPU search takes them),
    # and R == 0 has no grids worth a dispatch.
    return pre.kernel is not None and pre.R > 0 and pre.n > 0


def _shape_key(shape: tuple, lanes: int) -> str:
    from jepsen_tpu.lin import supervise

    n_pad, r_pad, w_pad, vw = shape
    return supervise.shape_key("pack-dev", cap=n_pad, window=w_pad,
                               kernel=f"pack-vw{vw}",
                               rows=max(1, lanes), band=f"r{r_pad}")


def materialize(pre: PrePacked, *, stats: dict | None = None
                ) -> PackedHistory:
    """Finish one pack: the supervised device program when eligible
    and enabled, else (or on wedge / fault / quarantine / static
    flag) the host FAST_PACK path. Verdict-neutral by construction —
    both rungs produce the bit-identical PackedHistory."""
    if not (pack_dev_enabled() and _device_eligible(pre)):
        return _host_materialize(pre)
    out = _materialize_wave([pre], stats=stats, batched=False)
    return out[0]


def materialize_batch(pres: list, *, stats: dict | None = None
                      ) -> list:
    """Pack K histories; same-bucket eligible lanes ride ONE vmapped
    device dispatch (the daemon's bin-wave admission offload), the
    rest take the host path. Waves below ``min_batch_k()`` —
    singletons included — pack host-side: the device program only
    amortizes its dispatch + compile overhead across K lanes
    (doc/env.md § JEPSEN_TPU_PACK_DEV_MIN_K; :func:`materialize` is
    the explicit single-pack device entry). Order-preserving."""
    out: list = [None] * len(pres)
    groups: dict = {}
    for i, pre in enumerate(pres):
        if pack_dev_enabled() and _device_eligible(pre):
            shape = pad_shape(pre.n, pre.R, pre.window,
                              pre.op_v.shape[1])
            groups.setdefault(shape, []).append(i)
        else:
            out[i] = _host_materialize(pre)
    for shape, ix in groups.items():
        wave = [pres[i] for i in ix]
        if len(wave) < max(1, min_batch_k()):
            packs = [_host_materialize(p) for p in wave]
        else:
            packs = _materialize_wave(
                wave, stats=stats, batched=len(wave) > 1)
        for i, p in zip(ix, packs):
            out[i] = p
    return out


def _materialize_wave(wave: list, *, stats: dict | None,
                      batched: bool) -> list:
    """One supervised pack-dev dispatch over same-bucket lanes, host
    fallback per lane on any non-ok outcome."""
    from jepsen_tpu.lin import supervise
    from jepsen_tpu.obs import trace as obs_trace

    pre0 = wave[0]
    shape = pad_shape(pre0.n, pre0.R, pre0.window,
                      pre0.op_v.shape[1])
    key = _shape_key(shape, len(wave) if batched else 1)
    if supervise.quarantined(key) is not None:
        _dev_stats["quarantine_skips"] += 1
        _dev_stats["host_fallbacks"] += len(wave)
        obs_trace.instant("pack-dev-skip", key=key,
                          reason="quarantined")
        return [_host_materialize(p) for p in wave]
    t0 = time.perf_counter()
    cols = [_pad_columns(p, shape) for p in wave]
    if batched:
        args = tuple(np.stack([c[k] for c in cols])
                     for k in range(8))
    else:
        args = cols[0]
    fn = _compiled(shape, batched)

    def thunk():
        import jax

        res = fn(*args)
        return jax.device_get(res)

    with obs_trace.span("pack-dev", lanes=len(wave),
                        shape=str(shape)) as sp:
        outcome, res = supervise.run_guarded(
            "pack-dev", key, thunk, stats=stats,
            traceable=pack_traceable(
                shape, lanes=len(wave) if batched else 0))
        sp.note(outcome=outcome)
    if outcome != "ok":
        _dev_stats["host_fallbacks"] += len(wave)
        _dev_stats["wedges" if outcome == "wedge" else
                    "faults" if outcome == "fault" else
                    "static_skips"] += 1
        return [_host_materialize(p) for p in wave]
    dt = time.perf_counter() - t0
    _dev_stats["dev_packs"] += 1
    _dev_stats["dev_lanes"] += len(wave)
    _dev_stats["dev_pack_s"] += dt
    st = prepare._pack_stats
    st["prepare_s"] += dt
    st["prepare_calls"] += len(wave)
    st["mode"] = "dev"
    if batched:
        return [_assemble_dev(p, tuple(np.asarray(a[i])
                                       for a in res))
                for i, p in enumerate(wave)]
    return [_assemble_dev(wave[0], tuple(np.asarray(a)
                                         for a in res))]


# --- the streaming paint (stream/incr.py's settled-row increments) ----------


def _paint_program(W: int, rows_pad: int, vw: int):
    """The stream settle's grid half on device: interval paint +
    snapshot gathers over the carried painter set (stream/incr.py
    computes painters/slots/intervals host-side with carried state —
    the O(rows x W) tail runs here). ``op_crash`` is a host-computed
    bool column (the stream's never-returns sentinel is an int64 the
    int32-only device never sees)."""
    import jax.numpy as jnp

    def paint(p_slot, r0, r1, ids1, opf, opv, op_crash, n1):
        col = jnp.where((p_slot < 0) | (p_slot >= W), W, p_slot)
        occ = jnp.zeros((W + 1) * (rows_pad + 1), jnp.int32)
        occ = occ.at[col * (rows_pad + 1) + r0].add(ids1, mode="drop")
        occ = occ.at[col * (rows_pad + 1) + r1].add(-ids1,
                                                   mode="drop")
        occ = jnp.cumsum(occ.reshape(W + 1, rows_pad + 1), axis=1)
        grid = occ[:W, :rows_pad].T
        active = grid != 0
        slot_op = grid - 1
        gidx = jnp.where(active, slot_op, n1)
        slot_f = opf[gidx]
        slot_v = opv[gidx]
        crashed = op_crash[gidx] & active
        return grid, active, slot_f, slot_v, slot_op, crashed

    return paint


_paint_cache: dict = {}


def paint_tables_dev(p_slot, r0, r1, ids1, op_f, op_v, op_crashed,
                     n1: int, n_new: int, W: int, *,
                     kernel: str, stats: dict | None = None):
    """Supervised device paint for one stream settle batch. Returns
    the (grid, active, slot_f, slot_v, slot_op, crashed) numpy tables
    sliced to ``n_new`` rows, or None when the dispatch (or its
    quarantine/static check) says the caller should take its numpy
    path — never an exception, never a verdict cost."""
    from jepsen_tpu.lin import supervise
    from jepsen_tpu.obs import trace as obs_trace

    if not pack_dev_enabled():
        return None
    p = len(p_slot)
    p_pad = _pow2(p, 8)
    rows_pad = _pow2(n_new, 8)
    c_pad = _pow2(n1 + 1, 8)
    vw = op_v.shape[1]
    key = supervise.shape_key("pack-dev", cap=p_pad, window=W,
                              kernel=f"paint-{kernel}",
                              rows=rows_pad, band="stream")
    if supervise.quarantined(key) is not None:
        _dev_stats["quarantine_skips"] += 1
        _dev_stats["host_fallbacks"] += 1
        return None
    import jax.numpy as jnp

    ckey = (W, rows_pad, p_pad, c_pad, vw)
    fn = _paint_cache.get(ckey)
    if fn is None:
        import jax

        fn = jax.jit(_paint_program(W, rows_pad, vw))
        _paint_cache[ckey] = fn

    def padded(a, size, fill=0, dtype=np.int32):
        out = np.full((size,) + np.asarray(a).shape[1:], fill, dtype)
        out[:len(a)] = a
        return out

    ps = padded(np.where(np.asarray(p_slot) < 0, W, p_slot), p_pad,
                fill=W)
    r0p = padded(r0, p_pad)
    r1p = padded(r1, p_pad)
    idp = padded(ids1, p_pad)
    opf = padded(op_f, c_pad)
    opv = np.full((c_pad, vw), int(NIL), np.int32)
    opv[:len(op_v)] = op_v
    opc = padded(op_crashed, c_pad, dtype=bool)

    def thunk():
        import jax

        return jax.device_get(fn(ps, r0p, r1p, idp, opf, opv, opc,
                                 jnp.int32(n1)))

    t0 = time.perf_counter()
    with obs_trace.span("pack-dev", lanes=1, shape=f"paint-{ckey}",
                        rows=n_new) as sp:
        outcome, res = supervise.run_guarded("pack-dev", key, thunk,
                                             stats=stats)
        sp.note(outcome=outcome)
    if outcome != "ok":
        _dev_stats["host_fallbacks"] += 1
        _dev_stats["wedges" if outcome == "wedge" else
                    "faults" if outcome == "fault" else
                    "static_skips"] += 1
        return None
    grid, active, slot_f, slot_v, slot_op, crashed = (
        np.asarray(a) for a in res)
    dt = time.perf_counter() - t0
    _dev_stats["dev_packs"] += 1
    _dev_stats["dev_lanes"] += 1
    _dev_stats["dev_pack_s"] += dt
    s = np.s_[:n_new]
    return (np.ascontiguousarray(grid[s], np.int32),
            np.ascontiguousarray(active[s]),
            np.ascontiguousarray(slot_f[s], np.int32),
            np.ascontiguousarray(slot_v[s], np.int32),
            np.ascontiguousarray(slot_op[s], np.int32),
            np.ascontiguousarray(crashed[s]))
