"""`make pack-smoke`: both packer modes, bit parity + speedup sanity.

The serve/txn/trace/stream/perf-smoke habit for the host packer
(lin/prepare, ISSUE 16): a FRESH-process, chip-free proof on the forced
CPU platform that

- the vectorized packer (JEPSEN_TPU_FAST_PACK=1, the default) produces
  a BIT-IDENTICAL packed history to the Python spec walk on the
  partitioned register shape AND the mutex family
  (supervise.history_fingerprint over every hashed array, plus an
  explicit slot_op comparison — the fingerprint excludes it),
- the vectorized path is actually faster (soft gate: >=1.5x on the
  smoke's mid-size shape; the bench `pack` micro-rung holds the real
  >=5x evidence at the 100k-op scale, this guard only catches a
  packer that silently fell back to the walk), and
- the pack meter accumulated and its fields ride the smoke's own
  perf-ledger record (the `pack` sub-dict schema bench forwards).

Packing is pure numpy — no device program runs — but the cpu platform
is forced anyway so an accidental backend init can never take the
chip. Prints one JSON result line and exits 0/1 — timeout-guarded by
the Makefile so a wedge cannot hold the shell.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    t_start = time.time()
    # CPU platform BEFORE any jax backend init (CLAUDE.md: the TPU
    # plugin force-selects its platform; the smoke must never take the
    # chip).
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from jepsen_tpu import models as m
    from jepsen_tpu.lin import prepare, supervise, synth

    out: dict = {"checks": []}
    ok = True

    def both(model, h):
        """Pack one history under both modes; return (vec, py, walls)."""
        packs = {}
        walls = {}
        for mode in ("1", "0"):
            os.environ["JEPSEN_TPU_FAST_PACK"] = mode
            # The spec leg must be the PYTHON walk: NATIVE_PACK=1
            # would swap in the ctypes slot walk and the "py" wall
            # would measure the wrong baseline (doc/env.md).
            os.environ["JEPSEN_TPU_NATIVE_PACK"] = mode
            prepare.reset_pack_stats()
            t0 = time.time()
            packs[mode] = prepare.prepare(model, list(h))
            walls[mode] = time.time() - t0
        os.environ.pop("JEPSEN_TPU_FAST_PACK", None)
        os.environ.pop("JEPSEN_TPU_NATIVE_PACK", None)
        return packs["1"], packs["0"], walls

    def parity(p_vec, p_py):
        return (supervise.history_fingerprint(p_vec)
                == supervise.history_fingerprint(p_py)
                and np.array_equal(np.asarray(p_vec.slot_op),
                                   np.asarray(p_py.slot_op)))

    # 1. Partitioned register shape (the config-5 family) at a
    # mid-size: big enough for the speedup to show, small enough to
    # keep the smoke seconds-scale.
    h = synth.generate_partitioned_register_history(
        10_000, seed=7, invoke_bias=0.45)
    p_vec, p_py, walls = both(m.cas_register(), h)
    speedup = round(walls["0"] / walls["1"], 2) if walls["1"] else None
    good = parity(p_vec, p_py) and bool(speedup) and speedup >= 1.5
    out["checks"].append({"case": "partitioned-10k",
                          "window": p_vec.window,
                          "vec_s": round(walls["1"], 3),
                          "py_s": round(walls["0"], 3),
                          "speedup": speedup,
                          "bit_parity": parity(p_vec, p_py),
                          "ok": good})
    ok = ok and good
    pack = {"prepare_s": round(walls["1"], 3), "py_s": round(
        walls["0"], 3), "speedup": speedup, "mode": "vec"}

    # 2. Mutex family (different kernel, crashed ops): parity only —
    # the speedup gate lives on the register shape above.
    h = synth.generate_mutex_history(
        2000, concurrency=10, seed=3, crash_prob=0.01, max_crashes=4)
    p_vec, p_py, _ = both(m.mutex(), h)
    good = parity(p_vec, p_py)
    out["checks"].append({"case": "mutex-2k", "bit_parity": good,
                          "ok": good})
    ok = ok and good

    # 3. The pack meter accumulated under the vec mode (the fields the
    # service daemon's stats() and bench's artifacts surface).
    st = prepare.pack_stats()
    good = st["prepare_calls"] > 0 and st["prepare_s"] > 0
    out["checks"].append({"case": "pack-meter",
                          "stats": {k: (round(v, 4)
                                        if isinstance(v, float) else v)
                                    for k, v in st.items()},
                          "ok": good})
    ok = ok and good

    # 4. Device packer (lin/pack_dev.py, ISSUE 20): supervised
    # materialization on the forced CPU platform must be BIT-IDENTICAL
    # to the host pack (fingerprint + slot_op) on both families, and a
    # 4-lane same-shape wave must ride ONE vmapped dispatch.
    from jepsen_tpu import util
    from jepsen_tpu.lin import pack_dev

    os.environ["JEPSEN_TPU_PACK_DEV"] = "1"
    pack_dev.reset_dev_stats()
    dev_cases = [
        ("partitioned-3k", m.cas_register(),
         list(synth.generate_partitioned_register_history(
             3000, seed=11, invoke_bias=0.45))),
        ("mutex-1k", m.mutex(), list(synth.generate_mutex_history(
            1000, concurrency=8, seed=5, crash_prob=0.01,
            max_crashes=4)))]
    for name, model, h in dev_cases:
        spec = prepare.prepare(model, list(h))
        got = pack_dev.materialize(pack_dev.prepack(model, list(h)))
        good = parity(got, spec)
        out["checks"].append({"case": f"pack-dev-{name}",
                              "bit_parity": good, "ok": good})
        ok = ok and good
    _, model, h = dev_cases[0]
    spec = prepare.prepare(model, list(h))
    wave = pack_dev.materialize_batch(
        [pack_dev.prepack(model, list(h)) for _ in range(4)])
    st = pack_dev.dev_stats()
    good = (st["dev_packs"] == 3 and st["dev_lanes"] == 6
            and st["host_fallbacks"] == 0
            and all(parity(g, spec) for g in wave))
    out["checks"].append(
        {"case": "pack-dev-batched-wave",
         "stats": {k: (round(v, 4) if isinstance(v, float) else v)
                   for k, v in st.items()}, "ok": good})
    ok = ok and good
    pack["dev_s"] = round(st["dev_pack_s"], 3)
    pack["dev_packs"] = st["dev_packs"]

    # 5. JEPSEN_TPU_WEDGE=pack-dev (the supervision test hook,
    # quarantine redirected to a throwaway path): a wedged pack
    # dispatch must degrade to the numpy pack with IDENTICAL tables —
    # a pack wedge is observability, never a verdict cost or a hang.
    os.environ["JEPSEN_TPU_QUARANTINE"] = os.path.join(
        util.cache_dir(), "pack_smoke_quarantine.json")
    os.environ["JEPSEN_TPU_WEDGE"] = "pack-dev:4:0.2"
    os.environ["JEPSEN_TPU_DISPATCH_RETRIES"] = "0"
    supervise.reset_injections()
    supervise._env_wedge_loaded = None
    pack_dev.reset_dev_stats()
    try:
        got = pack_dev.materialize(pack_dev.prepack(model, list(h)))
    finally:
        os.environ.pop("JEPSEN_TPU_WEDGE", None)
        os.environ.pop("JEPSEN_TPU_QUARANTINE", None)
        os.environ.pop("JEPSEN_TPU_DISPATCH_RETRIES", None)
        os.environ.pop("JEPSEN_TPU_PACK_DEV", None)
        supervise.reset_injections()
    st = pack_dev.dev_stats()
    good = (parity(got, spec) and st["wedges"] >= 1
            and st["host_fallbacks"] >= 1 and st["dev_packs"] == 0)
    out["checks"].append(
        {"case": "pack-dev-wedge-fallback",
         "bit_parity": parity(got, spec),
         "wedges": st["wedges"],
         "host_fallbacks": st["host_fallbacks"], "ok": good})
    ok = ok and good

    out["ok"] = ok
    # Cross-run perf ledger (doc/observability.md § Perf ledger): the
    # smoke's own record carries the pack sub-dict so `cli.py perf
    # report` trends the pack wall. record() never raises — a ledger
    # failure cannot cost the smoke.
    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record("pack-smoke", kind="smoke",
                       wall_s=time.time() - t_start, verdict=ok,
                       extra={"pack": pack})
    print(json.dumps(out, default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
