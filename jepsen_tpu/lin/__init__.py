"""Linearizability checking — the compute kernel of the framework.

This package replaces the external knossos solver the reference delegates to
(`jepsen/src/jepsen/checker.clj:82-107`, knossos 0.3.1 per
`jepsen/project.clj:9`). The search is reframed TPU-first: instead of
knossos's JVM graph search (`knossos.linear` / `knossos.wgl`), linearizability
is decided by a breadth-first frontier over
``(linearized-op-bitset x model-state)`` configurations:

- :mod:`jepsen_tpu.lin.prepare` — host-side packing: invoke/completion
  pairing, concurrency-window slot assignment, value interning, the
  return-event table both backends consume.
- :mod:`jepsen_tpu.lin.cpu`     — host reference implementation of the
  just-in-time linearization closure (semantic spec + fallback for models
  without device kernels; analogue of knossos.linear).
- :mod:`jepsen_tpu.lin.bfs`     — the sparse device kernel: frontier in
  HBM as packed u32 keys (single word to window 31-b, (hi, lo) pairs to
  60), mutator-compacted expansion, per-row count tiers, canonical
  chains + dominance pruning over crashed/read bits.
- :mod:`jepsen_tpu.lin.dense`   — the dense config-space bitmap engine
  (windows <= 20): the frontier as its characteristic function.
- :mod:`jepsen_tpu.lin.psort`   — in-VMEM pallas bitonic sort-dedup
  kernels backing the sparse engine's per-pass dedup.
- :mod:`jepsen_tpu.lin.sharded` — pjit/shard_map multi-chip frontier with
  collective dedup over ICI.
- :mod:`jepsen_tpu.lin.brute`   — tiny exhaustive search used to test the
  testers.

``analysis(model, history)`` mirrors the shape of
``knossos.competition/analysis`` results consumed at checker.clj:104-107:
``{"valid?": bool, "op": ..., "configs": [...], "final-paths": [...]}``.
"""

from __future__ import annotations

import threading
from typing import Any

from jepsen_tpu.lin import prepare as _prepare_mod
from jepsen_tpu.lin.prepare import PackedHistory, UnsupportedHistory


def analysis(model, history, algorithm: str = "competition", **kw) -> dict:
    """Decide linearizability of ``history`` against ``model``.

    algorithm: ``"tpu"`` (device BFS), ``"cpu"`` (host reference), or
    ``"competition"`` — race both like knossos.competition (the reference
    selects among these at checker.clj:90-93).
    """
    known = {"witness", "cancel", "chunk", "cap_schedule", "explain",
             "checkpoint", "resume"}
    if kw.keys() - known:
        raise TypeError(f"unknown analysis options {kw.keys() - known}")
    try:
        packed = _prepare_mod.prepare(model, history)
    except UnsupportedHistory as e:
        if getattr(e, "kind", None) == "window" and algorithm != "tpu":
            # Past the device bitset (window > 64) the host search still
            # applies — Python int bitsets have no width limit. knossos
            # would grind on such histories too; grinding honestly beats
            # refusing (checker.clj:82-107 never gives up on width).
            try:
                packed = _prepare_mod.prepare(model, history,
                                              max_window=1 << 14)
            except UnsupportedHistory as e2:
                return {"valid?": "unknown", "error": str(e2),
                        "analyzer": "prepare"}
            from jepsen_tpu.lin import cpu

            ckw = {k: v for k, v in kw.items()
                   if k in ("witness", "cancel")}
            return cpu.check_packed(packed, **ckw)
        return {"valid?": "unknown", "error": str(e), "analyzer": "prepare"}

    if algorithm == "cpu":
        from jepsen_tpu.lin import cpu

        ckw = {k: v for k, v in kw.items() if k in ("witness", "cancel")}
        return cpu.check_packed(packed, **ckw)
    if algorithm == "tpu":
        return device_check_packed(packed, **kw)
    if algorithm == "competition":
        return _competition(packed, **kw)
    raise ValueError(f"unknown linearizability algorithm {algorithm!r}")


def device_check_packed(packed: PackedHistory, cancel=None, **kw) -> dict:
    """The device search, routed by history shape: the dense config-space
    bitmap engine (:mod:`jepsen_tpu.lin.dense`) when window and state count
    fit its bounds — including every crashed-op history within them — else
    the sparse sort-dedup frontier (:mod:`jepsen_tpu.lin.bfs`)."""
    from jepsen_tpu.lin import bfs, dense
    from jepsen_tpu.obs import trace as _trace

    known = {"chunk", "cap_schedule", "explain", "checkpoint", "resume",
             "frontier", "frontier_row", "partial", "host_caps"}
    if kw.keys() - known:
        # e.g. snapshots= is dense-only: call dense.check_packed directly.
        raise TypeError(f"unknown device-check options {kw.keys() - known}")
    # Streaming incremental entry (frontier carry / partial verdicts,
    # jepsen_tpu.stream): always the sparse engine — the carried
    # frontier is in its multiword layout, which the dense config-space
    # bitmap cannot re-enter.
    incremental = kw.get("partial") or kw.get("frontier") is not None
    if not incremental and dense.plan(packed) is not None:
        # checkpoint/resume are sparse-engine options (dense histories
        # decide in seconds; there is nothing worth resuming).
        dkw = {k: v for k, v in kw.items() if k in ("chunk", "explain")}
        # The top-level "check" span anchors time attribution: every
        # dispatch/compile span nests inside it, and the trace report's
        # per-site rows sum against its wall time (doc/observability.md).
        with _trace.span("check", engine="dense", rows=int(packed.R),
                         window=int(packed.window)) as sp:
            r = dense.check_packed(packed, cancel=cancel, **dkw)
            sp.note(verdict=str(r.get("valid?")))
            return r
    with _trace.span("check", engine="sparse", rows=int(packed.R),
                     window=int(packed.window)) as sp:
        r = bfs.check_packed(packed, cancel=cancel, **kw)
        sp.note(verdict=str(r.get("valid?")))
        return r


def _competition(packed: PackedHistory, cancel=None, **kw) -> dict:
    """Race the device and host searches; the first *definite* verdict wins
    (knossos.competition/analysis semantics). A racer returning "unknown"
    (e.g. no device kernel for this model) does not end the race — only
    when both racers fail to decide is "unknown" returned. An external
    ``cancel`` event (e.g. a checker time budget) aborts both racers;
    the race also sets it internally to stop the loser."""
    from jepsen_tpu.lin import cpu

    cpu_kw = {k: v for k, v in kw.items() if k in ("witness",)}
    dev_kw = {k: v for k, v in kw.items()
              if k in ("chunk", "cap_schedule", "explain", "checkpoint",
                       "resume")}
    lock = threading.Lock()
    state: dict = {"result": None, "finished": 0}
    done = threading.Event()
    cancel = cancel if cancel is not None else threading.Event()

    def run(fn, name, fkw):
        try:
            r = fn(packed, cancel=cancel, **fkw)
        except Exception as e:  # noqa: BLE001 - loser may die, race decides
            r = {"valid?": "unknown", "error": f"{name}: {e!r}"}
        with lock:
            state["finished"] += 1
            if r.get("valid?") in (True, False):
                if not done.is_set():
                    state["result"] = r
                    done.set()
            else:
                if state["result"] is None and \
                        r.get("error") != "cancelled":
                    state["result"] = r  # fallback if nobody decides
                if state["finished"] == 2:
                    done.set()

    threads = [threading.Thread(target=run,
                                args=(cpu.check_packed, "cpu", cpu_kw)),
               threading.Thread(target=run,
                                args=(device_check_packed, "tpu", dev_kw))]
    for t in threads:
        t.start()
    done.wait()
    # Stop the loser (it checks `cancel` between rows/chunks) and join it —
    # an abandoned thread still inside XLA aborts the process at exit.
    cancel.set()
    for t in threads:
        t.join()
    with lock:
        if state["result"] is None:
            # Both racers were cancelled before deciding (e.g. an
            # external time budget fired): honest unknown.
            return {"valid?": "unknown", "error": "cancelled"}
        return dict(state["result"])
