"""Host-side history packing for the linearizability search.

Converts a Jepsen-style history (vector of invoke/ok/fail/info op maps,
reference core.clj:143-217) into the dense int-array form both the CPU
reference checker and the TPU BFS kernel consume:

1. **Pairing** — each invocation is matched with the next completion by the
   same process. ``fail`` ops are removed entirely (a failed op definitely
   did not happen); ``info`` ops (crashed/indeterminate, produced by the
   runner at core.clj:185-217) stay concurrent with everything after them
   and may be linearized at any later point, or never.
2. **Crashed-read elision** — an unobserved read with no return can always
   be linearized (it never changes state), so crashed reads are dropped.
3. **Slot assignment** — the linearized-op bitset only needs bits for ops
   whose linearized-status varies across frontier configs: exactly the
   *pending* ops. Slots are recycled when an op returns (its bit is then 1
   in every surviving config and is cleared for reuse), so the bitset width
   is the max concurrency window, not the history length. This is the key
   compression that keeps 100k-op histories in a 32/64-bit bitset.
4. **Value interning** — op values (arbitrary hashables) become dense int32
   ids shared with model states, so the device kernel only ever compares
   ints. ``None`` maps to the NIL sentinel (a read invoked with nil matches
   any state, model.clj:31-32).
5. **Return-event table** — the frontier only changes at completion events,
   so the search iterates over R = #ok-ops rows, each carrying the
   returning slot plus the snapshot of active slots with their (f, value).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import Op
from jepsen_tpu.models import kernels as K
from jepsen_tpu.models.kernels import (F_IDS, NIL, VALUE_WIDTH, KernelModel,
                                       kernel_for)


class UnsupportedHistory(Exception):
    """Raised when a history cannot be packed (unknown f, window overflow
    beyond the configured maximum, un-internable values).

    ``kind`` is a stable machine-readable tag ("window" for concurrency-
    window overflow, "other" otherwise) — callers branch on it, never on
    the message text (jepsen_tpu.lin.analysis routes window overflows to
    the unbounded host search)."""

    def __init__(self, message: str, kind: str = "other"):
        super().__init__(message)
        self.kind = kind


@dataclass
class LinOp:
    """One logical operation (invocation + optional completion)."""

    op_index: int           # index of the invocation in the history
    process: Any
    f: str
    value: Any              # semantic value: completion value for ok reads
    ok: bool                # True if completed ok; False if crashed (info)
    invoke_pos: int         # position of invocation event
    return_pos: int | None  # position of ok completion event, None if crashed


@dataclass
class PackedHistory:
    """Dense arrays driving the frontier search; see module docstring."""

    model: Any                   # the Python model (semantic reference)
    kernel: KernelModel | None   # device kernel, None if model unsupported
    ops: list[LinOp]             # logical ops (reporting / witnesses)
    window: int                  # W = bitset width in use
    R: int                       # number of return events
    ret_slot: np.ndarray         # i32[R]   slot of the returning op
    ret_op: np.ndarray           # i32[R]   index into ops of the returner
    active: np.ndarray           # bool[R,W] slots invoked & unreturned
    slot_f: np.ndarray           # i32[R,W] function id per active slot
    slot_v: np.ndarray           # i32[R,W,VALUE_WIDTH] interned values
    slot_op: np.ndarray          # i32[R,W] index into ops per active slot
    crashed: np.ndarray          # bool[R,W] active slot holds a crashed op
    init_state: np.ndarray       # i32[S]
    intern: dict                 # value -> id
    unintern: list               # id -> value
    crashed_ops: list[LinOp]     # info ops pending at end (never linearized)

    @property
    def state_width(self) -> int:
        return len(self.init_state)


MAX_WINDOW = 64


def _semantic_value(f: str, invoke: Op, completion: Op | None) -> Any:
    """The value the model checks: reads and dequeues are checked against
    what they *observed* (the completion's value, knossos.history/complete
    semantics); mutations against what they *requested* (the invocation's
    value)."""
    if f == "read":
        return completion.value if (completion is not None
                                    and completion.is_ok) else None
    if f == "dequeue" and completion is not None and completion.is_ok \
            and completion.value is not None:
        return completion.value
    return invoke.value


def pair_ops(history: list[Op]) -> list[LinOp]:
    """Match invocations with completions; drop failed ops and crashed
    reads. Dangling invocations at the end of history count as crashed
    (the runner emits :info for those, core.clj:185-217)."""
    ops: list[LinOp] = []
    pending: dict[Any, tuple[int, Op]] = {}
    for pos, op in enumerate(history):
        if op.process == "nemesis" or op.f in ("start", "stop"):
            continue
        if op.is_invoke:
            if op.process in pending:
                raise UnsupportedHistory(
                    f"process {op.process} invoked twice without completing "
                    f"(positions {pending[op.process][0]} and {pos})")
            pending[op.process] = (pos, op)
        elif op.process in pending:
            ipos, inv = pending.pop(op.process)
            if op.is_fail:
                continue  # failed ops definitely did not happen
            ok = op.is_ok
            ops.append(LinOp(
                op_index=inv.index if inv.index is not None else ipos,
                process=op.process, f=inv.f,
                value=_semantic_value(inv.f, inv, op),
                ok=ok, invoke_pos=ipos,
                return_pos=pos if ok else None))
    # Dangling invokes = crashed.
    for proc, (ipos, inv) in pending.items():
        ops.append(LinOp(
            op_index=inv.index if inv.index is not None else ipos,
            process=proc, f=inv.f,
            value=_semantic_value(inv.f, inv, None),
            ok=False, invoke_pos=ipos, return_pos=None))
    # Crashed reads never constrain anything: elide.
    ops = [o for o in ops if o.ok or o.f != "read"]
    ops.sort(key=lambda o: o.invoke_pos)
    return ops


class _Interner:
    def __init__(self):
        self.ids: dict = {}
        self.values: list = []

    def __call__(self, v) -> int:
        if v is None:
            return int(NIL)
        try:
            key = v
            hash(key)
        except TypeError:
            key = repr(v)
        if key not in self.ids:
            self.ids[key] = len(self.values)
            self.values.append(v)
        return self.ids[key]


def _op_f_and_values(o: LinOp, intern: _Interner) -> tuple[int, list[int]]:
    if o.f not in F_IDS:
        raise UnsupportedHistory(f"unknown op f={o.f!r} for device packing")
    f_id = F_IDS[o.f]
    v = [int(NIL)] * VALUE_WIDTH
    if o.f == "cas":
        if not isinstance(o.value, (list, tuple)) or len(o.value) != 2:
            raise UnsupportedHistory(f"cas value must be a pair: {o.value!r}")
        v[0] = intern(o.value[0])
        v[1] = intern(o.value[1])
    elif o.f in ("read", "write"):
        v[0] = intern(o.value)
    return f_id, v


# Device-formulation size bounds: histories past these fall back to the
# generic CPU search (kernel=None) rather than failing.
MAX_SET_WORDS = 16        # 16 x 31 = 496 distinct set elements
MAX_QUEUE_VALUES = 32     # distinct unordered-queue values (state width)
MAX_FIFO_CAP = 31         # fifo depth bound (state width 32)


def _max_queue_depth(ops: list[LinOp], n_initial: int) -> int:
    """Upper bound on FIFO depth over every possible linearization: at any
    event position t, at most the enqueues *invoked* by t have linearized,
    and at least the ok dequeues *returned* by t have linearized."""
    events = []
    for o in ops:
        if o.f == "enqueue":
            events.append((o.invoke_pos, 1))
        elif o.f == "dequeue" and o.return_pos is not None:
            events.append((o.return_pos, -1))
    events.sort()
    depth = peak = n_initial
    for _, d in events:
        depth += d
        peak = max(peak, depth)
    return peak


def _no_kernel(n: int):
    return (None, np.array([0], np.int32), np.zeros(n, np.int32),
            np.full((n, VALUE_WIDTH), int(NIL), np.int32))


def _kernelize(model, ops: list[LinOp], intern: _Interner):
    """Build the device kernel sized for this history plus the per-op
    interned (f, value-words) tables.

    Returns ``(kernel, init_state, op_f, op_v)``; kernel is None when the
    model — or this particular history — has no device formulation, in
    which case the generic CPU search takes over with exact semantics.
    The set/queue kernels are sized from the history (element count, value
    count, queue depth bound), so their packed-state width is data-driven.
    """
    n = len(ops)

    def tables(vw):
        return (np.zeros(n, np.int32),
                np.full((n, vw), int(NIL), np.int32))

    if isinstance(model, (model_ns.CASRegister, model_ns.Register,
                          model_ns.Mutex)):
        kernel = kernel_for(model)
        if isinstance(model, model_ns.Mutex):
            init_state = kernel.init_state()
        else:
            init_state = np.array([intern(model.value)], np.int32)
        op_f, op_v = tables(kernel.value_width)
        for i, o in enumerate(ops):
            f_id, v = _op_f_and_values(o, intern)
            op_f[i] = f_id
            op_v[i] = v
        return kernel, init_state, op_f, op_v

    if isinstance(model, model_ns.SetModel):
        if any(o.f not in F_IDS for o in ops) or \
                any(o.f == "add" and o.value is None for o in ops) or \
                any(e is None for e in model.s):
            return _no_kernel(n)
        # Dense element ids: initial elements first, then history order.
        initial_ids = [intern(e) for e in sorted(model.s, key=repr)]
        for o in ops:
            if o.f == "add":
                intern(o.value)
            elif o.f == "read":
                try:
                    for e in (o.value if o.value is not None else ()):
                        intern(e)
                except TypeError:
                    pass
        n_elements = max(1, len(intern.values))
        n_words = -(-n_elements // K.SET_BITS)
        if n_words > MAX_SET_WORDS:
            return _no_kernel(n)
        kernel = K.set_kernel(n_elements, initial_ids)
        op_f, op_v = tables(kernel.value_width)
        for i, o in enumerate(ops):
            op_f[i] = F_IDS[o.f]
            if o.f == "add":
                op_v[i, 0] = intern(o.value)
            elif o.f == "read":
                try:
                    elems = [intern(e) for e in o.value] \
                        if o.value is not None else None
                except TypeError:
                    elems = None
                if elems is not None and int(NIL) in elems:
                    # A None element can never be in the state (nil adds
                    # were rejected above), so this read can never match.
                    elems = None
                if elems is not None:
                    # Observed mask; all-NIL (never matches) when the
                    # read's value is not a collection (= inconsistent).
                    op_v[i, :n_words] = 0
                    for e in elems:
                        op_v[i, e // K.SET_BITS] |= np.int32(
                            1 << (e % K.SET_BITS))
        return kernel, kernel.init_state(), op_f, op_v

    if isinstance(model, (model_ns.UnorderedQueue, model_ns.FIFOQueue)):
        initial = list(model.pending)
        if any(o.f not in F_IDS for o in ops) \
                or any(v is None for v in initial) \
                or any(o.f == "enqueue" and o.value is None for o in ops):
            return _no_kernel(n)
        initial_ids = [intern(v) for v in initial]
        for o in ops:
            if o.f in ("enqueue", "dequeue") and o.value is not None:
                intern(o.value)
        if isinstance(model, model_ns.FIFOQueue):
            depth = _max_queue_depth(ops, len(initial))
            if depth > MAX_FIFO_CAP:
                return _no_kernel(n)
            kernel = K.fifo_queue_kernel(max(1, depth), initial_ids)
        else:
            n_values = max(1, len(intern.values))
            enq_ids = initial_ids + [intern(o.value) for o in ops
                                     if o.f == "enqueue"]
            if len(set(enq_ids)) == len(enq_ids):
                # All enqueued values distinct: pending multiset is a set,
                # packed as a bitmask (31 values/word).
                n_words = -(-n_values // K.SET_BITS)
                if n_words > MAX_SET_WORDS:
                    return _no_kernel(n)
                kernel = K.unordered_unique_kernel(n_values, initial_ids)
            elif n_values <= MAX_QUEUE_VALUES:
                kernel = K.unordered_queue_kernel(n_values, initial_ids)
            else:
                return _no_kernel(n)
        op_f, op_v = tables(kernel.value_width)
        for i, o in enumerate(ops):
            op_f[i] = F_IDS[o.f]
            if o.f in ("enqueue", "dequeue"):
                # A nil dequeue interns to NIL, which is never legal — the
                # same verdict the Python models give (None not in pending,
                # since nil enqueues were rejected above).
                op_v[i, 0] = intern(o.value)
        return kernel, kernel.init_state(), op_f, op_v

    return _no_kernel(n)


def _pack_events_native(invoke_pos, return_pos, op_f, op_v, max_window,
                        fill_fv, R):
    """The packing walk via native/history_pack.cc (ctypes). None when the
    native library is unavailable."""
    from jepsen_tpu import native_ext

    try:
        out = native_ext.pack_events(
            invoke_pos, return_pos, op_f, op_v[:, 0], op_v[:, 1],
            nil_value=int(NIL), max_window=max_window,
            fill_fv=fill_fv, R=R)
    except native_ext.WindowOverflow as e:
        raise UnsupportedHistory(
            f"concurrency window exceeds {max_window} pending ops "
            f"at history position {e.pos}", kind="window") from None
    return out


def _pack_events_py(invoke_pos, return_pos, op_f, op_v, max_window,
                    fill_fv, R):
    """Pure-Python packing walk (semantics twin of jtpu_pack_events)."""
    n = len(invoke_pos)
    W_alloc = max_window
    vw = op_v.shape[1]
    ret_slot = np.zeros(R, np.int32)
    ret_op = np.zeros(R, np.int32)
    active = np.zeros((R, W_alloc), bool)
    slot_f = np.zeros((R, W_alloc), np.int32)
    slot_v = np.full((R, W_alloc, vw), int(NIL), np.int32)
    slot_op = np.full((R, W_alloc), -1, np.int32)

    # Event stream over op endpoints: (pos, kind, op_id); invokes before
    # returns at equal positions can't happen (distinct history positions).
    events: list[tuple[int, int, int]] = []
    for i in range(n):
        events.append((int(invoke_pos[i]), 0, i))
        if return_pos[i] >= 0:
            events.append((int(return_pos[i]), 1, i))
    events.sort()

    free = list(range(W_alloc))[::-1]
    slot_of: dict[int, int] = {}
    cur_active: dict[int, int] = {}   # slot -> op id
    max_used = 0
    r = 0
    for pos, kind, i in events:
        if kind == 0:  # invoke
            if not free:
                raise UnsupportedHistory(
                    f"concurrency window exceeds {max_window} pending ops "
                    f"at history position {pos}", kind="window")
            s = free.pop()
            slot_of[i] = s
            cur_active[s] = i
            max_used = max(max_used, s + 1)
        else:  # ok return
            s = slot_of[i]
            ret_slot[r] = s
            ret_op[r] = i
            for slot, op_id in cur_active.items():
                active[r, slot] = True
                slot_op[r, slot] = op_id
                if fill_fv:
                    slot_f[r, slot] = op_f[op_id]
                    slot_v[r, slot] = op_v[op_id]
            r += 1
            del cur_active[s]
            del slot_of[i]
            free.append(s)
    return ret_slot, ret_op, active, slot_f, slot_v, slot_op, max_used


def prepare(model, history, max_window: int = MAX_WINDOW) -> PackedHistory:
    """Pack a history for the frontier search. See module docstring."""
    history = list(history)
    ops = pair_ops(history)
    intern = _Interner()

    # Per-op (f, values) interned ONCE up front — the packing walk below
    # references ops (R x W) times and must not re-intern per reference.
    kernel, init_state, op_f, op_v = _kernelize(model, ops, intern)

    n = len(ops)
    R = sum(1 for o in ops if o.ok)

    invoke_pos = np.fromiter((o.invoke_pos for o in ops), np.int32, n)
    return_pos = np.fromiter(
        (-1 if o.return_pos is None else o.return_pos for o in ops),
        np.int32, n)

    fill_fv = kernel is not None
    packed = None
    if op_v.shape[1] == 2:  # the native walk is specialized to 2-word values
        packed = _pack_events_native(
            invoke_pos, return_pos, op_f, op_v, max_window, fill_fv, R)
    if packed is None:
        packed = _pack_events_py(
            invoke_pos, return_pos, op_f, op_v, max_window, fill_fv, R)
    ret_slot, ret_op, active, slot_f, slot_v, slot_op, max_used = packed

    crashed = [o for o in ops if o.return_pos is None]

    # Per-slot crashed mask. CONSUMED BY THE DEVICE ENGINES: the
    # crashed-op canonical chains (reduction_tables) and the sparse
    # engine's crashed-subset dominance prune (bfs.expansion_tables
    # builds its key-space crash masks from this; bfs.check_packed
    # gates the prune on it) — its semantics ("this active slot's op
    # never returns") are exactness-critical, not just reporting.
    crashed_tbl = np.zeros_like(active)
    live = active & (slot_op >= 0)
    crashed_tbl[live] = return_pos[slot_op[live]] < 0

    W = max(1, max_used)
    return PackedHistory(
        model=model, kernel=kernel, ops=ops, window=W, R=R,
        ret_slot=ret_slot, ret_op=ret_op,
        active=active[:, :W], slot_f=slot_f[:, :W],
        slot_v=slot_v[:, :W], slot_op=slot_op[:, :W],
        crashed=crashed_tbl[:, :W],
        init_state=init_state, intern=intern.ids, unintern=intern.values,
        crashed_ops=crashed)


# --- search-space reductions -------------------------------------------------
#
# Two exact (verdict- and death-row-preserving) reductions of the frontier
# search, consumed by the CPU oracle and the sparse device engine. Both are
# new to this build — knossos has no analogue; they are what lets the sparse
# band (windows 21..64, e.g. cockroach's concurrency-30 registers,
# cockroach.clj:40-41) stay tractable where the JVM search DNFs.
#
# 1. **Pure-op saturation.** A pure op (one whose step never changes state:
#    register/set reads) need not branch the search. Its linearization
#    point can be ANY moment its legality predicate holds between invoke
#    and return, so the search just marks its bit the first moment the
#    config's state matches ("greedy read linearization"). Soundness: read
#    bits are only ever tested positively at the op's return and never
#    affect other transitions, so greedily setting them dominates; any
#    plain survivor maps to a greedy survivor of the same row and vice
#    versa. This removes pure ops from the exponential branching entirely.
#
# 2. **Canonical chains.** Two concurrently-pending identical ops
#    (same f, same value — e.g. two pending write(3)s, two mutex acquires)
#    are exchangeable: swapping their linearization points yields another
#    valid linearization. LIVE ops chain by return order (both intervals
#    cover both points while both are pending, and the earlier-returning
#    interval is the binding one); CRASHED ops chain among themselves by
#    invoke order (their windows never close, so any point past the later
#    invoke lies in every earlier sibling's window). The two families
#    never cross — a crashed op cannot stand in for a live one whose
#    window ends at its return. Slot j with an unlinearized canonical
#    predecessor is blocked until the predecessor's bit is set.
#
# (A third reduction — dominance pruning over crashed-op subsets and
# read bits — lives in the device engine's dedup, jepsen_tpu.lin.bfs
# ._dedup_keys_dom, since it prunes between configs rather than gating
# transitions.)
#
# Config counts on a 2k-op concurrency-30 register history (window 28):
# plain search >170k configs by row 40 (DNF); with both reductions the
# peak frontier is ~20k and the whole history closes.


def reduction_tables(p: PackedHistory) -> tuple[np.ndarray, np.ndarray]:
    """Per-row reduction tables ``(pure, pred)`` for a packed history.

    pure: bool[R, W] — active slot holds a pure (state-preserving) op.
    pred: i32[R, W]  — canonical-chain predecessor slot (-1 when none):
    slot j may linearize in row r only once ``pred[r, j]``'s bit is set.
    Cached on the PackedHistory after first computation.
    """
    cached = getattr(p, "_reduction_tables", None)
    if cached is not None:
        return cached

    R, W = p.active.shape
    if p.kernel is None or R == 0:
        out = (np.zeros((R, W), bool), np.full((R, W), -1, np.int32))
        p._reduction_tables = out
        return out

    pure_fs = {int(K.F_IDS[f]) for f in ("read",)
               if f in K.F_IDS}
    pure = p.active & np.isin(p.slot_f, list(pure_fs))

    # Return row per slot occurrence: the row at which this slot's op
    # returns; crashed ops get a sentinel past any row.
    NEVER = np.int32(R + 1)
    ret_row_of_op = np.full(len(p.ops), NEVER, np.int64)
    ret_row_of_op[np.asarray(p.ret_op)] = np.arange(R)
    slot_ret = np.where(p.slot_op >= 0,
                        ret_row_of_op[np.clip(p.slot_op, 0, None)], NEVER)

    # Chainable = active, not pure. Identical LIVE ops chain in return
    # order (the earlier-returning interval is the binding one). Identical
    # CRASHED ops (:info, never return — their windows extend to the end
    # of history) chain in INVOKE order: any linearization using a later
    # chain member maps to one using the invoke-order prefix at the same
    # points (each point lies past the later member's invoke, hence past
    # every earlier member's), so WLOG the prefix linearizes first. The
    # two families never cross (a crashed op cannot stand in for a live
    # one whose window ends at its return): the class key carries a
    # crashed flag. This collapses the 2^k subset blowup of k identical
    # crashed mutators — the partitioned-nemesis history shape
    # (BASELINE config 5) — to the k+1 prefixes.
    invoke_of_op = np.fromiter((o.invoke_pos for o in p.ops), np.int64,
                               len(p.ops))
    slot_inv = np.where(p.slot_op >= 0,
                        invoke_of_op[np.clip(p.slot_op, 0, None)], 0)
    is_crashed = slot_ret >= NEVER
    ordkey = np.where(is_crashed, np.int64(R + 2) + slot_inv, slot_ret)

    chainable = p.active & ~pure & (p.slot_op >= 0)
    sent = -1 - np.arange(W, dtype=np.int64)          # unique per column
    f_key = np.where(
        chainable,
        (p.slot_f.astype(np.int64) << 1) | is_crashed,
        sent[None, :])
    v_keys = [p.slot_v[:, :, k].astype(np.int64)
              for k in range(p.slot_v.shape[2])]

    # Row-wise canonical order: sort slots by (class, return row | invoke
    # position); equal classes become adjacent runs in canonical order.
    order = np.lexsort(tuple([ordkey] + v_keys[::-1] + [f_key]), axis=1)
    rows = np.arange(R)[:, None]
    f_s = np.take_along_axis(f_key, order, axis=1)
    same = f_s[:, 1:] == f_s[:, :-1]
    for vk in v_keys:
        v_s = np.take_along_axis(vk, order, axis=1)
        same &= v_s[:, 1:] == v_s[:, :-1]
    pred = np.full((R, W), -1, np.int32)
    cols = order[:, 1:]
    prev = order[:, :-1]
    np.put_along_axis(
        pred, cols, np.where(same, prev, -1).astype(np.int32), axis=1)
    out = (pure, pred)
    p._reduction_tables = out
    return out


# --- pure-python packed step (mirror of models.kernels, for the CPU
# reference checker's inner loop and witness replay) -------------------------

def py_step_fn(kernel_name: str) -> Callable:
    """Python twin of the device step kernels, operating on
    (state tuple, f id, value ids) — must agree exactly with
    jepsen_tpu.models.kernels (parity-tested)."""
    from jepsen_tpu.models import kernels as K

    nil = int(K.NIL)

    if kernel_name in ("cas-register", "register"):
        allow_cas = kernel_name == "cas-register"

        def step(state, f, v):
            cur = state[0]
            if f == K.F_READ:
                return (v[0] == nil or v[0] == cur), state
            if f == K.F_WRITE:
                return True, (v[0],)
            if f == K.F_CAS and allow_cas:
                if v[0] == cur:
                    return True, (v[1],)
                return False, state
            return False, state

        return step

    if kernel_name == "mutex":
        def step(state, f, v):
            locked = state[0]
            if f == K.F_ACQUIRE:
                return locked == 0, (1,)
            if f == K.F_RELEASE:
                return locked == 1, (0,)
            return False, state

        return step

    if kernel_name == "set":
        def step(state, f, v):
            if f == K.F_ADD:
                e = v[0]
                if e == nil:
                    return False, state
                w, b = divmod(e, K.SET_BITS)
                s = list(state)
                s[w] |= 1 << b
                return True, tuple(s)
            if f == K.F_READ:
                return tuple(v[:len(state)]) == tuple(state), state
            return False, state

        return step

    if kernel_name == "unordered-unique":
        def step(state, f, v):
            e = v[0]
            if e == nil:
                return False, state
            w, b = divmod(e, K.SET_BITS)
            has = bool((state[w] >> b) & 1)
            if f == K.F_ENQUEUE and not has:
                s = list(state)
                s[w] |= 1 << b
                return True, tuple(s)
            if f == K.F_DEQUEUE and has:
                s = list(state)
                s[w] &= ~(1 << b)
                return True, tuple(s)
            return False, state

        return step

    if kernel_name == "unordered-queue":
        def step(state, f, v):
            e = v[0]
            if f == K.F_ENQUEUE:
                s = list(state)
                s[e] += 1
                return True, tuple(s)
            if f == K.F_DEQUEUE:
                if 0 <= e < len(state) and state[e] > 0:
                    s = list(state)
                    s[e] -= 1
                    return True, tuple(s)
                return False, state
            return False, state

        return step

    if kernel_name == "fifo-queue":
        def step(state, f, v):
            size, buf = state[0], state[1:]
            if f == K.F_ENQUEUE:
                if size >= len(buf):
                    return False, state
                s = list(buf)
                s[size] = v[0]
                return True, (size + 1, *s)
            if f == K.F_DEQUEUE:
                if size > 0 and buf[0] == v[0]:
                    return True, (size - 1, *buf[1:], 0)
                return False, state
            return False, state

        return step

    raise ValueError(f"no python step for kernel {kernel_name!r}")
