"""Host-side history packing for the linearizability search.

Converts a Jepsen-style history (vector of invoke/ok/fail/info op maps,
reference core.clj:143-217) into the dense int-array form both the CPU
reference checker and the TPU BFS kernel consume:

1. **Pairing** — each invocation is matched with the next completion by the
   same process. ``fail`` ops are removed entirely (a failed op definitely
   did not happen); ``info`` ops (crashed/indeterminate, produced by the
   runner at core.clj:185-217) stay concurrent with everything after them
   and may be linearized at any later point, or never.
2. **Crashed-read elision** — an unobserved read with no return can always
   be linearized (it never changes state), so crashed reads are dropped.
3. **Slot assignment** — the linearized-op bitset only needs bits for ops
   whose linearized-status varies across frontier configs: exactly the
   *pending* ops. Slots are recycled when an op returns (its bit is then 1
   in every surviving config and is cleared for reuse), so the bitset width
   is the max concurrency window, not the history length. This is the key
   compression that keeps 100k-op histories in a 32/64-bit bitset.
4. **Value interning** — op values (arbitrary hashables) become dense int32
   ids shared with model states, so the device kernel only ever compares
   ints. ``None`` maps to the NIL sentinel (a read invoked with nil matches
   any state, model.clj:31-32).
5. **Return-event table** — the frontier only changes at completion events,
   so the search iterates over R = #ok-ops rows, each carrying the
   returning slot plus the snapshot of active slots with their (f, value).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from operator import attrgetter
from typing import Any, Callable

import numpy as np

from jepsen_tpu import models as model_ns
from jepsen_tpu.history import INFO, INVOKE, OK, Op
from jepsen_tpu.history import FAIL as H_FAIL
from jepsen_tpu.models import kernels as K
from jepsen_tpu.models.kernels import (F_IDS, NIL, VALUE_WIDTH, KernelModel,
                                       kernel_for)


def fast_pack_enabled() -> bool:
    """``JEPSEN_TPU_FAST_PACK``: the vectorized packer (sort/searchsorted/
    cumsum numpy passes, bit-identical to the Python walk). Default on;
    ``=0`` falls back to the Python spec walk, which stays the executable
    reference. Re-read per call (the env-knob convention, doc/env.md)."""
    return os.environ.get("JEPSEN_TPU_FAST_PACK", "") != "0"


# Pack-wall accounting (bench's pack rung + the service's pack-seconds
# counter read this; obs/trace spans carry the per-call attribution).
_pack_stats = {"prepare_s": 0.0, "prepare_calls": 0,
               "reduction_s": 0.0, "reduction_calls": 0,
               "incr_s": 0.0, "incr_calls": 0, "mode": ""}


def pack_stats() -> dict:
    """Snapshot of cumulative packing wall this process (seconds)."""
    return dict(_pack_stats)


def reset_pack_stats() -> None:
    for k in _pack_stats:
        _pack_stats[k] = "" if k == "mode" else (0.0 if k.endswith("_s")
                                                 else 0)


class UnsupportedHistory(Exception):
    """Raised when a history cannot be packed (unknown f, window overflow
    beyond the configured maximum, un-internable values).

    ``kind`` is a stable machine-readable tag ("window" for concurrency-
    window overflow, "other" otherwise) — callers branch on it, never on
    the message text (jepsen_tpu.lin.analysis routes window overflows to
    the unbounded host search)."""

    def __init__(self, message: str, kind: str = "other"):
        super().__init__(message)
        self.kind = kind


@dataclass
class LinOp:
    """One logical operation (invocation + optional completion)."""

    op_index: int           # index of the invocation in the history
    process: Any
    f: str
    value: Any              # semantic value: completion value for ok reads
    ok: bool                # True if completed ok; False if crashed (info)
    invoke_pos: int         # position of invocation event
    return_pos: int | None  # position of ok completion event, None if crashed


@dataclass
class PackedHistory:
    """Dense arrays driving the frontier search; see module docstring."""

    model: Any                   # the Python model (semantic reference)
    kernel: KernelModel | None   # device kernel, None if model unsupported
    ops: list[LinOp]             # logical ops (reporting / witnesses)
    window: int                  # W = bitset width in use
    R: int                       # number of return events
    ret_slot: np.ndarray         # i32[R]   slot of the returning op
    ret_op: np.ndarray           # i32[R]   index into ops of the returner
    active: np.ndarray           # bool[R,W] slots invoked & unreturned
    slot_f: np.ndarray           # i32[R,W] function id per active slot
    slot_v: np.ndarray           # i32[R,W,VALUE_WIDTH] interned values
    slot_op: np.ndarray          # i32[R,W] index into ops per active slot
    crashed: np.ndarray          # bool[R,W] active slot holds a crashed op
    init_state: np.ndarray       # i32[S]
    intern: dict                 # value -> id
    unintern: list               # id -> value
    crashed_ops: list[LinOp]     # info ops pending at end (never linearized)

    @property
    def state_width(self) -> int:
        return len(self.init_state)


MAX_WINDOW = 64


def _semantic_value(f: str, invoke: Op, completion: Op | None) -> Any:
    """The value the model checks: reads and dequeues are checked against
    what they *observed* (the completion's value, knossos.history/complete
    semantics); mutations against what they *requested* (the invocation's
    value)."""
    if f == "read":
        return completion.value if (completion is not None
                                    and completion.is_ok) else None
    if f == "dequeue" and completion is not None and completion.is_ok \
            and completion.value is not None:
        return completion.value
    return invoke.value


def pair_ops(history: list[Op]) -> list[LinOp]:
    """Match invocations with completions; drop failed ops and crashed
    reads. Dangling invocations at the end of history count as crashed
    (the runner emits :info for those, core.clj:185-217)."""
    ops: list[LinOp] = []
    pending: dict[Any, tuple[int, Op]] = {}
    for pos, op in enumerate(history):
        if op.process == "nemesis" or op.f in ("start", "stop"):
            continue
        if op.is_invoke:
            if op.process in pending:
                raise UnsupportedHistory(
                    f"process {op.process} invoked twice without completing "
                    f"(positions {pending[op.process][0]} and {pos})")
            pending[op.process] = (pos, op)
        elif op.process in pending:
            ipos, inv = pending.pop(op.process)
            if op.is_fail:
                continue  # failed ops definitely did not happen
            ok = op.is_ok
            ops.append(LinOp(
                op_index=inv.index if inv.index is not None else ipos,
                process=op.process, f=inv.f,
                value=_semantic_value(inv.f, inv, op),
                ok=ok, invoke_pos=ipos,
                return_pos=pos if ok else None))
    # Dangling invokes = crashed.
    for proc, (ipos, inv) in pending.items():
        ops.append(LinOp(
            op_index=inv.index if inv.index is not None else ipos,
            process=proc, f=inv.f,
            value=_semantic_value(inv.f, inv, None),
            ok=False, invoke_pos=ipos, return_pos=None))
    # Crashed reads never constrain anything: elide.
    ops = [o for o in ops if o.ok or o.f != "read"]
    ops.sort(key=lambda o: o.invoke_pos)
    return ops


_TYPE_CODE = {INVOKE: 0, OK: 1, H_FAIL: 2, INFO: 3}


def _pair_ops_vec(history: list[Op]) -> list[LinOp]:
    """Vectorized twin of :func:`pair_ops` (JEPSEN_TPU_FAST_PACK).
    Produces the identical LinOp list (same order, same ops, same
    errors) as the spec loop."""
    return _pair_ops_vec_arrays(history)[0]


def _pair_ops_vec_arrays(history: list[Op]):
    """Core of :func:`_pair_ops_vec`: the per-event pending-dict walk
    becomes a stable sort by (process, time) — within one process the
    relevant events alternate invoke/completion, so a completion pairs
    with its invocation exactly when the previous same-process event is
    an invoke, and an invoke following an invoke is the double-invoke
    error. Returns ``(ops, invoke_pos, return_pos, ok)`` with the
    position/ok columns as arrays so :func:`prepare` skips re-walking
    the LinOp list (return_pos is -1 for crashed ops)."""
    empty = ([], np.zeros(0, np.int32), np.zeros(0, np.int32),
             np.zeros(0, bool))
    n_ev = len(history)
    if n_ev == 0:
        return empty
    get_code = _TYPE_CODE.get
    tc = np.frombuffer(
        bytes(get_code(t, 4) for t in map(attrgetter("type"), history)),
        np.int8)
    fs = list(map(attrgetter("f"), history))
    # Factorize processes / fs to dense ids (arbitrary hashables).
    pmap: dict = {}
    pids = np.fromiter((pmap.setdefault(op.process, len(pmap))
                        for op in history), np.int64, n_ev)
    fmap: dict = {}
    fids = np.fromiter((fmap.setdefault(f, len(fmap)) for f in fs),
                       np.int64, n_ev)
    keep = np.ones(n_ev, bool)
    nem = pmap.get("nemesis")
    if nem is not None:
        keep &= pids != nem
    for excl in ("start", "stop"):
        fe = fmap.get(excl)
        if fe is not None:
            keep &= fids != fe
    idx = np.flatnonzero(keep)
    if idx.size == 0:
        return empty
    # Group by process, time order within group (stable).
    g = idx[np.argsort(pids[idx], kind="stable")]
    pid_s = pids[g]
    tc_s = tc[g]
    first = np.empty(len(g), bool)
    first[0] = True
    first[1:] = pid_s[1:] != pid_s[:-1]
    prev_invoke = np.zeros(len(g), bool)
    prev_invoke[1:] = (tc_s[:-1] == 0) & ~first[1:]
    dbl = (tc_s == 0) & prev_invoke
    if dbl.any():
        j = np.flatnonzero(dbl)
        jj = j[np.argmin(g[j])]          # earliest second-invoke in history
        p1, p2 = int(g[jj - 1]), int(g[jj])
        raise UnsupportedHistory(
            f"process {history[p2].process} invoked twice without "
            f"completing (positions {p1} and {p2})")
    paired = (tc_s != 0) & prev_invoke & (tc_s != 2)   # fails drop
    last = np.empty(len(g), bool)
    last[-1] = True
    last[:-1] = first[1:]
    dangling = (tc_s == 0) & last                      # pending at end
    pj = np.flatnonzero(paired)
    ipos = np.concatenate([g[pj - 1], g[np.flatnonzero(dangling)]])
    cpos = np.concatenate([g[pj], np.full(int(dangling.sum()), -1,
                                          g.dtype)])
    okc = np.concatenate([tc_s[pj] == 1,               # OK completions
                          np.zeros(int(dangling.sum()), bool)])
    # Crashed reads constrain nothing: drop them here, vectorized, so
    # the build loop below is branch-light and 1:1 with the arrays.
    rf = fmap.get("read")
    if rf is not None:
        keep2 = okc | (fids[ipos] != rf)
        ipos, cpos, okc = ipos[keep2], cpos[keep2], okc[keep2]
    order = np.argsort(ipos, kind="stable")
    ipos, cpos, okc = ipos[order], cpos[order], okc[order]
    ops: list[LinOp] = []
    app = ops.append
    H = history
    new = LinOp.__new__
    for ip, cp, ok in zip(ipos.tolist(), cpos.tolist(), okc.tolist()):
        inv = H[ip]
        f = inv.f
        if f == "read":                  # ok is always True here
            value = H[cp].value
        elif f == "dequeue" and ok:
            cv = H[cp].value
            value = cv if cv is not None else inv.value
        else:
            value = inv.value
        o = new(LinOp)
        o.__dict__ = {
            "op_index": inv.index if inv.index is not None else ip,
            "process": inv.process, "f": f, "value": value, "ok": ok,
            "invoke_pos": ip, "return_pos": cp if ok else None}
        app(o)
    return (ops, ipos.astype(np.int32, copy=False),
            np.where(okc, cpos, -1).astype(np.int32, copy=False), okc)


class _Interner:
    def __init__(self):
        self.ids: dict = {}
        self.values: list = []

    def __call__(self, v) -> int:
        if v is None:
            return int(NIL)
        try:
            key = v
            hash(key)
        except TypeError:
            key = repr(v)
        if key not in self.ids:
            self.ids[key] = len(self.values)
            self.values.append(v)
        return self.ids[key]


def _op_f_and_values(o: LinOp, intern: _Interner) -> tuple[int, list[int]]:
    if o.f not in F_IDS:
        raise UnsupportedHistory(f"unknown op f={o.f!r} for device packing")
    f_id = F_IDS[o.f]
    v = [int(NIL)] * VALUE_WIDTH
    if o.f == "cas":
        if not isinstance(o.value, (list, tuple)) or len(o.value) != 2:
            raise UnsupportedHistory(f"cas value must be a pair: {o.value!r}")
        v[0] = intern(o.value[0])
        v[1] = intern(o.value[1])
    elif o.f in ("read", "write"):
        v[0] = intern(o.value)
    return f_id, v


# Device-formulation size bounds: histories past these fall back to the
# generic CPU search (kernel=None) rather than failing.
MAX_SET_WORDS = 16        # 16 x 31 = 496 distinct set elements
MAX_QUEUE_VALUES = 32     # distinct unordered-queue values (state width)
MAX_FIFO_CAP = 31         # fifo depth bound (state width 32)


def _max_queue_depth(ops: list[LinOp], n_initial: int) -> int:
    """Upper bound on FIFO depth over every possible linearization: at any
    event position t, at most the enqueues *invoked* by t have linearized,
    and at least the ok dequeues *returned* by t have linearized."""
    events = []
    for o in ops:
        if o.f == "enqueue":
            events.append((o.invoke_pos, 1))
        elif o.f == "dequeue" and o.return_pos is not None:
            events.append((o.return_pos, -1))
    events.sort()
    depth = peak = n_initial
    for _, d in events:
        depth += d
        peak = max(peak, depth)
    return peak


def _no_kernel(n: int):
    return (None, np.array([0], np.int32), np.zeros(n, np.int32),
            np.full((n, VALUE_WIDTH), int(NIL), np.int32))


def _kernelize(model, ops: list[LinOp], intern: _Interner):
    """Build the device kernel sized for this history plus the per-op
    interned (f, value-words) tables.

    Returns ``(kernel, init_state, op_f, op_v)``; kernel is None when the
    model — or this particular history — has no device formulation, in
    which case the generic CPU search takes over with exact semantics.
    The set/queue kernels are sized from the history (element count, value
    count, queue depth bound), so their packed-state width is data-driven.
    """
    n = len(ops)

    def tables(vw):
        return (np.zeros(n, np.int32),
                np.full((n, vw), int(NIL), np.int32))

    if isinstance(model, (model_ns.CASRegister, model_ns.Register,
                          model_ns.Mutex)):
        kernel = kernel_for(model)
        if isinstance(model, model_ns.Mutex):
            init_state = kernel.init_state()
        else:
            init_state = np.array([intern(model.value)], np.int32)
        op_f, op_v = tables(kernel.value_width)
        for i, o in enumerate(ops):
            f_id, v = _op_f_and_values(o, intern)
            op_f[i] = f_id
            op_v[i] = v
        return kernel, init_state, op_f, op_v

    if isinstance(model, model_ns.SetModel):
        if any(o.f not in F_IDS for o in ops) or \
                any(o.f == "add" and o.value is None for o in ops) or \
                any(e is None for e in model.s):
            return _no_kernel(n)
        # Dense element ids: initial elements first, then history order.
        initial_ids = [intern(e) for e in sorted(model.s, key=repr)]
        for o in ops:
            if o.f == "add":
                intern(o.value)
            elif o.f == "read":
                try:
                    for e in (o.value if o.value is not None else ()):
                        intern(e)
                except TypeError:
                    pass
        n_elements = max(1, len(intern.values))
        n_words = -(-n_elements // K.SET_BITS)
        if n_words > MAX_SET_WORDS:
            return _no_kernel(n)
        kernel = K.set_kernel(n_elements, initial_ids)
        op_f, op_v = tables(kernel.value_width)
        for i, o in enumerate(ops):
            op_f[i] = F_IDS[o.f]
            if o.f == "add":
                op_v[i, 0] = intern(o.value)
            elif o.f == "read":
                try:
                    elems = [intern(e) for e in o.value] \
                        if o.value is not None else None
                except TypeError:
                    elems = None
                if elems is not None and int(NIL) in elems:
                    # A None element can never be in the state (nil adds
                    # were rejected above), so this read can never match.
                    elems = None
                if elems is not None:
                    # Observed mask; all-NIL (never matches) when the
                    # read's value is not a collection (= inconsistent).
                    op_v[i, :n_words] = 0
                    for e in elems:
                        op_v[i, e // K.SET_BITS] |= np.int32(
                            1 << (e % K.SET_BITS))
        return kernel, kernel.init_state(), op_f, op_v

    if isinstance(model, (model_ns.UnorderedQueue, model_ns.FIFOQueue)):
        initial = list(model.pending)
        if any(o.f not in F_IDS for o in ops) \
                or any(v is None for v in initial) \
                or any(o.f == "enqueue" and o.value is None for o in ops):
            return _no_kernel(n)
        initial_ids = [intern(v) for v in initial]
        for o in ops:
            if o.f in ("enqueue", "dequeue") and o.value is not None:
                intern(o.value)
        if isinstance(model, model_ns.FIFOQueue):
            depth = _max_queue_depth(ops, len(initial))
            if depth > MAX_FIFO_CAP:
                return _no_kernel(n)
            kernel = K.fifo_queue_kernel(max(1, depth), initial_ids)
        else:
            n_values = max(1, len(intern.values))
            enq_ids = initial_ids + [intern(o.value) for o in ops
                                     if o.f == "enqueue"]
            if len(set(enq_ids)) == len(enq_ids):
                # All enqueued values distinct: pending multiset is a set,
                # packed as a bitmask (31 values/word).
                n_words = -(-n_values // K.SET_BITS)
                if n_words > MAX_SET_WORDS:
                    return _no_kernel(n)
                kernel = K.unordered_unique_kernel(n_values, initial_ids)
            elif n_values <= MAX_QUEUE_VALUES:
                kernel = K.unordered_queue_kernel(n_values, initial_ids)
            else:
                return _no_kernel(n)
        op_f, op_v = tables(kernel.value_width)
        for i, o in enumerate(ops):
            op_f[i] = F_IDS[o.f]
            if o.f in ("enqueue", "dequeue"):
                # A nil dequeue interns to NIL, which is never legal — the
                # same verdict the Python models give (None not in pending,
                # since nil enqueues were rejected above).
                op_v[i, 0] = intern(o.value)
        return kernel, kernel.init_state(), op_f, op_v

    return _no_kernel(n)


def _kernelize_vec(model, ops: list[LinOp], intern: _Interner):
    """Vectorized twin of :func:`_kernelize` for the fixed-layout band
    (register / cas-register / mutex) over int-or-None values: the
    first-occurrence interner becomes one ``np.unique`` + argsort pass.
    Returns the spec-identical ``(kernel, init_state, op_f, op_v)`` or
    None when the model/value domain defeats the vector form (caller
    falls back to the spec loop, which handles everything)."""
    if not isinstance(model, (model_ns.CASRegister, model_ns.Register,
                              model_ns.Mutex)):
        return None
    n = len(ops)
    fs = [o.f for o in ops]
    get = F_IDS.get
    op_f = np.fromiter((get(f, -1) for f in fs), np.int64, n) \
        if n else np.zeros(0, np.int64)
    bad = np.flatnonzero(op_f < 0)
    if bad.size:
        raise UnsupportedHistory(
            f"unknown op f={fs[int(bad[0])]!r} for device packing")
    kernel = kernel_for(model)
    # The intern-call sequence, in the exact order the spec loop makes
    # them: model.value first (registers), then per op — assembled by
    # np.repeat over per-op entry counts (cas: 2 words, read/write: 1,
    # else 0) with a small fix-up loop over the cas subset only.
    f_cas = F_IDS.get("cas", -9)
    f_read = F_IDS.get("read", -9)
    f_write = F_IDS.get("write", -9)
    is_cas = op_f == f_cas
    ent = is_cas * 2 + ((op_f == f_read) | (op_f == f_write))
    tgt_i_ops = np.repeat(np.arange(n, dtype=np.int64), ent)
    m_ops = len(tgt_i_ops)
    tgt_w_ops = np.zeros(m_ops, np.int64)
    starts = np.cumsum(ent) - ent        # seq start of each op's run
    ci = np.flatnonzero(is_cas)
    tgt_w_ops[starts[ci] + 1] = 1        # second cas word
    vlist = [o.value for o in ops]
    arr = np.empty(n, object)
    arr[:] = vlist
    seq = np.repeat(arr, ent).tolist()   # cas slots hold the pair; fix:
    for j, i in zip(starts[ci].tolist(), ci.tolist()):
        v = vlist[i]
        if not isinstance(v, (list, tuple)) or len(v) != 2:
            raise UnsupportedHistory(
                f"cas value must be a pair: {v!r}")
        seq[j] = v[0]
        seq[j + 1] = v[1]
    if isinstance(model, model_ns.Mutex):
        tgt_i = tgt_i_ops
        tgt_w = tgt_w_ops
    else:
        seq.insert(0, model.value)
        tgt_i = np.concatenate([np.full(1, -1, np.int64), tgt_i_ops])
        tgt_w = np.concatenate([np.zeros(1, np.int64), tgt_w_ops])
    m = len(seq)
    flags = bytearray(m)                 # 1 where seq[j] is a live int
    vals_list: list = []
    vapp = vals_list.append
    lo, hi = -(1 << 62), 1 << 62
    for j, v in enumerate(seq):
        if v is None:
            continue
        if type(v) is not int or v < lo or v >= hi:
            return None                  # non-int domain: spec interner
        flags[j] = 1
        vapp(v)
    ids_all = np.full(m, int(NIL), np.int64)
    nn = np.frombuffer(bytes(flags), bool)
    vals = np.array(vals_list, np.int64) \
        if vals_list else np.zeros(0, np.int64)
    if vals.size:
        uniq, first, inverse = np.unique(vals, return_index=True,
                                         return_inverse=True)
        rank = np.argsort(first, kind="stable")   # first-occurrence order
        idmap = np.empty(len(uniq), np.int64)
        idmap[rank] = np.arange(len(uniq))
        ids_all[nn] = idmap[inverse]
        intern.values = uniq[rank].tolist()
        intern.ids = {v: i for i, v in enumerate(intern.values)}
    op_v = np.full((n, kernel.value_width), int(NIL), np.int32)
    ti = np.asarray(tgt_i, np.int64)
    tw = np.asarray(tgt_w, np.int64)
    opm = ti >= 0
    op_v[ti[opm], tw[opm]] = ids_all[opm].astype(np.int32)
    if isinstance(model, model_ns.Mutex):
        init_state = kernel.init_state()
    else:
        init_state = np.array([int(ids_all[0])], np.int32)
    return kernel, init_state, op_f.astype(np.int32), op_v


def _pack_events_vec(invoke_pos, return_pos, op_f, op_v, max_window,
                     fill_fv, R):
    """Vectorized twin of the packing walk (JEPSEN_TPU_FAST_PACK): the
    sequential LIFO free-list becomes sort/cumsum passes. An invoke pops
    the most recently freed slot — i.e. returns are opens, invokes are
    closes, and bracket-matching pairs each non-fresh invoke with the
    return whose slot it reuses (within one stack level, opens and
    closes strictly alternate). Fresh invokes (those popping the virgin
    region — exactly the running-min depth records) take slots 0,1,2...
    in order; slots propagate along reuse chains by pointer doubling,
    and the R x W snapshot tables are painted as per-op row intervals
    (cumsum of endpoint deltas). Bit-identical to _pack_events_py
    (fuzzed in tests/test_fast_pack.py); returns arrays already at the
    live window width."""
    n = len(invoke_pos)
    vw = op_v.shape[1]
    nil = int(NIL)
    has_ret = return_pos >= 0
    ret_ids = np.flatnonzero(has_ret)
    ev_pos = np.concatenate([np.asarray(invoke_pos, np.int64),
                             np.asarray(return_pos, np.int64)[ret_ids]])
    ev_op = np.concatenate([np.arange(n, dtype=np.int64), ret_ids])
    n_inv = n
    order = np.argsort(ev_pos, kind="stable")   # endpoint positions unique
    kind_ret = order >= n_inv
    op_s = ev_op[order]
    delta = np.where(kind_ret, -1, 1)
    depth = np.cumsum(delta)
    W_used = int(depth.max(initial=0))
    if W_used > max_window:
        t = int(np.flatnonzero(depth > max_window)[0])
        raise UnsupportedHistory(
            f"concurrency window exceeds {max_window} pending ops "
            f"at history position {int(ev_pos[order[t]])}", kind="window")
    W = max(1, W_used)
    slot = np.zeros(n, np.int32)
    if n:
        # Fresh invokes: the recycle stack is empty exactly when the
        # return-minus-invoke running sum hits a new minimum.
        sigma = np.cumsum(-delta)
        runmin = np.minimum.accumulate(np.minimum(sigma, 0))
        prev_runmin = np.empty_like(runmin)
        prev_runmin[0] = 0
        prev_runmin[1:] = runmin[:-1]
        fresh = (~kind_ret) & (sigma < prev_runmin)
        fresh_ops = op_s[fresh]
        slot_root = np.full(n, -1, np.int32)
        slot_root[fresh_ops] = np.arange(len(fresh_ops), dtype=np.int32)
        # Bracket-match recycled invokes to the return they reuse.
        sub = kind_ret | ((~kind_ret) & ~fresh)
        si = np.flatnonzero(sub)
        lev = sigma - runmin             # stack size after event
        lv = np.where(kind_ret[si], lev[si], lev[si] + 1)
        so = np.argsort(lv, kind="stable")
        ss = si[so]
        lvs = lv[so]
        run_first = np.empty(len(ss), bool)
        if len(ss):
            run_first[0] = True
            run_first[1:] = lvs[1:] != lvs[:-1]
        base = np.maximum.accumulate(
            np.where(run_first, np.arange(len(ss)), 0))
        rank = np.arange(len(ss)) - base
        mpair = rank % 2 == 1            # close at odd rank matches prev
        parent = np.arange(n, dtype=np.int64)
        parent[op_s[ss[mpair]]] = op_s[ss[np.flatnonzero(mpair) - 1]]
        while True:
            pp = parent[parent]
            if np.array_equal(pp, parent):
                break
            parent = pp
        slot = slot_root[parent]
    ret_op = op_s[kind_ret].astype(np.int32)
    ret_slot = slot[ret_op]
    # Row intervals: op i is active in rows [r0, r1) at column slot[i].
    ret_pos_sorted = ev_pos[order[kind_ret]]
    r0 = np.searchsorted(ret_pos_sorted, np.asarray(invoke_pos, np.int64))
    r1 = np.full(n, R, np.int64)
    r1[ret_op] = np.arange(R) + 1
    # Column-major paint (cumsum along the contiguous axis) of op id + 1.
    occ = np.zeros((W, R + 1), np.int32)
    flat = occ.reshape(-1)
    col = slot.astype(np.int64)
    ids1 = np.arange(1, n + 1, dtype=np.int32)
    np.add.at(flat, col * (R + 1) + r0, ids1)
    np.subtract.at(flat, col * (R + 1) + r1, ids1)
    np.cumsum(occ, axis=1, out=occ)
    grid = np.ascontiguousarray(occ[:, :R].T)    # (R, W) op id + 1
    active = grid != 0
    slot_op = grid - 1
    if fill_fv:
        # slot_op is -1 at inactive cells: a sentinel row appended to the
        # per-op tables makes the plain fancy-index land on the inactive
        # fill values there, skipping two full-grid np.where passes.
        op_f_ext = np.concatenate([op_f.astype(np.int32, copy=False),
                                   np.zeros(1, np.int32)])
        op_v_ext = np.concatenate([op_v.astype(np.int32, copy=False),
                                   np.full((1, vw), nil, np.int32)])
        slot_f = op_f_ext[slot_op]
        slot_v = op_v_ext[slot_op]
    else:
        slot_f = np.zeros((R, W), np.int32)
        slot_v = np.full((R, W, vw), nil, np.int32)
    return ret_slot, ret_op, active, slot_f, slot_v, slot_op, W_used


def _pack_events_native(invoke_pos, return_pos, op_f, op_v, max_window,
                        fill_fv, R):
    """The packing walk via native/history_pack.cc (ctypes). None when the
    native library is unavailable or disabled (JEPSEN_TPU_NATIVE_PACK=0
    — fault isolation for the ctypes layer, and the pack bench rung's
    pure-Python spec leg)."""
    if os.environ.get("JEPSEN_TPU_NATIVE_PACK", "") == "0":
        return None
    from jepsen_tpu import native_ext

    try:
        out = native_ext.pack_events(
            invoke_pos, return_pos, op_f, op_v[:, 0], op_v[:, 1],
            nil_value=int(NIL), max_window=max_window,
            fill_fv=fill_fv, R=R)
    except native_ext.WindowOverflow as e:
        raise UnsupportedHistory(
            f"concurrency window exceeds {max_window} pending ops "
            f"at history position {e.pos}", kind="window") from None
    return out


def _pack_events_py(invoke_pos, return_pos, op_f, op_v, max_window,
                    fill_fv, R):
    """Pure-Python packing walk (semantics twin of jtpu_pack_events)."""
    n = len(invoke_pos)
    W_alloc = max_window
    vw = op_v.shape[1]
    ret_slot = np.zeros(R, np.int32)
    ret_op = np.zeros(R, np.int32)
    active = np.zeros((R, W_alloc), bool)
    slot_f = np.zeros((R, W_alloc), np.int32)
    slot_v = np.full((R, W_alloc, vw), int(NIL), np.int32)
    slot_op = np.full((R, W_alloc), -1, np.int32)

    # Event stream over op endpoints: (pos, kind, op_id); invokes before
    # returns at equal positions can't happen (distinct history positions).
    events: list[tuple[int, int, int]] = []
    for i in range(n):
        events.append((int(invoke_pos[i]), 0, i))
        if return_pos[i] >= 0:
            events.append((int(return_pos[i]), 1, i))
    events.sort()

    free = list(range(W_alloc))[::-1]
    slot_of: dict[int, int] = {}
    cur_active: dict[int, int] = {}   # slot -> op id
    max_used = 0
    r = 0
    for pos, kind, i in events:
        if kind == 0:  # invoke
            if not free:
                raise UnsupportedHistory(
                    f"concurrency window exceeds {max_window} pending ops "
                    f"at history position {pos}", kind="window")
            s = free.pop()
            slot_of[i] = s
            cur_active[s] = i
            max_used = max(max_used, s + 1)
        else:  # ok return
            s = slot_of[i]
            ret_slot[r] = s
            ret_op[r] = i
            for slot, op_id in cur_active.items():
                active[r, slot] = True
                slot_op[r, slot] = op_id
                if fill_fv:
                    slot_f[r, slot] = op_f[op_id]
                    slot_v[r, slot] = op_v[op_id]
            r += 1
            del cur_active[s]
            del slot_of[i]
            free.append(s)
    return ret_slot, ret_op, active, slot_f, slot_v, slot_op, max_used


def prepare(model, history, max_window: int = MAX_WINDOW) -> PackedHistory:
    """Pack a history for the frontier search. See module docstring.

    The vectorized fast path (JEPSEN_TPU_FAST_PACK, default on) runs the
    pairing, interning, and slot walk as numpy passes producing output
    BIT-IDENTICAL to the Python spec walk (fuzzed in
    tests/test_fast_pack.py); ``=0`` — or data the vector form does not
    cover — takes the spec path below unchanged."""
    from jepsen_tpu.obs import trace as obs_trace

    t_start = time.perf_counter()
    history = list(history)
    fast = fast_pack_enabled()
    with obs_trace.span("prepare", events=len(history),
                        mode="vec" if fast else "spec") as sp:
        ok_col = None
        if fast:
            ops, invoke_pos, return_pos, ok_col = \
                _pair_ops_vec_arrays(history)
        else:
            ops = pair_ops(history)
        intern = _Interner()

        # Per-op (f, values) interned ONCE up front — the packing walk
        # below references ops (R x W) times, never re-interning.
        kv = _kernelize_vec(model, ops, intern) if fast else None
        if kv is None:
            kernel, init_state, op_f, op_v = _kernelize(
                model, ops, intern)
        else:
            kernel, init_state, op_f, op_v = kv

        n = len(ops)
        if ok_col is not None:
            R = int(ok_col.sum())
        else:
            R = sum(1 for o in ops if o.ok)
            invoke_pos = np.fromiter(
                (o.invoke_pos for o in ops), np.int32, n)
            return_pos = np.fromiter(
                (-1 if o.return_pos is None else o.return_pos
                 for o in ops), np.int32, n)

        fill_fv = kernel is not None
        packed = None
        mode = "vec"
        if fast:
            packed = _pack_events_vec(
                invoke_pos, return_pos, op_f, op_v, max_window, fill_fv,
                R)
        if packed is None and op_v.shape[1] == 2:
            # the native walk is specialized to 2-word values
            mode = "native"
            packed = _pack_events_native(
                invoke_pos, return_pos, op_f, op_v, max_window, fill_fv,
                R)
        if packed is None:
            mode = "py"
            packed = _pack_events_py(
                invoke_pos, return_pos, op_f, op_v, max_window, fill_fv,
                R)
        ret_slot, ret_op, active, slot_f, slot_v, slot_op, max_used = \
            packed

        if ok_col is not None:
            crashed = [ops[i] for i in np.flatnonzero(~ok_col).tolist()]
        else:
            crashed = [o for o in ops if o.return_pos is None]

        # Per-slot crashed mask. CONSUMED BY THE DEVICE ENGINES: the
        # crashed-op canonical chains (reduction_tables) and the sparse
        # engine's crashed-subset dominance prune (bfs.expansion_tables
        # builds its key-space crash masks from this; bfs.check_packed
        # gates the prune on it) — its semantics ("this active slot's op
        # never returns") are exactness-critical, not just reporting.
        # Sentinel append: slot_op = -1 (inactive) wraps to a live value,
        # and the & active keeps those cells False, matching the old
        # masked scatter exactly.
        ret_ext = np.concatenate(
            [return_pos.astype(np.int32, copy=False),
             np.zeros(1, np.int32)])
        crashed_tbl = (ret_ext[slot_op] < 0) & active

        W = max(1, max_used)
        out = PackedHistory(
            model=model, kernel=kernel, ops=ops, window=W, R=R,
            ret_slot=ret_slot, ret_op=ret_op,
            active=active[:, :W], slot_f=slot_f[:, :W],
            slot_v=slot_v[:, :W], slot_op=slot_op[:, :W],
            crashed=crashed_tbl[:, :W],
            init_state=init_state, intern=intern.ids,
            unintern=intern.values, crashed_ops=crashed)
        # Per-op interned tables ride along for the vectorized chain
        # core (reduction_tables); views rebuilt elsewhere (service
        # codec, stream packer) recover them from the slot tables.
        out._op_fv = (op_f, op_v, invoke_pos)
        sp.note(n_ops=n, R=R, W=W, walk=mode)
    _pack_stats["prepare_s"] += time.perf_counter() - t_start
    _pack_stats["prepare_calls"] += 1
    _pack_stats["mode"] = mode
    return out


# --- search-space reductions -------------------------------------------------
#
# Two exact (verdict- and death-row-preserving) reductions of the frontier
# search, consumed by the CPU oracle and the sparse device engine. Both are
# new to this build — knossos has no analogue; they are what lets the sparse
# band (windows 21..64, e.g. cockroach's concurrency-30 registers,
# cockroach.clj:40-41) stay tractable where the JVM search DNFs.
#
# 1. **Pure-op saturation.** A pure op (one whose step never changes state:
#    register/set reads) need not branch the search. Its linearization
#    point can be ANY moment its legality predicate holds between invoke
#    and return, so the search just marks its bit the first moment the
#    config's state matches ("greedy read linearization"). Soundness: read
#    bits are only ever tested positively at the op's return and never
#    affect other transitions, so greedily setting them dominates; any
#    plain survivor maps to a greedy survivor of the same row and vice
#    versa. This removes pure ops from the exponential branching entirely.
#
# 2. **Canonical chains.** Two concurrently-pending identical ops
#    (same f, same value — e.g. two pending write(3)s, two mutex acquires)
#    are exchangeable: swapping their linearization points yields another
#    valid linearization. LIVE ops chain by return order (both intervals
#    cover both points while both are pending, and the earlier-returning
#    interval is the binding one); CRASHED ops chain among themselves by
#    invoke order (their windows never close, so any point past the later
#    invoke lies in every earlier sibling's window). The two families
#    never cross — a crashed op cannot stand in for a live one whose
#    window ends at its return. Slot j with an unlinearized canonical
#    predecessor is blocked until the predecessor's bit is set.
#
# (A third reduction — dominance pruning over crashed-op subsets and
# read bits — lives in the device engine's dedup, jepsen_tpu.lin.bfs
# ._dedup_keys_dom, since it prunes between configs rather than gating
# transitions.)
#
# Config counts on a 2k-op concurrency-30 register history (window 28):
# plain search >170k configs by row 40 (DNF); with both reductions the
# peak frontier is ~20k and the whole history closes.


def _chain_tables_vec(active, slot_f, slot_v, slot_op, op_ordkey,
                      op_crashed, op_f_ops=None, op_v_ops=None):
    """The canonical-chain core of :func:`reduction_tables`, vectorized
    (JEPSEN_TPU_FAST_PACK): the per-row 6-key lexsort becomes one
    rank-compressed int32 key per slot — class rank (lexicographic over
    (f<<1|crashed, value words), via one O(n log n) sort over OPS) in
    the high bits, per-op ordkey rank in the low bits — and a single
    stable per-row argsort. Strictly order-isomorphic to the spec's
    lexsort tuple, so the stable sorts produce identical permutations
    and the identical ``pred``. Shared by the one-shot path and the
    IncrementalPacker (which passes position-based ordkeys).

    ``op_ordkey`` i64[n]: return row / position, crashed past every live
    (unique per op). ``op_crashed`` bool[n]."""
    n_rows, W = active.shape
    pure_fs = {int(K.F_IDS[f]) for f in ("read",) if f in K.F_IDS}
    if len(pure_fs) == 1:
        pure = active & (slot_f == np.int32(next(iter(pure_fs))))
    else:
        pure = active & np.isin(slot_f, list(pure_fs))
    if n_rows == 0:
        return pure, np.full((n_rows, W), -1, np.int32)

    n = len(op_ordkey)
    # Per-op class rank, lexicographic over (f<<1|crashed, v words).
    if op_f_ops is None:
        # Recover per-op f/v from the slot tables (constant per op;
        # every op the chains reference is active in some row).
        op_f_ops = np.zeros(n, np.int64)
        op_v_ops = np.full((n, slot_v.shape[2]), int(NIL), np.int64)
        lin = active.ravel()
        ops_flat = slot_op.ravel()[lin]
        op_f_ops[ops_flat] = slot_f.ravel()[lin]
        op_v_ops[ops_flat] = slot_v.reshape(-1, slot_v.shape[2])[lin]
    else:
        op_f_ops = np.asarray(op_f_ops, np.int64)
        op_v_ops = np.asarray(op_v_ops, np.int64)
    cls_cols = [op_v_ops[:, k] for k in
                range(op_v_ops.shape[1] - 1, -1, -1)]
    cls_cols.append((op_f_ops << 1) | op_crashed)
    o_ops = np.lexsort(tuple(cls_cols))
    chg = np.zeros(n, bool)
    if n > 1:
        for c in cls_cols:
            cs = c[o_ops]
            chg[1:] |= cs[1:] != cs[:-1]
    # Ranks fit int32 for any n < 2^31; int32 fancy-indexing of the
    # (R, W) grids is ~6x faster than int64 on this box.
    cid_sorted = np.cumsum(chg, dtype=np.int32)
    class_rank = np.empty(n, np.int32)
    class_rank[o_ops] = cid_sorted
    n_classes = int(cid_sorted[-1]) + 1 if n else 0
    # Per-op ordkey rank (ordkeys are unique per op).
    ord_rank = np.empty(n, np.int32)
    ord_rank[np.argsort(op_ordkey, kind="stable")] = np.arange(
        n, dtype=np.int32)

    ob = max(1, n).bit_length()
    cb = max(1, W + n_classes).bit_length()
    dtype = np.int32 if (ob + cb) <= 31 else np.int64
    chainable = active & ~pure & (slot_op >= 0)
    # slot_op = -1 wraps to the last op's rank: harmless garbage, masked
    # by ``chainable`` at every use below.
    cls_slot = (class_rank[slot_op] + np.int32(W)).astype(
        dtype, copy=False)
    ord_slot = ord_rank[slot_op].astype(dtype, copy=False)
    sent_cls = (W - 1 - np.arange(W, dtype=dtype))[None, :]
    key = np.where(chainable,
                   (cls_slot << np.array(ob, dtype)) | ord_slot,
                   sent_cls << np.array(ob, dtype))
    idt = np.int32 if n_rows * W < (1 << 31) else np.int64
    order = np.argsort(key, axis=1, kind="stable").astype(
        idt, copy=False)
    cls_key = np.where(chainable, cls_slot, sent_cls).astype(
        np.int32, copy=False)
    # Flat int32 gathers/scatters in place of take/put_along_axis (the
    # int64 index paths are several times slower on this box). Row
    # permutations never collide, so the scatter is well-defined.
    flat = order + (np.arange(n_rows, dtype=idt) * idt(W))[:, None]
    cs = cls_key.ravel()[flat]
    same = cs[:, 1:] == cs[:, :-1]
    pred = np.full(n_rows * W, -1, np.int32)
    pred[flat[:, 1:]] = np.where(same, order[:, :-1], np.int32(-1))
    return pure, pred.reshape(n_rows, W)


def reduction_tables(p: PackedHistory) -> tuple[np.ndarray, np.ndarray]:
    """Per-row reduction tables ``(pure, pred)`` for a packed history.

    pure: bool[R, W] — active slot holds a pure (state-preserving) op.
    pred: i32[R, W]  — canonical-chain predecessor slot (-1 when none):
    slot j may linearize in row r only once ``pred[r, j]``'s bit is set.
    Cached on the PackedHistory after first computation.
    """
    cached = getattr(p, "_reduction_tables", None)
    if cached is not None:
        return cached

    R, W = p.active.shape
    if p.kernel is None or R == 0:
        out = (np.zeros((R, W), bool), np.full((R, W), -1, np.int32))
        p._reduction_tables = out
        return out

    if fast_pack_enabled():
        t0 = time.perf_counter()
        n = len(p.ops)
        ret_row = np.full(n, -1, np.int64)
        ret_row[np.asarray(p.ret_op)] = np.arange(R)
        fv = getattr(p, "_op_fv", (None, None))
        if len(fv) > 2:
            inv_pos = fv[2].astype(np.int64, copy=False)
        else:
            inv_pos = np.fromiter((o.invoke_pos for o in p.ops),
                                  np.int64, n)
        crashed_op = ret_row < 0
        ordkey = np.where(crashed_op, np.int64(R + 2) + inv_pos, ret_row)
        out = _chain_tables_vec(p.active, p.slot_f, p.slot_v, p.slot_op,
                                ordkey, crashed_op, fv[0], fv[1])
        p._reduction_tables = out
        _pack_stats["reduction_s"] += time.perf_counter() - t0
        _pack_stats["reduction_calls"] += 1
        return out
    t0 = time.perf_counter()

    pure_fs = {int(K.F_IDS[f]) for f in ("read",)
               if f in K.F_IDS}
    pure = p.active & np.isin(p.slot_f, list(pure_fs))

    # Return row per slot occurrence: the row at which this slot's op
    # returns; crashed ops get a sentinel past any row.
    NEVER = np.int32(R + 1)
    ret_row_of_op = np.full(len(p.ops), NEVER, np.int64)
    ret_row_of_op[np.asarray(p.ret_op)] = np.arange(R)
    slot_ret = np.where(p.slot_op >= 0,
                        ret_row_of_op[np.clip(p.slot_op, 0, None)], NEVER)

    # Chainable = active, not pure. Identical LIVE ops chain in return
    # order (the earlier-returning interval is the binding one). Identical
    # CRASHED ops (:info, never return — their windows extend to the end
    # of history) chain in INVOKE order: any linearization using a later
    # chain member maps to one using the invoke-order prefix at the same
    # points (each point lies past the later member's invoke, hence past
    # every earlier member's), so WLOG the prefix linearizes first. The
    # two families never cross (a crashed op cannot stand in for a live
    # one whose window ends at its return): the class key carries a
    # crashed flag. This collapses the 2^k subset blowup of k identical
    # crashed mutators — the partitioned-nemesis history shape
    # (BASELINE config 5) — to the k+1 prefixes.
    invoke_of_op = np.fromiter((o.invoke_pos for o in p.ops), np.int64,
                               len(p.ops))
    slot_inv = np.where(p.slot_op >= 0,
                        invoke_of_op[np.clip(p.slot_op, 0, None)], 0)
    is_crashed = slot_ret >= NEVER
    ordkey = np.where(is_crashed, np.int64(R + 2) + slot_inv, slot_ret)

    chainable = p.active & ~pure & (p.slot_op >= 0)
    sent = -1 - np.arange(W, dtype=np.int64)          # unique per column
    f_key = np.where(
        chainable,
        (p.slot_f.astype(np.int64) << 1) | is_crashed,
        sent[None, :])
    v_keys = [p.slot_v[:, :, k].astype(np.int64)
              for k in range(p.slot_v.shape[2])]

    # Row-wise canonical order: sort slots by (class, return row | invoke
    # position); equal classes become adjacent runs in canonical order.
    order = np.lexsort(tuple([ordkey] + v_keys[::-1] + [f_key]), axis=1)
    rows = np.arange(R)[:, None]
    f_s = np.take_along_axis(f_key, order, axis=1)
    same = f_s[:, 1:] == f_s[:, :-1]
    for vk in v_keys:
        v_s = np.take_along_axis(vk, order, axis=1)
        same &= v_s[:, 1:] == v_s[:, :-1]
    pred = np.full((R, W), -1, np.int32)
    cols = order[:, 1:]
    prev = order[:, :-1]
    np.put_along_axis(
        pred, cols, np.where(same, prev, -1).astype(np.int32), axis=1)
    out = (pure, pred)
    p._reduction_tables = out
    _pack_stats["reduction_s"] += time.perf_counter() - t0
    _pack_stats["reduction_calls"] += 1
    return out


# --- pure-python packed step (mirror of models.kernels, for the CPU
# reference checker's inner loop and witness replay) -------------------------

def py_step_fn(kernel_name: str) -> Callable:
    """Python twin of the device step kernels, operating on
    (state tuple, f id, value ids) — must agree exactly with
    jepsen_tpu.models.kernels (parity-tested)."""
    from jepsen_tpu.models import kernels as K

    nil = int(K.NIL)

    if kernel_name in ("cas-register", "register"):
        allow_cas = kernel_name == "cas-register"

        def step(state, f, v):
            cur = state[0]
            if f == K.F_READ:
                return (v[0] == nil or v[0] == cur), state
            if f == K.F_WRITE:
                return True, (v[0],)
            if f == K.F_CAS and allow_cas:
                if v[0] == cur:
                    return True, (v[1],)
                return False, state
            return False, state

        return step

    if kernel_name == "mutex":
        def step(state, f, v):
            locked = state[0]
            if f == K.F_ACQUIRE:
                return locked == 0, (1,)
            if f == K.F_RELEASE:
                return locked == 1, (0,)
            return False, state

        return step

    if kernel_name == "set":
        def step(state, f, v):
            if f == K.F_ADD:
                e = v[0]
                if e == nil:
                    return False, state
                w, b = divmod(e, K.SET_BITS)
                s = list(state)
                s[w] |= 1 << b
                return True, tuple(s)
            if f == K.F_READ:
                return tuple(v[:len(state)]) == tuple(state), state
            return False, state

        return step

    if kernel_name == "unordered-unique":
        def step(state, f, v):
            e = v[0]
            if e == nil:
                return False, state
            w, b = divmod(e, K.SET_BITS)
            has = bool((state[w] >> b) & 1)
            if f == K.F_ENQUEUE and not has:
                s = list(state)
                s[w] |= 1 << b
                return True, tuple(s)
            if f == K.F_DEQUEUE and has:
                s = list(state)
                s[w] &= ~(1 << b)
                return True, tuple(s)
            return False, state

        return step

    if kernel_name == "unordered-queue":
        def step(state, f, v):
            e = v[0]
            if f == K.F_ENQUEUE:
                s = list(state)
                s[e] += 1
                return True, tuple(s)
            if f == K.F_DEQUEUE:
                if 0 <= e < len(state) and state[e] > 0:
                    s = list(state)
                    s[e] -= 1
                    return True, tuple(s)
                return False, state
            return False, state

        return step

    if kernel_name == "fifo-queue":
        def step(state, f, v):
            size, buf = state[0], state[1:]
            if f == K.F_ENQUEUE:
                if size >= len(buf):
                    return False, state
                s = list(buf)
                s[size] = v[0]
                return True, (size + 1, *s)
            if f == K.F_DEQUEUE:
                if size > 0 and buf[0] == v[0]:
                    return True, (size - 1, *buf[1:], 0)
                return False, state
            return False, state

        return step

    raise ValueError(f"no python step for kernel {kernel_name!r}")
