"""SVG rendering of linearizability counterexamples.

The analogue of knossos.linear.report/render-analysis!, which the reference
invokes when a history is non-linearizable to produce ``linear.svg``
(`jepsen/src/jepsen/checker.clj:96-103`). Draws per-process swimlanes of the
operations in the neighbourhood of the failure: one bar per op spanning
invocation → completion, the inconsistent op highlighted, and the surviving
configurations' model states printed beneath.

Self-contained XML string assembly — no plotting dependency.
"""

from __future__ import annotations

from xml.sax.saxutils import escape

from jepsen_tpu.history import Op

BAR_H = 22
LANE_GAP = 10
LEFT_MARGIN = 110
TOP_MARGIN = 34
PX_PER_COL = 46          # one column per history event in the window
TYPE_FILL = {"ok": "#a8e6a1", "info": "#ffd9a8", "fail": "#f4a6a6"}
BAD_FILL = "#ff5555"
CONTEXT_OPS = 24         # ops on either side of the failure to draw


def _op_label(f, value) -> str:
    if value is None:
        return str(f)
    if isinstance(value, (list, tuple)):
        return f"{f} {' '.join(str(v) for v in value)}"
    return f"{f} {value}"


def _window(history: list[Op], analysis: dict) -> list[tuple[Op, Op | None]]:
    """Invoke/completion pairs near the failing op, in invocation order."""
    pairs: list[tuple[Op, Op | None]] = []
    pending: dict = {}
    for op in history:
        if op.process == "nemesis":
            continue
        if op.is_invoke:
            pending[op.process] = len(pairs)
            pairs.append((op, None))
        elif op.process in pending:
            i = pending.pop(op.process)
            pairs[i] = (pairs[i][0], op)

    bad = (analysis or {}).get("op") or {}
    bad_index = bad.get("index")
    center = len(pairs) - 1
    if bad_index is not None:
        for i, (inv, _) in enumerate(pairs):
            if inv.index == bad_index:
                center = i
                break
    lo = max(0, center - CONTEXT_OPS)
    hi = min(len(pairs), center + CONTEXT_OPS + 1)
    return pairs[lo:hi]


def _event_columns(history: list[Op],
                   pairs: list[tuple[Op, Op | None]]) \
        -> tuple[dict[int, int], dict[int, float], int]:
    """Column per invocation and completion, ordered by history position,
    so concurrent ops visually overlap: a bar spans its invocation event's
    column to its completion event's column."""
    pos = {id(op): i for i, op in enumerate(history)}
    events = []
    for inv, comp in pairs:
        events.append((pos.get(id(inv), 0), 0, id(inv)))
        if comp is not None:
            events.append((pos.get(id(comp), len(history)), 1, id(inv)))
    events.sort()
    inv_col: dict[int, int] = {}
    comp_col: dict[int, float] = {}
    for col, (_, kind, key) in enumerate(events):
        if kind == 0:
            inv_col[key] = col
        else:
            comp_col[key] = col + 0.8
    return inv_col, comp_col, max(1, len(events))


def render_analysis(history, analysis: dict, path) -> str:
    """Write an SVG counterexample for an invalid analysis to ``path``;
    returns the SVG text (knossos.linear.report/render-analysis! parity)."""
    history = list(history)
    pairs = _window(history, analysis)
    bad = (analysis or {}).get("op") or {}

    processes = []
    for inv, _ in pairs:
        if inv.process not in processes:
            processes.append(inv.process)
    lane_of = {p: i for i, p in enumerate(processes)}

    inv_col, comp_col, n_cols = _event_columns(history, pairs)
    width = LEFT_MARGIN + (n_cols + 1) * PX_PER_COL + 40
    height = (TOP_MARGIN + len(processes) * (BAR_H + LANE_GAP)
              + 30 + 16 * min(6, len((analysis or {}).get("configs", [])))
              + (16 if (analysis or {}).get("final-paths") else 0))

    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="sans-serif" font-size="11">',
           f'<text x="8" y="16" font-size="13">Non-linearizable: '
           f'{escape(_op_label(bad.get("f"), bad.get("value")))} by process '
           f'{escape(str(bad.get("process")))}</text>']

    for inv, comp in pairs:
        lane = lane_of[inv.process]
        y = TOP_MARGIN + lane * (BAR_H + LANE_GAP)
        x0 = LEFT_MARGIN + inv_col[id(inv)] * PX_PER_COL
        x1 = (LEFT_MARGIN + comp_col[id(inv)] * PX_PER_COL
              if comp is not None
              else LEFT_MARGIN + n_cols * PX_PER_COL)
        ctype = comp.type if comp is not None else "info"
        is_bad = (bad.get("index") is not None
                  and inv.index == bad.get("index"))
        fill = BAD_FILL if is_bad else TYPE_FILL.get(ctype, "#d0d0d0")
        out.append(
            f'<rect x="{x0:.0f}" y="{y}" width="{max(8, x1 - x0):.0f}" '
            f'height="{BAR_H}" rx="3" fill="{fill}" stroke="#555"/>')
        label = _op_label(inv.f, comp.value if comp is not None
                          and inv.f == "read" else inv.value)
        out.append(f'<text x="{x0 + 4:.0f}" y="{y + 15}">'
                   f'{escape(label)}</text>')

    for p, lane in lane_of.items():
        y = TOP_MARGIN + lane * (BAR_H + LANE_GAP) + 15
        out.append(f'<text x="8" y="{y}">process {escape(str(p))}</text>')

    # Path badges: number the ops along the first final-path (the
    # linearization order that reached a dying config), so the SVG shows
    # HOW the search got stuck, not just where (knossos render parity).
    paths = (analysis or {}).get("final-paths") or []
    first_path = next((fp.get("path") for fp in paths
                       if isinstance(fp, dict) and fp.get("path")), None)
    if first_path:
        order_of = {o.get("index"): i + 1 for i, o in enumerate(first_path)
                    if isinstance(o, dict) and o.get("index") is not None}
        for inv, comp in pairs:
            n = order_of.get(inv.index)
            if n is None:
                continue
            lane = lane_of[inv.process]
            y = TOP_MARGIN + lane * (BAR_H + LANE_GAP)
            x0 = LEFT_MARGIN + inv_col[id(inv)] * PX_PER_COL
            out.append(f'<circle cx="{x0:.0f}" cy="{y:.0f}" r="8" '
                       f'fill="#4a6fd4"/>')
            out.append(f'<text x="{x0 - 3:.0f}" y="{y + 4:.0f}" '
                       f'fill="#fff" font-size="10">{n}</text>')

    y = TOP_MARGIN + len(processes) * (BAR_H + LANE_GAP) + 16
    if first_path:
        steps = " -> ".join(_op_label(o.get("f"), o.get("value"))
                            for o in first_path if isinstance(o, dict))
        out.append(f'<text x="8" y="{y}" fill="#333">path: '
                   f'{escape(steps)}</text>')
        y += 16
    for cfg in (analysis or {}).get("configs", [])[:6]:
        model = cfg.get("model") if isinstance(cfg, dict) else cfg
        pend = cfg.get("pending", []) if isinstance(cfg, dict) else []
        pend_s = ", ".join(_op_label(o.get("f"), o.get("value"))
                           for o in pend if isinstance(o, dict))
        out.append(f'<text x="8" y="{y}" fill="#333">config: model='
                   f'{escape(repr(model))}'
                   f'{escape(" pending=[" + pend_s + "]" if pend_s else "")}'
                   f'</text>')
        y += 16

    out.append("</svg>")
    svg = "\n".join(out)
    with open(path, "w") as fh:
        fh.write(svg)
    return svg
