"""`make mesh-smoke`: the crash-dom mesh engine's chip-free habit.

The serve/txn/trace/stream/perf/pack-smoke pattern for the sharded
compact band (lin/sharded.py, doc/sharding.md): a FRESH-process proof
on the forced 8-device virtual CPU mesh that

- a crash-dom register history (crashed mutators => the forced-lax
  dominance prune is live) DECIDES on the mesh with verdict parity vs
  the ``lin/cpu.py`` oracle, and its corrupted twin dies on the SAME
  op — with the per-device mesh-stats counters attached to both
  verdicts,
- a ``JEPSEN_TPU_WEDGE=mesh-chunk`` injected run (the supervision test
  hook, quarantine ledger redirected to a throwaway path) returns an
  HONEST ``overflow: wedge`` unknown — never a hang, never a flipped
  verdict — with the watchdog trip counted in its mesh-stats, and
- the smoke's own perf-ledger record carries the mesh sub-dict
  (dispatches / dispatch-wall-s / peak-occupancy) so `cli.py perf
  report` trends the mesh path like every other surface.

Prints one JSON result line and exits 0/1 — timeout-guarded by the
Makefile so a wedge cannot hold the shell.
"""

from __future__ import annotations

import json
import os
import sys
import time


def main() -> int:
    t_start = time.time()
    # 8-device CPU mesh BEFORE any jax backend init (CLAUDE.md: the TPU
    # plugin force-selects its platform; the smoke must never take the
    # chip, and the mesh needs the virtual device count).
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    from jax.sharding import Mesh

    from jepsen_tpu import models as m, util
    from jepsen_tpu.lin import cpu, prepare, sharded, supervise, synth

    util.enable_compile_cache()
    mesh = Mesh(np.array(jax.devices()[:8]), ("d",))
    out: dict = {"checks": []}
    ok = True

    # A crash-dom shape: crashed mutators put the forced-lax dominance
    # prune (and its collective analogue) on the hot path; small caps
    # keep the mesh programs seconds-scale on the CPU backend.
    h = list(synth.generate_register_history(
        60, concurrency=5, seed=11, value_range=4, crash_prob=0.25,
        max_crashes=5))
    p = prepare.prepare(m.cas_register(), h)
    assert p.crashed.any(), "smoke history must carry crashed mutators"

    def mesh_check(pp):
        return sharded.check_packed(pp, mesh=mesh, cap_schedule=(8, 512),
                                    engine="sparse")

    # 1. Round trip: valid history decides, verdict parity vs oracle,
    # mesh-stats flowing (crash-dom band, per-device occupancy).
    want = cpu.check_packed(p)["valid?"]
    r = mesh_check(p)
    ms = r.get("mesh-stats", {})
    good = (r["valid?"] == want
            and ms.get("crash-dom") is True
            and ms.get("devices") == 8
            and ms.get("dispatches", 0) >= 1
            and len(ms.get("peak-occupancy", [])) == 8)
    out["checks"].append({"case": "crash-dom-valid", "want": want,
                          "got": r["valid?"], "mesh": ms, "ok": good})
    ok = ok and good
    mesh_rec = ms

    # 2. Corrupted twin: same violating op as the oracle.
    pb = prepare.prepare(m.cas_register(),
                         list(synth.corrupt_history(h, seed=4)))
    wb = cpu.check_packed(pb, witness=True)
    rb = mesh_check(pb)
    good = (wb["valid?"] is False and rb["valid?"] is False
            and rb["op"]["index"] == wb["op"]["index"])
    out["checks"].append({"case": "crash-dom-corrupted",
                          "want_op": (wb.get("op") or {}).get("index"),
                          "got_op": (rb.get("op") or {}).get("index"),
                          "ok": good})
    ok = ok and good

    # 3. Wedge leg (LAST — leftover armed injections must not leak into
    # the parity legs): every mesh-chunk dispatch fake-wedges past a
    # 0.2 s deadline, so detection + the bounded retry both trip and
    # the engine must return an honest unknown, not hang or flip.
    os.environ["JEPSEN_TPU_QUARANTINE"] = os.path.join(
        util.cache_dir(), "mesh_smoke_quarantine.json")
    os.environ["JEPSEN_TPU_WEDGE"] = "mesh-chunk:8:0.2"
    supervise.reset_injections()
    supervise._env_wedge_loaded = None
    try:
        rw = mesh_check(p)
    finally:
        os.environ.pop("JEPSEN_TPU_WEDGE", None)
        os.environ.pop("JEPSEN_TPU_QUARANTINE", None)
        supervise.reset_injections()
    msw = rw.get("mesh-stats", {})
    good = (rw["valid?"] == "unknown"
            and rw.get("overflow") == "wedge"
            and msw.get("watchdog_trips", 0) >= 1)
    out["checks"].append({"case": "wedge-honest-unknown",
                          "got": rw["valid?"],
                          "overflow": rw.get("overflow"),
                          "trips": msw.get("watchdog_trips"),
                          "ok": good})
    ok = ok and good

    out["ok"] = ok
    # Cross-run perf ledger (doc/observability.md § Perf ledger): the
    # smoke's record carries the mesh sub-dict so `cli.py perf report`
    # trends mesh dispatch wall/occupancy. record() never raises — a
    # ledger failure cannot cost the smoke.
    from jepsen_tpu.obs import ledger as perf_ledger

    perf_ledger.record("mesh-smoke", kind="smoke",
                       wall_s=time.time() - t_start, verdict=ok,
                       extra={"mesh": mesh_rec})
    print(json.dumps(out, default=str))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
