"""The device linearizability kernel: BFS frontier over
(linearized-op-bitset x model-state) configurations.

This replaces the reference's exponential JVM search (knossos.linear /
knossos.wgl, selected at checker.clj:90-93) with a data-parallel formulation
designed for the TPU's compilation model:

- The frontier lives in fixed-capacity device arrays: ``bits: u32[CAP]``
  (which pending ops each config has linearized — slot-compressed by
  :mod:`jepsen_tpu.lin.prepare` so 32 bits cover the concurrency window,
  not the history length) and ``state: i32[CAP, S]`` (packed model state).
- One outer `lax.while_loop` walks the R return events. Each step runs the
  just-in-time closure as an inner `lax.while_loop`: candidate transitions
  are the full cross product (config x pending slot), evaluated in one shot
  by the branchless model step kernels (vmap x vmap) — this is the op that
  fills the vector units; there is no per-config control flow anywhere.
- Dedup is a lexicographic `lax.sort` over (invalid, bits, state) followed
  by adjacent-duplicate masking and a cumsum scatter compaction. Fixpoint
  is detected by the unique-config count not growing (the old frontier is
  part of the candidate pool, so the set is monotone).
- Static shapes throughout: frontier capacity CAP is a compile-time
  constant. Searches run on an escalating CAP schedule — almost all real
  histories need a tiny frontier, so the common case compiles small and
  fast, and only pathological histories pay for big buffers. Overflow is
  detected exactly (a lost config could flip the verdict) and escalates.

The same jitted function is the unit that :mod:`jepsen_tpu.lin.sharded`
shards over a device mesh and that the independent-keys checker vmaps over
batched per-key histories.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu.lin.prepare import PackedHistory

DEFAULT_CAP_SCHEDULE = (64, 1024, 16384)
MAX_DEVICE_WINDOW = 32


def _dedup(bits, state, valid, cap):
    """Sort-dedup-compact. Returns (bits[cap], state[cap,S], count, overflow).

    Invalid rows sort last; duplicates are adjacent after the lexicographic
    sort and masked; survivors are scatter-compacted to the front.
    """
    n = bits.shape[0]
    s_width = state.shape[1]
    inv = (~valid).astype(jnp.uint32)
    operands = (inv, bits) + tuple(state[:, k] for k in range(s_width))
    sorted_ops = lax.sort(operands, num_keys=len(operands))
    inv_s, bits_s = sorted_ops[0], sorted_ops[1]
    state_s = jnp.stack(sorted_ops[2:], axis=1)

    prev_differs = (bits_s != jnp.roll(bits_s, 1)) | \
        jnp.any(state_s != jnp.roll(state_s, 1, axis=0), axis=1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask & (pos < cap), pos, n)

    out_n = max(n, cap) + 1
    out_bits = jnp.zeros(out_n, jnp.uint32).at[idx].set(bits_s)[:cap]
    out_state = jnp.zeros((out_n, s_width), jnp.int32) \
        .at[idx].set(state_s)[:cap]
    count = jnp.minimum(total, cap)
    return out_bits, out_state, count, overflow


@partial(jax.jit, static_argnames=("cap", "step_fn"))
def _search(ret_slot, active, slot_f, slot_v, init_state, *, cap, step_fn):
    """Run the full search. Returns (ok, dead_row, overflow, final_count).

    ret_slot: i32[R]; active: bool[R,W]; slot_f: i32[R,W];
    slot_v: i32[R,W,VW]; init_state: i32[S].
    """
    R, W = active.shape
    S = init_state.shape[0]

    bits0 = jnp.zeros(cap, jnp.uint32)
    state0 = jnp.zeros((cap, S), jnp.int32) \
        .at[0].set(init_state)
    count0 = jnp.int32(1)

    step_cfg_slot = jax.vmap(                 # over configs
        jax.vmap(step_fn, in_axes=(None, 0, 0)),   # over slots
        in_axes=(0, None, None))

    slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

    def closure_cond(c):
        _, _, count, prev, ovf = c
        return (count != prev) & ~ovf

    def row_body(carry):
        r, bits, state, count, dead, ovf = carry
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        s = ret_slot[r]

        def closure_body(c):
            bits, state, count, prev, ovf = c
            cfg_valid = jnp.arange(cap) < count

            # the hot op: every (config x pending-slot) transition at once
            ok, new_state = step_cfg_slot(state, f_row, v_row)
            already = (bits[:, None] & slot_bit[None, :]) != 0
            legal = ok & act[None, :] & ~already & cfg_valid[:, None]
            new_bits = bits[:, None] | slot_bit[None, :]

            cand_bits = jnp.concatenate([bits, new_bits.reshape(-1)])
            cand_state = jnp.concatenate(
                [state, new_state.reshape(-1, S)], axis=0)
            cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

            b2, s2, n2, o2 = _dedup(cand_bits, cand_state, cand_valid, cap)
            return (b2, s2, n2, count, ovf | o2)

        init = (bits, state, count, jnp.int32(-1), ovf)
        bits, state, count, _, ovf = lax.while_loop(
            closure_cond, closure_body, init)

        # Filter: the returning op's linearization point must precede its
        # return; then recycle its slot bit.
        s_bit = jnp.uint32(1) << s.astype(jnp.uint32)
        cfg_valid = jnp.arange(cap) < count
        keep = cfg_valid & ((bits & s_bit) != 0)
        bits = bits & ~s_bit
        bits, state, count, o2 = _dedup(bits, state, keep, cap)
        dead = count == 0
        return (r + 1, bits, state, count, dead, ovf | o2)

    def row_cond(carry):
        r, _, _, _, dead, ovf = carry
        return (r < R) & ~dead & ~ovf

    r, bits, state, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), bits0, state0, count0, False, False))
    # dead_row is the row at which the frontier died (r was incremented)
    return ~dead & ~ovf, r - 1, ovf, count


def _pad_rows(p: PackedHistory):
    """Bucket R up to a power of two with identity rows so XLA compiles one
    kernel per bucket instead of one per history length.

    An identity row uses a dedicated pad slot (column W) carrying the
    universal no-op f: every config linearizes it (state unchanged), the
    filter keeps everyone, and the recycle clears the bit — frontier exactly
    preserved. Requires one spare bit, so only applied when window < 32.
    """
    from jepsen_tpu.models.kernels import F_NOOP

    R, W = p.active.shape
    R_pad = 1 << max(4, (R - 1).bit_length())
    if R_pad == R or W >= MAX_DEVICE_WINDOW:
        return (np.asarray(p.ret_slot), np.asarray(p.active),
                np.asarray(p.slot_f), np.asarray(p.slot_v))

    pad = R_pad - R
    ret_slot = np.concatenate([p.ret_slot, np.full(pad, W, np.int32)])
    active = np.zeros((R_pad, W + 1), bool)
    active[:R, :W] = p.active
    active[R:, W] = True
    slot_f = np.zeros((R_pad, W + 1), np.int32)
    slot_f[:R, :W] = p.slot_f
    slot_f[R:, W] = F_NOOP
    slot_v = np.zeros((R_pad, W + 1, p.slot_v.shape[2]), np.int32)
    slot_v[:R, :W] = p.slot_v
    return ret_slot, active, slot_f, slot_v


def check_packed(p: PackedHistory,
                 cap_schedule=DEFAULT_CAP_SCHEDULE) -> dict:
    """Decide linearizability of a packed history on device."""
    if p.kernel is None:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"no device kernel for {type(p.model).__name__}"}
    if p.window > MAX_DEVICE_WINDOW:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"concurrency window {p.window} exceeds device "
                         f"bitset width {MAX_DEVICE_WINDOW}"}
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-bfs", "configs": []}

    ret_slot_h, active_h, slot_f_h, slot_v_h = _pad_rows(p)
    ret_slot = jnp.asarray(ret_slot_h)
    active = jnp.asarray(active_h)
    slot_f = jnp.asarray(slot_f_h)
    slot_v = jnp.asarray(slot_v_h)
    init_state = jnp.asarray(p.init_state)

    for cap in cap_schedule:
        ok, dead_row, overflow, count = _search(
            ret_slot, active, slot_f, slot_v, init_state,
            cap=cap, step_fn=p.kernel.step)
        overflow = bool(overflow)
        if not overflow:
            break
    if overflow:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"frontier exceeded capacity {cap_schedule[-1]}"}

    if bool(ok):
        return {"valid?": True, "analyzer": "tpu-bfs",
                "configs": [], "final-frontier-size": int(count)}
    r = int(dead_row)
    ret = p.ops[int(p.ret_op[r])]
    return {"valid?": False, "analyzer": "tpu-bfs",
            "op": {"process": ret.process, "f": ret.f, "value": ret.value,
                   "index": ret.op_index, "ok": ret.ok},
            "configs": [], "final-paths": []}
