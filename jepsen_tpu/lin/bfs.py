"""The sparse device linearizability kernel: BFS frontier over
(linearized-op-bitset x model-state) configurations.

This replaces the reference's exponential JVM search (knossos.linear /
knossos.wgl, selected at checker.clj:90-93) with a data-parallel
formulation designed for the TPU's compilation model:

- The frontier lives in fixed-capacity device arrays: ``bits: u32[CAP,NW]``
  (which pending ops each config has linearized — slot-compressed by
  :mod:`jepsen_tpu.lin.prepare` so NW*32 bits cover the concurrency
  window, not the history length; NW is 1 for windows <= 32, 2 up to 64)
  and ``state: i32[CAP, S]`` (packed model state).
- One `lax.while_loop` walks the R return events. Each step runs the
  just-in-time closure as an inner `lax.while_loop`: candidate transitions
  are the full cross product (config x pending slot), evaluated in one shot
  by the branchless model step kernels (vmap x vmap) — this is the op that
  fills the vector units; there is no per-config control flow anywhere.
- Dedup is a lexicographic `lax.sort` over (invalid, bits, state) followed
  by adjacent-duplicate masking and a cumsum-gather compaction. When the
  window plus a compact state id fit in 31 bits, the whole config packs
  into ONE u32 sort key (several times faster on TPU).
- Static shapes throughout: frontier capacity CAP is a compile-time
  constant. Searches run on an escalating CAP schedule — almost all real
  histories need a tiny frontier, so the common case compiles small and
  fast, and only pathological histories pay for big buffers. Overflow is
  detected exactly (a lost config could flip the verdict) and escalates.

This engine is the wide-window fallback: histories whose window and state
count fit the dense config-space bitmap (:mod:`jepsen_tpu.lin.dense`,
window <= 20 and <= 32 states) are routed there instead
(`jepsen_tpu.lin.device_check_packed`), which absorbs crash-heavy
histories for free. For the band outside the dense bounds — windows
21..64, value-rich registers, set/queue states — two EXACT search-space
reductions keep the frontier tractable (prepare.reduction_tables:
pure-op saturation and canonical chains; knossos has neither), and
frontier spikes past the chunked engine's largest runtime-safe
512-row-chunk capacity re-run as SPIKE_CHUNK-row mini-chunks of the
same program at capacities up to ~1M configs (it is rows-times-capacity
program complexity the runtime objects to, not capacity). Only when
even that overflows does the verdict become an honest "unknown"
(competition then falls back to the host search).
"""

from __future__ import annotations

import os
import time as _time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu import util
from jepsen_tpu.lin import psort, psort_fused, supervise
from jepsen_tpu.lin.prepare import PackedHistory
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace

# Caps for the nested-while chunked engine. 131072 is the largest level
# at which a full 512-row chunk program holds up on the axon TPU
# runtime: the same program at 262144 kernel-faults the worker (the
# components — sorts to 32M elements, the vmapped step, the expansion
# algebra — are each fine standalone at that scale; it is the rows×cap
# program COMPLEXITY that trips the runtime: 8/32/64-row chunks all run
# clean at cap 2^20, 512 faults at 2^18). Frontier spikes past this cap
# therefore switch to SPIKE_CHUNK-row mini-chunks of the same program
# at the SPIKE_CAP_SCHEDULE capacities (32 keeps a 16x margin to the
# known-bad 512 while amortizing dispatch overhead).
DEFAULT_CAP_SCHEDULE = (256, 2048, 16384, 131072)
# The compact packed-key register band adapts INSIDE the program (see
# ROW_TIERS: per-row count-tiered prefixes), so its chunk-level ladder
# only needs a small level (cheap compile, covers most histories and
# the CPU test mesh) and a top level chosen so that even the TOP
# tier's grouped dedups stay inside the windowed dominance bound
# (131072 * (1 + Mg=1) = 2^18 = psort.DOM_WINDOW_MAX_N): partition
# histories' crashed-subset waves (BASELINE config 5) are only held
# down by the windowed prune, so every capacity must carry it. Spikes
# past 131072 go to the (grouped, unwindowed) spike executor.
PACKED_CAP_SCHEDULE = (16384, 131072)
SPIKE_CAP_SCHEDULE = (262144, 524288, 1048576)
SPIKE_CHUNK = 32
# Chunks dispatched between host flag syncs on the optimistic fast
# path: each device->host flag fetch pays the ~100 ms tunnel round
# trip, so checking every chunk costs more than the 512 rows of
# compute it gates. Flags are fetched for SYNC_CHUNKS chunks in one
# transfer; a tripped flag rewinds to the batch entry and replays
# chunk-by-chunk (escalation/spike/dead handling live there).
# 2 by default: queueing 8 unsynced chunk programs on the axon worker
# "kernel-faulted" it on the 100k partitioned history in round 4 — but
# round 5 attributed that round's faults to the grouped-closure orbit
# (an infinite in-program loop the watchdog kills), so the queue-depth
# blame was never re-established. Env JEPSEN_TPU_SYNC_CHUNKS overrides
# so the bench can re-test deeper queues on the literal config-5
# history (fault-isolated in its probe subprocess) and gate the value
# on evidence instead of superstition.
SYNC_CHUNKS = 2
# Frontier size at which spike mode hands back to full-size chunks (at
# a mini-chunk boundary with count at most this).
SPIKE_DROPBACK = 32768
MAX_DEVICE_WINDOW = 64
CHUNK = 512

# In-chunk tier ceiling for the pair-key crash-dom band (the 100k
# partitioned-history class, BASELINE config 5). Round-5 probes on the
# exact faulting history discriminated the fault: the GROUP-CYCLING
# closure path (G > 1 — the lax.dynamic_slice expansion-group subpass
# machinery inside the nested while) kernel-faults the axon worker at
# the first partition wave (chunk 1536, G=17 at tier 16384), while the
# same pad-2^18 windowed quad dedups run clean in-chunk when UNGROUPED
# (G=1) and clean standalone at any pad to 2^19. At or below this tier
# the DOM_WINDOW_MAX_N grouping bound gives Mg >= 63 >= M for every
# pair-band history (M <= W <= 57), so in-chunk closure is always
# ungrouped; rows needing more overflow OUT of the chunk program into
# the host-row executor (_host_rows), whose grouping is host-sequenced
# numpy slicing — no in-program slice path exists there.
# Env JEPSEN_TPU_TIER_CAP overrides for fault triage.
CHUNK_TIER_CAP = 65536

# Host-row mode: a blowup row's closure passes run as SINGLE-dispatch
# programs sequenced from the host — no nested while, no tier switch —
# with the dominance window engaged at every capacity (dom_force) and
# the expansion UNGROUPED (all M columns per pass). Ungrouped matters
# for termination, not just shape: with grouped passes the frontier is
# a function of (input, group) and can enter a period-G orbit under
# the content-dependent windowed prune (observed live: count
# oscillating 4124<->4110 forever at row 1579 of the 100k partitioned
# history) — which inside a nested lax.while_loop is an infinite loop
# the runtime kills, i.e. the very "kernel fault" that blocked this
# class. Ungrouped passes make the frontier a deterministic function
# of itself alone, so the changed-vs-input fixpoint terminates.
HOST_ROW_CAPS = (4096, 16384, 65536, 262144, 524288)

# The crash-dom band's in-chunk candidate bound (tier*(1+Mg)): large
# enough that closure stays UNGROUPED (G=1) at every tier up to
# CHUNK_TIER_CAP for any window (Mg >= M always) — grouping is the
# nontermination hazard, and the band's dominance dedups force the lax
# chain path regardless of size, so no psort/window size gate applies.
# Env JEPSEN_TPU_CAND_MAX overrides for fault triage.
CHUNK_CAND_MAX = 1 << 22


def _tier_cap() -> int:
    env = os.environ.get("JEPSEN_TPU_TIER_CAP", "")
    return int(env) if env else CHUNK_TIER_CAP


def _cand_max() -> int:
    """Resolved ONCE per check_packed call and threaded into
    _search_chunk as a static argname (like max_tier), so an env change
    between checks in one process retraces instead of silently reusing
    the previously traced grouping."""
    env = os.environ.get("JEPSEN_TPU_CAND_MAX", "")
    return int(env) if env else CHUNK_CAND_MAX


def _sync_chunks() -> int:
    env = os.environ.get("JEPSEN_TPU_SYNC_CHUNKS", "")
    return max(1, int(env)) if env else SYNC_CHUNKS


def _fused_closure() -> bool:
    """The host-row closure fixpoint runs as ONE device while_loop
    program per (row, capacity) by default; ``JEPSEN_TPU_FUSED_CLOSURE=0``
    falls back to one dispatch per closure pass (the round-5 shape) for
    fault triage on the real chip."""
    return os.environ.get("JEPSEN_TPU_FUSED_CLOSURE", "1") != "0"


def _host_sticky() -> bool:
    """Sticky-cap escalation in the host-row executor: the wave's last
    converged capacity level seeds the next row's starting level, so a
    blowup wave stops re-climbing the ladder from ``lvl_for(count)``
    on every row (each failed climb is a full fused fixpoint run whose
    output is thrown away). Host-side scheduling only — the escalate-
    on-overflow semantics (and therefore soundness) are untouched.
    ``JEPSEN_TPU_HOST_STICKY=0`` restores the round-6 cold ladder for
    fault triage and A/B timing."""
    return os.environ.get("JEPSEN_TPU_HOST_STICKY", "1") != "0"


def _host_rows_k() -> int:
    """Rows per fused multi-row closure program (the wave fast path):
    a same-cap wave of K consecutive rows costs ~1 dispatch per K rows
    instead of one per row. Default 4 — well inside the runtime-safety
    envelope (8-row chunks run clean at cap 2^20; rows*cap program
    complexity is the fault driver, round-3/5 lore).
    ``JEPSEN_TPU_HOST_ROWS_K=1`` forces the proven one-row-per-dispatch
    round-6 path (also forced when FUSED_CLOSURE=0)."""
    env = os.environ.get("JEPSEN_TPU_HOST_ROWS_K", "")
    return max(1, int(env)) if env else 4


def _host_it_max(W: int) -> int:
    """Closure pass budget per (row, capacity) in the host-row executor:
    ungrouped convergence needs O(window) passes; the ceiling converts a
    would-be nontermination into an honest budget overflow. Env
    JEPSEN_TPU_HOST_IT_MAX overrides for fault triage and tests."""
    env = os.environ.get("JEPSEN_TPU_HOST_IT_MAX", "")
    return int(env) if env else 4 * W + 16


def _host_sched() -> bool:
    """Device-resident episode SCHEDULER (the kill-the-tunnel
    tentpole): the host-row wave LOOP itself runs on device —
    one ``lax.while_loop`` over a row QUEUE whose body is the proven
    per-row fixpoint + filter pipeline, with the escalation decision
    (trip on overflow/budget/death) made in-program and only per-row
    trip metadata returned. ~1 dispatch per clean EPISODE (up to
    ``JEPSEN_TPU_SCHED_QUEUE`` rows) instead of per K=4 wave rows.
    ``JEPSEN_TPU_HOST_SCHED=0`` restores the round-7 wave executor for
    fault triage and A/B timing (also forced off by
    ``JEPSEN_TPU_FUSED_CLOSURE=0``)."""
    return os.environ.get("JEPSEN_TPU_HOST_SCHED", "1") != "0"


def _sched_queue() -> int:
    """Rows per scheduler episode program. Default 32 — the largest
    row count proven clean at the big caps on this runtime (32-row
    spike mini-chunks ran clean at cap 2^20; rows*cap program
    complexity is the fault driver, round-2/3/5 lore), so the queue
    stays inside the probed envelope while amortizing ~32 rows per
    tunnel round trip. ``JEPSEN_TPU_SCHED_QUEUE`` overrides for fault
    triage and envelope probes."""
    env = os.environ.get("JEPSEN_TPU_SCHED_QUEUE", "")
    return max(2, int(env)) if env else 32


KEY_FILL = jnp.uint32(0xFFFFFFFF)  # pad beyond count; sorts after any config


def _dedup_keys(key, valid, cap, use_psort: bool = False):
    """Single-u32-key sort-dedup (invalid flag in bit 31), compacted by a
    SECOND sort: survivors keep their key, duplicates/invalid become
    KEY_FILL, so sorting packs survivors (still ascending) to the front.
    Two plain sorts, no searchsorted and no big gather — both of which
    kernel-fault the axon TPU runtime past ~2^17-row frontiers, while
    lax.sort is proven safe standalone to 32M elements here.

    With ``use_psort`` (and a size within the in-VMEM bound) both sorts
    plus the masking run as ONE pallas kernel with the keys resident in
    VMEM (:mod:`jepsen_tpu.lin.psort`) — 3-30x faster than the
    stage-overhead-bound lax.sort at frontier sizes.

    Returns (keys[cap] ascending + KEY_FILL padding, count, overflow)."""
    n = key.shape[0]
    if use_psort and psort.available(n):
        return psort.dedup_keys(key, valid, cap)
    key = key | ((~valid).astype(jnp.uint32) << 31)
    key_s = lax.sort(key)
    inv_s = key_s >> 31

    prev_differs = key_s != jnp.roll(key_s, 1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap
    out = lax.sort(jnp.where(mask, key_s, KEY_FILL))[:cap]
    count = jnp.minimum(total, cap)
    return out, count, overflow


def _dedup_keys2(hi, lo, valid, cap, use_psort: bool = False):
    """Pair-key twin of _dedup_keys for 64-bit packed configs (hi, lo
    u32 words, lexicographic order, invalid flag in hi bit 31). Routes
    to the in-VMEM pallas pair kernel when sized for it, else two
    two-operand lax.sorts. Returns (hi[cap], lo[cap], count,
    overflow)."""
    n = hi.shape[0]
    if use_psort and psort.available(n):
        return psort.dedup_keys2(hi, lo, valid, cap)
    hi = hi | ((~valid).astype(jnp.uint32) << 31)
    hi_s, lo_s = lax.sort((hi, lo), num_keys=2)
    dup = (hi_s == jnp.roll(hi_s, 1)) & (lo_s == jnp.roll(lo_s, 1))
    first = jnp.arange(n) == 0
    mask = (hi_s >> 31 == 0) & (first | ~dup)
    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap
    hi2 = jnp.where(mask, hi_s, KEY_FILL)
    lo2 = jnp.where(mask, lo_s, KEY_FILL)
    hi_o, lo_o = lax.sort((hi2, lo2), num_keys=2)
    return hi_o[:cap], lo_o[:cap], jnp.minimum(total, cap), overflow


def _seg_first(c, start):
    """Segmented broadcast: value of the nearest start<=i position —
    Hillis-Steele over rolls (no gather/scatter; TPU-runtime-safe inside
    nested while loops). ``start`` must be True at position 0."""
    n = c.shape[0]
    f = c
    done = start
    d = 1
    while d < n:
        f = jnp.where(done, f, jnp.roll(f, d))
        done = done | jnp.roll(done, d)
        d <<= 1
    return f


def _dedup_keys_dom(key, valid, cap, cmask, rmask,
                    use_psort: bool = False, dom_force: bool = False,
                    dom_iters: int = 1):
    """Sort-dedup with DOMINANCE pruning over crashed-op and read bits.
    ``cmask``/``rmask`` are the key-space masks of this row's crashed
    and pure (read) slots.

    Config X dominates config Y when they agree on mutator bits and
    state, X consumed a SUBSET of Y's crashed ops, and X holds a
    SUPERSET of Y's read bits:

    - crashed ops never face the return filter (no return), so consuming
      fewer leaves strictly more future moves — and if X lacks a
      chain-predecessor bit Y holds, X may linearize that same-class
      predecessor (identical effect) and stays componentwise below;
    - read bits never gate anything except the read's own return filter,
      where more is strictly safer.

    Dominated configs are pruned EXACTLY against their group's first
    entry after sorting (group, crashed asc, ~reads asc): the crashed
    blowup of partition-shaped histories (BASELINE config 5) collapses
    to the untouched representative, and saturation stragglers fold
    into their fully-read twin. The group representative is broadcast
    with a segmented scan of rolls. Output is full-key ascending like
    _dedup_keys. Returns (keys[cap], count, overflow)."""
    n = key.shape[0]
    gmask = ~(cmask | rmask)
    a = (key & gmask) | ((~valid).astype(jnp.uint32) << 31)
    # The two dominance axes pack into ONE word: crashed bits as-is,
    # read bits complemented. The masks are disjoint, so "rep's crashed
    # set is a subset AND rep's reads a superset" is exactly "rep's
    # packed word is a subset" — one sort operand, one subset test.
    w = (key & cmask) | ((~key) & rmask)
    if use_psort and psort.available(n):
        return psort.dedup_keys_dom(a, w, cmask, rmask, cap,
                                    force_window=dom_force)
    a_s, w_s = lax.sort((a, w), num_keys=2)
    first = jnp.arange(n) == 0
    idx = jnp.arange(n)
    total = jnp.int32(0)
    keep = first
    for round_ in range(max(1, dom_iters if dom_force else 1)):
        if round_:
            # Compact survivors (order-preserving) so distant
            # dominators become chain-reachable — see _dedup_keys2_dom.
            fill = jnp.uint32(KEY_FILL)
            a_s = jnp.where(keep, a_s, fill)
            w_s = jnp.where(keep, w_s, fill)
            a_s, w_s = lax.sort((a_s, w_s), num_keys=2)
        dup = (a_s == jnp.roll(a_s, 1)) & (w_s == jnp.roll(w_s, 1)) \
            & ~first
        start = first | (a_s != jnp.roll(a_s, 1))
        f = _seg_first(w_s, start)
        dominated = ((f & ~w_s) == 0) & (w_s != f)
        # Windowed pairwise (psort.DOM_WINDOW): a subset sorts earlier,
        # so predecessors at small offsets catch the chain parents the
        # group representative misses.
        for dd in psort.dom_window(n, dom_force):
            a_d = jnp.roll(a_s, dd)
            w_d = jnp.roll(w_s, dd)
            dominated = dominated | (
                (idx >= dd) & (a_d == a_s) & ((w_d & ~w_s) == 0)
                & (w_d != w_s))
        if dom_force:
            # Chain scan over distances 1..DOM_CHAIN (psort.DOM_CHAIN):
            # loop-carried roll, exact predicate at every span.
            def chain_body(i, c):
                ra, rw, dom = c
                ra = jnp.roll(ra, 1)
                rw = jnp.roll(rw, 1)
                dom = dom | ((idx >= i) & (ra == a_s)
                             & ((rw & ~w_s) == 0) & (rw != w_s))
                return ra, rw, dom

            _, _, dominated = lax.fori_loop(
                1, psort.DOM_CHAIN + 1, chain_body,
                (a_s, w_s, dominated))
        keep = (a_s >> 31 == 0) & ~dup & ~dominated
        total = jnp.sum(keep.astype(jnp.int32))
    overflow = total > cap
    full = (a_s & 0x7FFFFFFF) | (w_s & cmask) | ((~w_s) & rmask)
    out = lax.sort(jnp.where(keep, full, KEY_FILL))
    return out[:cap], jnp.minimum(total, cap), overflow


def _dedup_keys2_dom(hi, lo, valid, cap, cmask_hi, cmask_lo,
                     rmask_hi, rmask_lo, use_psort: bool = False,
                     dom_force: bool = False, dom_iters: int = 1):
    """Pair-key twin of _dedup_keys_dom (see there): 4-operand sort by
    (group, dominance-word) pairs, group-representative dominance
    prune, full-key-ascending compaction. Routes to the in-VMEM pallas
    quad kernel when sized for it. With ``dom_force`` the prune also
    runs the chain scan, ITERATED ``dom_iters`` times: each round
    compacts survivors (preserving sort order), so previously-distant
    dominators become chain-reachable — iterated rounds approach the
    true antichain where one round is span-limited (measured on the
    100k partitioned history's mid-waves: one round leaves 500k+ live,
    overflowing every capacity). Returns (hi[cap], lo[cap], count,
    overflow)."""
    n = hi.shape[0]
    g_hi = ~(cmask_hi | rmask_hi)
    g_lo = ~(cmask_lo | rmask_lo)
    a_hi = (hi & g_hi) | ((~valid).astype(jnp.uint32) << 31)
    a_lo = lo & g_lo
    w_hi = (hi & cmask_hi) | ((~hi) & rmask_hi)
    w_lo = (lo & cmask_lo) | ((~lo) & rmask_lo)
    if use_psort and psort.available(n):
        return psort.dedup_keys2_dom(a_hi, a_lo, w_hi, w_lo, cmask_hi,
                                     cmask_lo, rmask_hi, rmask_lo, cap,
                                     force_window=dom_force)
    ah, al, wh, wl = lax.sort((a_hi, a_lo, w_hi, w_lo), num_keys=4)
    first = jnp.arange(n) == 0
    idx = jnp.arange(n)

    def eqp(x):
        return x == jnp.roll(x, 1)

    total = jnp.int32(0)
    keep = first
    for round_ in range(max(1, dom_iters if dom_force else 1)):
        if round_:
            # Compact survivors to a sorted prefix: masking to KEY_FILL
            # (invalid flag set) and re-sorting preserves the 4-word
            # lexicographic order among the living.
            fill = jnp.uint32(KEY_FILL)
            ah = jnp.where(keep, ah, fill)
            al = jnp.where(keep, al, fill)
            wh = jnp.where(keep, wh, fill)
            wl = jnp.where(keep, wl, fill)
            ah, al, wh, wl = lax.sort((ah, al, wh, wl), num_keys=4)
        dup = eqp(ah) & eqp(al) & eqp(wh) & eqp(wl) & ~first
        start = first | ~(eqp(ah) & eqp(al))
        fh = _seg_first(wh, start)
        fl = _seg_first(wl, start)
        dominated = ((fh & ~wh) == 0) & ((fl & ~wl) == 0) & \
            ~((wh == fh) & (wl == fl))
        for dd in psort.dom_window(n, dom_force):
            ah_d = jnp.roll(ah, dd)
            al_d = jnp.roll(al, dd)
            wh_d = jnp.roll(wh, dd)
            wl_d = jnp.roll(wl, dd)
            dominated = dominated | (
                (idx >= dd) & (ah_d == ah) & (al_d == al)
                & ((wh_d & ~wh) == 0) & ((wl_d & ~wl) == 0)
                & ~((wh_d == wh) & (wl_d == wl)))
        if dom_force:
            # Chain scan over distances 1..DOM_CHAIN (psort.DOM_CHAIN).
            def chain_body(i, c):
                rah, ral, rwh, rwl, dom = c
                rah = jnp.roll(rah, 1)
                ral = jnp.roll(ral, 1)
                rwh = jnp.roll(rwh, 1)
                rwl = jnp.roll(rwl, 1)
                dom = dom | (
                    (idx >= i) & (rah == ah) & (ral == al)
                    & ((rwh & ~wh) == 0) & ((rwl & ~wl) == 0)
                    & ~((rwh == wh) & (rwl == wl)))
                return rah, ral, rwh, rwl, dom

            _, _, _, _, dominated = lax.fori_loop(
                1, psort.DOM_CHAIN + 1, chain_body, (ah, al, wh, wl,
                                                     dominated))
        keep = (ah >> 31 == 0) & ~dup & ~dominated
        total = jnp.sum(keep.astype(jnp.int32))
    overflow = total > cap
    out_hi = jnp.where(
        keep, (ah & 0x7FFFFFFF) | (wh & cmask_hi) | ((~wh) & rmask_hi),
        KEY_FILL)
    out_lo = jnp.where(
        keep, al | (wl & cmask_lo) | ((~wl) & rmask_lo), KEY_FILL)
    hi_o, lo_o = lax.sort((out_hi, out_lo), num_keys=2)
    return hi_o[:cap], lo_o[:cap], jnp.minimum(total, cap), overflow


def _dedup(bits, state, valid, cap):
    """Sort-dedup-compact over multi-word configs. bits: u32[n, NW];
    state: i32[n, S]. Returns (bits[cap,NW], state[cap,S], count,
    overflow). Invalid rows sort last; duplicates are adjacent after the
    lexicographic sort and masked; survivors are compacted by a second
    rank-keyed sort (see _dedup_keys: searchsorted/gather compaction
    faults the TPU runtime at large caps)."""
    n, nw = bits.shape
    s_width = state.shape[1]
    inv = (~valid).astype(jnp.uint32)
    operands = (inv,) + tuple(bits[:, k] for k in range(nw)) \
        + tuple(state[:, k] for k in range(s_width))
    sorted_ops = lax.sort(operands, num_keys=len(operands))
    inv_s = sorted_ops[0]
    bits_s = jnp.stack(sorted_ops[1:1 + nw], axis=1)
    state_s = jnp.stack(sorted_ops[1 + nw:], axis=1)

    prev_differs = \
        jnp.any(bits_s != jnp.roll(bits_s, 1, axis=0), axis=1) | \
        jnp.any(state_s != jnp.roll(state_s, 1, axis=0), axis=1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap
    rank = jnp.where(mask, jnp.arange(n, dtype=jnp.int32), jnp.int32(n))
    packed = lax.sort((rank,) + tuple(bits_s[:, k] for k in range(nw))
                      + tuple(state_s[:, k] for k in range(s_width)),
                      num_keys=1)
    live = jnp.arange(cap) < total
    out_bits = jnp.where(live[:, None],
                         jnp.stack(packed[1:1 + nw], axis=1)[:cap], 0)
    out_state = jnp.where(live[:, None],
                          jnp.stack(packed[1 + nw:], axis=1)[:cap], 0)
    count = jnp.minimum(total, cap)
    return out_bits, out_state, count, overflow


def _slot_bits(W: int, nw: int):
    """u32[W, NW] table: row j has bit j%32 set in word j//32."""
    tbl = np.zeros((W, nw), np.uint32)
    for j in range(W):
        tbl[j, j // 32] = np.uint32(1) << (j % 32)
    return jnp.asarray(tbl)


# Expansion-column buckets: the compact tables are padded to the next
# bucket so one program serves a range of mutator widths.
_M_BUCKETS = (4, 8, 16, 32)


def _key_bit_words(pos):
    """(lo, hi) u32 masks for KEY-space bit position(s) ``pos`` (numpy
    int array; negative = no bit)."""
    pos = np.asarray(pos)
    live = pos >= 0
    lo = np.where(live & (pos < 32),
                  np.uint32(1) << (np.clip(pos, 0, 31).astype(np.uint32)),
                  np.uint32(0))
    hi = np.where(live & (pos >= 32),
                  np.uint32(1) << (np.clip(pos - 32, 0, 31)
                                   .astype(np.uint32)),
                  np.uint32(0))
    return lo, hi


def expansion_tables(p: PackedHistory, b: int, lazy: bool = True):
    """Host-side mutator-compacted expansion tables for the packed-key
    register band, in KEY space (config key = bitset << b | state-id,
    held as one u32 for window+b <= 31 or an (hi, lo) u32 pair up to
    60 — slot j lives at key bit b+j).

    Only active non-pure slots can branch the search (pure slots are
    absorbed by saturation, prepare.reduction_tables), yet the generic
    closure pass evaluates candidates for the full window — at
    cockroach-class concurrency (window ~26-30, half of it reads,
    cockroach.clj:40-41) more than half the candidate array and the
    model-step evaluation is dead weight. These tables gather each row's
    mutator slots into M <= window compact columns (M bucketed so one
    compiled program serves the history):

    exp_lo/exp_hi[R, M]      u32  slot key-bit (0 = padding)
    exp_f[R, M]              i32  function id
    exp_v[R, M, VW]          i32  interned value words
    exp_act[R, M]            bool column live
    exp_pred_lo/_hi[R, M]    u32  canonical-chain predecessor key-bit
    crash_lo/crash_hi[R]     u32  key-space mask of crashed slots
    read_lo/read_hi[R]       u32  key-space mask of pure (read) slots
                                  (both for the dominance prune)
    exp_jit[R, M]            bool column statically useful (see below)
    exp_rv_lo/_hi[R, M]      u32  key-space mask of active reads whose
                                  value equals the column's post-state

    ``exp_jit``/``exp_rv`` carry the JUST-IN-TIME linearization
    reduction (Lowe's JIT canonicalization, the idea behind
    knossos.linear): a mutator need only linearize when it (a) is the
    returner, (b) feeds the returner's precondition chain, or (c) makes
    a pending unheld read legal. Any valid linearization rewrites into
    this canonical form — a mutator linearized at a point satisfying
    none of (a)-(c) either moves to its first such point (its window
    extends there: live ops force (a) at their return row, crashed ops
    never close) or, if its effect is overwritten unobserved, drops
    from the sequence (the config without it dominates). (a)+(b) are
    static per row: ``exp_jit[r,k]`` = k is the returner or post(k)
    lies in the fixpoint P = {pre(returner)} growing by pre(m) for
    every mutator m with post(m) in P or read-observed. (c) is
    per-config: post(k) must match a read the config hasn't absorbed —
    ``exp_rv`` masks against the config's unheld read bits. Without
    this gate, the closure at a return row materializes the full
    reachability wave over pending-mutator subsets — measured >10^6
    transient configs on the 100k partitioned history (24 permanently
    pending crashed mutators) whose boundary frontiers are ~30 configs.

    Cached on the PackedHistory after first computation.
    """
    cached = getattr(p, "_expansion_tables", None)
    if cached is not None and cached[0] == (b, lazy):
        return cached[1]

    from jepsen_tpu.lin.prepare import reduction_tables
    from jepsen_tpu.models.kernels import F_CAS, F_WRITE, NIL

    pure, pred = reduction_tables(p)
    act = np.asarray(p.active)
    slot_f = np.asarray(p.slot_f)
    slot_v = np.asarray(p.slot_v)
    R, W = act.shape
    vw = slot_v.shape[2]
    mut = act & ~pure
    counts = mut.sum(axis=1)
    need = max(1, int(counts.max()) if R else 1)
    M = next((bk for bk in _M_BUCKETS if bk >= need), W)

    exp_lo = np.zeros((R, M), np.uint32)
    exp_hi = np.zeros((R, M), np.uint32)
    exp_f = np.zeros((R, M), np.int32)
    exp_v = np.zeros((R, M, vw), np.int32)
    exp_act = np.zeros((R, M), bool)
    exp_pred_lo = np.zeros((R, M), np.uint32)
    exp_pred_hi = np.zeros((R, M), np.uint32)
    exp_slot = np.full((R, M), -1, np.int64)

    rr, jj = np.nonzero(mut)
    mm = (mut.cumsum(axis=1) - 1)[rr, jj]
    exp_lo[rr, mm], exp_hi[rr, mm] = _key_bit_words(b + jj)
    exp_f[rr, mm] = slot_f[rr, jj]
    exp_v[rr, mm] = slot_v[rr, jj]
    exp_act[rr, mm] = True
    exp_slot[rr, mm] = jj
    pj = pred[rr, jj]
    pl_, ph_ = _key_bit_words(np.where(pj >= 0, b + pj, -1))
    exp_pred_lo[rr, mm] = pl_
    exp_pred_hi[rr, mm] = ph_

    crash_lo = np.zeros(R, np.uint32)
    crash_hi = np.zeros(R, np.uint32)
    cr, cj = np.nonzero(np.asarray(p.crashed) & act)
    cl_, ch_ = _key_bit_words(b + cj)
    np.bitwise_or.at(crash_lo, cr, cl_)
    np.bitwise_or.at(crash_hi, cr, ch_)
    read_lo = np.zeros(R, np.uint32)
    read_hi = np.zeros(R, np.uint32)
    pr_, pj_ = np.nonzero(pure & act)
    rl_, rh_ = _key_bit_words(b + pj_)
    np.bitwise_or.at(read_lo, pr_, rl_)
    np.bitwise_or.at(read_hi, pr_, rh_)

    # --- JIT-linearization gating tables (see docstring) ----------------
    exp_jit = np.ones((R, M), bool)
    exp_rv_lo = np.zeros((R, M), np.uint32)
    exp_rv_hi = np.zeros((R, M), np.uint32)
    if lazy and R:
        V = 1 << b
        # Post-state and precondition per column, as value-bitmasks over
        # interned ids (registers: write v -> v[0]; cas [cur,new] ->
        # pre cur, post new). Ids are < 2^b <= 64 by the packed-key
        # bound, so one u64 mask per row suffices.
        # NIL-valued words map to the nil state id (the register's nil
        # state is a real, reachable state: cas(None, x) runs from it
        # and write(None) re-enters it).
        nil_sid = max(len(p.unintern), 2)

        def as_sid(w):
            return np.where(w == NIL, nil_sid, w)

        is_cas = exp_f == F_CAS
        is_wr = exp_f == F_WRITE
        post = np.where(is_cas, as_sid(exp_v[:, :, 1]),
                        as_sid(exp_v[:, :, 0]))
        post = np.where(exp_act & (is_cas | is_wr), post, -1)
        pre_v = np.where(exp_act & is_cas, as_sid(exp_v[:, :, 0]), -1)

        def vbit(ids):
            ok = (ids >= 0) & (ids < V)
            return np.where(ok, np.uint64(1) << np.clip(ids, 0, V - 1)
                            .astype(np.uint64), np.uint64(0))

        post_bit = vbit(post)
        pre_bit = vbit(pre_v)
        # Read-observed values per row (NIL-valued reads match any state
        # and saturate unconditionally — they gate nothing).
        rv = np.where(pure & act & (slot_v[:, :, 0] != NIL)
                      & (slot_v[:, :, 0] >= 0),
                      slot_v[:, :, 0], -1)
        read_mask = np.bitwise_or.reduce(vbit(rv), axis=1)
        # Returner: its own column is always expandable; a cas returner
        # seeds the precondition fixpoint.
        ret = np.asarray(p.ret_slot)
        is_ret_col = exp_slot == ret[:, None]
        ret_f = slot_f[np.arange(R), ret]
        ret_pre = np.where(ret_f == F_CAS,
                           as_sid(slot_v[np.arange(R), ret, 0]), -1)
        P = vbit(ret_pre)
        # Fixpoint: pre(m) joins P for every mutator m whose post-state
        # is in P or read-observed (chain hops toward an observation).
        for _ in range(V):
            useful = (post_bit & (P | read_mask)[:, None]) != 0
            P2 = P | np.bitwise_or.reduce(
                np.where(useful, pre_bit, np.uint64(0)), axis=1)
            if np.array_equal(P2, P):
                break
            P = P2
        exp_jit = is_ret_col | ((post_bit & P[:, None]) != 0)
        # Per-value read masks in key space, gathered per column by its
        # post-state: rv_val[r, v] = OR of key bits of active reads of v.
        rv_lo_v = np.zeros((R, V), np.uint32)
        rv_hi_v = np.zeros((R, V), np.uint32)
        rr2, jj2 = np.nonzero((rv >= 0) & (rv < V))
        vv2 = rv[rr2, jj2]
        kl_, kh_ = _key_bit_words(b + jj2)
        np.bitwise_or.at(rv_lo_v, (rr2, vv2), kl_)
        np.bitwise_or.at(rv_hi_v, (rr2, vv2), kh_)
        pcl = np.clip(post, 0, V - 1)
        has_post = (post >= 0) & (post < V)
        exp_rv_lo = np.where(
            has_post, np.take_along_axis(rv_lo_v, pcl, axis=1), 0) \
            .astype(np.uint32)
        exp_rv_hi = np.where(
            has_post, np.take_along_axis(rv_hi_v, pcl, axis=1), 0) \
            .astype(np.uint32)

    out = (exp_lo, exp_hi, exp_f, exp_v, exp_act, exp_pred_lo,
           exp_pred_hi, crash_lo, crash_hi, read_lo, read_hi,
           exp_jit, exp_rv_lo, exp_rv_hi)
    p._expansion_tables = ((b, lazy), out)
    return out


def reduction_bit_tables(p: PackedHistory, nw: int):
    """Host-side (pure[R,W], pred_bit[R,W,nw]) from
    prepare.reduction_tables: pred slot indices become per-word bitmasks
    (all-zero when a slot has no chain predecessor)."""
    from jepsen_tpu.lin.prepare import reduction_tables

    pure, pred = reduction_tables(p)
    R, W = pred.shape
    pred_bit = np.zeros((R, W, nw), np.uint32)
    rr, jj = np.nonzero(pred >= 0)
    pj = pred[rr, jj]
    pred_bit[rr, jj, pj // 32] = np.uint32(1) << (pj % 32).astype(np.uint32)
    return pure, pred_bit


@partial(jax.jit, static_argnames=("cap", "step_fn", "state_bits",
                                   "nil_id", "read_value_match",
                                   "use_psort", "row_tiers", "key_hi",
                                   "crash_dom", "max_tier", "cand_max",
                                   "use_fused"))
def _search_chunk(n_rows, ret_slot, active, slot_f, slot_v, pure, pred_bit,
                  bits, state, count, exp_tables=None, *, cap, step_fn,
                  state_bits=None, nil_id=None, read_value_match=False,
                  use_psort=False, row_tiers=True, key_hi=False,
                  crash_dom=False, max_tier=None, cand_max=None,
                  use_fused=False):
    """Process up to n_rows return events (tables are CHUNK-row static
    shapes; rows past n_rows are ignored) starting from a carried frontier.

    The chunk is the unit of device dispatch: every chunk of every history
    reuses the same compiled program per (cap, step_fn), each program runs
    for bounded time (no watchdog kills on 100k-row histories), and a
    transient frontier spike re-runs one chunk at a bigger cap instead of
    the whole search.

    ``pure``/``pred_bit`` carry the exact search-space reductions of
    prepare.reduction_tables: pure[C,W] marks state-preserving slots —
    these never branch the search; instead every config greedily absorbs
    the bit of each legal pure slot (saturation). pred_bit[C,W,NW] is the
    canonical-chain gate: slot j may linearize only in configs that
    already hold its identical earlier-returning sibling's bit.

    With ``state_bits`` set (windows <= 31 - state_bits) the whole row
    loop runs on packed u32 config keys.

    Returns (bits[cap,NW], state[cap,S], count, rows_done, dead, overflow).
    """
    if state_bits is not None:
        return _search_chunk_keys(
            n_rows, ret_slot, active, slot_f, slot_v, pure, pred_bit,
            bits, state, count, exp_tables, cap=cap, step_fn=step_fn,
            state_bits=state_bits, nil_id=nil_id,
            read_value_match=read_value_match, use_psort=use_psort,
            row_tiers=row_tiers, key_hi=key_hi, crash_dom=crash_dom,
            max_tier=max_tier, cand_max=cand_max, use_fused=use_fused)
    C, W = active.shape
    nw = bits.shape[1]
    # Closure-iteration ceiling (post-round-5 invariant: every closure
    # loop converts a would-be nontermination into an honest overflow).
    # This band's closure is monotone — no content-sensitive dominance
    # prune, candidates include the current frontier — so convergence
    # takes O(W) passes and the ceiling can never bind on a healthy
    # program; exhaustion with changes pending flags OVERFLOW, which
    # escalates/routes exactly like a capacity overflow (sound: the
    # frontier restarts from the row entry on the next rung).
    it_max = 4 * W + 16

    def closure_cond(c):
        _, _, _, changed, ovf, it = c
        return changed & ~ovf & (it < it_max)

    def row_body(carry):
        r, bits, state, count, dead, ovf = carry
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        pure_row = pure[r]                             # [W]
        pred_row = pred_bit[r]                         # [W, NW]

        def closure_body(c):
            bits_in, state, count, _, ovf, it = c
            b2, s2, n2, changed, o2 = _closure_pass_mw(
                bits_in, state, count, act, f_row, v_row, pure_row,
                pred_row, cap=cap, W=W, nw=nw, step_fn=step_fn)
            o3 = ovf | o2 | ((it + 1 >= it_max) & changed)
            return (b2, s2, n2, changed, o3, it + 1)

        init = (bits, state, count, jnp.bool_(True), ovf,
                jnp.int32(0))
        bits, state, count, _, ovf, _ = lax.while_loop(
            closure_cond, closure_body, init)

        bits, state, count, dead = _filter_pass_mw(
            bits, state, count, ret_slot[r], cap=cap, W=W, nw=nw)
        return (r + 1, bits, state, count, dead, ovf)

    def row_cond(carry):
        r, _, _, _, dead, ovf = carry
        return (r < n_rows) & ~dead & ~ovf

    r, bits, state, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), bits, state, count, False, False))
    return bits, state, count, r, dead, ovf


def _closure_pass_mw(bits_in, state, count, act, f_row, v_row, pure_row,
                     pred_row, *, cap, W, nw, step_fn):
    """ONE closure pass over multi-word configs (bits u32[cap,NW] +
    state i32[cap,S]); the multiword twin of _closure_pass_keys, shared
    by the chunked engine and the multiword spike executor.
    Returns (bits, state, count, changed, overflow)."""
    S = state.shape[1]
    slot_bit = _slot_bits(W, nw)                       # [W, NW]
    step_cfg_slot = jax.vmap(
        jax.vmap(step_fn, in_axes=(None, 0, 0)),
        in_axes=(0, None, None))

    cfg_valid = jnp.arange(cap) < count
    ok, new_state = step_cfg_slot(state, f_row, v_row)
    already = jnp.any(
        (bits_in[:, None, :] & slot_bit[None, :, :]) != 0, axis=-1)
    fresh = ok & act[None, :] & ~already & cfg_valid[:, None]
    # Saturation: carried configs absorb every legal pure bit in place
    # (new configs pick theirs up next pass, when carried). Statically
    # unrolled OR per slot, not a vector reduce: axis-reductions inside
    # the nested while loops kernel-fault this TPU runtime.
    sat_w = [jnp.zeros(cap, jnp.uint32) for _ in range(nw)]
    for j in range(W):
        cond = fresh[:, j] & pure_row[j]
        sat_w[j // 32] = sat_w[j // 32] | jnp.where(
            cond, jnp.uint32(1) << (j % 32), jnp.uint32(0))
    sat = jnp.stack(sat_w, axis=1)                     # [cap, NW]
    bits = jnp.where(cfg_valid[:, None], bits_in | sat, bits_in)
    # Expansion: non-pure slots only, gated by the canonical chain.
    chain_ok = jnp.all(
        (bits[:, None, :] & pred_row[None, :, :]) == pred_row,
        axis=-1)
    legal = fresh & ~pure_row[None, :] & chain_ok
    new_bits = bits[:, None, :] | slot_bit[None, :, :]

    cand_bits = jnp.concatenate([bits, new_bits.reshape(-1, nw)])
    cand_state = jnp.concatenate(
        [state, new_state.reshape(-1, S)], axis=0)
    cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

    b2, s2, n2, o2 = _dedup(cand_bits, cand_state, cand_valid, cap)
    # Fixpoint test is against the pass INPUT (the stable set keeps both
    # a config and its saturated twin; see _search_chunk_keys).
    changed = jnp.any(b2 != bits_in) | jnp.any(s2 != state) | \
        (n2 != count)
    return b2, s2, n2, changed, o2


def _filter_pass_mw(bits, state, count, s, *, cap, W, nw):
    """Return-event filter over multi-word configs: keep configs holding
    the returner's bit, then recycle it. Returns (bits, state, count,
    dead)."""
    slot_bit = _slot_bits(W, nw)
    s_mask = slot_bit[s]                               # [NW]
    cfg_valid = jnp.arange(cap) < count
    keep = cfg_valid & jnp.any((bits & s_mask[None, :]) != 0, axis=-1)
    bits = bits & ~s_mask[None, :]
    bits, state, count, _ = _dedup(bits, state, keep, cap)
    return bits, state, count, count == 0


def _expand_keys(keys_in, count, act, f_row, v_row, pure_row, pred_row,
                 *, cap, W, b, nil_id, step_fn, read_value_match):
    """Candidate generation for ONE closure pass over packed u32 keys
    (bits << b | state id): unpack, step, saturate (carried keys in
    place; expansions against their post-transition state), gate
    expansion by the canonical chain. THE single definition of the
    packed-key pass semantics — the chunked engine, the spike executor,
    and the sharded mesh engine all build their candidates here and
    differ only in HOW they dedup (local sort vs collective).
    Returns (cand[cap*(1+W)], cand_valid)."""
    from jepsen_tpu.models.kernels import NIL

    slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))
    step_cfg_slot = jax.vmap(
        jax.vmap(step_fn, in_axes=(None, 0, 0)),
        in_axes=(0, None, None))

    cfg_valid = jnp.arange(cap) < count
    bits_w, state = _unpack_frontier_keys(keys_in, count, cap, b, nil_id)
    bits1 = bits_w[:, 0]
    ok, new_state = step_cfg_slot(state, f_row, v_row)
    already = (bits1[:, None] & slot_bit[None, :]) != 0
    fresh = ok & act[None, :] & ~already & cfg_valid[:, None]
    nsv = new_state[..., 0]
    pns = jnp.where(nsv == NIL, nil_id, nsv).astype(jnp.uint32)
    # Saturation: every config (carried in place, and each expansion
    # against its post-transition state) absorbs the bits of all its
    # legal pure slots. Statically unrolled ORs, not vector reduces
    # (axis-reductions inside the nested while loops kernel-fault this
    # TPU runtime — see the dense-engine comment).
    if read_value_match and b <= 6:
        # Register-family read legality is a plain value match, so the
        # pure-slot mask depends only on the state ID: one tiny per-row
        # table (W ops over [2^b]), then a 2^b-way unrolled select —
        # O(W + 2^b) program ops instead of O(W^2). Value-rich histories
        # (b > 6) take the generic branch to keep the unroll bounded.
        sid = jnp.arange(1 << b, dtype=jnp.int32)
        raw = jnp.where(sid == nil_id, NIL, sid)
        sat_tbl = jnp.zeros(1 << b, jnp.uint32)
        for k in range(W):
            m = (v_row[k, 0] == NIL) | (v_row[k, 0] == raw)
            sat_tbl = sat_tbl | jnp.where(
                m & pure_row[k] & act[k], slot_bit[k], jnp.uint32(0))
        sv = (jnp.where(cfg_valid, keys_in, 0)
              & jnp.uint32((1 << b) - 1)).astype(jnp.int32)
        sat = jnp.zeros_like(keys_in)
        nsat = jnp.zeros(pns.shape, jnp.uint32)
        for s_id in range(1 << b):
            sat = sat | jnp.where(sv == s_id, sat_tbl[s_id],
                                  jnp.uint32(0))
            nsat = nsat | jnp.where(pns == jnp.uint32(s_id),
                                    sat_tbl[s_id], jnp.uint32(0))
    else:
        # Generic packed kernels (mutex: no pure ops — this folds away):
        # carried keys absorb legal pure bits via the step kernel's own
        # legality; expansions pick theirs up next pass, when carried.
        sat = jnp.zeros_like(keys_in)
        for j in range(W):
            sat = sat | jnp.where(fresh[:, j] & pure_row[j],
                                  slot_bit[j], jnp.uint32(0))
        nsat = jnp.zeros(pns.shape, jnp.uint32)
    keys = jnp.where(cfg_valid, keys_in | (sat << b), keys_in)
    bits1 = bits1 | sat
    chain_ok = (bits1[:, None] & pred_row[None, :]) == pred_row[None, :]
    legal = fresh & ~pure_row[None, :] & chain_ok
    new_bits = bits1[:, None] | slot_bit[None, :] | nsat
    new_keys = (new_bits << b) | pns

    cand = jnp.concatenate([jnp.where(cfg_valid, keys, 0),
                            new_keys.reshape(-1)])
    cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])
    return cand, cand_valid


def _closure_pass_keys(keys_in, count, act, f_row, v_row, pure_row,
                       pred_row, *, cap, W, b, nil_id, step_fn,
                       read_value_match, use_psort=False):
    """ONE just-in-time closure pass over packed u32 keys: _expand_keys
    candidates + local sort-dedup. Shared verbatim by the nested-while
    chunk engine and the host-driven spike executor so their semantics
    cannot diverge. Returns (keys, count, changed, overflow)."""
    cand, cand_valid = _expand_keys(
        keys_in, count, act, f_row, v_row, pure_row, pred_row, cap=cap,
        W=W, b=b, nil_id=nil_id, step_fn=step_fn,
        read_value_match=read_value_match)
    k2, n2, o2 = _dedup_keys(cand, cand_valid, cap, use_psort=use_psort)
    # Fixpoint test is against the pass INPUT: the stable set contains
    # both a config and its saturated twin (expansion keeps regenerating
    # the unsaturated parent), so comparing against the in-place-saturated
    # array would never settle.
    changed = jnp.any(k2 != keys_in) | (n2 != count)
    return k2, n2, changed, o2


def _sat_tables(act, v_row, pure_row, *, W, b, nil_id):
    """Per-row saturation tables for the compact register band: the
    pure-slot legality is a plain value match, so the key-space
    saturation mask depends only on the state id — ``(sat_lo[2^b],
    sat_hi[2^b])`` u32 tables (hi all-zero for windows inside one
    word). THE single definition, shared by the unfused compact pass
    (:func:`_closure_pass_keys_compact`) and the fused in-VMEM
    fixpoint kernel (:mod:`jepsen_tpu.lin.psort_fused`) so their
    saturation semantics cannot drift."""
    from jepsen_tpu.models.kernels import NIL

    kbit_lo, kbit_hi = _key_bit_words(b + np.arange(W))
    sid = jnp.arange(1 << b, dtype=jnp.int32)
    raw = jnp.where(sid == nil_id, NIL, sid)
    sat_tbl_lo = jnp.zeros(1 << b, jnp.uint32)
    sat_tbl_hi = jnp.zeros(1 << b, jnp.uint32)
    for k in range(W):
        m = (v_row[k, 0] == NIL) | (v_row[k, 0] == raw)
        cond = m & pure_row[k] & act[k]
        if int(kbit_lo[k]):
            sat_tbl_lo = sat_tbl_lo | jnp.where(
                cond, jnp.uint32(int(kbit_lo[k])), jnp.uint32(0))
        else:
            sat_tbl_hi = sat_tbl_hi | jnp.where(
                cond, jnp.uint32(int(kbit_hi[k])), jnp.uint32(0))
    return sat_tbl_lo, sat_tbl_hi


def _fused_row_tables(exp_r, act, v_row, pure_row, *, W, b, nil_id):
    """Per-row scalar tables for the fused in-VMEM fixpoint kernel
    (:mod:`jepsen_tpu.lin.psort_fused`): the register family's mutator
    step is a pure value match (write always applies, cas applies iff
    the state equals its precondition), so ok/post per (column, state)
    collapse to per-column scalars — ``cols`` u32[10, M] (key bit,
    chain mask, rv mask, the OR-in word for new keys incl. the
    post-state id and its saturation mask, the cas precondition id,
    and act/write/jit flags) plus the shared saturation tables
    (``sats`` u32[2, 2^b], :func:`_sat_tables`). Gated to the compact
    register band (read_value_match, b <= 6) whose parity the fused
    kernel is fuzzed on — tests/test_lin_psort_fused.py."""
    from jepsen_tpu.lin import psort_fused
    from jepsen_tpu.models.kernels import F_CAS, F_WRITE, NIL

    (exp_lo, exp_hi, exp_f, exp_v, exp_act, exp_pred_lo, exp_pred_hi,
     _cl, _ch, _rl, _rh, exp_jit, exp_rv_lo, exp_rv_hi) = exp_r
    sat_lo, sat_hi = _sat_tables(act, v_row, pure_row, W=W, b=b,
                                 nil_id=nil_id)
    is_cas = exp_f == F_CAS
    is_wr = exp_f == F_WRITE

    def as_sid(w):
        return jnp.where(w == NIL, nil_id, w).astype(jnp.uint32)

    # A write's precondition never matches (state ids are < 2^b).
    pre = jnp.where(is_cas, as_sid(exp_v[:, 0]), jnp.uint32(0xFFFF))
    post = jnp.where(is_cas, as_sid(exp_v[:, 1]), as_sid(exp_v[:, 0]))
    post_i = post.astype(jnp.int32)
    or_lo = exp_lo | jnp.take(sat_lo, post_i) | post
    or_hi = exp_hi | jnp.take(sat_hi, post_i)
    flags = (exp_act.astype(jnp.uint32) * psort_fused.FLAG_ACT
             | is_wr.astype(jnp.uint32) * psort_fused.FLAG_WRITE
             | exp_jit.astype(jnp.uint32) * psort_fused.FLAG_JIT)
    cols = jnp.stack([exp_lo, exp_hi, exp_pred_lo, exp_pred_hi,
                      exp_rv_lo, exp_rv_hi, or_lo, or_hi, pre,
                      flags]).astype(jnp.uint32)
    sats = jnp.stack([sat_lo, sat_hi])
    return cols, sats


def _expand_keys_compact(lo_in, hi_in, count, act, v_row, pure_row,
                         exp, *, cap, W, b, nil_id, step_fn):
    """The CANDIDATE-GENERATION half of _closure_pass_keys_compact —
    in-place saturation plus mutator-column expansion with the chain
    and JIT gates — factored out so the mesh engine
    (:mod:`jepsen_tpu.lin.sharded`) pairs the identical expansion with
    its COLLECTIVE dedup while this module's passes keep the local
    one; a single definition keeps the two engines' pass semantics
    equal by construction (the _expand_keys precedent). Returns
    (cand_lo, cand_hi, cand_valid) with cand_hi None for single-word
    keys; candidate arrays are cap*(1+M)."""
    from jepsen_tpu.models.kernels import NIL

    (exp_lo, exp_hi, exp_f, exp_v, exp_act, exp_pred_lo, exp_pred_hi,
     crash_lo, crash_hi, read_lo, read_hi, exp_jit, exp_rv_lo,
     exp_rv_hi) = exp
    pair = hi_in is not None
    step_cfg_slot = jax.vmap(
        jax.vmap(step_fn, in_axes=(None, 0, 0)),
        in_axes=(0, None, None))

    cfg_valid = jnp.arange(cap) < count
    state_mask = jnp.uint32((1 << b) - 1)
    sv = (jnp.where(cfg_valid, lo_in, 0) & state_mask).astype(jnp.int32)
    state = jnp.where(cfg_valid, jnp.where(sv == nil_id, NIL, sv),
                      0)[:, None]

    # Saturation tables: pure-slot legality is a plain value match, so
    # the mask depends only on the state id (see _expand_keys); the
    # shared _sat_tables definition also feeds the fused in-VMEM
    # fixpoint kernel (psort_fused).
    sat_tbl_lo, sat_tbl_hi = _sat_tables(act, v_row, pure_row, W=W,
                                         b=b, nil_id=nil_id)

    # Expansion over the M mutator columns only.
    ok, new_state = step_cfg_slot(state, exp_f, exp_v)
    nsv = new_state[..., 0]
    pns = jnp.where(nsv == NIL, nil_id, nsv).astype(jnp.uint32)
    sat_lo = jnp.zeros_like(lo_in)
    sat_hi = jnp.zeros_like(lo_in)
    nsat_lo = jnp.zeros(pns.shape, jnp.uint32)
    nsat_hi = jnp.zeros(pns.shape, jnp.uint32)
    for s_id in range(1 << b):
        sel = sv == s_id
        nsel = pns == jnp.uint32(s_id)
        sat_lo = sat_lo | jnp.where(sel, sat_tbl_lo[s_id], jnp.uint32(0))
        nsat_lo = nsat_lo | jnp.where(nsel, sat_tbl_lo[s_id],
                                      jnp.uint32(0))
        if pair:
            sat_hi = sat_hi | jnp.where(sel, sat_tbl_hi[s_id],
                                        jnp.uint32(0))
            nsat_hi = nsat_hi | jnp.where(nsel, sat_tbl_hi[s_id],
                                          jnp.uint32(0))
    lo1 = jnp.where(cfg_valid, lo_in | sat_lo, lo_in)
    hi1 = jnp.where(cfg_valid, hi_in | sat_hi, hi_in) if pair else None

    already = (lo1[:, None] & exp_lo[None, :]) != 0
    chain_ok = (lo1[:, None] & exp_pred_lo[None, :]) == \
        exp_pred_lo[None, :]
    # JIT-linearization gate (expansion_tables): a column expands only
    # when statically useful (returner / precondition chain) or when its
    # post-state absorbs a read this config hasn't (unheld rv bits).
    jit_ok = exp_jit[None, :] | \
        ((exp_rv_lo[None, :] & ~lo1[:, None]) != 0)
    if pair:
        already = already | ((hi1[:, None] & exp_hi[None, :]) != 0)
        chain_ok = chain_ok & (
            (hi1[:, None] & exp_pred_hi[None, :]) == exp_pred_hi[None, :])
        jit_ok = jit_ok | ((exp_rv_hi[None, :] & ~hi1[:, None]) != 0)
    fresh = ok & exp_act[None, :] & ~already & cfg_valid[:, None]
    legal = fresh & chain_ok & jit_ok
    new_lo = (lo1[:, None] & ~state_mask) | exp_lo[None, :] | nsat_lo \
        | pns
    cand_lo = jnp.concatenate([jnp.where(cfg_valid, lo1, 0),
                               new_lo.reshape(-1)])
    cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])
    cand_hi = None
    if pair:
        new_hi = hi1[:, None] | exp_hi[None, :] | nsat_hi
        cand_hi = jnp.concatenate([jnp.where(cfg_valid, hi1, 0),
                                   new_hi.reshape(-1)])
    return cand_lo, cand_hi, cand_valid


def _closure_pass_keys_compact(lo_in, hi_in, count, act, v_row, pure_row,
                               exp, *, cap, W, b, nil_id, step_fn,
                               use_psort=False, crash_dom=False,
                               dom_iters=2):
    """ONE closure pass over packed key configs with mutator-compacted
    expansion columns (bfs.expansion_tables): semantically identical to
    _closure_pass_keys for the read-value-match register family (fuzzed
    in tests/test_lin_psort.py and the engine parity suites), but the
    model step runs over M mutator columns instead of the full window,
    and the candidate array is cap*(1+M) instead of cap*(1+W).
    Carried-key saturation needs no step evaluation at all here: read
    legality is a pure state-id match, so the per-row saturation table
    (the rvm branch of _expand_keys) covers it.

    Keys are KEY-space words: ``lo`` u32 (bits << b | state), plus
    ``hi`` u32 for windows past 31-b bits (None otherwise — the
    cockroach-class concurrency-30 band lives there). Returns
    (lo, hi, count, changed, overflow)."""
    (_el, _eh, _ef, _ev, _ea, _epl, _eph,
     crash_lo, crash_hi, read_lo, read_hi, _ej, _ervl,
     _ervh) = exp
    pair = hi_in is not None
    cand_lo, cand_hi, cand_valid = _expand_keys_compact(
        lo_in, hi_in, count, act, v_row, pure_row, exp, cap=cap, W=W,
        b=b, nil_id=nil_id, step_fn=step_fn)
    if pair:
        if crash_dom:
            # Dominance dedups ALWAYS take the forced lax path (window
            # + chain scan + iterated prune-compact rounds); the chain
            # catches dominators at EVERY offset up to DOM_CHAIN where
            # the static window tests exact offsets only, and it is
            # what collapses the crashed-subset transients. The psort
            # dom kernels are additionally excluded on stability
            # grounds: both round-5 runs that routed small dom dedups
            # through them (probe_r5fc/fd) killed the worker mid-
            # history (~rows 13-20k) where the all-lax run (probe_r5fa)
            # ran clean to 35k+, matching round 4's in-chunk faults.
            h2, l2, n2, o2 = _dedup_keys2_dom(
                cand_hi, cand_lo, cand_valid, cap, crash_hi, crash_lo,
                read_hi, read_lo, use_psort=False,
                dom_force=True, dom_iters=dom_iters)
        else:
            h2, l2, n2, o2 = _dedup_keys2(cand_hi, cand_lo, cand_valid,
                                          cap, use_psort=use_psort)
        changed = jnp.any(l2 != lo_in) | jnp.any(h2 != hi_in) | \
            (n2 != count)
        return l2, h2, n2, changed, o2
    if crash_dom:
        # Forced lax path always — see the pair-key branch above.
        l2, n2, o2 = _dedup_keys_dom(cand_lo, cand_valid, cap, crash_lo,
                                     read_lo, use_psort=False,
                                     dom_force=True,
                                     dom_iters=dom_iters)
    else:
        l2, n2, o2 = _dedup_keys(cand_lo, cand_valid, cap,
                                 use_psort=use_psort)
    changed = jnp.any(l2 != lo_in) | (n2 != count)
    return l2, None, n2, changed, o2


def _filter_pass_keys(keys, count, s, *, cap, b, use_psort=False):
    """Return-event filter over packed keys: the returner's linearization
    point must precede its return; survivors drop its (recycled) bit.

    The filter never creates duplicates — every survivor held the SAME
    bit, so dropping it is injective — and dropping a common bit is
    monotone, so survivor order is preserved. When nothing is dropped
    the whole pass is one bit-clear; otherwise dropped entries become
    KEY_FILL and ONE sort compacts (no dedup machinery). Dominance
    pruning is deliberately absent here: it is an optimization, not a
    soundness requirement, and the next closure pass's dedup prunes.
    Returns (keys, count, dead)."""
    s_key_bit = jnp.uint32(1) << (b + s).astype(jnp.uint32)
    cfg_valid = jnp.arange(cap) < count
    keep = cfg_valid & ((keys & s_key_bit) != 0)
    n_keep = jnp.sum(keep.astype(jnp.int32))

    def clear_only():
        return jnp.where(cfg_valid, keys & ~s_key_bit, keys), count

    def compacting():
        dropped = jnp.where(keep, keys & ~s_key_bit, KEY_FILL)
        if use_psort and psort.available(cap):
            return psort.compact_keys(dropped, cap)
        return lax.sort(dropped), n_keep

    keys, count = lax.cond(n_keep == count, clear_only, compacting)
    return keys, count, count == 0


def _filter_pass_keys2(lo, hi, count, s, *, cap, b, use_psort=False):
    """Pair-key return-event filter (see _filter_pass_keys: injective
    bit-drop, clear-only fast path, one compacting sort otherwise). The
    returner's key bit (b + s) may live in either word. Returns
    (lo, hi, count, dead)."""
    pos = (b + s).astype(jnp.uint32)
    in_lo = pos < 32
    bit_lo = jnp.where(in_lo, jnp.uint32(1) << (pos & 31), jnp.uint32(0))
    bit_hi = jnp.where(in_lo, jnp.uint32(0),
                       jnp.uint32(1) << (pos & 31))
    cfg_valid = jnp.arange(cap) < count
    keep = cfg_valid & (((lo & bit_lo) | (hi & bit_hi)) != 0)
    n_keep = jnp.sum(keep.astype(jnp.int32))

    def clear_only():
        return (jnp.where(cfg_valid, lo & ~bit_lo, lo),
                jnp.where(cfg_valid, hi & ~bit_hi, hi), count)

    def compacting():
        d_hi = jnp.where(keep, hi & ~bit_hi, KEY_FILL)
        d_lo = jnp.where(keep, lo & ~bit_lo, KEY_FILL)
        if use_psort and psort.available(cap):
            h2, l2, n2 = psort.compact_keys2(d_hi, d_lo, cap)
        else:
            h2, l2 = lax.sort((d_hi, d_lo), num_keys=2)
            n2 = n_keep
        return l2, h2, n2

    lo, hi, count = lax.cond(n_keep == count, clear_only, compacting)
    return lo, hi, count, count == 0


# Row tiers for the packed-key engine: a row whose frontier is small
# runs its whole closure + filter on a static PREFIX of the (compacted)
# frontier array, so sort sizes track the live count instead of the
# capacity — the frontier trajectory of real wide-window histories is
# spiky (median a few hundred configs, brief 10-50k bursts), and
# without tiers every row pays for the burst capacity. A tier whose
# dedup overflows retries the row at the full cap (one lax.cond).
# The ladder is geometric x4 from 256: partitioned cockroach-class
# histories (BASELINE config 5) sit at counts 4-1000 for most rows, and
# the sort cost of a row tracks tier*(1+M), so the bottom tiers carry
# the throughput.
ROW_TIERS = (256, 1024, 4096, 16384, 65536)
# Tier selection margin: the chosen tier must hold margin x the live
# count, since mid-closure frontiers (config + saturated twin +
# expansions, pre-filter) overshoot the settled count.
TIER_MARGIN = 4


def _search_chunk_keys(n_rows, ret_slot, active, slot_f, slot_v,
                       pure, pred_bit, bits, state, count,
                       exp_tables=None, *, cap, step_fn,
                       state_bits, nil_id, read_value_match=False,
                       use_psort=False, row_tiers=True, key_hi=False,
                       crash_dom=False, max_tier=None, cand_max=None,
                       use_fused=False):
    """Packed-key row loop (see _search_chunk): each config is ONE
    uint32 (bits << state_bits | state id) — or an (lo, hi) u32 pair
    when ``key_hi`` (windows up to 60+state bits; the cockroach-class
    concurrency-30 band) — so dedup is a payload-free sort and
    compaction a second sort. With ``exp_tables`` (the chunk slice of
    bfs.expansion_tables) the closure pass runs with mutator-compacted
    expansion columns, and rows are count-TIERED (see ROW_TIERS)."""
    from jepsen_tpu.models.kernels import NIL

    C, W = active.shape
    b = state_bits
    nw = bits.shape[1]
    if key_hi:
        assert exp_tables is not None, "pair keys require compact tables"
    # Spike-cap programs (row_tiers=False) process known-big frontiers,
    # so tier branches there are compile-time dead weight. The compact
    # register band and the generic packed band (mutex — BASELINE
    # config 3's lock histories) both tier.
    tiered = row_tiers
    # ``max_tier`` caps the in-chunk ladder BELOW the frontier capacity:
    # rows needing bigger tiers overflow to the host-row executor
    # (_host_rows) instead of running the big windowed-dominance dedups
    # inside this nested-while program — the shapes that kernel-fault
    # the axon runtime on the 100k partitioned history (round-4 lore).
    top = cap if max_tier is None else min(cap, max_tier)
    tiers = tuple(t for t in ROW_TIERS if t < top) + (top,) \
        if tiered else (cap,)

    def row_at_tier(tier, r, lo, hi, count):
        """One full row (closure fixpoint + return filter) on the first
        ``tier`` entries of the frontier (live entries are a prefix:
        dedup compacts and count <= tier/TIER_MARGIN at selection, or
        this is the escalation/top tier with count <= cap). Returns
        (lo[cap], hi[cap]|None, count, dead, overflow).

        The compact-table closure runs GROUPED: expansion columns are
        processed Mg at a time so every dedup stays within the windowed
        dominance bound (tier*(1+Mg) <= psort.DOM_WINDOW_MAX_N) — the
        crashed-subset wave of partition histories must meet the
        windowed prune at EVERY capacity, or a single row's transient
        blowup (measured 389k configs from a 26-config entry) rides an
        unwindowed big-tier dedup into overflow. The fixpoint ends
        after G consecutive unchanged subpasses (one full group
        cycle)."""
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        pure_row = pure[r]                              # [W]
        pred_row = pred_bit[r, :, 0]                    # [W] slot-space
        l_t = lo[:tier] if tier < cap else lo
        h_t = (hi[:tier] if tier < cap else hi) if key_hi else None

        if exp_tables is not None:
            M_cols = exp_tables[0].shape[-1]
            # Candidate bound: ALL crash-dom rows (pair AND single-key)
            # use the large CHUNK_CAND_MAX bound so in-chunk closure is
            # ungrouped (G=1) at every tier — grouping is the period-G
            # orbit hazard, and crash-dom dedups force the lax chain
            # path regardless of size, so the psort/window size-gate
            # rationale behind the smaller bound does not apply to them.
            # (Round 5 covered only the pair band; the single-key band
            # still ran grouped closures at tiers 16384/65536 and paid
            # needless host-row escalations.) Other bands group to keep
            # the windowed dominance prune engaged in psort-sized
            # dedups.
            cand_bound = (cand_max or CHUNK_CAND_MAX) if crash_dom \
                else psort.DOM_WINDOW_MAX_N
            Mg = max(1, cand_bound // tier - 1)
            G = -(-M_cols // Mg) if Mg < M_cols else 1
            Mg = min(Mg, M_cols)
        else:
            G = 1

        # Closure-iteration ceiling: the windowed dominance prune is
        # content-sensitive, so a GROUPED closure (frontier a function
        # of input AND group) can enter a period-G orbit that never
        # meets the G-consecutive-unchanged fixpoint — inside this
        # lax.while_loop that is an infinite loop the runtime watchdog
        # kills (the round-4/5 "kernel faults" on the partitioned
        # class). Legitimate convergence needs O(G * window) passes;
        # exhaustion beyond the ceiling flags OVERFLOW — sound: the
        # row re-runs in the host executor, whose ungrouped passes
        # terminate.
        it_max = G * (W + 4) + 8

        def closure_cond(c):
            return (c[-3] < G) & ~c[-1]

        def closure_body(c):
            if key_hi:
                lo_in, hi_in, count, g, since, it, ovf = c
            else:
                lo_in, count, g, since, it, ovf = c
                hi_in = None
            if exp_tables is not None:
                exp_r = []
                for t in exp_tables:
                    tr = t[r]
                    if tr.ndim >= 1 and G > 1:
                        pad = G * Mg - M_cols
                        if pad:
                            tr = jnp.pad(
                                tr, ((0, pad),) + ((0, 0),)
                                * (tr.ndim - 1))
                        tr = lax.dynamic_slice_in_dim(tr, g * Mg, Mg, 0)
                    exp_r.append(tr)
                l2, h2, n2, changed, o2 = _closure_pass_keys_compact(
                    lo_in, hi_in, count, act, v_row, pure_row,
                    tuple(exp_r), cap=tier, W=W, b=b, nil_id=nil_id,
                    step_fn=step_fn, use_psort=use_psort,
                    crash_dom=crash_dom)
            else:
                l2, n2, changed, o2 = _closure_pass_keys(
                    lo_in, count, act, f_row, v_row, pure_row, pred_row,
                    cap=tier, W=W, b=b, nil_id=nil_id, step_fn=step_fn,
                    read_value_match=read_value_match,
                    use_psort=use_psort)
                h2 = None
            g2 = jnp.where(g + 1 >= G, 0, g + 1)
            since2 = jnp.where(changed, jnp.int32(0), since + 1)
            # Convergence before ceiling: a pass that completes the
            # G-unchanged fixpoint exactly at the iteration budget is
            # converged, not overflowed (the ceiling exists to convert
            # nontermination into an honest overflow, and since2 >= G
            # IS termination).
            o3 = ovf | o2 | ((it + 1 >= it_max) & (since2 < G))
            if key_hi:
                return (l2, h2, n2, g2, since2, it + 1, o3)
            return (l2, n2, g2, since2, it + 1, o3)

        if exp_tables is not None and not crash_dom and use_fused \
                and psort_fused.fits(tier, M_cols, b,
                                     max_pad=int(use_fused)):
            # Fused in-VMEM fixpoint: the whole expand -> sort-dedup
            # pass chain as ONE pallas kernel with the frontier
            # resident in VMEM across passes (psort_fused — the
            # kill-the-tunnel stage-floor half). Non-dominance dedups
            # only: the crash-dom band keeps the forced-lax chain
            # rule (round-5 lore), enforced by the crash_dom gate
            # here. Ungrouped by construction (semantically identical
            # for this band's monotone closure); non-convergence at
            # the ceiling maps to the same honest overflow the
            # unfused chain flags.
            exp_row = tuple(t[r] for t in exp_tables)
            cols, sats = _fused_row_tables(exp_row, act, v_row,
                                           pure_row, W=W, b=b,
                                           nil_id=nil_id)
            l_t, h_t, count, conv, o2 = psort_fused.fixpoint(
                l_t, h_t, count, cols, sats, cap=tier, b=b,
                it_max=it_max)
            ovf = o2 | ~conv
            if key_hi:
                l_t, h_t, count, dead = _filter_pass_keys2(
                    l_t, h_t, count, ret_slot[r], cap=tier, b=b,
                    use_psort=use_psort)
            else:
                l_t, count, dead = _filter_pass_keys(
                    l_t, count, ret_slot[r], cap=tier, b=b,
                    use_psort=use_psort)
        elif key_hi:
            init = (l_t, h_t, count, jnp.int32(0), jnp.int32(0),
                    jnp.int32(0), jnp.bool_(False))
            l_t, h_t, count, _, _, _, ovf = lax.while_loop(
                closure_cond, closure_body, init)
            l_t, h_t, count, dead = _filter_pass_keys2(
                l_t, h_t, count, ret_slot[r], cap=tier, b=b,
                use_psort=use_psort)
        else:
            init = (l_t, count, jnp.int32(0), jnp.int32(0),
                    jnp.int32(0), jnp.bool_(False))
            l_t, count, _, _, _, ovf = lax.while_loop(
                closure_cond, closure_body, init)
            l_t, count, dead = _filter_pass_keys(
                l_t, count, ret_slot[r], cap=tier, b=b,
                use_psort=use_psort)
        if tier < cap:
            fill = jnp.full(cap - tier, KEY_FILL, jnp.uint32)
            l_t = jnp.concatenate([l_t, fill])
            if key_hi:
                h_t = jnp.concatenate([h_t, fill])
        if not key_hi:
            h_t = lo[:0]  # zero-size placeholder keeps carries uniform
        return l_t, h_t, count, dead, ovf

    def row_body(carry):
        r, lo, hi, count, dead, ovf = carry

        def run_row():
            if len(tiers) == 1:
                return row_at_tier(tiers[0], r, lo, hi, count)
            # Smallest tier holding TIER_MARGIN x the live count; a
            # mid-row overflow escalates straight to the top tier (the
            # row is functional, so the retry is exact).
            idx = jnp.int32(0)
            for t in tiers[:-1]:
                idx = idx + (count * TIER_MARGIN > t).astype(jnp.int32)
            l2, h2, n2, d2, o2 = lax.switch(
                idx, [partial(row_at_tier, t) for t in tiers],
                r, lo, hi, count)
            need_top = o2 & (idx < len(tiers) - 1)
            return lax.cond(
                need_top,
                lambda: row_at_tier(tiers[-1], r, lo, hi, count),
                lambda: (l2, h2, n2, d2, o2))

        if tiers[-1] < cap:
            # Tier-capped band: an entry frontier bigger than the top
            # tier cannot run in-chunk at all — flag overflow with the
            # frontier untouched and let the host-row executor own the
            # row (slicing it to the tier would silently drop live
            # configs: verdict-flipping).
            l2, h2, n2, dead, o2 = lax.cond(
                count > tiers[-1],
                lambda: (lo, hi, count, jnp.bool_(False),
                         jnp.bool_(True)),
                run_row)
        else:
            l2, h2, n2, dead, o2 = run_row()
        return (r + 1, l2, h2, n2, dead, ovf | o2)

    def row_cond(carry):
        r, _, _, _, dead, ovf = carry
        return (r < n_rows) & ~dead & ~ovf

    if key_hi:
        lo0, hi0 = _pack_frontier_keys2(bits, state, count, cap, b,
                                        nil_id)
    else:
        lo0 = _pack_frontier_keys(bits, state, count, cap, b, nil_id)
        hi0 = lo0[:0]
    r, lo, hi, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), lo0, hi0, count, False, False))
    if key_hi:
        out_bits, out_state = _unpack_frontier_keys2(
            lo, hi, count, cap, b, nil_id, nw)
    else:
        out_bits, out_state = _unpack_frontier_keys(lo, count, cap, b,
                                                    nil_id)
    return out_bits, out_state, count, r, dead, ovf


# Multi-operand sorts at the 1M-cap multiword shape (34M rows x 4
# columns) kill the TPU worker; ~1.5 GiB of sort operands per pass is
# the measured-safe budget (the 524288 tier for window-33 registers).
_MW_SPIKE_BUDGET_BYTES = 3 << 29


def _mw_spike_caps(W, nw, S, chunk_top, spike_caps):
    """Memory-bounded spike-cap ladder for the multiword executor. Each
    closure pass materializes ~3 copies of cap*(W+1) candidate rows of
    (1 + nw + S) i32 words; wide windows and fat states (sets) get
    smaller ladders. Takes the configured spike levels above the chunked
    top cap that fit the budget; None when none do."""
    per_cand = 4 * 3 * (W + 1) * (1 + nw + S)
    max_cap = _MW_SPIKE_BUDGET_BYTES // max(per_cand, 1)
    caps = tuple(sorted(c for c in spike_caps if chunk_top < c <= max_cap))
    return caps or None


def _spike_rows(p, r0, bits, state, count, *, tables_h, caps, dropback,
                step_fn, state_bits, nil_id, read_value_match, cancel,
                snapshots, min_rows: int = 64, use_psort: bool = False,
                exp_h=None, key_hi: bool = False,
                crash_dom: bool = False, cand_max=None,
                stats: dict | None = None):
    """Spike mode: SPIKE_CHUNK-row mini-chunks of the SAME _search_chunk
    program at the big spike capacities. The axon runtime faults on a
    512-row chunk past cap 131072 but runs an 8-row chunk clean at 2^20
    — it objects to rows*cap program complexity, not capacity — so
    shrinking the chunk is all it takes to ride a frontier explosion
    out, with identical semantics to normal chunks by construction.

    Processes mini-chunks from ``r0`` until death, cancel, overflow of
    the last cap, history end, or (after at least ``min_rows`` rows, so
    dense spike regions don't thrash between modes) the frontier
    shrinking to ``dropback``. When ``snapshots`` is a list it receives
    each mini-chunk's entry frontier, so an explain replay spans at most
    SPIKE_CHUNK rows. Returns (bits, state, count_int, next_row, dead,
    overflowed, cancelled, top_cap_used)."""
    lvl = 0
    top_used = caps[0]

    def grow(b_, s_, to):
        g = to - b_.shape[0]
        return (jnp.pad(b_, ((0, g), (0, 0))),
                jnp.pad(s_, ((0, g), (0, 0))))

    bits, state = grow(bits, state, caps[0])
    r = r0
    while r < p.R:
        if cancel is not None and cancel.is_set():
            return bits, state, int(count), r, False, False, True, top_used
        if snapshots is not None:
            snapshots[:] = [(r, bits, state, count)]
        m_n = min(SPIKE_CHUNK, p.R - r)
        sp_tables = tuple(jnp.asarray(_chunk_slice(t, r, SPIKE_CHUNK))
                          for t in tables_h)
        sp_exp = None if exp_h is None else tuple(
            jnp.asarray(_chunk_slice(t, r, SPIKE_CHUNK)) for t in exp_h)
        while True:
            util.progress_tick()

            def _mini_prog(bits=bits, state=state, count=count,
                           lvl=lvl):
                return _search_chunk(
                    jnp.int32(m_n), *sp_tables, bits, state, count,
                    sp_exp, cap=caps[lvl], step_fn=step_fn,
                    state_bits=state_bits, nil_id=nil_id,
                    read_value_match=read_value_match,
                    use_psort=use_psort, row_tiers=False, key_hi=key_hi,
                    crash_dom=crash_dom, cand_max=cand_max)

            def _mini():
                out = _mini_prog()
                return out, bool(out[5])

            spike_key = supervise.shape_key(
                "spike", rows=SPIKE_CHUNK, cap=caps[lvl],
                window=p.window,
                kernel=p.kernel.name if p.kernel else "generic")
            outcome, val = supervise.run_guarded("spike", spike_key,
                                                 _mini, stats=stats,
                                                 traceable=_mini_prog)
            if outcome != "ok":
                return (bits, state, int(count), r, False,
                        "wedged" if outcome == "wedge" else "fault",
                        False, top_used)
            (b2, s2, c2, r_done, dead, ovf), ovf_b = val
            if not ovf_b:
                break
            if lvl + 1 >= len(caps):
                return (bits, state, int(count), r, False, True, False,
                        top_used)
            lvl += 1
            bits, state = grow(bits, state, caps[lvl])
            top_used = caps[lvl]
        if bool(dead):
            if snapshots is not None and int(r_done) > 1:
                # Re-anchor the explain snapshot at the dead ROW's entry
                # (one cheap re-run of the mini-chunk's surviving rows),
                # so the capacity-unbounded CPU replay spans ONE row of
                # this spike-sized frontier, not up to SPIKE_CHUNK.
                b3, s3, c3, _, _, o3 = _search_chunk(
                    jnp.int32(int(r_done) - 1), *sp_tables, bits, state,
                    count, sp_exp, cap=caps[lvl], step_fn=step_fn,
                    state_bits=state_bits, nil_id=nil_id,
                    read_value_match=read_value_match,
                    use_psort=use_psort, row_tiers=False, key_hi=key_hi,
                    crash_dom=crash_dom, cand_max=cand_max)
                if not bool(o3):
                    snapshots[:] = [(r + int(r_done) - 1, b3, s3, c3)]
            return (b2, s2, int(c2), r + int(r_done), True, False, False,
                    top_used)
        bits, state, count = b2, s2, c2
        r += m_n
        if r - r0 >= min_rows and int(count) <= dropback:
            break
    return bits, state, int(count), r, False, False, False, top_used


@partial(jax.jit, static_argnames=("cap", "W", "b", "nil_id", "step_fn",
                                   "use_psort", "crash_dom"))
def _host_closure_pass(lo, hi, count, act, v_row, pure_row, exp_r, *,
                       cap, W, b, nil_id, step_fn, use_psort,
                       crash_dom):
    """One host-dispatched closure pass (see _host_rows): exactly
    _closure_pass_keys_compact with the forced lax chain prune
    (use_psort off so every dedup takes it) at the aggressive
    iteration count — host rows are the blowups by definition, and the
    big caps need the extra prune-compact rounds to hold the
    mid-history waves (measured: one round leaves 500k+ live configs
    at row 22599, overflowing every capacity)."""
    del use_psort
    l2, h2, n2, changed, ovf = _closure_pass_keys_compact(
        lo, hi, count, act, v_row, pure_row, exp_r, cap=cap, W=W, b=b,
        nil_id=nil_id, step_fn=step_fn, use_psort=False,
        crash_dom=crash_dom, dom_iters=6)
    # The settled count rides the flag vector so the host's one
    # np.asarray fetch per pass covers it — the sticky-cap peak signal
    # must not cost a second ~100 ms tunnel round trip on the round-5
    # triage fallback path.
    return l2, h2, n2, jnp.stack([changed.astype(jnp.int32),
                                  ovf.astype(jnp.int32), n2])


@partial(jax.jit, static_argnames=("cap", "W", "b", "nil_id", "step_fn",
                                   "use_psort", "crash_dom", "key_hi",
                                   "it_max", "dom_iters"))
def _host_closure_fixpoint(lo, hi, count, act, v_row, pure_row, exp_r,
                           ret, *, cap, W, b, nil_id, step_fn,
                           use_psort, crash_dom, key_hi, it_max,
                           dom_iters=6):
    """The DEVICE-RESIDENT closure fixpoint for one host row: the whole
    multi-pass closure (each pass = _closure_pass_keys_compact with the
    forced lax chain prune at the aggressive dom_iters, exactly
    _host_closure_pass) runs as ONE ``lax.while_loop`` program, with
    the round-5 iteration ceiling carried IN-PROGRAM — the loop exits
    on convergence, dedup overflow, or ``it_max`` passes, so a would-be
    orbit still becomes an honest overflow flag instead of a watchdog
    kill, without paying the ~100 ms host tunnel round trip per pass
    (round 5 paid it_max-bounded multiples of it across ~90 episodes —
    the dominant cost of the 3217 s config-5 decide).

    Runtime-safety envelope: this is a ONE-row program — the axon
    runtime objects to rows*cap program complexity (8/32/64-row chunks
    run clean at cap 2^20 where 512-row chunks fault at 2^18), and the
    closure here is always UNGROUPED (all M columns per pass, no
    lax.dynamic_slice group machinery), which round 5 proved clean
    in-chunk at these dedup shapes and which makes the frontier a
    deterministic function of itself so the fixpoint terminates.

    The return-event filter is fused in: when the closure converges the
    returned arrays are already filtered, so a clean row costs ONE
    dispatch + one 5-int flag fetch. It honors ``use_psort`` exactly
    like the unfused fallback's _host_filter_pass (only the CLOSURE
    pass forces the lax chain path — see _host_closure_pass), so
    FUSED_CLOSURE=0 triage compares the same program mix, just
    unfused. On a non-converged exit the filter output is garbage by
    construction; the host discards it and restarts from its entry
    snapshot (escalation semantics unchanged).

    Convergence is tested before the ceiling: a pass that reaches the
    fixpoint exactly at ``it_max`` exits converged, not overflowed.

    ``peak`` (the largest SETTLED per-pass frontier count seen during
    the closure) rides the carry at zero extra dispatch cost: it is
    the sticky-cap decay signal — ``lvl_for(count)`` underestimates a
    wave row's true capacity need (the entry count is small; the
    mid-closure frontier is what outgrows the cap), while the peak is
    exactly the quantity the capacity must hold.

    Returns (lo, hi, flags) with flags = i32[5]:
    [converged, dedup_overflow, passes_used, post-filter count,
    peak settled count]."""
    lo, hi, count, it, converged, ovf, peak = _closure_fixpoint_loop(
        lo, hi, count, act, v_row, pure_row, exp_r, count, cap=cap,
        W=W, b=b, nil_id=nil_id, step_fn=step_fn, crash_dom=crash_dom,
        it_max=it_max, dom_iters=dom_iters)
    lo, hi, count = _filter_keys_any(lo, hi, count, ret, cap=cap, b=b,
                                     use_psort=use_psort, key_hi=key_hi)
    return lo, hi, jnp.stack([converged.astype(jnp.int32),
                              ovf.astype(jnp.int32), it, count, peak])


def _closure_fixpoint_loop(lo, hi, count, act, v_row, pure_row, exp_r,
                           peak, *, cap, W, b, nil_id, step_fn,
                           crash_dom, it_max, dom_iters):
    """THE one-row host closure fixpoint ``lax.while_loop`` (traceable,
    not jitted itself), shared by the one-row program
    (_host_closure_fixpoint) and the K-row wave program
    (_host_closure_fixpoint_rows) so the wave fast path can never
    silently drift from the proven per-row pass/ceiling semantics —
    the _filter_keys_any precedent from round 6. ``peak`` is the
    loop-carried max settled count (the sticky-cap decay signal).
    Returns (lo, hi, count, passes, converged, ovf, peak)."""
    def cond(c):
        _, _, _, it, changed, ovf, _ = c
        return changed & ~ovf & (it < it_max)

    def body(c):
        lo, hi, count, it, _, ovf, pk = c
        l2, h2, n2, changed, o2 = _closure_pass_keys_compact(
            lo, hi, count, act, v_row, pure_row, exp_r, cap=cap, W=W,
            b=b, nil_id=nil_id, step_fn=step_fn, use_psort=False,
            crash_dom=crash_dom, dom_iters=dom_iters)
        return (l2, h2, n2, it + 1, changed, ovf | o2,
                jnp.maximum(pk, n2))

    lo, hi, count, it, changed, ovf, peak = lax.while_loop(
        cond, body,
        (lo, hi, count, jnp.int32(0), jnp.bool_(True), jnp.bool_(False),
         peak))
    return lo, hi, count, it, ~changed & ~ovf, ovf, peak


@partial(jax.jit, static_argnames=("cap", "W", "b", "nil_id", "step_fn",
                                   "use_psort", "crash_dom", "key_hi",
                                   "it_max", "K", "dom_iters"))
def _host_closure_fixpoint_rows(lo, hi, count, acts, v_rows, pure_rows,
                                exp_rs, rets, n_rows, *, cap, W, b,
                                nil_id, step_fn, use_psort, crash_dom,
                                key_hi, it_max, K, dom_iters=6):
    """The WAVE fast path of the host-row executor: K consecutive rows
    as ONE device program — an outer ``lax.while_loop`` over row index
    whose body is exactly the one-row fused fixpoint
    (_host_closure_fixpoint: ungrouped closure passes with the
    iteration ceiling in-program, return filter fused in). A same-cap
    wave then costs ~1 tunnel dispatch per K rows instead of per row.

    Strictly OPTIMISTIC: the program commits only when every row
    converges cleanly. Any in-program trip — a dedup overflow, a pass
    budget exhaustion (both leave that row's arrays mid-closure
    garbage), or death (the explain snapshot must re-anchor at the
    dead row's entry) — exits the row loop early, and the host resumes
    PER-ROW from the batch entry snapshot, i.e. the proven round-6
    path with its escalation/taxonomy/witness semantics untouched.
    Soundness therefore never rests on this program: a committed batch
    is one that ran the identical per-row pass/filter pipeline to
    convergence, merely fused.

    Runtime-safety envelope: K rows at one cap — round-3/5 lore has
    8/32/64-row chunk programs clean at cap 2^20 while 512-row chunks
    fault at 2^18 (rows*cap complexity is the driver), and K defaults
    to 4 (_host_rows_k). The closure is ungrouped everywhere, so the
    per-row fixpoint terminates for the same reason the one-row
    program does. Per-row tables arrive stacked [K, ...]; rows past
    ``n_rows`` are zero padding and never execute (the row loop stops
    first — running a padding row's filter would corrupt the
    frontier).

    Returns (lo, hi, flags) with flags = i32[6]:
    [rows_done, all_converged, dead, total passes, peak settled count,
    post-filter count]."""
    def row_cond(c):
        i, _, _, _, _, _, clean, dead = c
        return (i < n_rows) & clean & ~dead

    def row_body(c):
        i, lo, hi, count, it_tot, peak, _, _ = c
        exp_r = tuple(t[i] for t in exp_rs)
        lo, hi, count, it, converged, _, peak = _closure_fixpoint_loop(
            lo, hi, count, acts[i], v_rows[i], pure_rows[i], exp_r,
            peak, cap=cap, W=W, b=b, nil_id=nil_id, step_fn=step_fn,
            crash_dom=crash_dom, it_max=it_max, dom_iters=dom_iters)
        lo, hi, count = _filter_keys_any(lo, hi, count, rets[i],
                                         cap=cap, b=b,
                                         use_psort=use_psort,
                                         key_hi=key_hi)
        return (i + 1, lo, hi, count, it_tot + it, peak, converged,
                count == 0)

    i, lo, hi, count, it_tot, peak, clean, dead = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), lo, hi, count, jnp.int32(0), count,
         jnp.bool_(True), jnp.bool_(False)))
    return lo, hi, jnp.stack([i, clean.astype(jnp.int32),
                              dead.astype(jnp.int32), it_tot, peak,
                              count])


@partial(jax.jit, static_argnames=("cap", "W", "b", "nil_id", "step_fn",
                                   "use_psort", "crash_dom", "key_hi",
                                   "it_max", "Q", "dom_iters"))
def _host_sched_rows(lo, hi, count, acts, v_rows, pure_rows, exp_rs,
                     rets, n_rows, dropback, min_left, *, cap, W, b,
                     nil_id, step_fn, use_psort, crash_dom, key_hi,
                     it_max, Q, dom_iters=6):
    """The DEVICE-RESIDENT EPISODE SCHEDULER (the kill-the-tunnel
    tentpole): one ``lax.while_loop`` over a row QUEUE of up to ``Q``
    rows whose body is exactly the proven per-row pipeline — the
    shared closure fixpoint (:func:`_closure_fixpoint_loop`, the same
    traceable the one-row and K-row wave programs run, so the
    scheduler can never drift from the per-row semantics) followed by
    the shared return filter (:func:`_filter_keys_any`) — with the
    ESCALATION DECISION made in-program: a row whose fixpoint
    overflows the capacity, exhausts its pass budget, or dies exits
    the loop immediately, and only per-row trip metadata comes back.

    Unlike the round-7 wave batch (strictly optimistic: ANY trip
    discards the whole K-row batch), the scheduler carries a COMMITTED
    frontier copy updated after every cleanly-converged row, so a trip
    at row i costs exactly row i's work — rows 0..i-1 stay committed.
    The host re-runs the tripped row on the proven
    per-row/unfused/CPU ladder (escalation, overflow taxonomy, and
    death/witness anchoring all live there), exactly like the wave
    discard; soundness therefore never rests on this program: a
    committed row is one that ran the identical per-row pass/filter
    pipeline to convergence, merely queued.

    Runtime-safety envelope: Q rows at one cap — Q defaults to 32
    (:func:`_sched_queue`), the row count proven clean at cap 2^20 by
    the spike executor's mini-chunks (rows*cap program complexity is
    the fault driver); the closure is ungrouped everywhere so each
    per-row fixpoint terminates for the round-5 reason, and every
    loop carries its iteration ceiling (``it_max`` per row; the row
    loop is bounded by ``n_rows``).

    In-program exit conditions: queue end, trip (overflow/budget),
    death, or — the dropback hand-off — the committed frontier
    shrinking to ``dropback`` after at least ``min_left`` rows (the
    host returns the search to the cheap chunked engine there, as it
    does after per-row commits).

    Rows past ``n_rows`` are zero padding and never execute. ``peak``
    (max settled per-pass count across the queue) is the sticky-cap
    decay signal, as in the wave program.

    Returns (committed lo, committed hi, flags) with flags = i32[8]:
    [rows committed, trip kind (0 none / 1 capacity / 2 budget),
    dead, total passes, peak settled count, committed count,
    rows attempted, passes spent in the non-committed row]."""
    def row_cond(c):
        i, _, _, _, _, _, ccount, crow, _, _, _, trip, dead = c
        return (i < n_rows) & (trip == 0) & ~dead \
            & ((i < min_left) | (ccount > dropback))

    def row_body(c):
        (i, lo, hi, count, clo, chi, ccount, crow, it_tot, it_last,
         peak, _, _) = c
        exp_r = tuple(t[i] for t in exp_rs)
        lo2, hi2, n2, it, converged, ovf, peak = _closure_fixpoint_loop(
            lo, hi, count, acts[i], v_rows[i], pure_rows[i], exp_r,
            peak, cap=cap, W=W, b=b, nil_id=nil_id, step_fn=step_fn,
            crash_dom=crash_dom, it_max=it_max, dom_iters=dom_iters)
        lo2, hi2, n2 = _filter_keys_any(lo2, hi2, n2, rets[i], cap=cap,
                                        b=b, use_psort=use_psort,
                                        key_hi=key_hi)
        dead2 = converged & (n2 == 0)
        commit = converged & ~dead2
        trip2 = jnp.where(converged, jnp.int32(0),
                          jnp.where(ovf, jnp.int32(1), jnp.int32(2)))
        clo2 = jnp.where(commit, lo2, clo)
        chi2 = None if chi is None else jnp.where(commit, hi2, chi)
        ccount2 = jnp.where(commit, n2, ccount)
        crow2 = jnp.where(commit, i + 1, crow)
        return (i + 1, lo2, hi2, n2, clo2, chi2, ccount2, crow2,
                it_tot + it, it, peak, trip2, dead2)

    (i, lo, hi, count, clo, chi, ccount, crow, it_tot, it_last, peak,
     trip, dead) = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), lo, hi, count, lo, hi, count, jnp.int32(0),
         jnp.int32(0), jnp.int32(0), count, jnp.int32(0),
         jnp.bool_(False)))
    # Passes inside a TRIPPED row are the thrown-away work the host's
    # waste observability prices (a dead row's passes produced the
    # verdict — not waste).
    wasted = jnp.where(trip != 0, it_last, jnp.int32(0))
    return clo, chi, jnp.stack([crow, trip, dead.astype(jnp.int32),
                                it_tot, peak, ccount, i, wasted])


def _filter_keys_any(lo, hi, count, s, *, cap, b, use_psort, key_hi):
    """The key_hi/use_psort return-filter dispatch, shared (traceable,
    not jitted itself) by the fused fixpoint and _host_filter_pass so
    the FUSED_CLOSURE=0 triage fallback can never silently diverge
    from the fused program's filter semantics."""
    if key_hi:
        lo, hi, count, _ = _filter_pass_keys2(lo, hi, count, s, cap=cap,
                                              b=b, use_psort=use_psort)
    else:
        lo, count, _ = _filter_pass_keys(lo, count, s, cap=cap, b=b,
                                         use_psort=use_psort)
    return lo, hi, count


@partial(jax.jit, static_argnames=("cap", "b", "use_psort", "key_hi"))
def _host_filter_pass(lo, hi, count, s, *, cap, b, use_psort, key_hi):
    """Host-dispatched return-event filter (see _host_rows)."""
    return _filter_keys_any(lo, hi, count, s, cap=cap, b=b,
                            use_psort=use_psort, key_hi=key_hi)


@partial(jax.jit, static_argnames=("cap", "b", "nil_id", "key_hi"))
def _host_pack(bits, state, count, *, cap, b, nil_id, key_hi):
    if key_hi:
        return _pack_frontier_keys2(bits, state, count, cap, b, nil_id)
    return _pack_frontier_keys(bits, state, count, cap, b, nil_id), None


@partial(jax.jit, static_argnames=("cap", "b", "nil_id", "nw", "key_hi"))
def _host_unpack(lo, hi, count, *, cap, b, nil_id, nw, key_hi):
    if key_hi:
        return _unpack_frontier_keys2(lo, hi, count, cap, b, nil_id, nw)
    return _unpack_frontier_keys(lo, count, cap, b, nil_id)


def _fit_keys(lo, hi, cap):
    """Grow (KEY_FILL pad) or shrink (prefix slice — live keys are a
    compacted ascending prefix; caller guarantees count <= cap) key
    arrays to ``cap``."""
    n = lo.shape[0]
    if n < cap:
        pad = jnp.full(cap - n, KEY_FILL, jnp.uint32)
        return (jnp.concatenate([lo, pad]),
                None if hi is None else jnp.concatenate([hi, pad]))
    if n > cap:
        return lo[:cap], None if hi is None else hi[:cap]
    return lo, hi


class _HostSnapshot:
    """LAZY explain snapshot for the host-row executor: holds the
    packed device key arrays (immutable) and unpacks only when the
    snapshot is actually consumed for an explain/replay. The eager
    shape paid one unpack program dispatch (a ~100 ms tunnel round
    trip) per host row, and a verdict consumes at most ONE snapshot —
    every other unpack was pure waste. ``materialize()`` returns the
    (base_row, bits, state, count) tuple witness.tail_replay_sparse
    expects; _materialize_snapshots normalizes mixed lists."""

    __slots__ = ("base", "_lo", "_hi", "_count", "_b", "_nil_id",
                 "_nw", "_key_hi")

    def __init__(self, base, lo, hi, count, b, nil_id, nw, key_hi):
        self.base = base
        self._lo, self._hi, self._count = lo, hi, count
        self._b, self._nil_id = b, nil_id
        self._nw, self._key_hi = nw, key_hi

    def materialize(self):
        bits, state = _host_unpack(
            self._lo, self._hi, self._count, cap=self._lo.shape[0],
            b=self._b, nil_id=self._nil_id, nw=self._nw,
            key_hi=self._key_hi)
        return (self.base, bits, state, self._count)


def _materialize_snapshots(snapshots):
    """Resolve any lazy host-row snapshots into the concrete
    (base, bits, state, count) tuples the witness replay consumes
    (chunk/spike snapshots are already concrete and pass through)."""
    if snapshots is None:
        return None
    return [s.materialize() if isinstance(s, _HostSnapshot) else s
            for s in snapshots]


def _host_row_cpu(p, r, lo, hi, count_i, *, b, nil_id, key_hi, nw,
                  crash_dom=False, cancel=None):
    """The LAST rung of the host-row fallback ladder: one row's whole
    closure + return filter on the CPU oracle (cpu.search_rows with
    ``reduce=True`` — the same exact reduction family every device
    engine consumes, parity-fuzzed in tests/test_lin_reductions.py),
    entered only when every device rung for this row has faulted or
    wedged. Deliberately DEVICE-FREE end to end: the packed keys are
    decoded and re-encoded with the numpy codec (supervise.np_*) since
    the device may be mid-restart after the fault that sent us here.

    With ``crash_dom`` the survivors additionally run the EXACT
    crashed-subset/read-bit dominance prune (the _dedup_keys_dom rule,
    group representative = popcount-ordered antichain scan) on the
    host — without it the handed-back frontier is the UNpruned
    crashed-subset wave, which overflows the very capacities whose
    device programs just faulted. Raises cpu.Cancelled through.
    Returns (lo_np, hi_np|None, count, dead); output arrays are sized
    max(input cap, survivor count), KEY_FILL padded, key-ascending."""
    from jepsen_tpu.lin import cpu
    from jepsen_tpu.models.kernels import NIL

    lo_h = np.asarray(lo)
    hi_h = np.asarray(hi) if key_hi else None
    cap = int(lo_h.shape[0])
    bits, state = supervise.np_unpack_keys(
        lo_h, hi_h, count_i, b, nil_id, nw, key_hi, int(NIL))
    packed = bits[:, 0].astype(object)
    for w in range(1, bits.shape[1]):
        packed = packed | (bits[:, w].astype(object) << (32 * w))
    configs = set(zip((int(x) for x in packed),
                      map(tuple, state.tolist())))
    try:
        configs, _ = cpu.search_rows(p, configs, None, r, r + 1,
                                     cancel=cancel, reduce=True)
    except cpu.Dead:
        return lo_h, hi_h, 0, True
    if crash_dom:
        from jepsen_tpu.lin.prepare import reduction_tables

        pure_tbl, _pred = reduction_tables(p)
        act = np.asarray(p.active)[r]
        crashed = np.asarray(p.crashed)[r]
        cmask = rmask = 0
        for j in range(p.window):
            if act[j] and crashed[j]:
                cmask |= 1 << j
            elif act[j] and pure_tbl[r, j]:
                rmask |= 1 << j
        if cmask or rmask:
            # Group by (mutator bits, state); within a group X
            # dominates Y iff X's packed dominance word (crashed bits
            # as-is, read bits complemented — disjoint masks, so
            # subset test is one AND) is a strict subset of Y's.
            # Popcount-ascending scan keeps exactly the antichain:
            # a dominator always has fewer bits than its victims.
            groups: dict = {}
            for bset, st in configs:
                w = (bset & cmask) | (~bset & rmask)
                groups.setdefault((bset & ~(cmask | rmask), st),
                                  []).append((bin(w).count("1"), w,
                                              bset))
            pruned = []
            for (gbits, st), lst in groups.items():
                lst.sort()
                kept: list[int] = []
                for _pc, w, bset in lst:
                    if any((kw & ~w) == 0 for kw in kept):
                        continue
                    kept.append(w)
                    pruned.append((bset, st))
            configs = pruned
    n2 = len(configs)

    def enc(bset, st):
        sid = nil_id if st[0] == int(NIL) else st[0]
        return (bset << b) | sid

    ordered = sorted(configs, key=lambda c: enc(*c))
    bits2 = np.zeros((n2, nw), np.uint32)
    state2 = np.zeros((n2, 1), np.int32)
    for i, (bset, st) in enumerate(ordered):
        for w in range(nw):
            bits2[i, w] = (bset >> (32 * w)) & 0xFFFFFFFF
        state2[i, 0] = st[0]
    lo2, hi2 = supervise.np_pack_keys(bits2, state2, b, nil_id, key_hi,
                                      int(NIL), max(cap, n2))
    return lo2, hi2, n2, False


def _host_rows(p, r0, bits, state, count, *, tables_h, exp_h, caps,
               dropback, step_fn, state_bits, nil_id, use_psort,
               key_hi, crash_dom, cancel, snapshots,
               min_rows: int = 2, stats: dict | None = None,
               ckpt=None, sticky0=None):
    """Host-sequenced row mode for the compact register band's blowup
    rows (the crashed-subset waves of BASELINE config 5's partition
    histories). Each row's whole closure fixpoint runs as ONE device
    dispatch (_host_closure_fixpoint: a lax.while_loop over ungrouped
    closure passes with the iteration ceiling in-program and the return
    filter fused in), with the host driving only capacity escalation —
    one ~100 ms tunnel round trip per (row, capacity) instead of one
    per closure PASS (the round-5 shape, ~12+ passes per row across
    ~90 episodes: the dominant cost of the 3217 s config-5 decide).
    ``JEPSEN_TPU_FUSED_CLOSURE=0`` falls back to per-pass dispatches
    (_host_closure_pass) for fault triage. Single-dispatch sequencing
    also keeps the dominance window engaged at EVERY capacity
    (psort dom_force), which is what collapses the wave (rep-only
    pruning leaves 389k configs; rep+window converges to ~14k). Only
    rows whose frontiers outgrow the chunked tiers ever come here.

    The executor is EPISODE-SCHEDULED (the kill-the-tunnel tentpole):
    by default a queue of up to ``JEPSEN_TPU_SCHED_QUEUE`` rows runs
    as ONE device program (:func:`_host_sched_rows`) that commits the
    clean prefix in-program and returns trip metadata — ~1 dispatch
    per clean episode. A tripped/quarantined/wedged scheduler row
    falls to the round-7 wave batch and then the proven per-row
    ladder below; ``JEPSEN_TPU_HOST_SCHED=0`` disables it.

    The executor is additionally WAVE-AWARE (round 7), on three
    independently env-gated axes over the unchanged escalation core:

    - STICKY CAPS (_host_sticky): a wave's last converged capacity
      level seeds the next row's starting level instead of the cold
      ``lvl_for(count)`` — entry counts are small even when the
      mid-closure frontier needs the big caps, so the cold ladder
      re-climbs (and throws away a full fixpoint run per failed rung)
      on every row of a wave. The sticky level decays one level per
      row whose in-program PEAK settled count fits comfortably below
      it, so an over-provisioned level drains back. Starting level is
      the only thing sticky touches: overflow still escalates, so
      soundness is untouched.
    - WAVE BATCHES (_host_rows_k): K consecutive rows run as ONE
      fused device program (_host_closure_fixpoint_rows) when the
      executor is not recovering from a trip — ~1 dispatch per K rows
      on a same-cap wave. Strictly optimistic: any in-program trip
      (overflow, budget, death) discards the batch and resumes
      PER-ROW from the batch entry (the proven round-6 shape).
    - TIMING/WASTE OBSERVABILITY: see the stats keys below — bench's
      partitioned probe surfaces them so the residual config-5 cost
      profile reads directly off the artifact.

    ``stats`` (when given) accumulates observability counters:
    ``rows`` (host rows run), ``dispatches`` (closure-program
    dispatches — the tunnel round trips the wave axes are cutting),
    ``passes`` (closure passes executed inside them),
    ``wasted_passes`` (passes whose output was discarded: failed
    escalation rungs, tripped wave batches, and tripped scheduler
    rows), ``sticky_hits`` / ``sticky_misses`` (rows whose
    sticky-raised starting level converged without / despite further
    escalation), ``multi_rows`` / ``multi_dispatches`` /
    ``multi_trips`` (wave-batch traffic), ``sched_rows`` /
    ``sched_dispatches`` / ``sched_trips`` (episode-scheduler
    traffic), and ``cap_seconds`` (wall seconds of closure dispatches
    per capacity).

    Same contract as _spike_rows: returns (bits, state, count_int,
    next_row, dead, overflowed, cancelled, top_cap_used) — except
    ``overflowed`` is falsy or a REASON string: "capacity" (a dedup
    overflowed the last host cap) or "budget" (the closure pass budget
    was exhausted there — the nontermination class round 5 diagnosed;
    reporting it as a capacity overflow would misdirect triage)."""
    ret_slot_h, active_h, _slot_f_h, slot_v_h, pure_h, _pred = tables_h
    b = state_bits
    W = p.window
    nw = (W + 31) // 32
    count_i = int(count)
    top_used = caps[0]
    fused = _fused_closure()
    sticky = _host_sticky()
    K = _host_rows_k() if fused else 1
    sched_on = _host_sched() and fused
    Q = _sched_queue()
    # Pass budget per (row, capacity): ungrouped convergence needs
    # O(window) passes; exhaustion escalates like an overflow (sound —
    # the row restarts from its entry frontier).
    it_max = _host_it_max(W)
    dbg = os.environ.get("JEPSEN_TPU_HOST_DEBUG") == "1"
    kname = p.kernel.name if p.kernel is not None else "generic"
    if stats is None:
        stats = {}
    for k in ("rows", "dispatches", "passes", "wasted_passes",
              "sticky_hits", "sticky_misses", "multi_rows",
              "multi_dispatches", "multi_trips", "sched_rows",
              "sched_dispatches", "sched_trips", "watchdog_trips",
              "faults", "quarantine_skips", "static_skips",
              "cpu_rows"):
        stats.setdefault(k, 0)
    stats.setdefault("cap_seconds", {})

    def skey(site, cap_, rows_=1):
        return supervise.shape_key(site, rows=rows_, cap=cap_, window=W,
                                   kernel=kname)

    def lvl_for(c):
        for i, cc in enumerate(caps):
            if c * TIER_MARGIN <= cc:
                return i
        return len(caps) - 1

    def unpack(lo, hi, cnt, cap):
        return _host_unpack(lo, hi, cnt, cap=cap, b=b, nil_id=nil_id,
                            nw=nw, key_hi=key_hi)

    def snap(at_r, lo, hi, cnt):
        # Lazy: the packed refs are stored; the device->host unpack
        # runs only if an explain/replay actually consumes them.
        if snapshots is not None:
            snapshots[:] = [_HostSnapshot(at_r, lo, hi, cnt, b, nil_id,
                                          nw, key_hi)]

    def save_ckpt(at_r, lo_, hi_, cnt_i):
        # Episode-boundary frontier checkpoint: packed keys + row
        # cursor + sticky level + host-stats, written only at COMMITTED
        # row boundaries (the resumed run re-runs the identical
        # deterministic dispatch sequence from here, so the verdict
        # provably matches the uninterrupted run). Interval-gated: the
        # device->host key copy is ~MBs, paid at most once per
        # ckpt.every_s.
        if ckpt is None or not ckpt.due():
            return
        arrays = {"lo": np.asarray(lo_)}
        if key_hi:
            arrays["hi"] = np.asarray(hi_)
        ckpt.save("host", at_r, cnt_i, arrays,
                  {"key_hi": key_hi, "b": b, "nil_id": nil_id, "nw": nw,
                   "sticky_lvl": sticky_lvl,
                   "host_stats": util.round_stats(stats)})

    if count_i > caps[-1]:
        return (bits, state, count_i, r0, False, "capacity", False,
                top_used)
    sticky_lvl = lvl = lvl_for(count_i)
    if sticky0 is not None:
        # Resume: the checkpoint carries the wave's sticky level so a
        # resumed run re-enters the wave at the capacity it had already
        # climbed to instead of re-paying the cold ladder.
        sticky_lvl = max(sticky_lvl, min(int(sticky0), len(caps) - 1))
    cap = caps[lvl]
    lo, hi = _host_pack(bits, state, jnp.int32(count_i), cap=cap, b=b,
                        nil_id=nil_id, key_hi=key_hi)
    count = jnp.int32(count_i)
    r = r0
    per_row_until = r0   # rows below this resume per-row after a trip
    while r < p.R:
        if cancel is not None and cancel.is_set():
            bits, state = unpack(lo, hi, count, lo.shape[0])
            return (bits, state, count_i, r, False, False, True,
                    top_used)
        natural = lvl_for(count_i)
        start_lvl = max(natural, sticky_lvl) if sticky else natural
        raised = start_lvl > natural
        # ---- device-resident episode scheduler: a row QUEUE as ONE
        # dispatch (the kill-the-tunnel tentpole). Commits the clean
        # prefix in-program; a trip costs only the tripped row, which
        # the proven per-row ladder below then owns.
        qn = min(Q, p.R - r)
        use_sched = sched_on and qn > 1 and r >= per_row_until
        if use_sched and supervise.quarantined(
                skey("host-sched", caps[start_lvl], qn)):
            # A quarantined scheduler shape routes to the proven
            # wave/per-row rungs — the fault lore as machine state.
            util.stat_bump(stats, "quarantine_skips")
            use_sched = False
        if use_sched:
            lvl = start_lvl
            cap = caps[lvl]
            top_used = max(top_used, cap)
            snap(r, lo, hi, count)
            lo, hi = _fit_keys(lo, hi, cap)
            entry = (lo, hi, count, lvl)
            acts = jnp.asarray(_chunk_slice(active_h, r, Q))
            v_rows = jnp.asarray(_chunk_slice(slot_v_h, r, Q))
            pure_rows = jnp.asarray(_chunk_slice(pure_h, r, Q))
            rets = jnp.asarray(_chunk_slice(ret_slot_h, r, Q))
            exp_rs = tuple(jnp.asarray(_chunk_slice(t, r, Q))
                           for t in exp_h)
            # Rows that must run regardless of the in-program dropback
            # exit (the min_rows contract is relative to the episode
            # entry r0, not this dispatch).
            min_left = max(1, min(qn, min_rows - (r - r0)))
            util.progress_tick()
            t0 = _time.monotonic()

            def _sched_prog(lo=lo, hi=hi, count=count, qn=qn,
                            min_left=min_left, acts=acts,
                            v_rows=v_rows, pure_rows=pure_rows,
                            exp_rs=exp_rs, rets=rets, cap=cap):
                return _host_sched_rows(
                    lo, hi, count, acts, v_rows, pure_rows, exp_rs,
                    rets, jnp.int32(qn), jnp.int32(dropback),
                    jnp.int32(min_left), cap=cap, W=W, b=b,
                    nil_id=nil_id, step_fn=step_fn,
                    use_psort=use_psort, crash_dom=crash_dom,
                    key_hi=key_hi, it_max=it_max, Q=Q)

            def _sched():
                clo, chi, flags = _sched_prog()
                return clo, chi, np.asarray(flags)

            # A whole episode legitimately runs many fixpoints in ONE
            # dispatch: scale the watchdog deadline with the queue
            # (the K-row wave's 3x, per 4 queued rows).
            outcome, val = supervise.run_guarded(
                "host-sched", skey("host-sched", cap, qn), _sched,
                scale=3.0 * max(1.0, qn / 4.0), stats=stats,
                traceable=_sched_prog)
            if outcome != "ok":
                # Wedged/faulted/static-flagged scheduler dispatch:
                # the proven wave/per-row rungs own the next row (its
                # non-ok dispatch span already prices the wall); the
                # scheduler resumes after it.
                lo, hi, count, lvl = entry
                per_row_until = r + 1
                continue
            clo, chi, flags = val
            (crow, trip, dead_f, it_tot, pk, ccnt, attempted,
             wasted) = (int(x) for x in flags)
            util.stat_time(stats, "cap_seconds", cap,
                           _time.monotonic() - t0)
            util.stat_bump(stats, "dispatches")
            util.stat_bump(stats, "sched_dispatches")
            util.stat_bump(stats, "passes", it_tot)
            obs_trace.tail_note(row=r, rows=crow, passes=it_tot,
                                count=ccnt)
            if dbg:
                print(f"[host] r={r} cap={cap} sched qn={qn} "
                      f"crow={crow} trip={trip} dead={dead_f} "
                      f"it={it_tot} peak={pk} count={ccnt}",
                      flush=True)
            # The committed copy is always valid (it initializes to
            # the episode entry), so the carried frontier advances to
            # it unconditionally.
            lo, hi, count = clo, chi, jnp.int32(ccnt)
            count_i = ccnt
            r += crow
            if crow:
                util.stat_bump(stats, "rows", crow)
                util.stat_bump(stats, "sched_rows", crow)
                if sticky:
                    if raised:
                        util.stat_bump(stats, "sticky_hits", crow)
                    if lvl > sticky_lvl:
                        sticky_lvl = lvl
                    elif lvl_for(pk) < sticky_lvl:
                        sticky_lvl -= 1
                save_ckpt(r, lo, hi, count_i)
                obs_metrics.REGISTRY.progress(row=r, frontier=count_i)
            if dead_f:
                # The committed frontier IS the dead row's entry —
                # anchor the explain snapshot there so the CPU replay
                # spans ONE row, exactly like the per-row dead path.
                snap(r, lo, hi, count)
                r += 1
                return (jnp.zeros((1, nw), jnp.uint32),
                        jnp.zeros((1, 1), jnp.int32), 0, r, True,
                        False, False, top_used)
            if trip:
                # Overflow/budget at row r: the proven per-row ladder
                # owns escalation and the overflow taxonomy for it.
                util.stat_bump(stats, "sched_trips")
                util.stat_bump(stats, "wasted_passes", wasted)
                obs_trace.instant(
                    "sched-trip", row=r, cap=cap, passes=wasted,
                    kind="capacity" if trip == 1 else "budget")
                per_row_until = r + 1
                continue
            if r >= p.R or (r - r0 >= min_rows
                            and count_i <= dropback):
                break
            continue
        # ---- wave fast path: K rows fused into ONE dispatch --------
        kn = min(K, p.R - r)
        # Reached only when the scheduler did not handle this
        # iteration (off, quarantined, or recovering per-row): the
        # wave batch is the scheduler's first fallback rung.
        use_wave = kn > 1 and r >= per_row_until
        if use_wave and supervise.quarantined(
                skey("host-wave", caps[start_lvl], kn)):
            # A quarantined wave shape routes straight to the proven
            # per-row rung — the round 2-5 fault lore as machine state.
            util.stat_bump(stats, "quarantine_skips")
            use_wave = False
        if use_wave:
            lvl = start_lvl
            cap = caps[lvl]
            top_used = max(top_used, cap)
            snap(r, lo, hi, count)
            lo, hi = _fit_keys(lo, hi, cap)
            entry = (lo, hi, count, lvl)
            acts = jnp.asarray(_chunk_slice(active_h, r, K))
            v_rows = jnp.asarray(_chunk_slice(slot_v_h, r, K))
            pure_rows = jnp.asarray(_chunk_slice(pure_h, r, K))
            rets = jnp.asarray(_chunk_slice(ret_slot_h, r, K))
            exp_rs = tuple(jnp.asarray(_chunk_slice(t, r, K))
                           for t in exp_h)
            util.progress_tick()
            t0 = _time.monotonic()

            def _wave_prog(lo=lo, hi=hi, count=count):
                return _host_closure_fixpoint_rows(
                    lo, hi, count, acts, v_rows, pure_rows, exp_rs,
                    rets, jnp.int32(kn), cap=cap, W=W, b=b,
                    nil_id=nil_id, step_fn=step_fn, use_psort=use_psort,
                    crash_dom=crash_dom, key_hi=key_hi, it_max=it_max,
                    K=K)

            def _wave():
                lo2, hi2, flags = _wave_prog()
                return lo2, hi2, np.asarray(flags)

            # The K-row fixpoint legitimately runs minutes in one
            # dispatch: 3x the base watchdog deadline.
            outcome, val = supervise.run_guarded(
                "host-wave", skey("host-wave", cap, kn), _wave,
                scale=3.0, stats=stats, traceable=_wave_prog)
            tripped = None if outcome == "ok" else outcome
            if tripped is None:
                lo2, hi2, flags = val
                done, clean, dead_f, it_tot, pk, cnt = (
                    int(x) for x in flags)
                util.stat_time(stats, "cap_seconds", cap,
                               _time.monotonic() - t0)
                util.stat_bump(stats, "dispatches")
                util.stat_bump(stats, "multi_dispatches")
                util.stat_bump(stats, "passes", it_tot)
                obs_trace.tail_note(row=r, rows=kn, passes=it_tot,
                                    count=cnt)
                if dbg:
                    print(f"[host] r={r} cap={cap} wave kn={kn} "
                          f"done={done} clean={clean} dead={dead_f} "
                          f"it={it_tot} peak={pk} count={cnt}",
                          flush=True)
            if tripped is None and clean and not dead_f and done == kn:
                lo, hi, count = lo2, hi2, jnp.int32(cnt)
                count_i = cnt
                util.stat_bump(stats, "rows", kn)
                util.stat_bump(stats, "multi_rows", kn)
                if sticky:
                    if raised:
                        util.stat_bump(stats, "sticky_hits", kn)
                    if lvl > sticky_lvl:
                        # A batch that ran at a HIGHER natural level
                        # converged there: seed the next wave rows at
                        # it (mirrors the per-row raise — without this
                        # a stale-low sticky re-trips every batch of
                        # the wave).
                        sticky_lvl = lvl
                    elif lvl_for(pk) < sticky_lvl:
                        sticky_lvl -= 1
                r += kn
                save_ckpt(r, lo, hi, count_i)
                obs_metrics.REGISTRY.progress(row=r, frontier=count_i)
                if r - r0 >= min_rows and count_i <= dropback:
                    break
                continue
            # Trip (overflow / budget / death somewhere in the batch —
            # or a wedged/faulted wave dispatch): the carried arrays
            # are mid-closure garbage for the tripped row — discard
            # the whole batch and resume PER-ROW from the batch entry,
            # where escalation, the overflow taxonomy, and death
            # snapshot anchoring live.
            util.stat_bump(stats, "multi_trips")
            # The tripped batch's dispatch wall is thrown away with it
            # — the residual-waste profile the attribution report
            # prices (wasted_seconds per cap; wave-trip trace event).
            wave_s = _time.monotonic() - t0
            util.stat_time(stats, "wasted_seconds", cap, wave_s)
            # A wedged/faulted wave's wall is already priced by its
            # non-ok dispatch span; carrying it on the instant too
            # would double-count wasted_s in the attribution report.
            obs_trace.instant("wave-trip", row=r, cap=cap, kn=kn,
                              outcome=tripped or "trip",
                              seconds=round(wave_s, 3)
                              if tripped is None else 0.0)
            if tripped is None:
                util.stat_bump(stats, "wasted_passes", it_tot)
            lo, hi, count, lvl = entry
            per_row_until = r + kn
        # ---- per-row path (the proven round-6 shape) ---------------
        snap(r, lo, hi, count)
        act = jnp.asarray(active_h[r])
        v_row = jnp.asarray(slot_v_h[r])
        pure_row = jnp.asarray(pure_h[r])
        ret = jnp.int32(int(ret_slot_h[r]))
        exp_r = tuple(jnp.asarray(t[r]) for t in exp_h)
        lvl = start_lvl
        entry = (lo, hi, count, lvl)
        stats["rows"] += 1
        budget_out = False
        filtered = False
        escalated = False
        cpu_row = False
        row_fused = fused
        peak_row = count_i
        while True:  # closure fixpoint, escalating capacity on overflow
            cap = caps[lvl]
            top_used = max(top_used, cap)
            lo, hi = _fit_keys(lo, hi, cap)
            rung_s = 0.0   # this rung's dispatch wall (wasted if it
            #                overflows and escalates)
            util.progress_tick()
            run_fused = row_fused
            if run_fused and supervise.quarantined(
                    skey("host-fixpoint", cap)):
                # Quarantined fused shape: run this capacity on the
                # proven per-pass rung instead of re-faulting it.
                util.stat_bump(stats, "quarantine_skips")
                run_fused = False
            if run_fused:
                t0 = _time.monotonic()

                def _fixpoint_prog(lo=lo, hi=hi, count=count):
                    return _host_closure_fixpoint(
                        lo, hi, count, act, v_row, pure_row, exp_r,
                        ret, cap=cap, W=W, b=b, nil_id=nil_id,
                        step_fn=step_fn, use_psort=use_psort,
                        crash_dom=crash_dom, key_hi=key_hi,
                        it_max=it_max)

                def _fixpoint():
                    l2, h2, flags = _fixpoint_prog()
                    return l2, h2, np.asarray(flags)

                # One fused fixpoint legitimately runs minutes:
                # 3x the base watchdog deadline.
                outcome, val = supervise.run_guarded(
                    "host-fixpoint", skey("host-fixpoint", cap),
                    _fixpoint, scale=3.0, stats=stats,
                    traceable=_fixpoint_prog)
                if outcome != "ok":
                    # Wedged/faulted fused program: this row falls to
                    # the unfused per-pass rung at the same capacity,
                    # restarting from its entry frontier.
                    row_fused = False
                    lo, hi, count, _ = entry
                    continue
                lo, hi, flags = val
                conv, ov, it, cnt, pk = (int(x) for x in flags)
                dt = _time.monotonic() - t0
                util.stat_time(stats, "cap_seconds", cap, dt)
                rung_s += dt
                stats["dispatches"] += 1
                stats["passes"] += it
                obs_trace.tail_note(row=r, passes=it, count=cnt)
                count = jnp.int32(cnt)
                ovf = not conv
                budget_out = bool(ovf and not ov)
                filtered = True
                if not ovf:
                    peak_row = max(peak_row, pk)
                if dbg:
                    print(f"[host] r={r} cap={cap} fused it={it} "
                          f"count={cnt} conv={conv} ov={ov}",
                          flush=True)
            else:
                if supervise.quarantined(skey("host-pass", cap)):
                    # Even the unfused per-pass program is quarantined
                    # at this shape: last rung — the CPU oracle.
                    util.stat_bump(stats, "quarantine_skips")
                    cpu_row = True
                    break
                it = 0
                ovf = False
                budget_out = False
                pk_att = count_i
                while True:
                    t0 = _time.monotonic()

                    def _pass_prog(lo=lo, hi=hi, count=count):
                        return _host_closure_pass(
                            lo, hi, count, act, v_row, pure_row,
                            exp_r, cap=cap, W=W, b=b,
                            nil_id=nil_id, step_fn=step_fn,
                            use_psort=use_psort,
                            crash_dom=crash_dom)

                    def _pass():
                        l2, h2, c2, flags = _pass_prog()
                        return l2, h2, c2, np.asarray(flags)

                    outcome, val = supervise.run_guarded(
                        "host-pass", skey("host-pass", cap), _pass,
                        stats=stats, traceable=_pass_prog)
                    if outcome != "ok":
                        # Wedged/faulted per-pass program: last rung —
                        # the CPU oracle owns this row.
                        cpu_row = True
                        break
                    lo, hi, count, flags = val
                    ch, ov, cnt = (int(x) for x in flags)
                    dt = _time.monotonic() - t0
                    util.stat_time(stats, "cap_seconds", cap, dt)
                    rung_s += dt
                    it += 1
                    stats["dispatches"] += 1
                    stats["passes"] += 1
                    obs_trace.tail_note(row=r, count=cnt)
                    pk_att = max(pk_att, cnt)
                    if dbg:
                        print(f"[host] r={r} cap={cap} it={it} "
                              f"count={cnt} ch={ch} ov={ov}",
                              flush=True)
                    if ov:
                        ovf = True
                        break
                    # Convergence BEFORE the ceiling: a pass that
                    # settles exactly at the budget is converged,
                    # not overflowed (the ceiling exists to convert
                    # nontermination into an honest overflow).
                    if not ch:
                        break
                    if it >= it_max:
                        ovf = True
                        budget_out = True
                        break
                if cpu_row:
                    break
                if not ovf:
                    peak_row = max(peak_row, pk_att)
            if not ovf:
                break
            # The failed rung's passes were thrown away — the waste
            # the sticky cap exists to cut (and the attribution
            # report prices: wasted_seconds per cap + trace event).
            util.stat_bump(stats, "wasted_passes", it)
            util.stat_time(stats, "wasted_seconds", cap, rung_s)
            obs_trace.instant("wasted-rung", row=r, cap=cap,
                              passes=it, seconds=round(rung_s, 3))
            if lvl + 1 >= len(caps):
                # Overflow of the last host cap: hand back the row's
                # ENTRY frontier (the escalation restart point — the
                # mid-pass arrays are truncated) as an honest failure,
                # tagged with WHY (capacity vs pass budget). Unpack at
                # the entry arrays' OWN size: entry lvl is the level
                # selected for the row, which can exceed the arrays'
                # cap when the previous row finished smaller.
                e_lo, e_hi, e_count, _ = entry
                bits, state = unpack(e_lo, e_hi, e_count,
                                     e_lo.shape[0])
                return (bits, state, int(e_count), r, False,
                        "budget" if budget_out else "capacity",
                        False, top_used)
            lo, hi, count, _ = entry
            lvl += 1
            escalated = True
        if cpu_row:
            # ---- CPU-oracle rung: every device rung for this row
            # faulted, wedged, or is quarantined. Run the row on the
            # host spec from its ENTRY frontier (the mid-closure
            # arrays are garbage), device-free.
            from jepsen_tpu.models.kernels import NIL

            e_lo, e_hi, e_count, _ = entry
            e_count_i = int(e_count)
            if e_count_i > supervise.cpu_row_max():
                # A frontier this size would grind the Python closure
                # for hours: honest give-up, tagged so triage chases
                # the fault, not frontier size.
                bits_np, state_np = supervise.np_unpack_keys(
                    np.asarray(e_lo),
                    np.asarray(e_hi) if key_hi else None,
                    e_count_i, b, nil_id, nw, key_hi, int(NIL))
                return (jnp.asarray(bits_np), jnp.asarray(state_np),
                        e_count_i, r, False, "wedged", False, top_used)
            from jepsen_tpu.lin import cpu as _cpu

            try:
                lo_np, hi_np, n2, dead_cpu = _host_row_cpu(
                    p, r, e_lo, e_hi, e_count_i, b=b, nil_id=nil_id,
                    key_hi=key_hi, nw=nw, crash_dom=crash_dom,
                    cancel=cancel)
            except _cpu.Cancelled:
                bits_np, state_np = supervise.np_unpack_keys(
                    np.asarray(e_lo),
                    np.asarray(e_hi) if key_hi else None,
                    e_count_i, b, nil_id, nw, key_hi, int(NIL))
                return (jnp.asarray(bits_np), jnp.asarray(state_np),
                        e_count_i, r, False, False, True, top_used)
            util.stat_bump(stats, "cpu_rows")
            if dbg:
                print(f"[host] r={r} cpu-oracle rung count={n2} "
                      f"dead={dead_cpu}", flush=True)
            r += 1
            if dead_cpu or n2 == 0:
                # Dead at row r-1; the explain snapshot is anchored at
                # its entry frontier (snap() above), exactly like the
                # device dead path.
                return (jnp.zeros((1, nw), jnp.uint32),
                        jnp.zeros((1, 1), jnp.int32), 0, r, True,
                        False, False, top_used)
            if n2 > caps[-1]:
                # The capacity-unbounded CPU closure outgrew the host
                # ladder: an honest overflow — handing the oversized
                # frontier forward would let the next row's _fit_keys
                # silently TRUNCATE live configs (verdict-flipping).
                bits_np2, state_np2 = supervise.np_unpack_keys(
                    lo_np, hi_np, n2, b, nil_id, nw, key_hi, int(NIL))
                return (jnp.asarray(bits_np2), jnp.asarray(state_np2),
                        n2, r, False, "capacity", False, top_used)
            lo = jnp.asarray(lo_np)
            hi = jnp.asarray(hi_np) if key_hi else None
            count = jnp.int32(n2)
            count_i = n2
            save_ckpt(r, lo, hi, count_i)
            obs_metrics.REGISTRY.progress(row=r, frontier=count_i)
            if r - r0 >= min_rows and count_i <= dropback:
                break
            continue
        if sticky:
            if raised:
                util.stat_bump(
                    stats, "sticky_misses" if escalated
                    else "sticky_hits")
            if lvl > sticky_lvl:
                sticky_lvl = lvl
            elif lvl_for(peak_row) < sticky_lvl:
                # Decay on the in-program peak, not the settled exit
                # count: waves enter small and blow up mid-closure, so
                # the exit count would decay sticky every row and
                # re-climb every next one.
                sticky_lvl -= 1
        if not filtered:
            lo, hi, count = _host_filter_pass(
                lo, hi, count, ret, cap=cap, b=b,
                use_psort=use_psort, key_hi=key_hi)
        count_i = int(count)
        r += 1
        if count_i == 0:
            # Dead at row r-1; the explain snapshot is anchored at its
            # entry frontier (set above), spanning ONE row of replay.
            bits, state = unpack(lo, hi, count, cap)
            return bits, state, 0, r, True, False, False, top_used
        save_ckpt(r, lo, hi, count_i)
        obs_metrics.REGISTRY.progress(row=r, frontier=count_i)
        if r - r0 >= min_rows and count_i <= dropback:
            break
    bits, state = unpack(lo, hi, count, lo.shape[0])
    return bits, state, count_i, r, False, False, False, top_used


def _pack_frontier_keys(bits, state, count, cap, b, nil_id):
    """THE packed-key encoding — ``bits << b | state-id`` with NIL
    remapped to nil_id, KEY_FILL past count, padded/sliced to ``cap``.
    Single definition shared by the chunked engine, the spike executor
    handoff, and the resume path, so the layout cannot drift."""
    from jepsen_tpu.models.kernels import NIL

    n = bits.shape[0]
    sv = state[:, 0]
    ps = jnp.where(sv == NIL, nil_id, sv).astype(jnp.uint32)
    keys = jnp.where(jnp.arange(n) < count, (bits[:, 0] << b) | ps,
                     KEY_FILL)
    if cap > n:
        keys = jnp.concatenate(
            [keys, jnp.full(cap - n, KEY_FILL, jnp.uint32)])
    return keys[:cap]


def _unpack_frontier_keys(keys, count, cap, b, nil_id):
    """Inverse of _pack_frontier_keys: (bits[cap,1], state[cap,1]),
    zeroed past count (count must fit cap)."""
    from jepsen_tpu.models.kernels import NIL

    k = keys[:cap]
    live = jnp.arange(cap) < count
    cfg = jnp.where(live, k, 0)
    sv = (cfg & jnp.uint32((1 << b) - 1)).astype(jnp.int32)
    state = jnp.where(live, jnp.where(sv == nil_id, NIL, sv), 0)
    return (cfg >> b)[:, None], state[:, None]


def _pack_frontier_keys2(bits, state, count, cap, b, nil_id):
    """Pair-key encoding for windows past 31-b bits: the 64-bit config
    ``bitset << b | state-id`` split into (lo, hi) u32 words. Inverse:
    _unpack_frontier_keys2."""
    from jepsen_tpu.models.kernels import NIL

    n = bits.shape[0]
    sv = state[:, 0]
    ps = jnp.where(sv == NIL, nil_id, sv).astype(jnp.uint32)
    b0 = bits[:, 0]
    b1 = bits[:, 1] if bits.shape[1] > 1 else jnp.zeros_like(b0)
    lo = (b0 << b) | ps
    hi = (b0 >> (32 - b)) | (b1 << b)
    live = jnp.arange(n) < count
    lo = jnp.where(live, lo, KEY_FILL)
    hi = jnp.where(live, hi, KEY_FILL)
    if cap > n:
        pad = jnp.full(cap - n, KEY_FILL, jnp.uint32)
        lo = jnp.concatenate([lo, pad])
        hi = jnp.concatenate([hi, pad])
    return lo[:cap], hi[:cap]


def _unpack_frontier_keys2(lo, hi, count, cap, b, nil_id, nw):
    """Inverse of _pack_frontier_keys2: (bits[cap,nw], state[cap,1])."""
    from jepsen_tpu.models.kernels import NIL

    live = jnp.arange(cap) < count
    lo = jnp.where(live, lo[:cap], 0)
    hi = jnp.where(live, hi[:cap], 0)
    sv = (lo & jnp.uint32((1 << b) - 1)).astype(jnp.int32)
    state = jnp.where(live, jnp.where(sv == nil_id, NIL, sv), 0)
    b0 = (lo >> b) | ((hi & jnp.uint32((1 << b) - 1)) << (32 - b))
    cols = [b0]
    if nw > 1:
        cols.append(hi >> b)
    bits = jnp.stack(cols, axis=1)
    if nw > len(cols):
        bits = jnp.pad(bits, ((0, 0), (0, nw - len(cols))))
    return bits, state[:, None]


def _chunk_slice(a: np.ndarray, base: int, chunk: int) -> np.ndarray:
    """Static-shape chunk slice, zero-padded past the end of the table."""
    end = min(base + chunk, a.shape[0])
    part = a[base:end]
    if part.shape[0] == chunk:
        return part
    pad = np.zeros((chunk - part.shape[0],) + a.shape[1:], a.dtype)
    return np.concatenate([part, pad], axis=0)


def _pad_rows(p: PackedHistory):
    """Bucket R up to a power of two with identity rows so XLA compiles one
    kernel per bucket instead of one per history length.

    An identity row uses a dedicated pad slot (column W) carrying the
    universal no-op f: every config linearizes it (state unchanged), the
    filter keeps everyone, and the recycle clears the bit — frontier exactly
    preserved. Requires one spare bit, so only applied when window < 32.
    """
    from jepsen_tpu.models.kernels import F_NOOP

    R, W = p.active.shape
    R_pad = 1 << max(4, (R - 1).bit_length())
    if R_pad == R or W >= 32:
        return (np.asarray(p.ret_slot), np.asarray(p.active),
                np.asarray(p.slot_f), np.asarray(p.slot_v))

    pad = R_pad - R
    ret_slot = np.concatenate([p.ret_slot, np.full(pad, W, np.int32)])
    active = np.zeros((R_pad, W + 1), bool)
    active[:R, :W] = p.active
    active[R:, W] = True
    slot_f = np.zeros((R_pad, W + 1), np.int32)
    slot_f[:R, :W] = p.slot_f
    slot_f[R:, W] = F_NOOP
    slot_v = np.zeros((R_pad, W + 1, p.slot_v.shape[2]), np.int32)
    slot_v[:R, :W] = p.slot_v
    return ret_slot, active, slot_f, slot_v


def check_packed(p: PackedHistory, cap_schedule=DEFAULT_CAP_SCHEDULE,
                 chunk: int = CHUNK, cancel=None, explain: bool = False,
                 spike_caps=SPIKE_CAP_SCHEDULE,
                 spike_dropback: int = SPIKE_DROPBACK,
                 packed_keys: bool | None = None,
                 lazy: bool = True, host_caps=HOST_ROW_CAPS,
                 checkpoint=None, resume=None, frontier=None,
                 frontier_row: int = 0, partial: bool = False) -> dict:
    """Decide linearizability of a packed history on device.

    Host loop over CHUNK-row device dispatches; the frontier carries
    between chunks. Capacity adapts per chunk: overflow re-runs just that
    chunk at the next cap level (from the pre-chunk frontier snapshot);
    when the frontier shrinks the cap drops back so the common case keeps
    running on the small fast program. ``cancel`` (a threading.Event) stops
    the search between chunks — set by a competition race once the other
    racer has decided. ``explain=True`` keeps chunk-entry frontier
    snapshots and, on an invalid verdict, replays the failing tail on
    the CPU oracle to emit configs + final-paths
    (:mod:`jepsen_tpu.lin.witness`).

    The search runs SUPERVISED (:mod:`jepsen_tpu.lin.supervise`): every
    dispatch carries a watchdog deadline with bounded retry (a wedged
    tunnel dispatch costs its detection window, not the process), a
    faulting program shape is quarantined so future runs route straight
    to its proven fallback rung, and — with ``checkpoint`` (a path, a
    prebuilt Checkpointer, or the ``JEPSEN_TPU_CKPT`` env) — the
    frontier is checkpointed at committed row boundaries so
    ``resume`` (a path, or by default the checkpoint file itself when
    it exists; ``False`` disables) continues a killed run mid-history.
    A resumed verdict provably equals the uninterrupted run: the
    checkpoint holds an exact committed frontier at a row boundary and
    the continuation re-runs the same deterministic dispatch sequence.
    Checkpoints are deleted on a definite verdict and kept on
    unknown/cancelled/wedged ones; the verdict carries
    ``resumed-from-row`` when a resume happened.

    **Incremental entry (the streaming checker,
    :mod:`jepsen_tpu.stream`):** ``frontier`` — a carried
    ``(bits u32[n, nw], state i32[n, S], count)`` committed frontier in
    the multiword layout of the chunk-kind checkpoint codec — re-enters
    the row loop at ``frontier_row`` exactly like a checkpoint resume
    (same invariant: an exact committed frontier at a row boundary).
    With ``partial=True`` a clean walk to ``p.R`` returns the committed
    frontier under ``"stream-frontier"`` (numpy, host-side) instead of
    a final run verdict, so the caller can extend the history and
    re-enter; death/overflow/wedge verdicts are unchanged. ``frontier``
    takes precedence over ``resume`` when both are given.
    """
    if p.kernel is None:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"no device kernel for {type(p.model).__name__}"}
    if p.window > MAX_DEVICE_WINDOW:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"concurrency window {p.window} exceeds device "
                         f"bitset width {MAX_DEVICE_WINDOW}"}
    if p.R == 0:
        out = {"valid?": True, "analyzer": "tpu-bfs", "configs": []}
        if partial:
            out["stream-frontier"] = {
                "bits": np.zeros((1, (p.window + 31) // 32), np.uint32),
                "state": np.asarray(p.init_state, np.int32)[None, :],
                "count": 1, "row": 0}
        return out

    ret_slot_h = np.asarray(p.ret_slot)
    active_h = np.asarray(p.active)
    slot_f_h = np.asarray(p.slot_f)
    slot_v_h = np.asarray(p.slot_v)
    S = p.init_state.shape[0]
    nw = (p.window + 31) // 32
    pure_h, pred_bit_h = reduction_bit_tables(p, nw)
    step_fn = p.kernel.step

    # Single-u32-key dedup packing: possible when the one-word state's
    # values (interned ids, 0/1 flags, or a set's element bitmask; NIL
    # remapped to nil_id) fit next to the W-bit bitset under the bit-31
    # invalid flag. packed_state_bound is the shared definition of that
    # range (register/mutex bound by the intern table, one-word sets by
    # their own state_bound) — other one-word states (e.g. a
    # single-value unordered-queue count) stay multiword.
    from jepsen_tpu.models.kernels import (PACKED_STATE_KERNELS,
                                           packed_state_bound)

    from jepsen_tpu.models.kernels import READ_VALUE_MATCH_KERNELS

    # ``packed_keys=False`` forces the multiword formulation (tests use
    # it to cover the wide-window machinery on small histories).
    read_value_match = p.kernel.name in READ_VALUE_MATCH_KERNELS
    state_bits = nil_id = None
    key_hi = False
    if S == 1 and p.kernel.name in PACKED_STATE_KERNELS \
            and packed_keys is not False:
        nid = packed_state_bound(p.kernel, len(p.unintern))
        b = nid.bit_length()
        if p.window + b <= 31:
            state_bits, nil_id = b, nid
        elif read_value_match and b <= 6 and p.window + b <= 60:
            # Pair keys: the 64-bit config (bits << b | state) as two
            # u32 words — covers the cockroach-class concurrency-30
            # band (windows 29+, cockroach.clj:40-41) that round 2
            # left to the slow multiword formulation.
            state_bits, nil_id, key_hi = b, nid, True
    # In-VMEM pallas sort-dedup for the packed-key path (platform/env
    # gate here; each dedup additionally size-gates — see psort).
    use_psort = state_bits is not None and psort.backend_ok()
    # Mutator-compacted expansion columns: the read-value-match register
    # band (the sat-table branch, b <= 6) never needs the full-window
    # step evaluation — see expansion_tables.
    exp_h = None
    crash_dom = False
    if state_bits is not None and read_value_match and state_bits <= 6:
        exp_h = expansion_tables(p, state_bits, lazy=lazy)
        # Crashed-subset dominance: only engage when crashed mutators
        # exist (the masks are all-zero otherwise and the pruning sort
        # would be pure overhead).
        crash_dom = bool(np.asarray(p.crashed).any())
        if cap_schedule is DEFAULT_CAP_SCHEDULE:
            # Row tiers make small frontiers cheap at ANY cap, so on the
            # real chip the band runs top-cap from the start — no chunk
            # re-runs on escalation. The CPU test mesh keeps a small
            # first level (compile cost).
            if jax.devices()[0].platform == "tpu":
                cap_schedule = PACKED_CAP_SCHEDULE[-1:]
            else:
                cap_schedule = PACKED_CAP_SCHEDULE
    # Crash-dom compact bands (the partitioned class, both key widths):
    # cap the in-chunk tier ladder so the group-cycled closure (whose
    # windowed prune can orbit instead of converging — see
    # CHUNK_TIER_CAP) never runs inside the nested-while program;
    # blowup rows overflow to the host-row executor instead.
    max_tier = _tier_cap() if (exp_h is not None and crash_dom) else None
    if max_tier is not None and cap_schedule in (PACKED_CAP_SCHEDULE,
                                                 PACKED_CAP_SCHEDULE[-1:]):
        # Counts never exceed the tier cap in this band, so the chunk
        # cap only needs selection margin over it: smaller carry
        # arrays, cheaper per-chunk fixed costs.
        cap_schedule = (TIER_MARGIN * max_tier,)
    # Env knobs resolved ONCE per check: cand_max is a static argname of
    # _search_chunk (so a changed JEPSEN_TPU_CAND_MAX retraces instead
    # of silently reusing a stale grouping), sync_chunks sets the fast
    # path's dispatch queue depth between host flag syncs.
    cand_max = _cand_max()
    sync_chunks = _sync_chunks()
    # Fused in-VMEM fixpoint kernel (psort_fused) for the compact
    # band's row tiers: NON-dominance dedups only — the crash-dom
    # band keeps the forced-lax chain rule (round-5 lore). The value
    # is the env-resolved candidate-space BOUND (0 = off): a static
    # argname of _search_chunk, so flipping JEPSEN_TPU_PSORT_FUSED or
    # raising JEPSEN_TPU_PSORT_FUSED_MAX_N retraces instead of hitting
    # a stale traced fits() gate.
    use_fused = (psort_fused.max_n()
                 if (exp_h is not None and not crash_dom
                     and psort_fused.enabled()) else 0)
    kname = p.kernel.name if p.kernel is not None else "generic"
    host_stats: dict = {"episodes": 0, "rows": 0, "dispatches": 0,
                        "passes": 0, "wasted_passes": 0,
                        "sticky_hits": 0, "sticky_misses": 0,
                        "multi_rows": 0, "multi_dispatches": 0,
                        "multi_trips": 0, "sched_rows": 0,
                        "sched_dispatches": 0, "sched_trips": 0,
                        "watchdog_trips": 0,
                        "faults": 0, "quarantine_skips": 0,
                        "static_skips": 0, "cpu_rows": 0,
                        "cap_seconds": {}, "wasted_seconds": {}}
    # Flight recorder: host-stats becomes a live named view of the obs
    # registry (one snapshot codec for every stats dict), and the run
    # gauges/sparkline behind web.py /run start here.
    obs_metrics.REGISTRY.view("host-stats", host_stats)
    obs_metrics.REGISTRY.start_run("lin-sparse", total=int(p.R),
                                   window=int(p.window))
    level = 0
    cap = cap_schedule[level]
    bits = jnp.zeros((cap, nw), jnp.uint32)
    state = jnp.zeros((cap, S), jnp.int32).at[0].set(
        jnp.asarray(p.init_state))
    count = jnp.int32(1)
    max_cap_used = cap
    snapshots: list | None = [] if explain else None

    # --- checkpoint/resume wiring (supervise module docstring) ------
    ckpt = None
    if checkpoint is not None and not isinstance(checkpoint, (str, bool)):
        ckpt = checkpoint                      # prebuilt Checkpointer
        ckpt_file = ckpt.path
    else:
        ckpt_file = checkpoint if isinstance(checkpoint, str) \
            else supervise.ckpt_path()
        if ckpt_file:
            ckpt = supervise.Checkpointer(
                ckpt_file, supervise.history_fingerprint(p))
    resume_host = None
    resumed_from = None
    start_row = 0
    if resume is not False:
        rpath = resume if isinstance(resume, str) else ckpt_file
        if rpath and os.path.exists(rpath):
            fp = ckpt.fingerprint if ckpt is not None \
                else supervise.history_fingerprint(p)
            rd = supervise.load_checkpoint(rpath, fp)
            if rd is not None:
                from jepsen_tpu.models.kernels import NIL

                rcount = rd["count"]
                if rd["kind"] == "host":
                    m = rd["meta"]
                    if (m.get("b") == state_bits
                            and m.get("key_hi") == key_hi
                            and m.get("nw") == nw
                            and exp_h is not None and crash_dom):
                        rbits, rstate = supervise.np_unpack_keys(
                            rd["lo"], rd.get("hi"), rcount, state_bits,
                            nil_id, nw, key_hi, int(NIL))
                        resume_host = (rbits, rstate, rcount,
                                       m.get("sticky_lvl"))
                        start_row = resumed_from = rd["row"]
                        for k, v in (m.get("host_stats") or {}).items():
                            if k in ("cap_seconds",
                                     "wasted_seconds") \
                                    and isinstance(v, dict):
                                # JSON stringified the int cap
                                # buckets; restore them or stat_time
                                # appends duplicate '4096'/4096 keys
                                # and pre-resume timings vanish.
                                host_stats[k] = {
                                    int(b) if str(b).isdigit() else b:
                                    t for b, t in v.items()}
                            elif k in host_stats:
                                host_stats[k] = v
                elif rcount <= cap_schedule[-1]:
                    level = next(i for i, c in enumerate(cap_schedule)
                                 if rcount <= c)
                    cap = cap_schedule[level]
                    max_cap_used = max(max_cap_used, cap)
                    rb = np.zeros((cap, nw), np.uint32)
                    rs = np.zeros((cap, S), np.int32)
                    rb[:rcount] = np.asarray(rd["bits"])[:rcount]
                    rs[:rcount] = np.asarray(rd["state"])[:rcount]
                    bits = jnp.asarray(rb)
                    state = jnp.asarray(rs)
                    count = jnp.int32(rcount)
                    start_row = resumed_from = rd["row"]

    if frontier is not None:
        # Streaming incremental entry: a carried committed frontier at
        # a row boundary, in the multiword chunk-checkpoint layout
        # (layout-stable under window growth and interner growth — the
        # packed-key b is re-derived per call above). Precedence over
        # any file resume: the caller owns the carry.
        fb = np.ascontiguousarray(np.asarray(frontier[0],
                                             dtype=np.uint32))
        fs = np.ascontiguousarray(np.asarray(frontier[1],
                                             dtype=np.int32))
        fc = int(frontier[2])
        if fb.ndim == 1:
            fb = fb[:, None]
        if fs.ndim == 1:
            fs = fs[:, None]
        if fs.shape[1] != S:
            return {"valid?": "unknown", "analyzer": "tpu-bfs",
                    "error": f"carried frontier state width "
                             f"{fs.shape[1]} != kernel width {S}"}
        if fc <= 0:
            # An empty committed frontier can only follow a death row,
            # which would have ended the stream already.
            return {"valid?": "unknown", "analyzer": "tpu-bfs",
                    "error": "carried stream frontier is empty"}
        if fb.shape[1] < nw:
            # The concurrency window crossed a 32-slot word boundary
            # between increments; high words are zero by construction.
            fb = np.pad(fb, ((0, 0), (0, nw - fb.shape[1])))
        resume_host = None
        resumed_from = None
        start_row = int(frontier_row)
        if fc <= cap_schedule[-1]:
            level = next(i for i, c in enumerate(cap_schedule)
                         if fc <= c)
            cap = cap_schedule[level]
            max_cap_used = max(max_cap_used, cap)
            rb = np.zeros((cap, nw), np.uint32)
            rs = np.zeros((cap, S), np.int32)
            rb[:fc] = fb[:fc, :nw]
            rs[:fc] = fs[:fc]
            bits = jnp.asarray(rb)
            state = jnp.asarray(rs)
            count = jnp.int32(fc)
        elif exp_h is not None and crash_dom:
            # Frontier bigger than the chunked top cap: re-enter the
            # host-row executor directly (the host-kind resume path).
            resume_host = (fb, fs, fc, None)
        else:
            return {"valid?": "unknown", "analyzer": "tpu-bfs",
                    "overflow": "capacity",
                    "error": f"carried stream frontier {fc} exceeds "
                             f"chunk capacity {cap_schedule[-1]}"}

    def _with_stats(out: dict) -> dict:
        if host_stats["episodes"] or host_stats["watchdog_trips"] \
                or host_stats["faults"] or host_stats["quarantine_skips"] \
                or host_stats["static_skips"] or host_stats["cpu_rows"]:
            out["host-stats"] = util.round_stats(host_stats)
        if resumed_from is not None:
            out["resumed-from-row"] = resumed_from
        if ckpt is not None and not partial \
                and out.get("valid?") in (True, False):
            # A finished search must not be resumed by a later fresh
            # run; an unknown/cancelled/wedged verdict keeps the
            # checkpoint so a re-run continues instead of restarting.
            ckpt.clear()
        return out

    def _final_valid(fb, fs, fc) -> dict:
        """The clean-walk-to-p.R verdict; with ``partial`` it carries
        the committed frontier (host numpy, multiword layout) so the
        stream session can extend the history and re-enter."""
        out = {"valid?": True, "analyzer": "tpu-bfs", "configs": [],
               "final-frontier-size": int(fc), "max-cap": max_cap_used}
        if partial:
            n = int(fc)
            out["stream-frontier"] = {
                "bits": np.asarray(fb)[:n].astype(np.uint32),
                "state": np.asarray(fs)[:n].astype(np.int32),
                "count": n, "row": int(p.R)}
        return _with_stats(out)

    def chunk_tables(base):
        tables = (jnp.asarray(_chunk_slice(ret_slot_h, base, chunk)),
                  jnp.asarray(_chunk_slice(active_h, base, chunk)),
                  jnp.asarray(_chunk_slice(slot_f_h, base, chunk)),
                  jnp.asarray(_chunk_slice(slot_v_h, base, chunk)),
                  jnp.asarray(_chunk_slice(pure_h, base, chunk)),
                  jnp.asarray(_chunk_slice(pred_bit_h, base, chunk)))
        exp_c = None if exp_h is None else tuple(
            jnp.asarray(_chunk_slice(t, base, chunk)) for t in exp_h)
        return tables, exp_c

    def _dead_verdict(dead_row: int) -> dict:
        ret = p.ops[int(p.ret_op[dead_row])]
        out = {"valid?": False, "analyzer": "tpu-bfs",
               "dead-row": dead_row,
               "op": {"process": ret.process, "f": ret.f,
                      "value": ret.value, "index": ret.op_index,
                      "ok": ret.ok},
               "configs": [], "final-paths": []}
        if snapshots and not (cancel is not None and cancel.is_set()):
            from jepsen_tpu.lin import witness

            out.update(witness.tail_replay_sparse(
                p, _materialize_snapshots(snapshots), dead_row,
                cancel=cancel))
        return _with_stats(out)

    def _consume_spiked(spiked, spike_top):
        """Fold a host-row/spike executor result back into the chunk
        loop state. Returns ("return", verdict) | ("continue", None) |
        ("dead", next_r) — shared by the overflow hand-off and the
        host-kind checkpoint resume so the two paths cannot drift."""
        nonlocal bits, state, count, base, level, cap, max_cap_used
        (s_bits, s_state, count_i, next_r, dead_h, ovf_h, cancelled,
         top_used) = spiked
        max_cap_used = max(max_cap_used, top_used)
        if cancelled:
            return ("return", _with_stats(
                {"valid?": "unknown", "analyzer": "tpu-bfs",
                 "error": "cancelled"}))
        if ovf_h:
            # Honest overflow taxonomy: a closure-pass-budget
            # exhaustion (the nontermination class round 5 diagnosed)
            # and a wedge/fault that survived the whole fallback
            # ladder must not masquerade as capacity overflows, or
            # triage chases frontier size instead of the real cause.
            if ovf_h == "budget":
                return ("return", _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "overflow": "budget",
                     "error": ("closure pass budget exceeded at "
                               f"capacity {spike_top}")}))
            if ovf_h in ("wedged", "fault"):
                return ("return", _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "overflow": "wedge" if ovf_h == "wedged"
                     else "fault",
                     "error": ("wedged/faulted dispatch survived the "
                               "fallback ladder near row "
                               f"{next_r}")}))
            return ("return", _with_stats(
                {"valid?": "unknown", "analyzer": "tpu-bfs",
                 "overflow": "capacity",
                 "error": ("frontier exceeded capacity "
                           f"{spike_top}")}))
        if dead_h:
            # Snapshots were re-anchored at the dead row's entry by
            # the executor (one row of CPU replay for explain).
            return ("dead", next_r)
        if next_r >= p.R:
            return ("return", _final_valid(s_bits, s_state, count_i))
        # Resume full-size chunks at the hand-back row — at the TOP
        # chunked level: the neighbourhood of a spike tends to spike
        # again, and re-climbing the whole cap ladder there costs far
        # more than one over-provisioned chunk. The shrink logic in
        # the main loop drops the level back once chunks run clean.
        level = len(cap_schedule) - 1
        cap = cap_schedule[level]
        _dlog(f"resume chunks at {next_r} count {count_i}")
        # Spike hands back oversized arrays (slice); host-row mode may
        # hand back smaller ones (pad).
        if s_bits.shape[0] >= cap:
            bits = s_bits[:cap]
            state = s_state[:cap]
        else:
            g = cap - s_bits.shape[0]
            bits = jnp.pad(s_bits, ((0, g), (0, 0)))
            state = jnp.pad(s_state, ((0, g), (0, 0)))
        count = jnp.int32(count_i)
        base = next_r
        return ("continue", None)

    base = start_row
    deferred = snapshots is None
    classic_until = -1
    _dbg = os.environ.get("JEPSEN_TPU_HOST_DEBUG") == "1"
    if _dbg:
        _t0 = _time.time()

        def _dlog(msg):
            print(f"[chunk +{_time.time()-_t0:7.1f}s] {msg}", flush=True)
    else:
        def _dlog(msg):
            pass
    while base < p.R:
        if resume_host is not None:
            # Host-kind checkpoint: re-enter the host-row executor
            # directly with the checkpointed frontier, sticky level,
            # and stats — the continuation of the interrupted episode.
            rbits, rstate, rcount, rsticky = resume_host
            resume_host = None
            host_stats["episodes"] += 1
            hdrop = min(spike_dropback,
                        (max_tier or cap_schedule[-1]) // TIER_MARGIN)
            _ep0 = _time.monotonic()
            _d0, _r0 = host_stats["dispatches"], host_stats["rows"]
            spiked = _host_rows(
                p, base, jnp.asarray(rbits), jnp.asarray(rstate),
                jnp.int32(rcount),
                tables_h=(ret_slot_h, active_h, slot_f_h, slot_v_h,
                          pure_h, pred_bit_h),
                exp_h=exp_h, caps=host_caps, dropback=hdrop,
                step_fn=step_fn, state_bits=state_bits, nil_id=nil_id,
                use_psort=use_psort, key_hi=key_hi, crash_dom=crash_dom,
                cancel=cancel, snapshots=snapshots, stats=host_stats,
                ckpt=ckpt, sticky0=rsticky)
            obs_trace.complete(
                "host-episode", _ep0, _time.monotonic() - _ep0,
                row=base, resumed=True, next_row=spiked[3],
                dispatches=host_stats["dispatches"] - _d0,
                rows=host_stats["rows"] - _r0)
            act_, payload = _consume_spiked(spiked, host_caps[-1])
            if act_ == "return":
                return payload
            if act_ == "dead":
                return _dead_verdict(payload - 1)
            continue
        if deferred and base >= classic_until:
            # Optimistic fast path: dispatch a batch of chunks without
            # host syncs, then fetch every chunk's (ovf, dead) flags in
            # ONE transfer. Clean batches (the overwhelmingly common
            # case) pay one round trip per SYNC_CHUNKS chunks; a
            # tripped flag rewinds to the batch entry (frontier arrays
            # are immutable device values) and replays chunk-by-chunk
            # through the classic path below, which owns escalation,
            # spike mode, and dead-row reporting. The whole batch runs
            # as ONE supervised unit: the thunk is a pure function of
            # the batch entry, so a watchdog retry re-dispatches from
            # there exactly.
            if cancel is not None and cancel.is_set():
                return _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "error": "cancelled"})
            entry = (bits, state, count, level, base)

            def _fast_batch_prog(entry=entry):
                bits, state, count, level, base = entry
                flags = []
                while base < p.R and len(flags) < sync_chunks:
                    n = min(chunk, p.R - base)
                    tables, exp_c = chunk_tables(base)
                    b2, s2, c2, r_done, dead, ovf = _search_chunk(
                        jnp.int32(n), *tables, bits, state, count,
                        exp_c, cap=cap_schedule[level], step_fn=step_fn,
                        state_bits=state_bits, nil_id=nil_id,
                        read_value_match=read_value_match,
                        use_psort=use_psort, key_hi=key_hi,
                        crash_dom=crash_dom, max_tier=max_tier,
                        cand_max=cand_max, use_fused=use_fused)
                    flags.append(jnp.stack((ovf.astype(jnp.int32),
                                            dead.astype(jnp.int32),
                                            c2)))
                    bits, state, count = b2, s2, c2
                    base += n
                return bits, state, count, base, jnp.stack(flags)

            def _fast_batch():
                bits, state, count, base, flags = _fast_batch_prog()
                # ONE transfer per batch
                return bits, state, count, base, np.asarray(flags)

            batch_key = supervise.shape_key(
                "chunk-batch", rows=chunk, cap=cap_schedule[level],
                window=p.window, kernel=kname)
            # The thunk runs up to sync_chunks sequential chunk
            # dispatches: the deadline scales with the batch so a
            # deep queue (bench's SYNC_CHUNKS=8 rung) of healthy
            # top-cap chunks cannot false-trip the watchdog (a
            # spurious retry would double the unsynced dispatch
            # queue depth — the round-4 fault condition). A fault
            # (dead worker) records its shape and reports honestly —
            # never escapes as a raw exception.
            outcome, val = supervise.run_guarded(
                "chunk-batch", batch_key, _fast_batch,
                scale=sync_chunks, stats=host_stats,
                traceable=_fast_batch_prog)
            if outcome == "wedge":
                return _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "overflow": "wedge", "error": str(val)})
            if outcome == "fault":
                return _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "overflow": "fault",
                     "error": f"dispatch fault near row {base}: "
                              f"{val!r}"})
            bits, state, count, base, fl = val
            util.progress_tick()
            if not fl[:, :2].any():
                cnt = int(fl[-1, 2])
                _dlog(f"fast batch -> base {base} count {cnt}")
                obs_trace.tail_note(row=base, count=cnt)
                obs_metrics.REGISTRY.progress(row=base, frontier=cnt)
                if ckpt is not None and ckpt.due():
                    ckpt.save("chunk", base, cnt,
                              {"bits": np.asarray(bits)[:max(cnt, 1)],
                               "state": np.asarray(state)
                               [:max(cnt, 1)]}, {})
                while level > 0 and \
                        cnt * 4 <= cap_schedule[level - 1]:
                    level -= 1
                    cap = cap_schedule[level]
                    bits = bits[:cap]
                    state = state[:cap]
                continue
            classic_until = base
            bits, state, count, level, base = entry
            cap = cap_schedule[level]
            _dlog(f"fast batch TRIPPED -> replay from {base}")
        if snapshots is not None:
            # only the last snapshot is ever replayed (the dead row is
            # always inside the current chunk): keep HBM flat
            snapshots[:] = [(base, bits, state, count)]
        if cancel is not None and cancel.is_set():
            return _with_stats({"valid?": "unknown",
                                "analyzer": "tpu-bfs",
                                "error": "cancelled"})
        n = min(chunk, p.R - base)
        tables, exp_c = chunk_tables(base)
        spiked = None
        while True:
            util.progress_tick()

            def _chunk_prog(bits=bits, state=state, count=count,
                            level=level):
                return _search_chunk(
                    jnp.int32(n), *tables, bits, state, count, exp_c,
                    cap=cap_schedule[level], step_fn=step_fn,
                    state_bits=state_bits, nil_id=nil_id,
                    read_value_match=read_value_match,
                    use_psort=use_psort, key_hi=key_hi,
                    crash_dom=crash_dom, max_tier=max_tier,
                    cand_max=cand_max, use_fused=use_fused)

            def _chunk():
                out = _chunk_prog()
                return out, bool(out[5])

            chunk_key = supervise.shape_key(
                "chunk", rows=chunk, cap=cap_schedule[level],
                window=p.window, kernel=kname)
            outcome, val = supervise.run_guarded(
                "chunk", chunk_key, _chunk, stats=host_stats,
                traceable=_chunk_prog)
            if outcome == "wedge":
                return _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "overflow": "wedge", "error": str(val)})
            if outcome == "fault":
                return _with_stats(
                    {"valid?": "unknown", "analyzer": "tpu-bfs",
                     "overflow": "fault",
                     "error": f"dispatch fault near row {base}: "
                              f"{val!r}"})
            (b2, s2, c2, r_done, dead, ovf), ovf_b = val
            if not ovf_b:
                break
            # With a tier cap, a bigger chunk cap cannot grow the
            # effective tier ladder (tiers top out at max_tier and
            # every dedup/filter bounds count by it), so retrying the
            # chunk at the next level is provably futile — skip the
            # redundant dispatch (and its 15-70 s compile) and route
            # straight past the chunked engine.
            no_grow = max_tier is not None \
                and level + 1 < len(cap_schedule) \
                and min(cap_schedule[level + 1], max_tier) \
                == min(cap_schedule[level], max_tier)
            if level + 1 >= len(cap_schedule) or no_grow:
                # Route past the chunked engine. The compact crash-dom
                # band goes to the HOST-ROW executor (its waves need
                # the dominance window at every capacity, which only
                # single-dispatch programs can carry safely on this
                # runtime); other bands go to the spike executor.
                host_mode = exp_h is not None and crash_dom
                if host_mode:
                    sp_caps = host_caps
                elif state_bits is None:
                    # Spike caps must strictly exceed the chunked top
                    # cap: a smaller cap would silently drop live
                    # frontier configs — verdict-flipping. The multiword
                    # ladder is additionally memory-bounded (fat
                    # states).
                    sp_caps = _mw_spike_caps(p.window, nw, S,
                                             cap_schedule[-1], spike_caps)
                else:
                    # Multi-operand lax sorts past ~100M cells KILL the
                    # axon TPU worker (round-2 lore; re-confirmed: the
                    # 6-operand pair-dom dedup crashed the worker at the
                    # 1M cap). The dominance word packing keeps the
                    # pair-dom dedup at 4 operands — probed clean at
                    # cap 1048576 x 32 rows — so the full ladder stands.
                    sp_caps = tuple(sorted(
                        c for c in spike_caps
                        if c > cap_schedule[-1])) or None
                if sp_caps is None:
                    return _with_stats(
                        {"valid?": "unknown", "analyzer": "tpu-bfs",
                         "overflow": "capacity",
                         "error": ("frontier exceeded capacity "
                                   f"{cap_schedule[-1]}")})
                # Recover the frontier just before the spike row with ONE
                # re-run of the rows that did fit (the failed run's
                # r_done-1), so spike mode starts at the spike, not at
                # chunk entry.
                n_pre = int(r_done) - 1
                _dlog(f"chunk {base} OVF at row {base + max(n_pre, 0)}"
                      f" -> recovery")
                if n_pre > 0:
                    b2, s2, c2, _, _, o_pre = _search_chunk(
                        jnp.int32(n_pre), *tables, bits, state, count,
                        exp_c, cap=cap_schedule[level], step_fn=step_fn,
                        state_bits=state_bits, nil_id=nil_id,
                        read_value_match=read_value_match,
                        use_psort=use_psort, key_hi=key_hi,
                        crash_dom=crash_dom, max_tier=max_tier,
                        cand_max=cand_max, use_fused=use_fused)
                    if not bool(o_pre):
                        bits, state, count = b2, s2, c2
                    else:
                        n_pre = 0  # extremely rare: spike at first row
                _dlog(f"recovered; host/spike from {base + n_pre}")
                _ep0 = _time.monotonic()
                _d0, _r0 = (host_stats["dispatches"],
                            host_stats["rows"])
                if host_mode:
                    # Dropback clamped so the handed-back frontier fits
                    # the capped in-chunk tiers with selection margin.
                    hdrop = min(spike_dropback,
                                (max_tier or cap_schedule[-1])
                                // TIER_MARGIN)
                    host_stats["episodes"] += 1
                    spiked = _host_rows(
                        p, base + n_pre, bits, state, count,
                        tables_h=(ret_slot_h, active_h, slot_f_h,
                                  slot_v_h, pure_h, pred_bit_h),
                        exp_h=exp_h, caps=sp_caps, dropback=hdrop,
                        step_fn=step_fn, state_bits=state_bits,
                        nil_id=nil_id, use_psort=use_psort,
                        key_hi=key_hi, crash_dom=crash_dom,
                        cancel=cancel, snapshots=snapshots,
                        stats=host_stats, ckpt=ckpt)
                else:
                    # Dropback clamped so the handed-back frontier
                    # always fits the chunked engine's top cap.
                    spiked = _spike_rows(
                        p, base + n_pre, bits, state, count,
                        tables_h=(ret_slot_h, active_h, slot_f_h,
                                  slot_v_h, pure_h, pred_bit_h),
                        caps=sp_caps,
                        dropback=min(spike_dropback, cap_schedule[-1]),
                        step_fn=step_fn, state_bits=state_bits,
                        nil_id=nil_id, read_value_match=read_value_match,
                        cancel=cancel, snapshots=snapshots,
                        use_psort=use_psort, exp_h=exp_h, key_hi=key_hi,
                        crash_dom=crash_dom, cand_max=cand_max,
                        stats=host_stats)
                obs_trace.complete(
                    "host-episode" if host_mode else "spike-episode",
                    _ep0, _time.monotonic() - _ep0, row=base + n_pre,
                    next_row=spiked[3],
                    dispatches=host_stats["dispatches"] - _d0,
                    rows=host_stats["rows"] - _r0)
                spike_top = sp_caps[-1]
                break
            # Retry this chunk from its entry frontier at the next cap.
            level += 1
            cap = cap_schedule[level]
            max_cap_used = max(max_cap_used, cap)
            grow = cap - bits.shape[0]
            bits = jnp.pad(bits, ((0, grow), (0, 0)))
            state = jnp.pad(state, ((0, grow), (0, 0)))
        if spiked is not None:
            act_, payload = _consume_spiked(spiked, spike_top)
            if act_ == "return":
                return payload
            if act_ == "dead":
                return _dead_verdict(payload - 1)
            continue
        if bool(dead):
            return _dead_verdict(base + int(r_done) - 1)
        bits, state, count = b2, s2, c2
        base += n
        cnt = int(count)
        if ckpt is not None and ckpt.due():
            ckpt.save("chunk", base, cnt,
                      {"bits": np.asarray(bits)[:max(cnt, 1)],
                       "state": np.asarray(state)[:max(cnt, 1)]}, {})
        obs_metrics.REGISTRY.progress(row=base, frontier=cnt)
        # Frontier is compacted to the front, so a shrunken frontier can
        # drop back to a smaller (faster) program by slicing.
        while level > 0 and cnt * 4 <= cap_schedule[level - 1]:
            level -= 1
            cap = cap_schedule[level]
            bits = bits[:cap]
            state = state[:cap]

    return _final_valid(bits, state, int(count))
