"""The sparse device linearizability kernel: BFS frontier over
(linearized-op-bitset x model-state) configurations.

This replaces the reference's exponential JVM search (knossos.linear /
knossos.wgl, selected at checker.clj:90-93) with a data-parallel
formulation designed for the TPU's compilation model:

- The frontier lives in fixed-capacity device arrays: ``bits: u32[CAP,NW]``
  (which pending ops each config has linearized — slot-compressed by
  :mod:`jepsen_tpu.lin.prepare` so NW*32 bits cover the concurrency
  window, not the history length; NW is 1 for windows <= 32, 2 up to 64)
  and ``state: i32[CAP, S]`` (packed model state).
- One `lax.while_loop` walks the R return events. Each step runs the
  just-in-time closure as an inner `lax.while_loop`: candidate transitions
  are the full cross product (config x pending slot), evaluated in one shot
  by the branchless model step kernels (vmap x vmap) — this is the op that
  fills the vector units; there is no per-config control flow anywhere.
- Dedup is a lexicographic `lax.sort` over (invalid, bits, state) followed
  by adjacent-duplicate masking and a cumsum-gather compaction. When the
  window plus a compact state id fit in 31 bits, the whole config packs
  into ONE u32 sort key (several times faster on TPU).
- Static shapes throughout: frontier capacity CAP is a compile-time
  constant. Searches run on an escalating CAP schedule — almost all real
  histories need a tiny frontier, so the common case compiles small and
  fast, and only pathological histories pay for big buffers. Overflow is
  detected exactly (a lost config could flip the verdict) and escalates.

This engine is the wide-window fallback: histories whose window and state
count fit the dense config-space bitmap (:mod:`jepsen_tpu.lin.dense`,
window <= 20 and <= 32 states) are routed there instead
(`jepsen_tpu.lin.device_check_packed`), which absorbs crash-heavy
histories for free. Crash-heavy histories OUTSIDE the dense bounds —
windows 21..64 or value-rich registers past 32 states — can legitimately
grow the sparse frontier by 2^crashes; the cap schedule bounds that
honestly ("unknown" at exhaustion, CPU fallback via competition) rather
than pruning: the round-1 dominance-pruning join that targeted this slice
kernel-faulted the TPU runtime on its own flagship workload and was
removed.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu.lin.prepare import PackedHistory

DEFAULT_CAP_SCHEDULE = (256, 2048, 16384, 131072)
MAX_DEVICE_WINDOW = 64
CHUNK = 512


def _compact_gather(mask, n, cap):
    """Positions of the first ``cap`` mask-survivors, via cumsum + binary
    search (TPU-friendly; scatter compaction serializes on TPU). Returns
    (sel[cap] clipped indices, total survivors)."""
    csum = jnp.cumsum(mask.astype(jnp.int32))
    total = csum[-1]
    sel = jnp.searchsorted(csum, jnp.arange(1, cap + 1, dtype=jnp.int32),
                           method='scan_unrolled')
    return jnp.clip(sel, 0, n - 1), total


KEY_FILL = jnp.uint32(0xFFFFFFFF)  # pad beyond count; sorts after any config


def _dedup_keys(key, valid, cap):
    """Single-u32-key sort-dedup (invalid flag in bit 31), compacted by
    gather. Returns (keys[cap] ascending + KEY_FILL padding, count,
    overflow)."""
    n = key.shape[0]
    key = key | ((~valid).astype(jnp.uint32) << 31)
    key_s = lax.sort(key)
    inv_s = key_s >> 31

    prev_differs = key_s != jnp.roll(key_s, 1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    sel, total = _compact_gather(mask, n, cap)
    overflow = total > cap
    out = jnp.where(jnp.arange(cap) < total, key_s[sel], KEY_FILL)
    count = jnp.minimum(total, cap)
    return out, count, overflow


def _dedup(bits, state, valid, cap):
    """Sort-dedup-compact over multi-word configs. bits: u32[n, NW];
    state: i32[n, S]. Returns (bits[cap,NW], state[cap,S], count,
    overflow). Invalid rows sort last; duplicates are adjacent after the
    lexicographic sort and masked; survivors are gather-compacted."""
    n, nw = bits.shape
    s_width = state.shape[1]
    inv = (~valid).astype(jnp.uint32)
    operands = (inv,) + tuple(bits[:, k] for k in range(nw)) \
        + tuple(state[:, k] for k in range(s_width))
    sorted_ops = lax.sort(operands, num_keys=len(operands))
    inv_s = sorted_ops[0]
    bits_s = jnp.stack(sorted_ops[1:1 + nw], axis=1)
    state_s = jnp.stack(sorted_ops[1 + nw:], axis=1)

    prev_differs = \
        jnp.any(bits_s != jnp.roll(bits_s, 1, axis=0), axis=1) | \
        jnp.any(state_s != jnp.roll(state_s, 1, axis=0), axis=1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    sel, total = _compact_gather(mask, n, cap)
    overflow = total > cap
    live = jnp.arange(cap) < total
    out_bits = jnp.where(live[:, None], bits_s[sel], 0)
    out_state = jnp.where(live[:, None], state_s[sel], 0)
    count = jnp.minimum(total, cap)
    return out_bits, out_state, count, overflow


def _slot_bits(W: int, nw: int):
    """u32[W, NW] table: row j has bit j%32 set in word j//32."""
    tbl = np.zeros((W, nw), np.uint32)
    for j in range(W):
        tbl[j, j // 32] = np.uint32(1) << (j % 32)
    return jnp.asarray(tbl)


@partial(jax.jit, static_argnames=("cap", "step_fn", "state_bits",
                                   "nil_id"))
def _search_chunk(n_rows, ret_slot, active, slot_f, slot_v,
                  bits, state, count, *, cap, step_fn,
                  state_bits=None, nil_id=None):
    """Process up to n_rows return events (tables are CHUNK-row static
    shapes; rows past n_rows are ignored) starting from a carried frontier.

    The chunk is the unit of device dispatch: every chunk of every history
    reuses the same compiled program per (cap, step_fn), each program runs
    for bounded time (no watchdog kills on 100k-row histories), and a
    transient frontier spike re-runs one chunk at a bigger cap instead of
    the whole search.

    With ``state_bits`` set (windows <= 31 - state_bits) the whole row
    loop runs on packed u32 config keys.

    Returns (bits[cap,NW], state[cap,S], count, rows_done, dead, overflow).
    """
    if state_bits is not None:
        return _search_chunk_keys(
            n_rows, ret_slot, active, slot_f, slot_v,
            bits, state, count, cap=cap, step_fn=step_fn,
            state_bits=state_bits, nil_id=nil_id)
    C, W = active.shape
    S = state.shape[1]
    nw = bits.shape[1]

    step_cfg_slot = jax.vmap(
        jax.vmap(step_fn, in_axes=(None, 0, 0)),
        in_axes=(0, None, None))
    slot_bit = _slot_bits(W, nw)                       # [W, NW]

    def closure_cond(c):
        _, _, count, prev, ovf = c
        return (count != prev) & ~ovf

    def row_body(carry):
        r, bits, state, count, dead, ovf = carry
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        s = ret_slot[r]

        def closure_body(c):
            bits, state, count, prev, ovf = c
            cfg_valid = jnp.arange(cap) < count
            ok, new_state = step_cfg_slot(state, f_row, v_row)
            already = jnp.any(
                (bits[:, None, :] & slot_bit[None, :, :]) != 0, axis=-1)
            legal = ok & act[None, :] & ~already & cfg_valid[:, None]
            new_bits = bits[:, None, :] | slot_bit[None, :, :]

            cand_bits = jnp.concatenate(
                [bits, new_bits.reshape(-1, nw)])
            cand_state = jnp.concatenate(
                [state, new_state.reshape(-1, S)], axis=0)
            cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

            b2, s2, n2, o2 = _dedup(cand_bits, cand_state, cand_valid, cap)
            return (b2, s2, n2, count, ovf | o2)

        init = (bits, state, count, jnp.int32(-1), ovf)
        bits, state, count, _, ovf = lax.while_loop(
            closure_cond, closure_body, init)

        # Filter: the returning op's linearization point must precede its
        # return; then recycle its slot bit.
        s_mask = slot_bit[s]                           # [NW]
        cfg_valid = jnp.arange(cap) < count
        keep = cfg_valid & jnp.any((bits & s_mask[None, :]) != 0, axis=-1)
        bits = bits & ~s_mask[None, :]
        bits, state, count, o2 = _dedup(bits, state, keep, cap)
        dead = count == 0
        return (r + 1, bits, state, count, dead, ovf | o2)

    def row_cond(carry):
        r, _, _, _, dead, ovf = carry
        return (r < n_rows) & ~dead & ~ovf

    r, bits, state, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), bits, state, count, False, False))
    return bits, state, count, r, dead, ovf


def _search_chunk_keys(n_rows, ret_slot, active, slot_f, slot_v,
                       bits, state, count, *, cap, step_fn,
                       state_bits, nil_id):
    """Packed-u32-key row loop (see _search_chunk): each config is ONE
    uint32 (bits << state_bits | state id), so dedup is a single payload-
    free sort and compaction a gather."""
    from jepsen_tpu.models.kernels import NIL

    C, W = active.shape
    b = state_bits
    bmask = jnp.uint32((1 << b) - 1)

    step_cfg_slot = jax.vmap(
        jax.vmap(step_fn, in_axes=(None, 0, 0)),
        in_axes=(0, None, None))
    slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

    def to_keys(bits, state, count):
        sv = state[:, 0]
        ps = jnp.where(sv == NIL, nil_id, sv).astype(jnp.uint32)
        return jnp.where(jnp.arange(cap) < count,
                         (bits[:, 0] << b) | ps, KEY_FILL)

    def from_keys(keys, count):
        live = jnp.arange(cap) < count
        cfg = jnp.where(live, keys, 0)
        bits = cfg >> b
        sv = (cfg & bmask).astype(jnp.int32)
        state = jnp.where(sv == nil_id, NIL, sv)[:, None]
        return (jnp.where(live, bits, 0)[:, None],
                jnp.where(live[:, None], state, 0))

    def row_body(carry):
        r, keys, count, dead, ovf = carry
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        s = ret_slot[r]

        def closure_cond(c):
            _, count, prev, ovf = c
            return (count != prev) & ~ovf

        def closure_body(c):
            keys, count, _, ovf = c
            cfg_valid = jnp.arange(cap) < count
            bits, state = from_keys(keys, count)
            bits1 = bits[:, 0]
            ok, new_state = step_cfg_slot(state, f_row, v_row)
            already = (bits1[:, None] & slot_bit[None, :]) != 0
            legal = ok & act[None, :] & ~already & cfg_valid[:, None]
            nsv = new_state[..., 0]
            pns = jnp.where(nsv == NIL, nil_id, nsv).astype(jnp.uint32)
            new_keys = (((bits1[:, None] | slot_bit[None, :]) << b) | pns)

            cand = jnp.concatenate([jnp.where(cfg_valid, keys, 0),
                                    new_keys.reshape(-1)])
            cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])
            k2, n2, o2 = _dedup_keys(cand, cand_valid, cap)
            return (k2, n2, count, ovf | o2)

        init = (keys, count, jnp.int32(-1), ovf)
        keys, count, _, ovf = lax.while_loop(
            closure_cond, closure_body, init)

        # Filter: the returner's linearization point must precede its
        # return; then recycle its slot bit.
        s_key_bit = jnp.uint32(1) << (b + s).astype(jnp.uint32)
        cfg_valid = jnp.arange(cap) < count
        keep = cfg_valid & ((keys & s_key_bit) != 0)
        keys, count, o2 = _dedup_keys(
            jnp.where(keep, keys & ~s_key_bit, 0), keep, cap)
        dead = count == 0
        return (r + 1, keys, count, dead, ovf | o2)

    def row_cond(carry):
        r, _, _, dead, ovf = carry
        return (r < n_rows) & ~dead & ~ovf

    keys0 = to_keys(bits, state, count)
    r, keys, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), keys0, count, False, False))
    out_bits, out_state = from_keys(keys, count)
    return out_bits, out_state, count, r, dead, ovf


def _chunk_slice(a: np.ndarray, base: int, chunk: int) -> np.ndarray:
    """Static-shape chunk slice, zero-padded past the end of the table."""
    end = min(base + chunk, a.shape[0])
    part = a[base:end]
    if part.shape[0] == chunk:
        return part
    pad = np.zeros((chunk - part.shape[0],) + a.shape[1:], a.dtype)
    return np.concatenate([part, pad], axis=0)


def _pad_rows(p: PackedHistory):
    """Bucket R up to a power of two with identity rows so XLA compiles one
    kernel per bucket instead of one per history length.

    An identity row uses a dedicated pad slot (column W) carrying the
    universal no-op f: every config linearizes it (state unchanged), the
    filter keeps everyone, and the recycle clears the bit — frontier exactly
    preserved. Requires one spare bit, so only applied when window < 32.
    """
    from jepsen_tpu.models.kernels import F_NOOP

    R, W = p.active.shape
    R_pad = 1 << max(4, (R - 1).bit_length())
    if R_pad == R or W >= 32:
        return (np.asarray(p.ret_slot), np.asarray(p.active),
                np.asarray(p.slot_f), np.asarray(p.slot_v))

    pad = R_pad - R
    ret_slot = np.concatenate([p.ret_slot, np.full(pad, W, np.int32)])
    active = np.zeros((R_pad, W + 1), bool)
    active[:R, :W] = p.active
    active[R:, W] = True
    slot_f = np.zeros((R_pad, W + 1), np.int32)
    slot_f[:R, :W] = p.slot_f
    slot_f[R:, W] = F_NOOP
    slot_v = np.zeros((R_pad, W + 1, p.slot_v.shape[2]), np.int32)
    slot_v[:R, :W] = p.slot_v
    return ret_slot, active, slot_f, slot_v


def check_packed(p: PackedHistory, cap_schedule=DEFAULT_CAP_SCHEDULE,
                 chunk: int = CHUNK, cancel=None,
                 explain: bool = False) -> dict:
    """Decide linearizability of a packed history on device.

    Host loop over CHUNK-row device dispatches; the frontier carries
    between chunks. Capacity adapts per chunk: overflow re-runs just that
    chunk at the next cap level (from the pre-chunk frontier snapshot);
    when the frontier shrinks the cap drops back so the common case keeps
    running on the small fast program. ``cancel`` (a threading.Event) stops
    the search between chunks — set by a competition race once the other
    racer has decided. ``explain=True`` keeps chunk-entry frontier
    snapshots and, on an invalid verdict, replays the failing tail on
    the CPU oracle to emit configs + final-paths
    (:mod:`jepsen_tpu.lin.witness`).
    """
    if p.kernel is None:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"no device kernel for {type(p.model).__name__}"}
    if p.window > MAX_DEVICE_WINDOW:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"concurrency window {p.window} exceeds device "
                         f"bitset width {MAX_DEVICE_WINDOW}"}
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-bfs", "configs": []}

    ret_slot_h = np.asarray(p.ret_slot)
    active_h = np.asarray(p.active)
    slot_f_h = np.asarray(p.slot_f)
    slot_v_h = np.asarray(p.slot_v)
    S = p.init_state.shape[0]
    nw = (p.window + 31) // 32
    step_fn = p.kernel.step

    # Single-u32-key dedup packing: possible when the one-word state's
    # values (interned ids or 0/1 flags; NIL remapped to nil_id) fit next
    # to the W-bit bitset under the bit-31 invalid flag. Only the register
    # and mutex families qualify — other one-word states (e.g. a
    # single-value unordered-queue count) range past the intern table.
    from jepsen_tpu.models.kernels import PACKED_STATE_KERNELS

    state_bits = nil_id = None
    if S == 1 and p.kernel.name in PACKED_STATE_KERNELS:
        nid = max(len(p.unintern), 2)
        b = nid.bit_length()
        if p.window + b <= 31:
            state_bits, nil_id = b, nid

    level = 0
    cap = cap_schedule[level]
    bits = jnp.zeros((cap, nw), jnp.uint32)
    state = jnp.zeros((cap, S), jnp.int32).at[0].set(
        jnp.asarray(p.init_state))
    count = jnp.int32(1)
    max_cap_used = cap
    snapshots: list | None = [] if explain else None

    base = 0
    while base < p.R:
        if snapshots is not None:
            # only the last snapshot is ever replayed (the dead row is
            # always inside the current chunk): keep HBM flat
            snapshots[:] = [(base, bits, state, count)]
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs",
                    "error": "cancelled"}
        n = min(chunk, p.R - base)
        tables = (jnp.asarray(_chunk_slice(ret_slot_h, base, chunk)),
                  jnp.asarray(_chunk_slice(active_h, base, chunk)),
                  jnp.asarray(_chunk_slice(slot_f_h, base, chunk)),
                  jnp.asarray(_chunk_slice(slot_v_h, base, chunk)))
        while True:
            b2, s2, c2, r_done, dead, ovf = _search_chunk(
                jnp.int32(n), *tables, bits, state, count,
                cap=cap_schedule[level], step_fn=step_fn,
                state_bits=state_bits, nil_id=nil_id)
            if not bool(ovf):
                break
            if level + 1 >= len(cap_schedule):
                return {"valid?": "unknown", "analyzer": "tpu-bfs",
                        "error": ("frontier exceeded capacity "
                                  f"{cap_schedule[-1]}")}
            # Retry this chunk from its entry frontier at the next cap.
            level += 1
            cap = cap_schedule[level]
            max_cap_used = max(max_cap_used, cap)
            grow = cap - bits.shape[0]
            bits = jnp.pad(bits, ((0, grow), (0, 0)))
            state = jnp.pad(state, ((0, grow), (0, 0)))
        if bool(dead):
            r = base + int(r_done) - 1
            ret = p.ops[int(p.ret_op[r])]
            out = {"valid?": False, "analyzer": "tpu-bfs",
                   "dead-row": r,
                   "op": {"process": ret.process, "f": ret.f,
                          "value": ret.value, "index": ret.op_index,
                          "ok": ret.ok},
                   "configs": [], "final-paths": []}
            if snapshots and not (cancel is not None and cancel.is_set()):
                from jepsen_tpu.lin import witness

                out.update(witness.tail_replay_sparse(p, snapshots, r,
                                                      cancel=cancel))
            return out
        bits, state, count = b2, s2, c2
        base += n
        # Frontier is compacted to the front, so a shrunken frontier can
        # drop back to a smaller (faster) program by slicing.
        while level > 0 and int(count) * 4 <= cap_schedule[level - 1]:
            level -= 1
            cap = cap_schedule[level]
            bits = bits[:cap]
            state = state[:cap]

    return {"valid?": True, "analyzer": "tpu-bfs", "configs": [],
            "final-frontier-size": int(count),
            "max-cap": max_cap_used}
