"""The device linearizability kernel: BFS frontier over
(linearized-op-bitset x model-state) configurations.

This replaces the reference's exponential JVM search (knossos.linear /
knossos.wgl, selected at checker.clj:90-93) with a data-parallel formulation
designed for the TPU's compilation model:

- The frontier lives in fixed-capacity device arrays: ``bits: u32[CAP]``
  (which pending ops each config has linearized — slot-compressed by
  :mod:`jepsen_tpu.lin.prepare` so 32 bits cover the concurrency window,
  not the history length) and ``state: i32[CAP, S]`` (packed model state).
- One outer `lax.while_loop` walks the R return events. Each step runs the
  just-in-time closure as an inner `lax.while_loop`: candidate transitions
  are the full cross product (config x pending slot), evaluated in one shot
  by the branchless model step kernels (vmap x vmap) — this is the op that
  fills the vector units; there is no per-config control flow anywhere.
- Dedup is a lexicographic `lax.sort` over (invalid, bits, state) followed
  by adjacent-duplicate masking and a cumsum scatter compaction. Fixpoint
  is detected by the unique-config count not growing (the old frontier is
  part of the candidate pool, so the set is monotone).
- Static shapes throughout: frontier capacity CAP is a compile-time
  constant. Searches run on an escalating CAP schedule — almost all real
  histories need a tiny frontier, so the common case compiles small and
  fast, and only pathological histories pay for big buffers. Overflow is
  detected exactly (a lost config could flip the verdict) and escalates.

The same jitted function is the unit that :mod:`jepsen_tpu.lin.sharded`
shards over a device mesh and that the independent-keys checker vmaps over
batched per-key histories.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu.lin.prepare import PackedHistory

DEFAULT_CAP_SCHEDULE = (256, 2048, 16384, 131072)
MAX_DEVICE_WINDOW = 32
CHUNK = 512


def _dedup(bits, state, valid, cap, state_bits=None, nil_id=None):
    """Sort-dedup-compact. Returns (bits[cap], state[cap,S], count, overflow).

    Invalid rows sort last; duplicates are adjacent after the lexicographic
    sort and masked; survivors are scatter-compacted to the front.

    When ``state_bits`` is set (single-word state whose values fit in that
    many bits next to the W-bit bitset), the whole config packs into ONE
    uint32 sort key — invalid flag in bit 31 — so the sort is a single
    payload-free u32 sort instead of a multi-key lexicographic one. This is
    the hot op of the whole search; on TPU the single-key sort is several
    times faster.
    """
    n = bits.shape[0]
    if state_bits is not None:
        from jepsen_tpu.models.kernels import NIL

        b = state_bits
        sv = state[:, 0]
        packed_state = jnp.where(sv == NIL, nil_id, sv).astype(jnp.uint32)
        key = ((bits << b) | packed_state) \
            | ((~valid).astype(jnp.uint32) << 31)
        key_s = lax.sort(key)
        inv_s = key_s >> 31
        cfg_s = key_s & jnp.uint32(0x7FFFFFFF)

        prev_differs = cfg_s != jnp.roll(cfg_s, 1)
        first = jnp.arange(n) == 0
        mask = (inv_s == 0) & (first | prev_differs)

        total = jnp.sum(mask.astype(jnp.int32))
        overflow = total > cap
        pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
        idx = jnp.where(mask & (pos < cap), pos, n)

        out_n = max(n, cap) + 1
        out_cfg = jnp.zeros(out_n, jnp.uint32).at[idx].set(cfg_s)[:cap]
        out_bits = out_cfg >> b
        sv_out = (out_cfg & jnp.uint32((1 << b) - 1)).astype(jnp.int32)
        out_state = jnp.where(sv_out == nil_id, NIL, sv_out)[:, None]
        count = jnp.minimum(total, cap)
        return out_bits, out_state, count, overflow
    s_width = state.shape[1]
    inv = (~valid).astype(jnp.uint32)
    operands = (inv, bits) + tuple(state[:, k] for k in range(s_width))
    sorted_ops = lax.sort(operands, num_keys=len(operands))
    inv_s, bits_s = sorted_ops[0], sorted_ops[1]
    state_s = jnp.stack(sorted_ops[2:], axis=1)

    prev_differs = (bits_s != jnp.roll(bits_s, 1)) | \
        jnp.any(state_s != jnp.roll(state_s, 1, axis=0), axis=1)
    first = jnp.arange(n) == 0
    mask = (inv_s == 0) & (first | prev_differs)

    total = jnp.sum(mask.astype(jnp.int32))
    overflow = total > cap
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    idx = jnp.where(mask & (pos < cap), pos, n)

    out_n = max(n, cap) + 1
    out_bits = jnp.zeros(out_n, jnp.uint32).at[idx].set(bits_s)[:cap]
    out_state = jnp.zeros((out_n, s_width), jnp.int32) \
        .at[idx].set(state_s)[:cap]
    count = jnp.minimum(total, cap)
    return out_bits, out_state, count, overflow


@partial(jax.jit, static_argnames=("cap", "step_fn"))
def _search(ret_slot, active, slot_f, slot_v, init_state, *, cap, step_fn):
    """Run the full search. Returns (ok, dead_row, overflow, final_count).

    ret_slot: i32[R]; active: bool[R,W]; slot_f: i32[R,W];
    slot_v: i32[R,W,VW]; init_state: i32[S].
    """
    R, W = active.shape
    S = init_state.shape[0]

    bits0 = jnp.zeros(cap, jnp.uint32)
    state0 = jnp.zeros((cap, S), jnp.int32) \
        .at[0].set(init_state)
    count0 = jnp.int32(1)

    step_cfg_slot = jax.vmap(                 # over configs
        jax.vmap(step_fn, in_axes=(None, 0, 0)),   # over slots
        in_axes=(0, None, None))

    slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

    def closure_cond(c):
        _, _, count, prev, ovf = c
        return (count != prev) & ~ovf

    def row_body(carry):
        r, bits, state, count, dead, ovf = carry
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        s = ret_slot[r]

        def closure_body(c):
            bits, state, count, prev, ovf = c
            cfg_valid = jnp.arange(cap) < count

            # the hot op: every (config x pending-slot) transition at once
            ok, new_state = step_cfg_slot(state, f_row, v_row)
            already = (bits[:, None] & slot_bit[None, :]) != 0
            legal = ok & act[None, :] & ~already & cfg_valid[:, None]
            new_bits = bits[:, None] | slot_bit[None, :]

            cand_bits = jnp.concatenate([bits, new_bits.reshape(-1)])
            cand_state = jnp.concatenate(
                [state, new_state.reshape(-1, S)], axis=0)
            cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

            b2, s2, n2, o2 = _dedup(cand_bits, cand_state, cand_valid, cap)
            return (b2, s2, n2, count, ovf | o2)

        init = (bits, state, count, jnp.int32(-1), ovf)
        bits, state, count, _, ovf = lax.while_loop(
            closure_cond, closure_body, init)

        # Filter: the returning op's linearization point must precede its
        # return; then recycle its slot bit.
        s_bit = jnp.uint32(1) << s.astype(jnp.uint32)
        cfg_valid = jnp.arange(cap) < count
        keep = cfg_valid & ((bits & s_bit) != 0)
        bits = bits & ~s_bit
        bits, state, count, o2 = _dedup(bits, state, keep, cap)
        dead = count == 0
        return (r + 1, bits, state, count, dead, ovf | o2)

    def row_cond(carry):
        r, _, _, _, dead, ovf = carry
        return (r < R) & ~dead & ~ovf

    r, bits, state, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), bits0, state0, count0, False, False))
    # dead_row is the row at which the frontier died (r was incremented)
    return ~dead & ~ovf, r - 1, ovf, count


@partial(jax.jit, static_argnames=("cap", "step_fn", "state_bits",
                                   "nil_id"))
def _search_chunk(n_rows, ret_slot, active, slot_f, slot_v,
                  bits, state, count, *, cap, step_fn,
                  state_bits=None, nil_id=None):
    """Process up to n_rows return events (tables are CHUNK-row static
    shapes; rows past n_rows are ignored) starting from a carried frontier.

    The chunk is the unit of device dispatch: every chunk of every history
    reuses the same compiled program per (cap, step_fn), each program runs
    for bounded time (no watchdog kills on 100k-row histories), and a
    transient frontier spike re-runs one chunk at a bigger cap instead of
    the whole search.

    Returns (bits[cap], state[cap,S], count, rows_done, dead, overflow).
    """
    C, W = active.shape
    S = state.shape[1]

    step_cfg_slot = jax.vmap(
        jax.vmap(step_fn, in_axes=(None, 0, 0)),
        in_axes=(0, None, None))
    slot_bit = (jnp.uint32(1) << jnp.arange(W, dtype=jnp.uint32))

    def closure_cond(c):
        _, _, count, prev, ovf = c
        return (count != prev) & ~ovf

    def row_body(carry):
        r, bits, state, count, dead, ovf = carry
        act = active[r]
        f_row = slot_f[r]
        v_row = slot_v[r]
        s = ret_slot[r]

        def closure_body(c):
            bits, state, count, prev, ovf = c
            cfg_valid = jnp.arange(cap) < count
            ok, new_state = step_cfg_slot(state, f_row, v_row)
            already = (bits[:, None] & slot_bit[None, :]) != 0
            legal = ok & act[None, :] & ~already & cfg_valid[:, None]
            new_bits = bits[:, None] | slot_bit[None, :]

            cand_bits = jnp.concatenate([bits, new_bits.reshape(-1)])
            cand_state = jnp.concatenate(
                [state, new_state.reshape(-1, S)], axis=0)
            cand_valid = jnp.concatenate([cfg_valid, legal.reshape(-1)])

            b2, s2, n2, o2 = _dedup(cand_bits, cand_state, cand_valid, cap,
                                    state_bits, nil_id)
            return (b2, s2, n2, count, ovf | o2)

        init = (bits, state, count, jnp.int32(-1), ovf)
        bits, state, count, _, ovf = lax.while_loop(
            closure_cond, closure_body, init)

        s_bit = jnp.uint32(1) << s.astype(jnp.uint32)
        cfg_valid = jnp.arange(cap) < count
        keep = cfg_valid & ((bits & s_bit) != 0)
        bits = bits & ~s_bit
        bits, state, count, o2 = _dedup(bits, state, keep, cap,
                                        state_bits, nil_id)
        dead = count == 0
        return (r + 1, bits, state, count, dead, ovf | o2)

    def row_cond(carry):
        r, _, _, _, dead, ovf = carry
        return (r < n_rows) & ~dead & ~ovf

    r, bits, state, count, dead, ovf = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), bits, state, count, False, False))
    return bits, state, count, r, dead, ovf


def _chunk_slice(a: np.ndarray, base: int, chunk: int) -> np.ndarray:
    """Static-shape chunk slice, zero-padded past the end of the table."""
    end = min(base + chunk, a.shape[0])
    part = a[base:end]
    if part.shape[0] == chunk:
        return part
    pad = np.zeros((chunk - part.shape[0],) + a.shape[1:], a.dtype)
    return np.concatenate([part, pad], axis=0)


def _pad_rows(p: PackedHistory):
    """Bucket R up to a power of two with identity rows so XLA compiles one
    kernel per bucket instead of one per history length.

    An identity row uses a dedicated pad slot (column W) carrying the
    universal no-op f: every config linearizes it (state unchanged), the
    filter keeps everyone, and the recycle clears the bit — frontier exactly
    preserved. Requires one spare bit, so only applied when window < 32.
    """
    from jepsen_tpu.models.kernels import F_NOOP

    R, W = p.active.shape
    R_pad = 1 << max(4, (R - 1).bit_length())
    if R_pad == R or W >= MAX_DEVICE_WINDOW:
        return (np.asarray(p.ret_slot), np.asarray(p.active),
                np.asarray(p.slot_f), np.asarray(p.slot_v))

    pad = R_pad - R
    ret_slot = np.concatenate([p.ret_slot, np.full(pad, W, np.int32)])
    active = np.zeros((R_pad, W + 1), bool)
    active[:R, :W] = p.active
    active[R:, W] = True
    slot_f = np.zeros((R_pad, W + 1), np.int32)
    slot_f[:R, :W] = p.slot_f
    slot_f[R:, W] = F_NOOP
    slot_v = np.zeros((R_pad, W + 1, p.slot_v.shape[2]), np.int32)
    slot_v[:R, :W] = p.slot_v
    return ret_slot, active, slot_f, slot_v


def check_packed(p: PackedHistory, cap_schedule=DEFAULT_CAP_SCHEDULE,
                 chunk: int = CHUNK, cancel=None) -> dict:
    """Decide linearizability of a packed history on device.

    Host loop over CHUNK-row device dispatches; the frontier carries
    between chunks. Capacity adapts per chunk: overflow re-runs just that
    chunk at the next cap level (from the pre-chunk frontier snapshot);
    when the frontier shrinks the cap drops back so the common case keeps
    running on the small fast program. ``cancel`` (a threading.Event) stops
    the search between chunks — set by a competition race once the other
    racer has decided.
    """
    if p.kernel is None:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"no device kernel for {type(p.model).__name__}"}
    if p.window > MAX_DEVICE_WINDOW:
        return {"valid?": "unknown", "analyzer": "tpu-bfs",
                "error": f"concurrency window {p.window} exceeds device "
                         f"bitset width {MAX_DEVICE_WINDOW}"}
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-bfs", "configs": []}

    ret_slot_h = np.asarray(p.ret_slot)
    active_h = np.asarray(p.active)
    slot_f_h = np.asarray(p.slot_f)
    slot_v_h = np.asarray(p.slot_v)
    S = p.init_state.shape[0]
    step_fn = p.kernel.step

    # Single-u32-key dedup packing: possible when the one-word state's
    # values (interned ids or 0/1 flags; NIL remapped to nil_id) fit next
    # to the W-bit bitset under the bit-31 invalid flag. Only the register
    # and mutex families qualify — other one-word states (e.g. a
    # single-value unordered-queue count) range past the intern table.
    state_bits = nil_id = None
    if S == 1 and p.kernel.name in ("cas-register", "register", "mutex"):
        nid = max(len(p.unintern), 2)
        b = nid.bit_length()
        if p.window + b <= 31:
            state_bits, nil_id = b, nid

    level = 0
    cap = cap_schedule[level]
    bits = jnp.zeros(cap, jnp.uint32)
    state = jnp.zeros((cap, S), jnp.int32).at[0].set(
        jnp.asarray(p.init_state))
    count = jnp.int32(1)
    max_cap_used = cap

    base = 0
    while base < p.R:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-bfs",
                    "error": "cancelled"}
        n = min(chunk, p.R - base)
        tables = (jnp.asarray(_chunk_slice(ret_slot_h, base, chunk)),
                  jnp.asarray(_chunk_slice(active_h, base, chunk)),
                  jnp.asarray(_chunk_slice(slot_f_h, base, chunk)),
                  jnp.asarray(_chunk_slice(slot_v_h, base, chunk)))
        while True:
            b2, s2, c2, r_done, dead, ovf = _search_chunk(
                jnp.int32(n), *tables, bits, state, count,
                cap=cap_schedule[level], step_fn=step_fn,
                state_bits=state_bits, nil_id=nil_id)
            if not bool(ovf):
                break
            if level + 1 >= len(cap_schedule):
                return {"valid?": "unknown", "analyzer": "tpu-bfs",
                        "error": ("frontier exceeded capacity "
                                  f"{cap_schedule[-1]}")}
            # Retry this chunk from its entry frontier at the next cap.
            level += 1
            cap = cap_schedule[level]
            max_cap_used = max(max_cap_used, cap)
            grow = cap - bits.shape[0]
            bits = jnp.pad(bits, (0, grow))
            state = jnp.pad(state, ((0, grow), (0, 0)))
        if bool(dead):
            r = base + int(r_done) - 1
            ret = p.ops[int(p.ret_op[r])]
            return {"valid?": False, "analyzer": "tpu-bfs",
                    "op": {"process": ret.process, "f": ret.f,
                           "value": ret.value, "index": ret.op_index,
                           "ok": ret.ok},
                    "configs": [], "final-paths": []}
        bits, state, count = b2, s2, c2
        base += n
        # Frontier is compacted to the front, so a shrunken frontier can
        # drop back to a smaller (faster) program by slicing.
        while level > 0 and int(count) * 4 <= cap_schedule[level - 1]:
            level -= 1
            cap = cap_schedule[level]
            bits = bits[:cap]
            state = state[:cap]

    return {"valid?": True, "analyzer": "tpu-bfs", "configs": [],
            "final-frontier-size": int(count),
            "max-cap": max_cap_used}
