"""Exhaustive linearizability search for tiny histories.

An independent implementation used only to test the testers: enumerates
every real-time-consistent linearization order directly over the original
history with the Python models (no packing, no slots, no interning), so a
bug shared by prepare/cpu/bfs cannot hide. Exponential; keep histories
under ~12 ops.
"""

from __future__ import annotations

from functools import lru_cache

from jepsen_tpu.history import Op
from jepsen_tpu.lin.prepare import pair_ops
from jepsen_tpu.models import is_inconsistent

INF = float("inf")


def check(model, history) -> bool:
    """True iff the history is linearizable against the model."""
    ops = pair_ops(list(history))
    n = len(ops)
    if n > 20:
        raise ValueError(f"brute force limited to tiny histories, got {n}")

    returns = [o.return_pos if o.return_pos is not None else INF for o in ops]
    invokes = [o.invoke_pos for o in ops]
    must = frozenset(i for i, o in enumerate(ops) if o.ok)

    def shim(i) -> Op:
        o = ops[i]
        return Op("invoke", o.f, o.value, o.process)

    seen = set()

    def dfs(remaining: frozenset, state) -> bool:
        if not (remaining & must):
            return True  # all ok ops linearized; leftover info ops may not happen
        key = (remaining, state)
        if key in seen:
            return False
        seen.add(key)
        # earliest return among remaining: nothing invoked after it may go first
        horizon = min(returns[i] for i in remaining)
        for i in remaining:
            if invokes[i] > horizon:
                continue
            st2 = state.step(shim(i))
            if is_inconsistent(st2):
                continue
            if dfs(remaining - {i}, st2):
                return True
        return False

    return dfs(frozenset(range(n)), model)
