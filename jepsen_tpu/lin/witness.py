"""Counterexample reconstruction for device-decided violations.

The dense engine (:mod:`jepsen_tpu.lin.dense`) decides validity with a
frontier bitmap that carries no parent pointers — storing paths on device
would burn HBM bandwidth on the 99% case (valid histories) to serve the
1% (violations). Instead the device search retains its per-chunk entry
bitmaps (a few KB each), and on an invalid verdict this module replays
JUST the failing tail on the host:

1. take the last snapshot at or before the dead row — the *exact* closed
   config set the device search had there (the bitmap is the
   characteristic function, so no information is lost);
2. run the CPU oracle's closure (:func:`jepsen_tpu.lin.cpu.search_rows`)
   from that set through the dead row, tracking linearization order via
   shared-structure cons cells;
3. emit knossos-style ``final-paths`` — for each config alive at the
   failure, its model state and the op path that reached it — the shape
   the reference renders at checker.clj:96-107.

The replay is bounded by one chunk of return events regardless of history
length, so a 100k-op violation costs a <=CHUNK-row host replay, not a
full re-check.
"""

from __future__ import annotations

from jepsen_tpu.lin import cpu, dense
from jepsen_tpu.lin.prepare import PackedHistory


def replay_configs(p: PackedHistory, configs: set, base: int,
                   dead_row: int, cancel=None) -> dict:
    """Run the CPU oracle's closure from a known config set at row
    ``base`` through ``dead_row``, tracking linearization order, and
    emit knossos-style configs + final-paths at the death. Returns {}
    on failure/cancel (reporting is best-effort, like the reference's
    render at checker.clj:96-103)."""
    if not configs:
        return {}
    order = {cfg: None for cfg in configs}
    try:
        cpu.search_rows(p, configs, order, base, dead_row + 1,
                        cancel=cancel)
    except cpu.Dead as d:
        return {"configs": cpu._decode_configs(p, d.seen, d.r),
                "final-paths": cpu._final_paths(p, d.seen, d.order)}
    except Exception:
        return {}
    # The tail replay survived where the device died: a disagreement
    # between engines — surface it rather than fabricate a path.
    return {"error": "tail replay disagrees with device verdict "
                     f"(rows {base}..{dead_row} survive on host)"}


def tail_replay(p: PackedHistory, nil_id: int, snapshots: list,
                dead_row: int, cancel=None) -> dict:
    """Dense-engine counterexample: decode the last chunk-entry bitmap
    at or before ``dead_row`` and replay the failing tail.
    ``cancel`` keeps a competition loser's replay from blocking the
    race join."""
    usable = [(b, F) for b, F in snapshots if b <= dead_row]
    if not usable:
        return {}
    base, F = usable[-1]
    configs = {(bits, st) for bits, st in dense.decode_bitmap(F, nil_id)}
    return replay_configs(p, configs, base, dead_row, cancel=cancel)


def tail_replay_sparse(p: PackedHistory, snapshots: list,
                       dead_row: int, cancel=None) -> dict:
    """Sparse-engine counterexample: snapshots are
    ``(base_row, bits[cap,NW], state[cap,S], count)`` chunk-entry
    frontiers; decode the multi-word bitsets and replay the tail."""
    import numpy as np

    usable = [s for s in snapshots if s[0] <= dead_row]
    if not usable:
        return {}
    base, bits, state, count = usable[-1]
    n = min(int(count), np.asarray(bits).shape[0])
    bits = np.asarray(bits)[:n].astype(object)
    state = np.asarray(state)[:n]
    packed = bits[:, 0]
    for w in range(1, bits.shape[1]):
        packed = packed | (bits[:, w] << (32 * w))
    configs = set(zip((int(b) for b in packed),
                      map(tuple, state.tolist())))
    return replay_configs(p, configs, base, dead_row, cancel=cancel)
