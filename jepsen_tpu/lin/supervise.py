"""Supervised dispatch runtime for the linearizability engines.

The chip's failure modes are the bottleneck of 100k-op on-device
decides as much as throughput (rounds 2-7 lore, CLAUDE.md): kernel
faults kill the TPU worker for ~a minute, the shared-chip tunnel can
wedge a single dispatch ~25 minutes, and a watchdog-killed in-program
orbit presents exactly like a fault. Until now recovery was "kill and
re-run" at the PROCESS level (bench.py's parent-side stall watchdog),
throwing away whole multi-hour runs. This module moves supervision
down into the library, per DISPATCH:

- :func:`call` — the dispatch watchdog: runs one engine dispatch in a
  worker thread under a per-call-site deadline and a bounded retry
  budget. Engine dispatch thunks are pure functions of immutable
  device arrays, so abandoning a wedged thread and re-dispatching is
  exact. Exhaustion raises :class:`WedgedDispatch`, which the call
  sites translate into their fallback ladder rung (wave -> per-row
  fused -> unfused passes -> CPU oracle) or an honest "unknown".
- The **fault-shape quarantine ledger** — a persistent JSON beside the
  XLA compile cache keyed by traced program shape (site, rows x cap,
  window, kernel family). A dispatch that faults (or repeatedly
  wedges — one wedge is often environmental, see
  WEDGE_QUARANTINE_COUNT) records its shape. The HOST-ROW sites
  (host-sched / host-wave / host-fixpoint / host-pass) consult the
  ledger and route
  quarantined shapes straight to their proven fallback rung in future
  runs, including fresh processes — the round 2-5 fault lore as
  machine state instead of CLAUDE.md prose. The base-rung sites
  (chunk, chunk-batch, spike, mesh-chunk) have no alternative rung:
  their entries are observability only (the `make probe-config5`
  ledger delta and triage), not routing. The ``pack-dev`` site (the
  device packer, lin/pack_dev.py) both routes AND stays sound on any
  outcome: a quarantined/wedged/faulted pack shape falls back to the
  bit-identical host packer, so its entries cost latency, never a
  verdict. ``cli.py quarantine list|clear|diff`` manages it.
- :class:`Checkpointer` / :func:`load_checkpoint` — **frontier
  checkpoint/resume**: at episode boundaries the engines serialize the
  packed frontier, row cursor, sticky level, and host-stats to an
  ``.npz`` beside the run; ``lin.device_check_packed(..., resume=...)``
  continues a killed or faulted run mid-history. Soundness rests on
  the checkpoint carrying an EXACT committed frontier at a row
  boundary: the continuation re-runs the identical deterministic
  dispatch sequence, so the resumed verdict and death row provably
  equal the uninterrupted run (parity-tested against lin/cpu.py).

Env knobs (all tabled in doc/env.md): JEPSEN_TPU_SUPERVISE,
JEPSEN_TPU_DISPATCH_DEADLINE_S, JEPSEN_TPU_DISPATCH_RETRIES,
JEPSEN_TPU_QUARANTINE, JEPSEN_TPU_CKPT, JEPSEN_TPU_CKPT_EVERY_S,
JEPSEN_TPU_WEDGE / JEPSEN_TPU_FAULT (test hooks),
JEPSEN_TPU_CPU_ROW_MAX. The predictive
twin of the ledger — the pre-dispatch STATIC GATE over traced jaxprs
(JEPSEN_TPU_STATIC_GATE, doc/analysis.md) — hooks in via
:func:`run_guarded`'s ``traceable`` parameter.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Callable

import numpy as np

from jepsen_tpu import util
from jepsen_tpu.obs import metrics as _obs_metrics
from jepsen_tpu.obs import trace as _obs_trace

CKPT_VERSION = 1
LEDGER_VERSION = 1
# Events kept in the in-stats trip log (monitoring-grade; the ledger
# holds the durable record).
MAX_EVENTS = 8


def enabled() -> bool:
    """Dispatch watchdog master switch; ``JEPSEN_TPU_SUPERVISE=0``
    runs every dispatch unwrapped (triage: rule the supervision layer
    itself out)."""
    return os.environ.get("JEPSEN_TPU_SUPERVISE", "1") != "0"


def base_deadline_s() -> float:
    """Base per-dispatch deadline. Call sites scale it (the fused
    closure fixpoint and the K-row wave program legitimately run
    minutes in ONE dispatch, so they pass scale 3; chunk-batch and
    per-pass sites use scale 1)."""
    return util.env_float("JEPSEN_TPU_DISPATCH_DEADLINE_S", 600.0)


def retry_budget() -> int:
    """Re-dispatches after a wedge before :class:`WedgedDispatch`."""
    return util.env_int("JEPSEN_TPU_DISPATCH_RETRIES", 1)


def cpu_row_max() -> int:
    """Largest frontier the CPU-oracle ladder rung accepts (a pure
    Python closure over a bigger set would grind for hours; past this
    the ladder reports an honest wedge/fault overflow instead)."""
    return util.env_int("JEPSEN_TPU_CPU_ROW_MAX", 1 << 16)


class WedgedDispatch(Exception):
    """A dispatch exceeded its watchdog deadline on every attempt.
    The call site falls to its next ladder rung or reports an honest
    "unknown" — it must never hang the process."""

    def __init__(self, site: str, deadline_s: float, attempts: int):
        self.site, self.deadline_s, self.attempts = \
            site, deadline_s, attempts
        super().__init__(
            f"dispatch at site {site!r} exceeded the {deadline_s:.0f}s "
            f"watchdog deadline on {attempts} attempt(s)")


# --- wedge injection (test hook) -------------------------------------------
# JEPSEN_TPU_WEDGE="site:count[:deadline_s]" (or inject_wedge()) makes
# the next ``count`` supervised calls at ``site`` run a fake thunk that
# blocks past the deadline WITHOUT touching the device — so tests (and
# the bench artifact test) exercise detection, retry, and the fallback
# ladder deterministically. The real thunk runs on the next attempt.
# The optional per-injection deadline applies ONLY to the injected
# attempts, so a test can prove fast detection at one site without
# starving every other dispatch in the process.

_injected: dict[str, list] = {}   # site -> [count, deadline_s | None]
_env_wedge_loaded: str | None = None
_lock = threading.Lock()

# JEPSEN_TPU_FAULT="site:count" (or inject_fault()) makes the next
# ``count`` supervised calls at ``site`` RAISE a RuntimeError before
# the real thunk runs — the fault twin of the wedge hook, so the chaos
# nemesis (service/chaos.py) and tests exercise the fault taxonomy
# (requeue, ledger recording, honest `overflow: fault`) without a real
# dead worker. The real thunk runs on the next attempt/retry.
_injected_faults: dict[str, int] = {}
_env_fault_loaded: str | None = None


def inject_wedge(site: str, n: int = 1,
                 deadline_s: float | None = None) -> None:
    with _lock:
        e = _injected.setdefault(site, [0, deadline_s])
        e[0] += n
        if deadline_s is not None:
            e[1] = deadline_s


def inject_fault(site: str, n: int = 1) -> None:
    with _lock:
        _injected_faults[site] = _injected_faults.get(site, 0) + n


def reset_injections() -> None:
    """Tests/chaos only: disarm every pending wedge/fault injection —
    a chaos schedule's leftover armed events must not leak into the
    next run (or the next test) in the same process."""
    global _env_wedge_loaded, _env_fault_loaded
    with _lock:
        _injected.clear()
        _injected_faults.clear()
        _env_wedge_loaded = os.environ.get("JEPSEN_TPU_WEDGE") or None
        _env_fault_loaded = os.environ.get("JEPSEN_TPU_FAULT") or None


def _consume_fault_injection(site: str) -> bool:
    global _env_fault_loaded
    with _lock:
        env = os.environ.get("JEPSEN_TPU_FAULT", "")
        if env and env != _env_fault_loaded:
            _env_fault_loaded = env
            for part in env.split(","):
                bits = part.split(":")
                if bits and bits[0]:
                    s = bits[0].strip()
                    _injected_faults[s] = _injected_faults.get(s, 0) + (
                        int(bits[1]) if len(bits) > 1 and bits[1]
                        else 1)
        n = _injected_faults.get(site, 0)
        if n > 0:
            _injected_faults[site] = n - 1
            return True
        return False


def _consume_injection(site: str):
    """None when this attempt runs the real thunk; otherwise the
    deadline to use for the injected (fake-wedged) attempt."""
    global _env_wedge_loaded
    with _lock:
        env = os.environ.get("JEPSEN_TPU_WEDGE", "")
        if env and env != _env_wedge_loaded:
            _env_wedge_loaded = env
            for part in env.split(","):
                bits = part.split(":")
                if bits and bits[0]:
                    e = _injected.setdefault(bits[0].strip(), [0, None])
                    e[0] += int(bits[1]) if len(bits) > 1 and bits[1] \
                        else 1
                    if len(bits) > 2 and bits[2]:
                        e[1] = float(bits[2])
        e = _injected.get(site)
        if e is not None and e[0] > 0:
            e[0] -= 1
            return e[1] if e[1] is not None else -1.0
        return None


def _note_event(stats: dict | None, site: str, kind: str,
                detail: str = "") -> None:
    # The obs event feed (web.py /run, cli.py host-stats) sees every
    # trip regardless of whether the call site passed a stats dict.
    _obs_metrics.REGISTRY.event(kind, site=site)
    if stats is None:
        return
    util.stat_bump(stats, "watchdog_trips" if kind == "wedge"
                   else "faults")
    ev = stats.setdefault("supervise_events", [])
    if len(ev) < MAX_EVENTS:
        e = {"site": site, "kind": kind}
        if detail:
            e["detail"] = detail[:200]
        ev.append(e)


def note_fault(stats: dict | None, site: str, detail: str = "") -> None:
    """Record a dispatch FAULT (the thunk raised — a dead worker, an
    XLA runtime error) in the stats trip log; the wedge twin is
    recorded by :func:`call` itself."""
    _note_event(stats, site, "fault", detail)


def call(site: str, thunk: Callable, *, scale: float = 1.0,
         deadline_s: float | None = None, retries: int | None = None,
         stats: dict | None = None, shape: str | None = None):
    """Run one engine dispatch thunk under the watchdog.

    The thunk is dispatched from a daemon worker thread and joined
    with the deadline; a join timeout is a WEDGE: the worker is
    abandoned (on a truly wedged tunnel it blocks in the runtime — the
    same state the process was in before, except now the search can
    act on it), the trip is recorded in ``stats``, and the thunk is
    re-dispatched up to the retry budget. Thunks MUST be pure
    functions of immutable inputs (every engine dispatch is: jitted
    programs of device arrays), so a retry is exact.

    After a deadline miss the worker gets one short GRACE join (25% of
    the deadline, capped at 60 s) before the retry dispatches: a stall
    that resolves just past the deadline — the common shared-chip case
    — is harvested instead of raced. The residual race is inherent (an
    XLA dispatch cannot be cancelled): a retry can overlap a still-
    wedged dispatch that later resumes, briefly doubling the queue
    depth; deadlines are therefore sized as upper bounds of legitimate
    dispatch time, not latency targets.

    Exceptions from the thunk propagate unchanged (fault
    classification and ledger recording are the call site's job — it
    knows the program shape; see :func:`run_guarded`). Raises
    :class:`WedgedDispatch` when the budget is exhausted.

    ``shape`` is observability only: the traced-program shape key
    recorded on the flight-recorder span (this function is the single
    choke point every engine dispatch passes through, so one span here
    instruments them all). The tracer observes; it never routes."""
    deadline = deadline_s if deadline_s is not None \
        else base_deadline_s() * scale
    sp = _obs_trace.span("dispatch", site=site, shape=shape,
                         deadline_s=round(deadline, 1)) \
        if _obs_trace.enabled() else _obs_trace.NULL_SPAN
    with sp:
        if not enabled():
            r = thunk()
            sp.note(outcome="ok", supervised=False)
            return r
        attempts = max(1, (retries if retries is not None
                           else retry_budget()) + 1)
        wedges = 0
        for _attempt in range(attempts):
            if _consume_fault_injection(site):
                # Injected FAULT (chaos/test hook): raise like a dead
                # worker would, without touching the device — the call
                # site's fault taxonomy (ledger, requeue, honest
                # unknown) takes it from here.
                sp.note(outcome="fault", error="InjectedFault")
                raise RuntimeError(
                    f"injected fault at site {site!r} "
                    f"(JEPSEN_TPU_FAULT/inject_fault test hook)")
            fn = thunk
            join_deadline = deadline
            inj = _consume_injection(site)
            if inj is not None:
                # Fake wedge: blocks past the deadline without running
                # the real dispatch (racing an abandoned REAL dispatch
                # against its retry would touch device state twice).
                # An injection-carried deadline applies to this
                # attempt only.
                if inj > 0:
                    join_deadline = inj
                fn = lambda: threading.Event().wait(  # noqa: E731
                    join_deadline * 10)
            result: list = []
            err: list = []

            def run(fn=fn):
                try:
                    result.append(fn())
                except BaseException as e:  # noqa: BLE001 - below
                    err.append(e)

            t = threading.Thread(target=run, daemon=True,
                                 name=f"supervised-{site}")
            t.start()
            t.join(join_deadline)
            if t.is_alive():
                # Grace join: harvest a just-late completion instead
                # of racing a second dispatch against it (docstring).
                t.join(min(join_deadline * 0.25, 60.0))
            if t.is_alive():
                wedges += 1
                _note_event(stats, site, "wedge")
                # Liveness: detection and the retry ARE forward
                # progress. Without this tick bench's parent stall
                # watchdog (whose windows are sized like these
                # deadlines) would kill the child at the same moment
                # the in-library ladder starts — making the recovery
                # paths unreachable exactly where they matter.
                util.progress_tick()
                continue
            if err:
                if isinstance(err[0], (RuntimeError, OSError)):
                    sp.note(outcome="fault",
                            error=type(err[0]).__name__)
                raise err[0]
            sp.note(outcome="ok")
            if wedges:
                sp.note(wedges=wedges, attempts=_attempt + 1)
            return result[0]
        sp.note(outcome="wedge", attempts=attempts, wedges=wedges)
        raise WedgedDispatch(site, deadline, attempts)


def run_guarded(site: str, key: str, thunk: Callable, *,
                scale: float = 1.0, stats: dict | None = None,
                retries: int | None = None,
                traceable: Callable | None = None):
    """:func:`call` + the fault taxonomy + ledger recording, in one
    place (the seven engine call sites differ only in their fallback
    ACTION). Returns ``(outcome, value)``: ``("ok", result)``,
    ``("wedge", WedgedDispatch)`` — budget exhausted, shape recorded —
    or ``("fault", exc)`` — the dispatch raised RuntimeError/OSError
    (dead worker, XLA runtime error), event noted in ``stats`` and
    shape recorded. Other exceptions (programming errors) propagate.

    ``traceable`` is the pure-jax half of the thunk (same program, no
    host fetches): when given, the STATIC GATE
    (:mod:`jepsen_tpu.analysis.gate`, ``JEPSEN_TPU_STATIC_GATE``)
    traces it against the fault-lore jaxpr rules before dispatch;
    under ``route`` a flagged program at a fallback-owning site
    returns ``("static", StaticallyFlagged)`` with ZERO device
    dispatches — the predictive twin of the quarantine check the host
    sites already do. The gate must never take a run down: any
    analysis error means "proceed"."""
    if traceable is not None:
        try:
            from jepsen_tpu.analysis import gate as _gate

            flagged = _gate.consider(site, key, traceable, stats=stats)
        except Exception:  # noqa: BLE001 - the gate observes; it must
            flagged = None  # never fail a healthy dispatch
        if flagged is not None:
            return "static", flagged
    try:
        return "ok", call(site, thunk, scale=scale, stats=stats,
                          retries=retries, shape=key)
    except WedgedDispatch as e:
        record_fault(key, "wedge")
        return "wedge", e
    except (RuntimeError, OSError) as e:
        note_fault(stats, site, repr(e))
        record_fault(key, "fault", repr(e))
        return "fault", e


# --- fault-shape quarantine ledger -----------------------------------------


def ledger_path() -> str | None:
    """The quarantine ledger lives beside the persistent XLA compile
    cache (both are per-checkout machine state). ``JEPSEN_TPU_QUARANTINE``
    overrides the path; ``0`` disables the ledger entirely."""
    env = os.environ.get("JEPSEN_TPU_QUARANTINE", "")
    if env == "0":
        return None
    if env:
        return env
    return os.path.join(util.cache_dir(), "quarantine.json")


def shape_key(site: str, *, cap: int, window: int, kernel: str,
              rows: int = 1, band: str = "") -> str:
    """The traced-program-shape key: what the runtime objects to is
    (program family) x (rows x cap complexity) x (window/kernel
    bucket) — the round 2-5 fault-lore coordinates. ``band`` tags
    program VARIANTS that share a site but compile different programs
    (the mesh engine's single-key vs pair-key vs episode-scheduler
    dispatches under ``mesh-chunk``): a faulting variant must not
    quarantine its healthy siblings."""
    base = f"{site}|rows{rows}|cap{cap}|w{window}|{kernel}"
    return f"{base}|{band}" if band else base


_ledger_cache: tuple[str, float, dict] | None = None


def load_ledger(path: str | None = None) -> dict:
    """The ledger's ``shapes`` dict ({} when absent/disabled/corrupt —
    a damaged ledger must never block a check). mtime-cached: the
    host-row executor consults it per row."""
    global _ledger_cache
    path = path or ledger_path()
    if path is None:
        return {}
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        return {}
    if _ledger_cache is not None and _ledger_cache[0] == path \
            and _ledger_cache[1] == mtime:
        return _ledger_cache[2]
    try:
        with open(path) as fh:
            shapes = json.load(fh).get("shapes", {})
    except (OSError, ValueError):
        return {}
    _ledger_cache = (path, mtime, shapes)
    return shapes


# A single WEDGE does not quarantine a shape: tunnel stalls are often
# environmental (the shared chip wedges healthy dispatches ~25 min,
# CLAUDE.md), and one transient event must not permanently route a
# healthy program to a slower rung. A STREAK of wedges of the SAME
# shape within the window is evidence (two isolated stalls weeks apart
# are still environmental — the streak resets); faults (the dispatch
# raised) quarantine immediately.
WEDGE_QUARANTINE_COUNT = 2
WEDGE_STREAK_WINDOW_S = 6 * 3600.0

_TS_FMT = "%Y-%m-%dT%H:%M:%SZ"


def _parse_ts(s) -> float | None:
    import calendar

    try:
        return calendar.timegm(time.strptime(s, _TS_FMT))
    except (TypeError, ValueError):
        return None


def quarantined(key: str, path: str | None = None) -> dict | None:
    e = load_ledger(path).get(key)
    if e is None:
        return None
    # STATIC entries (the analysis gate's predictions) are
    # observability, not quarantine: the gate re-derives its routing
    # per process, so turning it off must make the entry routing-inert
    # — only a real crash (faulted) hardens it.
    if e.get("reason") == "static" and not e.get("faulted"):
        return None
    # Wedge tolerance applies only to shapes that have NEVER faulted:
    # a fault is hard evidence regardless of later wedges.
    if e.get("reason") == "wedge" and not e.get("faulted") \
            and e.get("streak", e.get("count", 0)) \
            < WEDGE_QUARANTINE_COUNT:
        return None
    return e


def _write_ledger(path: str, shapes: dict) -> None:
    global _ledger_cache
    util.write_json_atomic(path,
                           {"version": LEDGER_VERSION, "shapes": shapes})
    _ledger_cache = None


def record_fault(key: str, reason: str, detail: str = "",
                 path: str | None = None) -> dict | None:
    """Record (or re-record) a faulting shape. ``reason`` is "fault"
    (the dispatch raised), "wedge" (watchdog deadline), or "static"
    (the analysis gate predicted a fault and routed — never a crash
    record, so it does not harden the entry). Last-writer-
    wins read-modify-write with an atomic replace — monitoring-grade
    concurrency, matching the bench's subprocess fan-out."""
    path = path or ledger_path()
    if path is None:
        return None
    shapes = dict(load_ledger(path))
    now_s = time.time()
    now = time.strftime(_TS_FMT, time.gmtime(now_s))
    e = dict(shapes.get(key) or {"first": now, "count": 0})
    if reason == "static" and e.get("reason") in ("wedge", "fault"):
        # A prediction must never overwrite CRASH evidence: a
        # wedge-streak (or faulted) entry keeps its reason and
        # streak so quarantined() still honors it with the gate off;
        # the prediction rides alongside as its own counter.
        e["static_count"] = e.get("static_count", 0) + 1
        e["last_static"] = now
        shapes[key] = e
        _write_ledger(path, shapes)
        _obs_metrics.REGISTRY.event("quarantine", key=key,
                                    reason=reason)
        return e
    if reason == "wedge":
        prev = _parse_ts(e.get("last"))
        within = prev is not None and now_s - prev <= \
            WEDGE_STREAK_WINDOW_S
        e["streak"] = (e.get("streak", 0) + 1) if within else 1
    elif reason == "fault":
        e["faulted"] = True
    e.update(reason=reason, count=e.get("count", 0) + 1, last=now)
    if detail:
        e["detail"] = detail[:500]
    shapes[key] = e
    _write_ledger(path, shapes)
    _obs_metrics.REGISTRY.event("quarantine", key=key, reason=reason)
    return e


def clear_ledger(keys=None, path: str | None = None) -> int:
    """Remove ``keys`` (or everything) from the ledger; returns the
    number of entries removed."""
    path = path or ledger_path()
    if path is None:
        return 0
    shapes = dict(load_ledger(path))
    if keys is None:
        removed = len(shapes)
        shapes = {}
    else:
        removed = 0
        for k in keys:
            if shapes.pop(k, None) is not None:
                removed += 1
    _write_ledger(path, shapes)
    return removed


def ledger_delta(before: dict, path: str | None = None) -> dict:
    """Shapes newly recorded (or re-faulted) since ``before`` (a prior
    ``load_ledger`` snapshot) — what ``make probe-config5`` prints so
    an engine change that newly faults a shape is visible in one
    command."""
    now = load_ledger(path)
    out = {}
    for k, e in now.items():
        old = before.get(k)
        if old is None or old.get("count") != e.get("count"):
            out[k] = e
    return out


# --- frontier checkpoint/resume --------------------------------------------


def ckpt_path() -> str | None:
    return os.environ.get("JEPSEN_TPU_CKPT", "") or None


def ckpt_every_s() -> float:
    return util.env_float("JEPSEN_TPU_CKPT_EVERY_S", 60.0)


def history_fingerprint(p) -> str:
    """Identity of a packed history for resume safety: a checkpoint
    resumes ONLY onto the exact same search input (tables, window,
    kernel, interning) — anything else is rejected and the run starts
    fresh."""
    h = hashlib.sha256()
    h.update(f"{p.kernel.name if p.kernel else None}|{p.window}|{p.R}|"
             f"{len(p.unintern)}".encode())
    for a in (p.ret_slot, p.active, p.slot_f, p.slot_v, p.crashed,
              p.init_state):
        arr = np.ascontiguousarray(np.asarray(a))
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class Checkpointer:
    """Interval-gated frontier checkpoint writer (atomic ``.npz``).

    ``save`` is called by the engines at committed row boundaries (the
    chunk loop after a clean batch, the host-row executor after each
    committed row/batch — the episode boundaries); ``due()`` gates the
    device->host frontier copy to once per ``every_s``. ``on_save`` is
    a test hook (the resume parity test kills the search right after a
    boundary write)."""

    def __init__(self, path: str, fingerprint: str,
                 every_s: float | None = None):
        self.path = path
        self.fingerprint = fingerprint
        self.every_s = ckpt_every_s() if every_s is None else every_s
        self._last = float("-inf")
        self.writes = 0
        self.on_save = None

    def due(self) -> bool:
        return time.monotonic() - self._last >= self.every_s

    def save(self, kind: str, row: int, count: int,
             arrays: dict, meta: dict | None = None) -> None:
        m = {"version": CKPT_VERSION, "fingerprint": self.fingerprint,
             "kind": kind, "row": int(row), "count": int(count)}
        m.update(meta or {})
        payload = {k: np.asarray(v) for k, v in arrays.items()}
        payload["__meta__"] = np.frombuffer(
            json.dumps(m, default=str).encode(), dtype=np.uint8)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as fh:
            np.savez(fh, **payload)
        os.replace(tmp, self.path)
        self._last = time.monotonic()
        self.writes += 1
        if self.on_save is not None:
            self.on_save(kind, int(row))

    def clear(self) -> None:
        """Remove the checkpoint (called on a DEFINITE verdict — a
        later fresh run must not resume a finished search; unknown /
        wedged / cancelled verdicts keep it so a re-run continues)."""
        try:
            os.remove(self.path)
        except OSError:
            pass


def load_checkpoint(path: str, fingerprint: str) -> dict | None:
    """Load + validate a checkpoint. Returns
    ``{"kind", "row", "count", "meta", <arrays>}`` or None when
    missing, corrupt, version-skewed, or fingerprint-mismatched —
    resume degrades to a fresh run, never an exception."""
    try:
        with np.load(path) as z:
            meta = json.loads(bytes(z["__meta__"]).decode())
            if meta.get("version") != CKPT_VERSION:
                return None
            if meta.get("fingerprint") != fingerprint:
                return None
            out = {k: z[k] for k in z.files if k != "__meta__"}
    except Exception:  # noqa: BLE001 - any damage means "no checkpoint"
        return None
    out.update(kind=meta["kind"], row=int(meta["row"]),
               count=int(meta["count"]), meta=meta)
    return out


# --- numpy (device-free) packed-key codec ----------------------------------
# Host-side mirror of bfs._pack/_unpack_frontier_keys[2]: the CPU-
# oracle ladder rung and host-kind checkpoint resume must decode/encode
# frontiers WITHOUT a device dispatch (the device may be the thing
# that's dead).

KEY_FILL = np.uint32(0xFFFFFFFF)


def np_unpack_keys(lo, hi, count, b, nil_id, nw, key_hi, nil_state):
    """(bits[count, nw] uint32, state[count, 1] int32) from packed key
    arrays (numpy, first ``count`` live entries)."""
    n = int(count)
    lo = np.asarray(lo)[:n].astype(np.uint64)
    mask = np.uint64((1 << b) - 1)
    if key_hi:
        full = lo | (np.asarray(hi)[:n].astype(np.uint64) << np.uint64(32))
    else:
        full = lo
    sv = (full & mask).astype(np.int64)
    state = np.where(sv == nil_id, nil_state, sv).astype(np.int32)
    bits_full = full >> np.uint64(b)
    cols = [(bits_full & np.uint64(0xFFFFFFFF)).astype(np.uint32)]
    if nw > 1:
        cols.append((bits_full >> np.uint64(32)).astype(np.uint32))
    bits = np.stack(cols, axis=1)
    if nw > len(cols):
        bits = np.pad(bits, ((0, 0), (0, nw - len(cols))))
    return bits, state[:, None]


def np_pack_keys(bits, state, b, nil_id, key_hi, nil_state, cap):
    """(lo[cap], hi[cap]|None) uint32 arrays from unpacked frontier
    rows (KEY_FILL padded) — numpy twin of bfs._pack_frontier_keys[2]."""
    bits = np.asarray(bits, dtype=np.uint64)
    state = np.asarray(state)
    n = bits.shape[0]
    sv = state[:, 0].astype(np.int64)
    ps = np.where(sv == nil_state, nil_id, sv).astype(np.uint64)
    full = (bits[:, 0] << np.uint64(b)) | ps
    if bits.shape[1] > 1:
        full = full | (bits[:, 1] << np.uint64(32 + b))
    lo = np.full(cap, KEY_FILL, np.uint32)
    lo[:n] = (full & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    if not key_hi:
        return lo, None
    hi = np.full(cap, KEY_FILL, np.uint32)
    hi[:n] = (full >> np.uint64(32)).astype(np.uint32)
    return lo, hi
