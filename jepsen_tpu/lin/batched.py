"""Batched per-key linearizability: vmap the frontier search over keys.

The device counterpart of :mod:`jepsen_tpu.independent`'s checker
(reference independent.clj:246-296 checks each key's subhistory one at a
time on the JVM): every key's packed history is padded to a common
(return-events x window) shape with identity rows, stacked on a leading
key axis, and ONE vmapped search decides all keys in a single device
program — the independent-keys data parallelism of the reference turned
into a tensor batch axis.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from jepsen_tpu import util
from jepsen_tpu.lin import bfs, prepare
from jepsen_tpu.lin.prepare import PackedHistory
from jepsen_tpu.models.kernels import F_NOOP
from jepsen_tpu.obs import trace as obs_trace

BATCH_CAP_SCHEDULE = (64, 1024)


@dataclass
class Decline:
    """Why a key group could NOT batch — the shape axis that failed.

    The batch helpers used to return a bare ``None`` on any unsupported
    shape, which made the service scheduler's fallthrough decision
    unexplainable ("the bin went to the slow path" with no why). A
    Decline names the failing axis so schedulers/stats can attribute
    it; it is FALSY so ``result or fallback`` call sites keep working.

    axis: "prepare" (history unpackable), "kernel" (model has no device
    kernel), "dense-plan" (outside the dense engine's bounds),
    "rows" / "bitmap-words" / "table-cells" (dense batch resource
    ceilings), "window" (past the sparse bitset), "frontier-overflow"
    (the vmapped sparse search overflowed its top capacity). Stream
    batches (:func:`try_stream_batch`) add "stream-group" (no
    shape-sharing peer in the flush) and "stream-dead" (the lane found
    a violation; the per-session solo path reproduces the witness).
    """

    axis: str
    detail: str = ""
    keys: list = field(default_factory=list)

    def __bool__(self) -> bool:
        return False

    def as_dict(self) -> dict:
        return {"axis": self.axis, "detail": self.detail,
                "keys": [repr(k) for k in self.keys[:8]]}

# Dense-batch resource ceilings: one vmapped dispatch carries K bitmaps
# of 2**w words plus [K, r_pad, w] tables; past these bounds a Decline
# names the failing axis so the caller can fall back (sparse batch /
# per-key checks) instead of an XLA allocation error escaping the
# checker.
MAX_BATCH_BITMAP_WORDS = 1 << 24      # 64 MiB of frontier bitmaps
MAX_BATCH_TABLE_CELLS = 1 << 27       # [K, r_pad, w] table budget
MAX_BATCH_ROWS = 1 << 14


def _result_rows(packed, ks, dead, r_done, analyzer) -> dict:
    """Per-key verdict dicts from a batched search's (dead, rows_done)."""
    results = {}
    for i, k in enumerate(ks):
        p = packed[k]
        if not dead[i]:
            results[k] = {"valid?": True, "analyzer": analyzer,
                          "configs": [], "final-paths": []}
        else:
            r = int(r_done[i]) - 1
            ret = p.ops[int(p.ret_op[r])] if 0 <= r < p.R else None
            results[k] = {
                "valid?": False, "analyzer": analyzer, "dead-row": r,
                "op": None if ret is None else
                {"process": ret.process, "f": ret.f, "value": ret.value,
                 "index": ret.op_index, "ok": ret.ok},
                "configs": [], "final-paths": []}
    return results


def _try_dense_batch(packed: dict) -> dict | Decline:
    """Batch all keys through the dense bitmap engine: one vmapped chunk
    over a leading key axis. Per-key history length (n_rows), state
    count (nil_id), and initial state ride the batch as vectors, so no
    identity-row padding is needed and crashed-op keys cost nothing.
    Returns {key: result}, or a falsy :class:`Decline` naming the shape
    axis when any key falls outside the dense bounds or the batch
    exceeds the resource ceilings (caller tries the sparse batch, then
    per-key host checks)."""
    import jax
    import jax.numpy as jnp

    from jepsen_tpu.lin import dense

    plans = {}
    for k, p in packed.items():
        pl = dense.plan(p)
        if pl is None:
            return Decline(
                "dense-plan",
                f"window {p.window} / state shape outside the dense "
                f"engine bounds", keys=[k])
        plans[k] = pl

    w = max(pl[0] for pl in plans.values())
    ns = max(pl[1] for pl in plans.values())
    r_max = max(p.R for p in packed.values())
    r_pad = 1 << max(4, (r_max - 1).bit_length())
    ks = sorted(packed, key=repr)
    K = len(ks)
    if r_pad > MAX_BATCH_ROWS:
        return Decline("rows", f"r_pad {r_pad} > {MAX_BATCH_ROWS}",
                       keys=ks)
    if K * (1 << w) > MAX_BATCH_BITMAP_WORDS:
        return Decline(
            "bitmap-words",
            f"{K} keys x 2^{w} words > {MAX_BATCH_BITMAP_WORDS}",
            keys=ks)
    if K * r_pad * w > MAX_BATCH_TABLE_CELLS:
        return Decline(
            "table-cells",
            f"{K} x {r_pad} x {w} cells > {MAX_BATCH_TABLE_CELLS}",
            keys=ks)

    F0 = np.zeros((K, 1 << w), np.uint32)
    n_rows = np.zeros(K, np.int32)
    nil_ids = np.zeros(K, np.int32)
    ret_slot = np.zeros((K, r_pad), np.int32)
    active = np.zeros((K, r_pad, w), bool)
    slot_f = np.zeros((K, r_pad, w), np.int32)
    slot_v = np.zeros((K, r_pad, w, packed[ks[0]].slot_v.shape[2]),
                      np.int32)
    for i, k in enumerate(ks):
        p = packed[k]
        _, _, nil_id, init_id = plans[k]
        F0[i, 0] = np.uint32(1) << init_id
        n_rows[i] = p.R
        nil_ids[i] = nil_id
        R, W = p.active.shape
        ret_slot[i, :R] = p.ret_slot
        active[i, :R, :W] = p.active
        slot_f[i, :R, :W] = p.slot_f
        slot_v[i, :R, :W] = p.slot_v

    step_fn = packed[ks[0]].kernel.step
    F, r_done, dead, trunc = jax.vmap(
        lambda f, n, nid, rs, ac, sf, sv: dense._dense_chunk(
            f, n, nid, rs, ac, sf, sv, w=w, ns=ns, step_fn=step_fn))(
        jnp.asarray(F0), jnp.asarray(n_rows), jnp.asarray(nil_ids),
        jnp.asarray(ret_slot), jnp.asarray(active), jnp.asarray(slot_f),
        jnp.asarray(slot_v))

    results = _result_rows(packed, ks, np.asarray(dead),
                           np.asarray(r_done), "tpu-dense-batch")
    # A key whose closure hit the pass ceiling with changes pending
    # (provably unreachable for the monotone dense closure) must
    # answer an honest unknown, never a verdict off an incomplete
    # frontier (the round-5 invariant; dense.check_packed's twin).
    for i, k in enumerate(ks):
        if bool(np.asarray(trunc)[i]):
            results[k] = {"valid?": "unknown",
                          "analyzer": "tpu-dense-batch",
                          "overflow": "budget",
                          "error": "dense closure pass ceiling hit "
                                   "with changes pending"}
    return results


def _pad_to(p: PackedHistory, r_pad: int, w_pad: int, nw: int):
    """Pad one packed history to (r_pad, w_pad + 1): columns beyond the
    key's own window are inactive; missing rows are identity rows on the
    shared pad slot w_pad (see bfs._pad_rows). Reduction tables pad inert
    (pad slot is impure and unchained)."""
    R, W = p.active.shape
    vw = p.slot_v.shape[2]
    ret_slot = np.concatenate(
        [p.ret_slot, np.full(r_pad - R, w_pad, np.int32)])
    active = np.zeros((r_pad, w_pad + 1), bool)
    active[:R, :W] = p.active
    active[R:, w_pad] = True
    slot_f = np.zeros((r_pad, w_pad + 1), np.int32)
    slot_f[:R, :W] = p.slot_f
    slot_f[R:, w_pad] = F_NOOP
    slot_v = np.zeros((r_pad, w_pad + 1, vw), np.int32)
    slot_v[:R, :W] = p.slot_v
    pure_k, pred_bit_k = bfs.reduction_bit_tables(p, nw)
    pure = np.zeros((r_pad, w_pad + 1), bool)
    pure[:R, :W] = pure_k
    pred_bit = np.zeros((r_pad, w_pad + 1, nw), np.uint32)
    pred_bit[:R, :W] = pred_bit_k
    return ret_slot, active, slot_f, slot_v, pure, pred_bit


def try_check_batch(model, subs: dict, declines: list | None = None) \
        -> dict | None:
    """Check keys' subhistories in vmapped device searches. Keys are
    GROUPED by (step function, state shape) — one stacked batch must be
    homogeneous, but history-sized kernels (set/queue widths differ per
    key) used to de-batch the whole key set on the first mismatch; now
    each homogeneous group batches independently. Returns {key: result}
    covering every key that batched (possibly a subset — the caller
    checks leftovers per key), or None when nothing could batch.

    ``declines``, when given a list, collects one :class:`Decline` per
    key/group that could NOT batch (the shape axis that failed), so a
    caller routing leftovers to a slow path can log WHY each bin fell
    through instead of a bare None.

    A ``subs`` value may be a raw history OR an already-packed
    :class:`PackedHistory` (the service daemon's admission tier packs
    its bin waves as one batched device program before calling here —
    doc/service.md § Device packing); packed values are used as-is."""
    if not subs:
        return {}
    packed: dict = {}
    # One batch-level pack span: per-key prepare spans exist, but a
    # 1000-key batch would attribute its packing wall as 1000 dust
    # motes — the rollup is what `cli.py trace report` can read.
    with obs_trace.span("pack-batch", keys=len(subs)) as sp:
        t0 = prepare.pack_stats()["prepare_s"]
        for k, sub in subs.items():
            try:
                p = sub if isinstance(sub, PackedHistory) \
                    else prepare.prepare(model, sub)
            except prepare.UnsupportedHistory as e:
                if declines is not None:
                    declines.append(Decline("prepare", str(e), keys=[k]))
                continue
            if p.kernel is None:
                if declines is not None:
                    declines.append(Decline(
                        "kernel", "model/history has no device kernel",
                        keys=[k]))
                continue
            packed[k] = p
        sp.note(packed=len(packed),
                pack_s=round(prepare.pack_stats()["prepare_s"] - t0, 4))

    groups: dict = {}
    for k, p in packed.items():
        sig = (p.kernel.step, tuple(p.init_state.shape))
        groups.setdefault(sig, {})[k] = p

    results: dict = {}
    for group in groups.values():
        with obs_trace.span("dispatch", site="batched-group",
                            keys=len(group)) as sp:
            r = _check_group(group)
            sp.note(outcome="ok", declined=isinstance(r, Decline))
        util.progress_tick()   # liveness: one tick per decided group
        if isinstance(r, Decline):
            if declines is not None:
                declines.append(r)
            continue
        results.update(r)
    return results or None


def _check_group(packed: dict) -> dict | Decline:
    """One homogeneous (shared step fn + state shape) key group through
    the dense batch, then the sparse batch. A falsy :class:`Decline`
    when the group can't run on device (window overflow, resource
    ceilings, or frontier overflow at max capacity)."""
    import jax
    import jax.numpy as jnp

    dense_res = _try_dense_batch(packed)
    if not isinstance(dense_res, Decline):
        return dense_res
    dense_decline = dense_res

    w_pad = max(p.window for p in packed.values())
    if w_pad + 1 > bfs.MAX_DEVICE_WINDOW:
        return Decline(
            "window",
            f"padded window {w_pad + 1} > device bitset "
            f"{bfs.MAX_DEVICE_WINDOW} (dense declined: "
            f"{dense_decline.axis})", keys=sorted(packed, key=repr))
    r_max = max((p.R for p in packed.values()), default=0)
    if r_max == 0:
        return {k: {"valid?": True, "analyzer": "tpu-bfs-batch"}
                for k in packed}
    r_pad = 1 << max(4, (r_max - 1).bit_length())

    ks = sorted(packed, key=repr)
    nw = (w_pad + 1 + 31) // 32
    rows = [_pad_to(packed[k], r_pad, w_pad, nw) for k in ks]
    ret_slot = jnp.asarray(np.stack([r[0] for r in rows]))
    active = jnp.asarray(np.stack([r[1] for r in rows]))
    slot_f = jnp.asarray(np.stack([r[2] for r in rows]))
    slot_v = jnp.asarray(np.stack([r[3] for r in rows]))
    pure = jnp.asarray(np.stack([r[4] for r in rows]))
    pred_bit = jnp.asarray(np.stack([r[5] for r in rows]))
    init_state = jnp.asarray(np.stack(
        [packed[k].init_state for k in ks]))

    step_fn = packed[ks[0]].kernel.step
    n_keys = len(ks)
    S = init_state.shape[1]
    for cap in BATCH_CAP_SCHEDULE:
        bits0 = jnp.zeros((n_keys, cap, nw), jnp.uint32)
        state0 = jnp.zeros((n_keys, cap, S), jnp.int32) \
            .at[:, 0, :].set(init_state)
        count0 = jnp.ones(n_keys, jnp.int32)

        def one(rs, ac, sf, sv, pu, pb, b0, s0, c0):
            return bfs._search_chunk(jnp.int32(r_pad), rs, ac, sf, sv,
                                     pu, pb, b0, s0, c0,
                                     cap=cap, step_fn=step_fn)

        _, _, count, rows, dead, overflow = jax.vmap(one)(
            ret_slot, active, slot_f, slot_v, pure, pred_bit,
            bits0, state0, count0)
        if not bool(jnp.any(overflow)):
            break
    if bool(jnp.any(overflow)):
        return Decline(
            "frontier-overflow",
            f"vmapped sparse search overflowed cap "
            f"{BATCH_CAP_SCHEDULE[-1]} (dense declined: "
            f"{dense_decline.axis})", keys=ks)

    return _result_rows(packed, ks, np.asarray(dead | overflow),
                        np.asarray(rows), "tpu-bfs-batch")


def try_stream_batch(jobs: list) -> list:
    """Run many sessions' pending stream increments as vmapped
    carried-frontier programs (the daemon's svc-stream bins).

    Each job is a :meth:`StreamChecker.increment_job` dict:
    ``{"packed", "row0", "rows", "frontier", "checker"}``. Jobs are
    grouped by the EXACT traced shape — (step fn, state shape, window,
    value words) — and each group of >= 2 lanes runs as ONE
    ``jax.vmap``'d :func:`bfs._search_chunk` over the lanes' sliced
    row tables, with per-lane row counts traced (``n_rows`` masks each
    lane's padding — rows past it are never processed) and per-lane
    carried frontiers zero-padded to a shared capacity.

    Exactness is the multiword engine's: every lane runs the same
    general formulation ``check_packed`` uses whenever packed keys are
    off, consuming the exact reduction tables
    (:func:`bfs.reduction_bit_tables`) sliced at the lane's frontier
    row — the same re-entry invariant as checkpoint resume, whichever
    engine produced the carried frontier.

    Returns a list parallel to ``jobs``: a result dict carrying
    ``"stream-frontier"`` for a lane that walked clean, or a falsy
    :class:`Decline` — the caller commits clean lanes via
    ``commit_increment`` and falls back per-session (``drive()``) on
    declines, including "stream-dead" lanes (the solo path re-runs
    from the SAME uncommitted frontier and reproduces the violation
    with its full witness machinery)."""
    out: list = [None] * len(jobs)
    groups: dict = {}
    for i, j in enumerate(jobs):
        p = j["packed"]
        if p.window > bfs.MAX_DEVICE_WINDOW:
            out[i] = Decline(
                "window", f"window {p.window} > device bitset "
                          f"{bfs.MAX_DEVICE_WINDOW}")
            continue
        sig = (p.kernel.step, tuple(p.init_state.shape),
               int(p.window), int(p.slot_v.shape[2]))
        groups.setdefault(sig, []).append(i)
    for ixs in groups.values():
        if len(ixs) < 2:
            for i in ixs:
                out[i] = Decline(
                    "stream-group",
                    "no shape-sharing peer in this flush")
            continue
        with obs_trace.span("dispatch", site="stream-batch-group",
                            lanes=len(ixs)) as sp:
            res = _stream_group([jobs[i] for i in ixs])
            sp.note(declined=isinstance(res, Decline))
        util.progress_tick()
        if isinstance(res, Decline):
            for i in ixs:
                out[i] = res
        else:
            for i, r in zip(ixs, res):
                out[i] = r
    return out


def _stream_group(jobs: list) -> list | Decline:
    """One exact-shape group of stream increments through a vmapped
    multiword search. A group-level Decline de-batches every lane;
    per-lane entries can still individually decline (overflow, dead)."""
    import jax
    import jax.numpy as jnp

    K = len(jobs)
    p0 = jobs[0]["packed"]
    window = int(p0.window)
    nw = (window + 31) // 32
    S = int(p0.init_state.shape[0])
    vw = int(p0.slot_v.shape[2])
    step_fn = p0.kernel.step
    rows_max = max(j["rows"] for j in jobs)
    r_pad = 1 << max(4, (rows_max - 1).bit_length())
    if r_pad > MAX_BATCH_ROWS:
        return Decline("rows", f"r_pad {r_pad} > {MAX_BATCH_ROWS}")
    if K * r_pad * window > MAX_BATCH_TABLE_CELLS:
        return Decline(
            "table-cells",
            f"{K} x {r_pad} x {window} cells > {MAX_BATCH_TABLE_CELLS}")
    counts = [int(j["frontier"][2]) if j["frontier"] is not None else 1
              for j in jobs]
    caps = [c for c in BATCH_CAP_SCHEDULE if c >= max(counts)]
    if not caps:
        return Decline(
            "frontier-overflow",
            f"carried frontier {max(counts)} > cap "
            f"{BATCH_CAP_SCHEDULE[-1]}")

    n_rows = np.zeros(K, np.int32)
    ret_slot = np.zeros((K, r_pad), np.int32)
    active = np.zeros((K, r_pad, window), bool)
    slot_f = np.zeros((K, r_pad, window), np.int32)
    slot_v = np.zeros((K, r_pad, window, vw), np.int32)
    pure = np.zeros((K, r_pad, window), bool)
    pred_bit = np.zeros((K, r_pad, window, nw), np.uint32)
    for i, j in enumerate(jobs):
        p, row0, rows = j["packed"], j["row0"], j["rows"]
        sl = slice(row0, row0 + rows)
        n_rows[i] = rows
        ret_slot[i, :rows] = np.asarray(p.ret_slot)[sl]
        active[i, :rows] = np.asarray(p.active)[sl]
        slot_f[i, :rows] = np.asarray(p.slot_f)[sl]
        slot_v[i, :rows] = np.asarray(p.slot_v)[sl]
        pure_k, pred_bit_k = bfs.reduction_bit_tables(p, nw)
        pure[i, :rows] = pure_k[sl]
        pred_bit[i, :rows] = pred_bit_k[sl]

    for cap in caps:
        bits0 = np.zeros((K, cap, nw), np.uint32)
        state0 = np.zeros((K, cap, S), np.int32)
        for i, j in enumerate(jobs):
            fr = j["frontier"]
            if fr is None:
                state0[i, 0] = np.asarray(j["packed"].init_state,
                                          np.int32)
            else:
                fb = np.asarray(fr[0], np.uint32)
                fs = np.asarray(fr[1], np.int32)
                fc = counts[i]
                # The carried frontier may be NARROWER than this
                # increment's window (the window grows with observed
                # concurrency; slot indices are stable): zero-pad the
                # high words, mirroring check_packed's re-entry.
                w_common = min(fb.shape[1], nw)
                bits0[i, :fc, :w_common] = fb[:fc, :w_common]
                state0[i, :fc] = fs[:fc]

        def one(n, rs, ac, sf, sv, pu, pb, b0, s0, c0):
            return bfs._search_chunk(n, rs, ac, sf, sv, pu, pb,
                                     b0, s0, c0, cap=cap,
                                     step_fn=step_fn)

        bits_o, state_o, count_o, _rows_done, dead, ovf = jax.vmap(one)(
            jnp.asarray(n_rows), jnp.asarray(ret_slot),
            jnp.asarray(active), jnp.asarray(slot_f),
            jnp.asarray(slot_v), jnp.asarray(pure),
            jnp.asarray(pred_bit), jnp.asarray(bits0),
            jnp.asarray(state0), jnp.asarray(counts, jnp.int32))
        if not bool(jnp.any(ovf)):
            break

    bits_h, state_h = np.asarray(bits_o), np.asarray(state_o)
    count_h = np.asarray(count_o)
    dead_h, ovf_h = np.asarray(dead), np.asarray(ovf)
    res: list = []
    for i, j in enumerate(jobs):
        if ovf_h[i]:
            res.append(Decline(
                "frontier-overflow",
                f"stream lane overflowed cap {cap}"))
        elif dead_h[i]:
            res.append(Decline(
                "stream-dead",
                "lane found a violation; the solo re-check from the "
                "same frontier reproduces the witness"))
        else:
            c = max(1, int(count_h[i]))
            res.append({
                "valid?": True, "analyzer": "tpu-bfs-stream-batch",
                "stream-frontier": {
                    "bits": bits_h[i, :c].copy(),
                    "state": state_h[i, :c].copy(),
                    "count": c, "row": j["row0"] + j["rows"]}})
    return res
