"""Batched per-key linearizability: vmap the frontier search over keys.

The device counterpart of :mod:`jepsen_tpu.independent`'s checker
(reference independent.clj:246-296 checks each key's subhistory one at a
time on the JVM): every key's packed history is padded to a common
(return-events x window) shape with identity rows, stacked on a leading
key axis, and ONE vmapped search decides all keys in a single device
program — the independent-keys data parallelism of the reference turned
into a tensor batch axis.
"""

from __future__ import annotations

import numpy as np

from jepsen_tpu.lin import bfs, prepare
from jepsen_tpu.lin.prepare import PackedHistory
from jepsen_tpu.models.kernels import F_NOOP

BATCH_CAP_SCHEDULE = (64, 1024)


def _pad_to(p: PackedHistory, r_pad: int, w_pad: int):
    """Pad one packed history to (r_pad, w_pad + 1): columns beyond the
    key's own window are inactive; missing rows are identity rows on the
    shared pad slot w_pad (see bfs._pad_rows)."""
    R, W = p.active.shape
    vw = p.slot_v.shape[2]
    ret_slot = np.concatenate(
        [p.ret_slot, np.full(r_pad - R, w_pad, np.int32)])
    active = np.zeros((r_pad, w_pad + 1), bool)
    active[:R, :W] = p.active
    active[R:, w_pad] = True
    slot_f = np.zeros((r_pad, w_pad + 1), np.int32)
    slot_f[:R, :W] = p.slot_f
    slot_f[R:, w_pad] = F_NOOP
    slot_v = np.zeros((r_pad, w_pad + 1, vw), np.int32)
    slot_v[:R, :W] = p.slot_v
    return ret_slot, active, slot_f, slot_v


def try_check_batch(model, subs: dict) -> dict | None:
    """Check every key's subhistory in one vmapped device search. Returns
    {key: result} or None when the batch can't run on device (no kernel,
    window overflow, or frontier overflow at max capacity) — caller falls
    back to per-key host checking."""
    import jax
    import jax.numpy as jnp

    if not subs:
        return {}
    packed: dict = {}
    for k, sub in subs.items():
        try:
            p = prepare.prepare(model, sub)
        except prepare.UnsupportedHistory:
            return None
        if p.kernel is None:
            return None
        packed[k] = p

    # Every key must share one step function (and thus state/value widths)
    # for the stacked batch to be well-formed; history-sized kernels
    # (set/queue) can differ per key, in which case fall back to per-key.
    steps = {p.kernel.step for p in packed.values()}
    if len(steps) > 1:
        return None
    if len({tuple(p.init_state.shape) for p in packed.values()}) > 1:
        return None

    w_pad = max(p.window for p in packed.values())
    if w_pad + 1 > bfs.MAX_DEVICE_WINDOW:
        return None
    r_max = max((p.R for p in packed.values()), default=0)
    if r_max == 0:
        return {k: {"valid?": True, "analyzer": "tpu-bfs-batch"}
                for k in packed}
    r_pad = 1 << max(4, (r_max - 1).bit_length())

    ks = sorted(packed, key=repr)
    rows = [_pad_to(packed[k], r_pad, w_pad) for k in ks]
    ret_slot = jnp.asarray(np.stack([r[0] for r in rows]))
    active = jnp.asarray(np.stack([r[1] for r in rows]))
    slot_f = jnp.asarray(np.stack([r[2] for r in rows]))
    slot_v = jnp.asarray(np.stack([r[3] for r in rows]))
    init_state = jnp.asarray(np.stack(
        [packed[k].init_state for k in ks]))

    step_fn = packed[ks[0]].kernel.step
    n_keys = len(ks)
    S = init_state.shape[1]
    nw = (w_pad + 1 + 31) // 32
    for cap in BATCH_CAP_SCHEDULE:
        bits0 = jnp.zeros((n_keys, cap, nw), jnp.uint32)
        state0 = jnp.zeros((n_keys, cap, S), jnp.int32) \
            .at[:, 0, :].set(init_state)
        count0 = jnp.ones(n_keys, jnp.int32)

        def one(rs, ac, sf, sv, b0, s0, c0):
            return bfs._search_chunk(jnp.int32(r_pad), rs, ac, sf, sv,
                                     b0, s0, c0, cap=cap, step_fn=step_fn)

        _, _, count, rows, dead, overflow = jax.vmap(one)(
            ret_slot, active, slot_f, slot_v, bits0, state0, count0)
        if not bool(jnp.any(overflow)):
            break
    if bool(jnp.any(overflow)):
        return None

    ok = np.asarray(~(dead | overflow))
    dead_row = np.asarray(rows) - 1
    results = {}
    for i, k in enumerate(ks):
        p = packed[k]
        if bool(ok[i]):
            results[k] = {"valid?": True, "analyzer": "tpu-bfs-batch",
                          "configs": [], "final-paths": []}
        else:
            r = int(dead_row[i])
            ret = p.ops[int(p.ret_op[r])] if 0 <= r < p.R else None
            results[k] = {
                "valid?": False, "analyzer": "tpu-bfs-batch",
                "op": None if ret is None else
                {"process": ret.process, "f": ret.f, "value": ret.value,
                 "index": ret.op_index, "ok": ret.ok},
                "configs": [], "final-paths": []}
    return results
