"""Dense config-space bitmap engine for the linearizability search.

The sparse engine (:mod:`jepsen_tpu.lin.bfs`) keeps the frontier as a
compacted list of (bitset, state) configs and pays a sort-dedup per step.
This module exploits a fact about the search space itself: with the
slot-compressed window W (:mod:`jepsen_tpu.lin.prepare`) and a single-word
model state of NS <= 32 reachable values, the ENTIRE config space has just
``2**W * NS`` points — so instead of deduplicating a list we represent the
frontier as its characteristic function, a ``uint32[2**W]`` bitmap::

    bit s of word B  ==  config (linearized-bitset B, state s) reachable

On this representation the whole just-in-time linearization closure
(reference semantics: knossos.linear / knossos.wgl, raced at
checker.clj:90-93) becomes branchless word-parallel bit algebra:

- *Linearize pending op in slot j*: rows with bit j clear contribute to
  rows with bit j set — a masked static shift of the bitmap by ``2**j``
  words, with the state transition applied as per-state-bit shifts through
  the op's transition table. No sort, no dedup (the bitmap IS the set), no
  capacity, no overflow, and therefore no cap escalation or host syncs.
- *Return of slot s*: keep rows with bit s, clear it — one masked shift.
- *Crashed (:info) ops* need no special machinery at all. They simply keep
  their slot bit forever; the 2^crashes subset blowup that inflates a list
  frontier is just... the bitmap, whose size is fixed up front. The sparse
  path's dominance-pruning join (the round-1 TPU kernel-faulter) has no
  dense analogue because nothing ever needs pruning.

The search is a `lax.while_loop` over return events inside chunked
dispatches; the host's only blocking fetch per chunk is the one-bit dead
flag (~13 round-trips for a 100k-op history). Entry-frontier snapshots
per chunk (a few KB each) can be retained via
``check_packed(snapshots=[...])`` so a counterexample pass can replay
just the failing tail on the CPU oracle (see :func:`decode_bitmap`).

Cost model: one closure pass is ``W * NS`` fused elementwise ops over
``2**W`` words. For the flagship 100k-op crashed-op history (W=15, NS~8)
that is ~4M word-ops per pass — microseconds on a TPU's vector units — and
the whole check is a handful of device programs with zero host round-trips,
vs. the reference's JVM graph search with a 32 GB heap
(jepsen/project.clj:22-25).
"""

from __future__ import annotations

from functools import partial
from time import monotonic as _monotonic

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu import util
from jepsen_tpu.lin.prepare import PackedHistory
from jepsen_tpu.obs import trace as obs_trace

# Largest window the dense representation will take: 2**20 words = 4 MiB
# bitmaps (x2 transient for the shift) — far below HBM, compile-bounded.
MAX_DENSE_WINDOW = 20
# States must fit one u32 word of bitmap per bitset row.
MAX_DENSE_STATES = 32
# Per-chunk fixed costs (table upload over the host link, dispatch)
# dominate at small chunks: measured on a v5e chip, 100k ops run at
# 42k/70k/102k/118k ops/s for chunks of 4k/8k/16k/32k. 16k balances
# throughput against the witness tail-replay window (one chunk).
CHUNK = 16384

_W_BUCKETS = (4, 6, 8, 10, 12, 14, 16, 18, 20)
_NS_BUCKETS = (4, 8, 16, 32)

def plan(p: PackedHistory):
    """Dense-searchability test. Returns ``(w, ns, nil_id, init_id)`` with
    bucketed w/ns, or None when this history needs the sparse engine."""
    from jepsen_tpu.models.kernels import (PACKED_STATE_KERNELS,
                                           packed_state_bound)

    if p.kernel is None or p.kernel.name not in PACKED_STATE_KERNELS:
        return None
    if p.state_width != 1 or p.window > MAX_DENSE_WINDOW:
        return None
    from jepsen_tpu.models.kernels import NIL

    nid = packed_state_bound(p.kernel, len(p.unintern))
    if nid + 1 > MAX_DENSE_STATES:
        return None
    w = next(b for b in _W_BUCKETS if b >= p.window)
    ns = next(b for b in _NS_BUCKETS if b >= nid + 1)
    init = int(p.init_state[0])
    init_id = nid if init == int(NIL) else init
    return w, ns, nid, init_id


def transition_tables(slot_f, slot_v, active, nil_id, *, ns, step_fn):
    """Per-(row, slot, state) transition tables from the model step
    kernel: ok[CH,w,ns] legality, to[CH,w,ns] successor state id (u32).
    One triple-vmap evaluates every transition a chunk can ever take in
    one shot. Inactive slots never linearize, and padded state ids past
    nil_id are masked inert. Shared by the XLA and pallas backends so
    the table semantics cannot diverge between them."""
    from jepsen_tpu.models.kernels import NIL

    sid = jnp.arange(ns, dtype=jnp.int32)
    states = jnp.where(sid == nil_id, NIL, sid)[:, None]     # [ns, 1]
    per_state = jax.vmap(step_fn, in_axes=(0, None, None))
    per_slot = jax.vmap(per_state, in_axes=(None, 0, 0))
    per_row = jax.vmap(per_slot, in_axes=(None, 0, 0))
    ok, new = per_row(states, slot_f, slot_v)
    to = jnp.where(new[..., 0] == NIL, nil_id, new[..., 0])
    to = jnp.clip(to, 0, ns - 1).astype(jnp.uint32)
    ok = ok & active[:, :, None] & (sid[None, None, :] <= nil_id)
    return ok, to


@partial(jax.jit, static_argnames=("w", "ns", "step_fn"))
def _dense_chunk(F, n_rows, nil_id, ret_slot, active, slot_f, slot_v,
                 *, w, ns, step_fn):
    """Advance the frontier bitmap through up to n_rows return events.

    F: u32[2**w]; ret_slot: i32[CH]; active: bool[CH,w];
    slot_f: i32[CH,w]; slot_v: i32[CH,w,VW]. Rows past n_rows ignored.
    Returns (F, rows_done, dead, trunc) — dead means the frontier
    emptied while filtering row rows_done-1, i.e. the history is not
    linearizable; trunc means a closure hit the w+2 pass ceiling with
    changes still pending (provably impossible for this monotone
    closure — the honest-overflow channel the round-5 invariant
    demands, so a hypothetical non-monotone edit can never ship a
    silently truncated frontier as a verdict).
    """
    n_words = 1 << w
    iota = lax.iota(jnp.uint32, n_words)

    ok, to = transition_tables(slot_f, slot_v, active, nil_id,
                               ns=ns, step_fn=step_fn)

    def row_body(carry):
        r, F, dead, trunc = carry
        ok_r = ok[r]                                          # [w, ns]
        to_r = to[r]                                          # [w, ns]

        def closure_pass(F):
            for j in range(w):
                # View the B axis as [.., bit j, 2**j]: index 0 along the
                # middle axis is exactly the rows with slot j unlinearized,
                # so "linearize j" is a half-size transform + a static
                # concatenate — no roll, no mask, half the words touched.
                # (A slot-batched gather/reduce formulation of this pass
                # kernel-faults the TPU runtime in this image; the
                # reshape/concat form is the one XLA handles robustly.)
                F3 = F.reshape(-1, 2, 1 << j)
                src = F3[:, 0, :]
                contrib = jnp.zeros_like(src)
                for s in range(ns):
                    bit = (src >> s) & jnp.uint32(1)
                    contrib = contrib | jnp.where(
                        ok_r[j, s], bit << to_r[j, s], jnp.uint32(0))
                hi = F3[:, 1, :] | contrib
                F = jnp.concatenate([F3[:, :1, :], hi[:, None, :]],
                                    axis=1).reshape(F.shape)
            return F

        def closure_body(c):
            F, _, it = c
            return closure_pass(F), F, it + 1

        # Do-while to fixpoint: the candidate pool includes the current
        # frontier (OR-accumulation), so the set is monotone and the loop
        # terminates in at most W+1 passes. The w+2 pass ceiling can
        # therefore never bind — it exists for the post-round-5
        # every-loop-carries-a-ceiling invariant (analysis/jaxpr_lint's
        # unbounded-while rule); exiting at the ceiling with changes
        # still pending flags ``trunc``, an HONEST overflow a caller
        # must turn into an unknown verdict, never a silently
        # incomplete frontier.
        F, F_prev, _ = lax.while_loop(
            lambda c: jnp.any(c[0] != c[1]) & (c[2] < w + 2),
            closure_body, closure_body((F, F, jnp.int32(0))))
        trunc = trunc | jnp.any(F != F_prev)

        # Return filter: the returner's linearization point must precede
        # its return; then recycle its slot bit. Rows without bit s wrap to
        # rows with it and contribute zero, so one masked roll does both.
        s = ret_slot[r]
        keep = jnp.where((iota >> s.astype(jnp.uint32)) & 1 == 1,
                         F, jnp.uint32(0))
        F = jnp.roll(keep, -(jnp.int32(1) << s))
        return r + 1, F, ~jnp.any(F != 0), trunc

    def row_cond(carry):
        r, _, dead, trunc = carry
        return (r < n_rows) & ~dead & ~trunc

    r, F, dead, trunc = lax.while_loop(
        row_cond, row_body,
        (jnp.int32(0), F, jnp.bool_(False), jnp.bool_(False)))
    return F, r, dead, trunc


def check_packed(p: PackedHistory, chunk: int = CHUNK, cancel=None,
                 snapshots: list | None = None, explain: bool = False,
                 backend: str = "auto") -> dict:
    """Decide linearizability of a packed history with the dense engine.

    The frontier carry chains device-side between chunk dispatches; the
    host's only blocking fetch per chunk is the one-bit dead flag, giving
    early exit on invalid histories and prompt race cancellation.
    ``snapshots``, if a list, receives ``(base_row, entry_bitmap)`` pairs
    (device arrays) for witness reconstruction; ``explain=True`` retains
    them internally and, on an invalid verdict, replays the failing tail
    on the CPU oracle to emit knossos-style configs + final-paths
    (:mod:`jepsen_tpu.lin.witness`). ``cancel`` (threading.Event) stops
    between dispatches.

    ``backend``: "pallas" runs the chunk loop as a TPU kernel with the
    bitmap resident in VMEM (:mod:`jepsen_tpu.lin.dense_pallas`;
    interpreted off-TPU), "xla" the lax.while_loop formulation, "auto"
    pallas on TPU-class hardware when the window fits, xla otherwise.
    """
    pl = plan(p)
    if pl is None:
        return {"valid?": "unknown", "analyzer": "tpu-dense",
                "error": "history outside dense engine bounds"}
    w, ns, nil_id, init_id = pl
    # Explicit callers get every chunk-entry snapshot; internal explain
    # only ever replays from the LAST one (the dead row is always inside
    # the current chunk), so retain just that and keep HBM flat.
    keep_all = snapshots is not None
    if explain and snapshots is None:
        snapshots = []

    if backend not in ("auto", "xla", "pallas"):
        raise ValueError(f"unknown dense backend {backend!r}")
    use_pallas = False
    interpret = False
    dp = None
    if backend in ("auto", "pallas"):
        from jepsen_tpu.lin import dense_pallas as dp

        fits = dp.supported_w(w) is not None
        on_tpu = jax.devices()[0].platform == "tpu"
        interpret = not on_tpu
        if backend == "pallas":
            if not fits:
                raise ValueError(
                    f"window {w} exceeds the pallas kernel bound "
                    f"{dp.MAX_PALLAS_W}; use backend='xla'")
            use_pallas = True
        else:
            use_pallas = fits and on_tpu
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-dense", "configs": []}

    from jepsen_tpu.lin.bfs import _chunk_slice

    step_fn = p.kernel.step
    ret_slot_h = np.asarray(p.ret_slot)
    active_h = np.asarray(p.active)
    slot_f_h = np.asarray(p.slot_f)
    slot_v_h = np.asarray(p.slot_v)
    W = p.window

    # Slot indices grow monotonically (freed slots are reused low-first,
    # crashed slots accumulate upward), so early chunks run on an
    # exponentially smaller bitmap: per-chunk width = that chunk's highest
    # active slot, bucketed. Growing between chunks is a zero-pad of F.
    row_hi = np.where(active_h.any(axis=1),
                      W - np.argmax(active_h[:, ::-1], axis=1), 1)

    def bucket_w(need):
        return next(b for b in _W_BUCKETS if b >= need)

    def pad_w(a, wc):
        if a.shape[1] > wc:      # slots above wc are inactive in this chunk
            return a[:, :wc]
        if a.shape[1] == wc:
            return a
        pad = [(0, 0)] * a.ndim
        pad[1] = (0, wc - a.shape[1])
        return np.pad(a, pad)

    def eng_w(need: int) -> int:
        wc = bucket_w(need)
        return dp.supported_w(wc) if use_pallas else wc

    w_cur = eng_w(int(row_hi[:min(chunk, p.R)].max()))
    F = jnp.zeros(1 << w_cur, jnp.uint32).at[0].set(jnp.uint32(1) << init_id)

    # One blocking fetch (the dead flag) per chunk: chunks are strictly
    # sequential so there is no pipelining to lose, it exits early on a
    # dead frontier, and it keeps a competition-race cancel prompt. For
    # the flagship 100k history that is ~13 round-trips total (the round-1
    # sparse engine paid ~196).
    base = 0
    while base < p.R:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-dense",
                    "error": "cancelled"}
        n = min(chunk, p.R - base)
        w_c = eng_w(int(row_hi[base:base + n].max()))
        if w_c > w_cur:
            F = jnp.pad(F, (0, (1 << w_c) - (1 << w_cur)))
            w_cur = w_c
        if snapshots is not None:
            if keep_all:
                snapshots.append((base, F))
            else:
                snapshots[:] = [(base, F)]
        _d0 = _monotonic()
        if use_pallas:
            # Bucket the kernel grid to the chunk's actual row count so a
            # short final chunk doesn't pay for thousands of no-op steps
            # (and don't upload the unused table tail at all).
            n_pad = min(chunk, max(512, 1 << (n - 1).bit_length()))
            sl = lambda a: _chunk_slice(a, base, chunk)[:n_pad]  # noqa: E731
            masks = dp.transition_masks(
                jnp.asarray(pad_w(sl(slot_f_h), w_cur)),
                jnp.asarray(pad_w(sl(slot_v_h), w_cur)),
                jnp.asarray(pad_w(sl(active_h), w_cur)),
                jnp.int32(nil_id), ns=ns, step_fn=step_fn)
            F, r_done, dead = dp.pallas_chunk(
                F, jnp.int32(n), masks, jnp.asarray(sl(ret_slot_h)),
                w=w_cur, ns=ns, chunk=n_pad, interpret=interpret)
            # The pallas closure runs to true fixpoint (its waived
            # unbounded loop) — no truncation channel to consult.
            trunc = jnp.bool_(False)
        else:
            F, r_done, dead, trunc = _dense_chunk(
                F, jnp.int32(n), jnp.int32(nil_id),
                jnp.asarray(_chunk_slice(ret_slot_h, base, chunk)),
                jnp.asarray(pad_w(_chunk_slice(active_h, base, chunk),
                                  w_cur)),
                jnp.asarray(pad_w(_chunk_slice(slot_f_h, base, chunk),
                                  w_cur)),
                jnp.asarray(pad_w(_chunk_slice(slot_v_h, base, chunk),
                                  w_cur)),
                w=w_cur, ns=ns, step_fn=step_fn)
        util.progress_tick()   # liveness: one tick per decided chunk
        # ONE blocking transfer carries both flags (the per-chunk
        # fetch budget this engine's cost model is built on).
        flags = np.asarray(jnp.stack([dead, trunc]))
        dead_b, trunc_b = bool(flags[0]), bool(flags[1])
        obs_trace.complete("dispatch", _d0, _monotonic() - _d0,
                           site="dense-pallas" if use_pallas
                           else "dense-chunk", rows=int(n),
                           outcome="ok")
        if trunc_b:
            # The closure ceiling fired with changes pending: the
            # frontier is incomplete, so neither a dead nor a live
            # result is trustworthy — honest unknown (round-5
            # invariant; provably unreachable for the monotone
            # closure).
            return {"valid?": "unknown", "analyzer": "tpu-dense",
                    "backend": "pallas" if use_pallas else "xla",
                    "overflow": "budget",
                    "error": f"dense closure pass ceiling hit with "
                             f"changes pending near row {base} "
                             f"(non-monotone closure edit?)"}
        if dead_b:
            r = base + int(r_done) - 1
            ret = p.ops[int(p.ret_op[r])]
            out = {"valid?": False, "analyzer": "tpu-dense",
                   "backend": "pallas" if use_pallas else "xla",
                   "dead-row": r,
                   "op": {"process": ret.process, "f": ret.f,
                          "value": ret.value, "index": ret.op_index,
                          "ok": ret.ok},
                   "configs": [], "final-paths": []}
            if explain and snapshots and \
                    not (cancel is not None and cancel.is_set()):
                from jepsen_tpu.lin import witness

                out.update(witness.tail_replay(p, nil_id, snapshots, r,
                                               cancel=cancel))
            return out
        base += n

    return {"valid?": True, "analyzer": "tpu-dense",
            "backend": "pallas" if use_pallas else "xla",
            "final-frontier-popcount": int(
                jnp.sum(lax.population_count(F))),
            "configs": []}


def decode_bitmap(F, nil_id: int) -> list[tuple[int, tuple]]:
    """Host-side decode of a frontier bitmap into (bitset, state-word)
    configs in the CPU oracle's representation (state NIL-restored)."""
    from jepsen_tpu.models.kernels import NIL

    F = np.asarray(F)
    out = []
    for B in np.nonzero(F)[0]:
        word = int(F[B])
        s = 0
        while word:
            if word & 1:
                sv = int(NIL) if s == nil_id else s
                out.append((int(B), (sv,)))
            word >>= 1
            s += 1
    return out
