"""Multi-chip dense bitmap search: the config space sharded as a hypercube.

The dense engine's frontier bitmap (:mod:`jepsen_tpu.lin.dense`) sharded
over a ``jax.sharding.Mesh``: with ``D = 2**k`` devices, the TOP k bits of
the config-bitset index ARE the device axis —

    config (B, s)  lives on  device d = B >> (w-k),  local word B mod 2**(w-k)

so the search's communication pattern is exactly the hypercube the slots
induce:

- Linearizing a *low* slot (j < w-k) stays entirely device-local: the same
  reshape/concat bit algebra as the single-chip engine, zero ICI traffic.
- Linearizing a *high* slot (j >= w-k) flips a device-axis bit: devices
  with that bit clear transform their whole local block and
  ``lax.ppermute`` it to their hypercube partner, which ORs it in. One
  block per link per pass — the minimal possible exchange, riding ICI
  neighbor links (contrast the reference, where the entire search shares
  one JVM heap, jepsen/project.clj:22-25).
- The return-event filter's slot is data-dependent, so it dispatches
  through ``lax.switch`` over per-slot branches: static local shifts for
  low slots, a partner-permute for high ones.
- Fixpoint/death decisions are ``psum``-replicated so every device takes
  identical `lax.while_loop` branches.

Slot assignment (prepare.py) allocates lowest-free-first, so the high,
device-axis slots are the *rarely-touched* tail of the window — crashed
ops and concurrency spikes — and steady-state traffic is almost all
local. Chunks chain their carries on device exactly like the single-chip
engine: no host syncs inside a check.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jepsen_tpu import util
from jepsen_tpu.lin import dense
from jepsen_tpu.lin.prepare import PackedHistory

CHUNK = dense.CHUNK


def plan(p: PackedHistory, n_devices: int):
    """Shardability test: the dense plan, with a device-axis width k such
    that every device keeps at least 4 local words. Returns
    (w, ns, nil_id, init_id, k) or None."""
    base = dense.plan(p)
    if base is None or n_devices < 2:
        return None
    if n_devices & (n_devices - 1):
        return None  # hypercube sharding wants a power of two
    w, ns, nil_id, init_id = base
    k = n_devices.bit_length() - 1
    if w - k < 2:
        w = min(k + 2, dense.MAX_DENSE_WINDOW)  # widen: padded slots are inert
        if w - k < 2 or w < p.window:
            return None
    return w, ns, nil_id, init_id, k


@partial(jax.jit, static_argnames=("w", "ns", "k", "step_fn", "mesh",
                                   "axis"))
def _chunk_sharded(F_local, n_rows, nil_id, ret_slot, active, slot_f,
                   slot_v, *, w, ns, k, step_fn, mesh, axis):
    """One chunk of return events over the hypercube-sharded bitmap.

    F_local: u32[D, 2**(w-k)] sharded on axis 0; tables replicated.
    Returns (F_local sharded, rows_done[D], dead[D]) — the scalar outputs
    are replicated across the device axis.
    """
    from jepsen_tpu.models.kernels import NIL

    lw = w - k
    n_local = 1 << lw
    D = 1 << k

    def body(F_local, n_rows, nil_id, ret_slot, active, slot_f, slot_v):
        F = F_local.reshape(n_local)
        d = lax.axis_index(axis)
        iota_l = lax.iota(jnp.uint32, n_local)

        # Transition tables, identical on every device (tables are
        # replicated; the triple-vmap is tiny next to the search).
        sid = jnp.arange(ns, dtype=jnp.int32)
        states = jnp.where(sid == nil_id, NIL, sid)[:, None]
        per_state = jax.vmap(step_fn, in_axes=(0, None, None))
        per_slot = jax.vmap(per_state, in_axes=(None, 0, 0))
        per_row = jax.vmap(per_slot, in_axes=(None, 0, 0))
        ok, new = per_row(states, slot_f, slot_v)
        to = jnp.where(new[..., 0] == NIL, nil_id, new[..., 0])
        to = jnp.clip(to, 0, ns - 1).astype(jnp.uint32)
        ok = ok & active[:, :, None] & (sid[None, None, :] <= nil_id)

        def transform(src, ok_j, to_j):
            contrib = jnp.zeros_like(src)
            for s in range(ns):
                bit = (src >> s) & jnp.uint32(1)
                contrib = contrib | jnp.where(
                    ok_j[s], bit << to_j[s], jnp.uint32(0))
            return contrib

        def row_body(carry):
            r, F, dead = carry
            ok_r = ok[r]
            to_r = to[r]

            def closure_pass(F):
                for j in range(lw):          # local slots: reshape algebra
                    F3 = F.reshape(-1, 2, 1 << j)
                    contrib = transform(F3[:, 0, :], ok_r[j], to_r[j])
                    hi = F3[:, 1, :] | contrib
                    F = jnp.concatenate([F3[:, :1, :], hi[:, None, :]],
                                        axis=1).reshape(F.shape)
                for jb in range(k):          # device slots: hypercube hop
                    j = lw + jb
                    src_dev = ((d >> jb) & 1) == 0
                    src = jnp.where(src_dev, F, jnp.uint32(0))
                    contrib = transform(src, ok_r[j], to_r[j])
                    perm = [(dd, dd | (1 << jb)) for dd in range(D)
                            if not (dd >> jb) & 1]
                    recv = lax.ppermute(contrib, axis, perm)
                    F = F | recv
                return F

            def closure_body(c):
                F, _ = c
                F2 = closure_pass(F)
                changed = lax.psum(
                    jnp.any(F2 != F).astype(jnp.int32), axis) > 0
                return F2, changed

            # lint: unbounded-ok — monotone OR-accumulated bitmap
            # closure (dense.py's termination argument: <= w+1 passes
            # globally, psum'd convergence).
            F, _ = lax.while_loop(lambda c: c[1], closure_body,
                                  closure_body((F, jnp.bool_(True))))

            # Return filter: keep configs that linearized the returner,
            # recycle its bit. Branch per slot: the shift is static for
            # local slots and a partner-permute for device-axis slots.
            def local_branch(s):
                def br(F):
                    F3 = F.reshape(-1, 2, 1 << s)
                    return jnp.concatenate(
                        [F3[:, 1:, :], jnp.zeros_like(F3[:, :1, :])],
                        axis=1).reshape(F.shape)
                return br

            def device_branch(jb):
                def br(F):
                    keep = jnp.where(((d >> jb) & 1) == 1, F, jnp.uint32(0))
                    perm = [(dd, dd ^ (1 << jb)) for dd in range(D)
                            if (dd >> jb) & 1]
                    return lax.ppermute(keep, axis, perm)
                return br

            branches = [local_branch(s) for s in range(lw)] + \
                       [device_branch(jb) for jb in range(k)]
            F = lax.switch(jnp.clip(ret_slot[r], 0, w - 1), branches, F)
            alive = lax.psum(jnp.any(F != 0).astype(jnp.int32), axis) > 0
            return r + 1, F, ~alive

        def row_cond(carry):
            r, _, dead = carry
            return (r < n_rows) & ~dead

        r, F, dead = lax.while_loop(
            row_cond, row_body, (jnp.int32(0), F, jnp.bool_(False)))
        return F.reshape(1, n_local), r[None], dead[None]

    fn = util.get_shard_map()(
        body, mesh=mesh,
        in_specs=(P(axis), P(), P(), P(), P(), P(), P()),
        out_specs=(P(axis), P(axis), P(axis)),
        check_vma=False)
    return fn(F_local, n_rows, nil_id, ret_slot, active, slot_f, slot_v)


def check_packed(p: PackedHistory, mesh: Mesh, chunk: int = CHUNK,
                 cancel=None, explain: bool = False) -> dict:
    """Decide linearizability with the config space sharded over ``mesh``
    (first axis). Same zero-host-sync chunk chaining as the single-chip
    dense engine. ``explain=True`` retains every chunk-entry bitmap (the
    chunks pipeline without host syncs, so the dead chunk is only known
    at the end) and, on an invalid verdict, replays the failing tail on
    the CPU oracle for knossos-style configs + final-paths."""
    n_devices = int(np.prod(mesh.devices.shape))
    pl = plan(p, n_devices)
    if pl is None:
        return {"valid?": "unknown", "analyzer": "tpu-dense-sharded",
                "error": "history or mesh outside dense sharding bounds"}
    w, ns, nil_id, init_id, k = pl
    axis = mesh.axis_names[0]
    if p.R == 0:
        return {"valid?": True, "analyzer": "tpu-dense-sharded"}

    from jepsen_tpu.lin.bfs import _chunk_slice

    lw = w - k
    F = np.zeros((1 << k, 1 << lw), np.uint32)
    F[0, 0] = np.uint32(1) << init_id      # init config lives on device 0
    F = jax.device_put(F, NamedSharding(mesh, P(axis)))

    step_fn = p.kernel.step
    ret_slot_h = np.asarray(p.ret_slot)
    active_h = np.asarray(p.active)
    slot_f_h = np.asarray(p.slot_f)
    slot_v_h = np.asarray(p.slot_v)

    def pad_w(a):
        if a.shape[1] == w:
            return a
        pad = [(0, 0)] * a.ndim
        pad[1] = (0, w - a.shape[1])
        return np.pad(a, pad)

    snapshots = [] if explain else None
    results = []
    base = 0
    while base < p.R:
        if cancel is not None and cancel.is_set():
            return {"valid?": "unknown", "analyzer": "tpu-dense-sharded",
                    "error": "cancelled"}
        if snapshots is not None:
            # Only the last snapshot is ever replayed; the per-chunk
            # dead fetch below keeps it the right one and HBM flat.
            snapshots[:] = [(base, F)]
        n = min(chunk, p.R - base)
        F, r_done, dead = _chunk_sharded(
            F, jnp.int32(n), jnp.int32(nil_id),
            jnp.asarray(_chunk_slice(ret_slot_h, base, chunk)),
            jnp.asarray(pad_w(_chunk_slice(active_h, base, chunk))),
            jnp.asarray(pad_w(_chunk_slice(slot_f_h, base, chunk))),
            jnp.asarray(pad_w(_chunk_slice(slot_v_h, base, chunk))),
            w=w, ns=ns, k=k, step_fn=step_fn, mesh=mesh, axis=axis)
        results.append((base, r_done, dead))
        base += n
        # In explain mode trade the zero-host-sync pipelining for one
        # dead-flag fetch per chunk: early exit at the death keeps the
        # retained snapshot the dead chunk's entry (dense.py's pattern).
        if snapshots is not None and bool(dead[0]):
            break

    for base, r_done, dead in results:
        if bool(dead[0]):
            r = base + int(r_done[0]) - 1
            ret = p.ops[int(p.ret_op[r])]
            out = {"valid?": False, "analyzer": "tpu-dense-sharded",
                   "dead-row": r,
                   "op": {"process": ret.process, "f": ret.f,
                          "value": ret.value, "index": ret.op_index,
                          "ok": ret.ok},
                   "configs": [], "final-paths": []}
            if snapshots:
                from jepsen_tpu.lin import witness

                # Gather only the last snapshot at or before the dead
                # row — the replay uses exactly one entry bitmap.
                usable = [sn for sn in snapshots if sn[0] <= r]
                flat = [(b0, np.asarray(f0).reshape(-1))
                        for b0, f0 in usable[-1:]]
                out.update(witness.tail_replay(p, nil_id, flat, r,
                                               cancel=cancel))
            return out
    return {"valid?": True, "analyzer": "tpu-dense-sharded",
            "final-frontier-popcount": int(
                jnp.sum(lax.population_count(F))),
            "n-devices": n_devices}
