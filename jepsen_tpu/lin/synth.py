"""Synthetic history generation for parity tests and benchmarks.

Simulates a *true* linearizable object driven by concurrent processes: each
op's linearization point is placed immediately before its completion event,
so the emitted history is linearizable by construction. Corruptions then
produce known-invalid histories. This stands in for the recorded etcd /
cockroach / hazelcast-lock histories of the reference's parity configs
(BASELINE.md: etcd r/w/cas registers, wgl synthetic CAS histories, hazelcast
lock mutex histories, 100k-op register histories).
"""

from __future__ import annotations

import random
from typing import Any

from jepsen_tpu.history import History, Op, index_history
from jepsen_tpu import models as m


def generate_register_history(n_ops: int,
                              concurrency: int = 5,
                              seed: int = 0,
                              value_range: int = 5,
                              crash_prob: float = 0.0,
                              max_crashes: int = 16,
                              fs: tuple = ("read", "write", "cas"),
                              ) -> History:
    """A linearizable-by-construction CAS-register history.

    Processes invoke read/write/cas ops; the simulated register applies each
    op atomically at completion time. CAS ops whose precondition fails
    complete with :fail (they did not take effect). With ``crash_prob``,
    an op crashes (:info) — applied or not with a coin flip — and its
    process is re-incarnated (process += concurrency, mirroring the
    reference runner's semantics at core.clj:185-217).
    """
    rng = random.Random(seed)
    value: Any = None
    h: list[Op] = []
    procs = list(range(concurrency))
    pending: dict[int, Op] = {}
    crashes = 0
    invoked = 0

    while invoked < n_ops or pending:
        can_invoke = invoked < n_ops and len(pending) < concurrency
        if can_invoke and (not pending or rng.random() < 0.6):
            free = [p for p in procs if p not in pending]
            proc = rng.choice(free)
            f = rng.choice(fs)
            if f == "read":
                op = Op("invoke", "read", None, proc)
            elif f == "write":
                op = Op("invoke", "write", rng.randrange(value_range), proc)
            else:
                op = Op("invoke", "cas",
                        [rng.randrange(value_range),
                         rng.randrange(value_range)], proc)
            pending[proc] = op
            h.append(op)
            invoked += 1
        else:
            proc = rng.choice(list(pending))
            op = pending.pop(proc)
            if crashes < max_crashes and rng.random() < crash_prob:
                # crash: apply or not, nobody knows
                if rng.random() < 0.5:
                    value = _apply(value, op)[0]
                h.append(Op("info", op.f, op.value, proc))
                crashes += 1
                # re-incarnate the process
                i = procs.index(proc)
                procs[i] = proc + concurrency
            else:
                value, result, ok = _apply_full(value, op)
                if ok:
                    h.append(Op("ok", op.f, result, proc))
                else:
                    h.append(Op("fail", op.f, op.value, proc))
    return index_history(History(h))


def _apply(value, op):
    if op.f == "write":
        return op.value, True
    if op.f == "cas":
        cur, new = op.value
        if cur == value:
            return new, True
        return value, False
    return value, True


def _apply_full(value, op):
    if op.f == "read":
        return value, value, True
    if op.f == "write":
        return op.value, op.value, True
    cur, new = op.value
    if cur == value:
        return new, op.value, True
    return value, op.value, False


def generate_partitioned_register_history(
        n_ops: int,
        concurrency: int = 30,
        seed: int = 0,
        value_range: int = 5,
        n_nodes: int = 5,
        partition_every: int = 2000,
        partition_len: int = 300,
        max_crashes: int = 24,
        fs: tuple = ("read", "write", "cas"),
        invoke_bias: float = 0.6,
) -> History:
    """A linearizable-by-construction register history under a partition
    nemesis — the shape BASELINE config 5 names (100k-op
    partitioned-nemesis cockroachdb/hazelcast register histories at
    cockroach's concurrency 30, cockroach.clj:40-41).

    Processes stripe over ``n_nodes`` nodes (the runner's node = process
    mod nodes assignment, core.clj:344-357). Every ``partition_every``
    invocations a partition isolates the minority nodes for
    ``partition_len`` invocations: minority mutators pending at the cut
    (and invoked during it) time out indeterminate — ``:info``, not
    applied, since a minority cannot commit, but the checker must treat
    them as possibly-applied forever — and minority reads fail safely.
    Crashed processes re-incarnate (core.clj:185-217). Total crashes are
    capped so the concurrency window stays inside the device band
    (window <= concurrency + max_crashes).

    ``invoke_bias`` sets how saturated the schedule runs: the default
    0.6 keeps nearly all 30 processes pending at once (the adversarial
    ceiling); lower values model the reference's staggered generators
    (e.g. etcd.clj:167-179 staggers invocations, so typical in-flight
    depth sits well below the process count, spiking only when a
    partition stalls completions).

    This is the history class the reference cannot check at all
    (independent.clj:2-7 exists because knossos DNFs on it): the crashed
    identical mutators that pile up during partitions are exactly what
    the crashed-op canonical chains (prepare.reduction_tables) collapse.
    """
    rng = random.Random(seed)
    value: Any = None
    h: list[Op] = []
    procs = list(range(concurrency))
    pending: dict[int, Op] = {}
    crashes = 0
    invoked = 0
    minority = {n_nodes - 2, n_nodes - 1}

    def node_of(proc: int) -> int:
        return proc % n_nodes

    def partitioned_at(k: int) -> bool:
        return partition_every > 0 and \
            0 <= (k % partition_every) - (partition_every - partition_len) \
            < partition_len

    while invoked < n_ops or pending:
        cut = partitioned_at(invoked)
        can_invoke = invoked < n_ops and len(pending) < concurrency
        if can_invoke and (not pending or rng.random() < invoke_bias):
            free = [p for p in procs if p not in pending]
            if cut:
                free = [p for p in free if node_of(p) not in minority] \
                    or free
            proc = rng.choice(free)
            f = rng.choice(fs)
            if f == "read":
                op = Op("invoke", "read", None, proc)
            elif f == "write":
                op = Op("invoke", "write", rng.randrange(value_range), proc)
            else:
                op = Op("invoke", "cas",
                        [rng.randrange(value_range),
                         rng.randrange(value_range)], proc)
            pending[proc] = op
            h.append(op)
            invoked += 1
        else:
            proc = rng.choice(list(pending))
            op = pending.pop(proc)
            if cut and node_of(proc) in minority:
                # Isolated client: reads fail safely; mutators time out
                # indeterminate (not applied — a minority can't commit).
                if op.f == "read" or crashes >= max_crashes:
                    h.append(Op("fail", op.f, op.value, proc))
                else:
                    h.append(Op("info", op.f, op.value, proc))
                    crashes += 1
                    i = procs.index(proc)
                    procs[i] = proc + concurrency
                continue
            value, result, ok = _apply_full(value, op)
            if ok:
                h.append(Op("ok", op.f, result, proc))
            else:
                h.append(Op("fail", op.f, op.value, proc))
    return index_history(History(h))


def generate_mutex_history(n_ops: int,
                           concurrency: int = 5,
                           seed: int = 0,
                           crash_prob: float = 0.0,
                           max_crashes: int = 8) -> History:
    """A linearizable-by-construction mutex history (acquire/release), the
    shape of the reference's hazelcast :lock workload
    (hazelcast.clj:379-386: model/mutex + linearizable)."""
    rng = random.Random(seed)
    locked = False
    holder: int | None = None
    h: list[Op] = []
    procs = list(range(concurrency))
    pending: dict[int, Op] = {}
    crashes = 0
    invoked = 0

    while invoked < n_ops or pending:
        can_invoke = invoked < n_ops and len(pending) < concurrency
        if can_invoke and (not pending or rng.random() < 0.6):
            free = [p for p in procs if p not in pending]
            proc = rng.choice(free)
            f = "release" if (locked and holder == proc) else "acquire"
            # sometimes try the wrong op, which will just :fail
            if rng.random() < 0.15:
                f = "acquire" if f == "release" else "release"
            op = Op("invoke", f, None, proc)
            pending[proc] = op
            h.append(op)
            invoked += 1
        else:
            proc = rng.choice(list(pending))
            op = pending.pop(proc)
            applies = (op.f == "acquire" and not locked) or \
                      (op.f == "release" and locked and holder == proc)
            if crashes < max_crashes and rng.random() < crash_prob:
                if applies and rng.random() < 0.5:
                    locked = op.f == "acquire"
                    holder = proc if locked else None
                h.append(Op("info", op.f, None, proc))
                crashes += 1
                i = procs.index(proc)
                procs[i] = proc + concurrency
            elif applies:
                locked = op.f == "acquire"
                holder = proc if locked else None
                h.append(Op("ok", op.f, None, proc))
            else:
                h.append(Op("fail", op.f, None, proc))
    return index_history(History(h))


def generate_queue_history(n_ops: int,
                           concurrency: int = 3,
                           seed: int = 0,
                           fifo: bool = True,
                           crash_prob: float = 0.0,
                           max_crashes: int = 8) -> History:
    """A linearizable-by-construction queue history (enqueue/dequeue), the
    shape of the reference's disque/rabbitmq queue workloads
    (disque.clj:305-310). Enqueued values are unique ints; dequeues
    complete with the value actually removed (FIFO order when ``fifo``,
    random otherwise). Dequeue on empty completes :fail."""
    rng = random.Random(seed)
    q: list[int] = []
    next_v = 0
    h: list[Op] = []
    procs = list(range(concurrency))
    pending: dict[int, Op] = {}
    crashes = 0
    invoked = 0

    while invoked < n_ops or pending:
        can_invoke = invoked < n_ops and len(pending) < concurrency
        if can_invoke and (not pending or rng.random() < 0.6):
            free = [p for p in procs if p not in pending]
            proc = rng.choice(free)
            if rng.random() < 0.5:
                op = Op("invoke", "enqueue", next_v, proc)
                next_v += 1
            else:
                op = Op("invoke", "dequeue", None, proc)
            pending[proc] = op
            h.append(op)
            invoked += 1
        else:
            proc = rng.choice(list(pending))
            op = pending.pop(proc)
            if crashes < max_crashes and rng.random() < crash_prob:
                if op.f == "enqueue" and rng.random() < 0.5:
                    q.append(op.value)
                h.append(Op("info", op.f, op.value, proc))
                crashes += 1
                i = procs.index(proc)
                procs[i] = proc + concurrency
            elif op.f == "enqueue":
                q.append(op.value)
                h.append(Op("ok", "enqueue", op.value, proc))
            elif q:
                v = q.pop(0) if fifo else q.pop(rng.randrange(len(q)))
                h.append(Op("ok", "dequeue", v, proc))
            else:
                h.append(Op("fail", "dequeue", None, proc))
    return index_history(History(h))


def generate_set_history(n_ops: int,
                         concurrency: int = 3,
                         seed: int = 0,
                         read_prob: float = 0.2) -> History:
    """A linearizable-by-construction set history (add/read), the shape of
    the reference's set workloads checked linearizably (model.clj:58-71).
    Reads complete with the full membership at their linearization point."""
    rng = random.Random(seed)
    s: set[int] = set()
    next_v = 0
    h: list[Op] = []
    procs = list(range(concurrency))
    pending: dict[int, Op] = {}
    invoked = 0

    while invoked < n_ops or pending:
        can_invoke = invoked < n_ops and len(pending) < concurrency
        if can_invoke and (not pending or rng.random() < 0.6):
            free = [p for p in procs if p not in pending]
            proc = rng.choice(free)
            if rng.random() < read_prob:
                op = Op("invoke", "read", None, proc)
            else:
                op = Op("invoke", "add", next_v, proc)
                next_v += 1
            pending[proc] = op
            h.append(op)
            invoked += 1
        else:
            proc = rng.choice(list(pending))
            op = pending.pop(proc)
            if op.f == "add":
                s.add(op.value)
                h.append(Op("ok", "add", op.value, proc))
            else:
                h.append(Op("ok", "read", sorted(s), proc))
    return index_history(History(h))


def corrupt_history(history: History, seed: int = 0,
                    n_corruptions: int = 1) -> History:
    """Corrupt ok-read values so the history is (very likely) not
    linearizable — the known-invalid side of parity tests."""
    rng = random.Random(seed)
    h = list(history)
    read_positions = [i for i, o in enumerate(h)
                      if o.is_ok and o.f == "read"]
    rng.shuffle(read_positions)
    for i in read_positions[:n_corruptions]:
        old = h[i].value
        bad = (old if old is not None else 0) + 1000
        h[i] = h[i].replace(value=bad)
        # also fix the completed invoke pairing downstream users may do
    return index_history(History(h))
