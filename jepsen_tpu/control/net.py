"""Node network helpers (reference `jepsen/src/jepsen/control/net.clj`,
30 LoC): reachability and IP lookup over the control plane."""

from __future__ import annotations

from jepsen_tpu import control as c


def reachable(host: str) -> bool:
    """Can the current node ping host? (control/net.clj:8-11)"""
    try:
        c.exec_("ping", "-w", "1", host)
        return True
    except c.RemoteError:
        return False


def local_ip() -> str:
    """The bound node's first global IP (control/net.clj:12-18)."""
    return c.exec_(c.Lit(
        "hostname -I | awk '{print $1}'"))


def ip(host: str) -> str:
    """Resolve a hostname to an IP via getent (control/net.clj:20-30)."""
    out = c.exec_("getent", "ahosts", host)
    for line in out.splitlines():
        parts = line.split()
        if parts and "STREAM" in line:
            return parts[0]
    return out.split()[0] if out.split() else host
