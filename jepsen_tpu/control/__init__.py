"""Remote execution / communication backend — the control plane.

Re-design of the reference's `jepsen/src/jepsen/control.clj` (361 LoC): THE
distributed communication layer of the harness half. A dynamically-scoped
session per node (control.clj:15-26), shell escaping (:53-96), sudo wrapping
(:98-106), exec with retry on transient transport failures (:140-160), scp
up/download (:190-217), and parallel fan-out over nodes (:314-353).

Transports are pluggable:

- :class:`SshTransport`   — drives the system ``ssh``/``scp`` binaries (the
  reference uses clj-ssh/JSch; an external-process transport is the
  TPU-image-friendly equivalent since no SSH library is vendored).
- :class:`LocalTransport` — runs commands in a local shell, for single-host
  dev clusters (docker-compose style) and tests.
- :class:`DummyTransport` — records commands and returns canned results;
  the analogue of the reference's ``*dummy*`` no-SSH stub (control.clj:15,
  274-281) used by the no-cluster tests.

The session is scoped with context variables rather than Clojure dynamic
vars; ``with_session(node)`` / ``on(node, f)`` bind it.
"""

from __future__ import annotations

import contextvars
import shlex
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

from jepsen_tpu.util import real_pmap


class RemoteError(Exception):
    """Command failed or transport broke."""

    def __init__(self, msg, exit_code=None, out="", err=""):
        super().__init__(msg)
        self.exit_code = exit_code
        self.out = out
        self.err = err


@dataclass
class Result:
    exit: int
    out: str
    err: str


class Lit:
    """A literal string that bypasses shell escaping (the reference's
    `jepsen.control/lit`)."""

    def __init__(self, s: str):
        self.s = s

    def __str__(self):
        return self.s


def escape(arg) -> str:
    """Escape one command token (control.clj:53-96): literals pass through,
    sequences join with spaces, everything else is shell-quoted when
    needed."""
    if isinstance(arg, Lit):
        return arg.s
    if isinstance(arg, (list, tuple)):
        return " ".join(escape(a) for a in arg)
    s = str(arg)
    if s == "":
        return "''"
    if all(c.isalnum() or c in "-_./=:@%+," for c in s):
        return s
    return shlex.quote(s)


def build_cmd(*args) -> str:
    return " ".join(escape(a) for a in args)


# --- dynamic scope ----------------------------------------------------------

_session_var: contextvars.ContextVar = contextvars.ContextVar(
    "control_session", default=None)
_sudo_var: contextvars.ContextVar = contextvars.ContextVar(
    "control_sudo", default=None)
_dir_var: contextvars.ContextVar = contextvars.ContextVar(
    "control_dir", default=None)
_trace_var: contextvars.ContextVar = contextvars.ContextVar(
    "control_trace", default=False)


def current_session():
    s = _session_var.get()
    if s is None:
        raise RemoteError("no control session bound; use on()/with_session()")
    return s


def current_node():
    return current_session().node


class _Binding:
    def __init__(self, var, value):
        self.var, self.value = var, value

    def __enter__(self):
        self.token = self.var.set(self.value)
        return self.value

    def __exit__(self, *exc):
        self.var.reset(self.token)
        return False


def su():
    """Within this scope, commands run as root via sudo
    (control.clj:98-106 `wrap-sudo` + `su` macro)."""
    return _Binding(_sudo_var, "root")


def sudo(user: str):
    return _Binding(_sudo_var, user)


def cd(directory: str):
    return _Binding(_dir_var, directory)


def trace():
    """Log commands before running them (control.clj:18,248-252)."""
    return _Binding(_trace_var, True)


def wrap_sudo(cmd: str, local_user: str | None = None) -> str:
    """Wrap a command in sudo when a sudo scope is active
    (control.clj:98-106). Skipped when the session already runs as the
    target user — minimal nodes (and the local/dummy transports) often
    have no sudo binary, and root needs no escalation."""
    user = _sudo_var.get()
    if not user:
        return cmd
    session = _session_var.get()
    runs_as = getattr(session, "user", None) or local_user
    if runs_as is None and isinstance(session, (LocalSession, DummySession)):
        import getpass

        try:
            runs_as = getpass.getuser()
        except (OSError, KeyError):  # stripped env / uid without passwd
            runs_as = None
    if runs_as == user:
        return cmd
    return f"sudo -S -u {user} bash -c {shlex.quote(cmd)}"


def wrap_cd(cmd: str) -> str:
    d = _dir_var.get()
    if d:
        return f"cd {shlex.quote(d)} && {cmd}"
    return cmd


# --- transports -------------------------------------------------------------

class Session:
    """One connection to one node."""

    node: str

    def execute(self, cmd: str, stdin: str | None = None) -> Result:
        raise NotImplementedError

    def upload(self, local: str, remote: str) -> None:
        raise NotImplementedError

    def download(self, remote: str, local: str) -> None:
        raise NotImplementedError

    def disconnect(self) -> None:
        pass


class Transport:
    def connect(self, node: str, ssh: dict) -> Session:
        raise NotImplementedError


class DummySession(Session):
    def __init__(self, node, log, results):
        self.node = node
        self.log = log
        self.results = results

    def execute(self, cmd, stdin=None):
        self.log.append((self.node, cmd))
        canned = self.results.get(cmd)
        if canned is None:
            return Result(0, "", "")
        if isinstance(canned, Result):
            return canned
        return Result(0, str(canned), "")

    def upload(self, local, remote):
        self.log.append((self.node, f"UPLOAD {local} -> {remote}"))

    def download(self, remote, local):
        self.log.append((self.node, f"DOWNLOAD {remote} -> {local}"))


class DummyTransport(Transport):
    """Records commands; returns canned results (the `*dummy*` affordance,
    control.clj:15,274-281)."""

    def __init__(self, results: dict | None = None):
        self.log: list = []
        self.results = results or {}

    def connect(self, node, ssh):
        return DummySession(node, self.log, self.results)


class LocalSession(Session):
    def __init__(self, node):
        self.node = node

    def execute(self, cmd, stdin=None):
        p = subprocess.run(["bash", "-c", cmd], capture_output=True,
                           text=True, input=stdin)
        return Result(p.returncode, p.stdout, p.stderr)

    def upload(self, local, remote):
        subprocess.run(["cp", "-r", local, remote], check=True)

    def download(self, remote, local):
        subprocess.run(["cp", "-r", remote, local], check=True)


class LocalTransport(Transport):
    """Run everything on localhost — single-host dev clusters."""

    def connect(self, node, ssh):
        return LocalSession(node)


class SshSession(Session):
    """Drives the system ssh/scp binaries. Equivalent role to the
    reference's clj-ssh/JSch sessions (control.clj:254-281), including the
    retry-on-transient-corruption loop (control.clj:140-160)."""

    RETRIES = 5

    def __init__(self, node, ssh: dict):
        self.node = node
        self.ssh = ssh or {}
        self.base = ["ssh"]
        port = self.ssh.get("port")
        if port:
            self.base += ["-p", str(port)]
        key = self.ssh.get("private-key-path")
        if key:
            self.base += ["-i", key]
        if not self.ssh.get("strict-host-key-checking", False):
            self.base += ["-o", "StrictHostKeyChecking=no",
                          "-o", "UserKnownHostsFile=/dev/null",
                          "-o", "LogLevel=ERROR"]
        self.user = self.ssh.get("username", "root")

    @property
    def dest(self):
        return f"{self.user}@{self.node}"

    def execute(self, cmd, stdin=None):
        last: Exception | None = None
        for attempt in range(self.RETRIES):
            try:
                p = subprocess.run(self.base + [self.dest, cmd],
                                   capture_output=True, text=True,
                                   input=stdin, timeout=600)
                if p.returncode == 255:  # ssh transport failure: retry
                    raise RemoteError(f"ssh transport error: {p.stderr}",
                                      255, p.stdout, p.stderr)
                return Result(p.returncode, p.stdout, p.stderr)
            except (RemoteError, subprocess.TimeoutExpired) as e:
                last = e
                time.sleep(0.2 * (attempt + 1))
        raise RemoteError(f"ssh to {self.node} failed after retries: {last}")

    def _scp_base(self):
        base = ["scp", "-r"]
        port = self.ssh.get("port")
        if port:
            base += ["-P", str(port)]
        key = self.ssh.get("private-key-path")
        if key:
            base += ["-i", key]
        if not self.ssh.get("strict-host-key-checking", False):
            base += ["-o", "StrictHostKeyChecking=no",
                     "-o", "UserKnownHostsFile=/dev/null",
                     "-o", "LogLevel=ERROR"]
        return base

    def upload(self, local, remote):
        subprocess.run(self._scp_base() + [local, f"{self.dest}:{remote}"],
                       check=True, capture_output=True)

    def download(self, remote, local):
        subprocess.run(self._scp_base() + [f"{self.dest}:{remote}", local],
                       check=True, capture_output=True)


class SshTransport(Transport):
    def connect(self, node, ssh):
        return SshSession(node, ssh)


def transport_for(test: dict) -> Transport:
    t = test.get("transport")
    if t is None or t == "ssh":
        return SshTransport()
    if t == "local":
        return LocalTransport()
    if t == "dummy":
        return DummyTransport()
    if isinstance(t, Transport):
        return t
    raise ValueError(f"unknown transport {t!r}")


# --- session management -----------------------------------------------------

def session(test: dict, node: str) -> Session:
    """Open a session to a node (control.clj:270-281)."""
    return transport_for(test).connect(node, test.get("ssh") or {})


def disconnect(sess: Session) -> None:
    sess.disconnect()


class with_session:
    """Bind the current session (control.clj `with-session`)."""

    def __init__(self, sess: Session):
        self.sess = sess

    def __enter__(self):
        self._token = _session_var.set(self.sess)
        return self.sess

    def __exit__(self, *exc):
        _session_var.reset(self._token)
        return False


def exec_(*args, stdin: str | None = None, may_fail: bool = False) -> str:
    """Run an escaped command on the currently-bound node, returning trimmed
    stdout; raises on non-zero exit (control.clj:175-181)."""
    cmd = wrap_cd(wrap_sudo(build_cmd(*args)))
    sess = current_session()
    if _trace_var.get():
        import logging

        logging.getLogger("jepsen.control").info(
            "[%s] %s", sess.node, cmd)
    res = sess.execute(cmd, stdin=stdin)
    if res.exit != 0 and not may_fail:
        raise RemoteError(
            f"command failed on {sess.node} (exit {res.exit}): {cmd}\n"
            f"stdout: {res.out}\nstderr: {res.err}",
            res.exit, res.out, res.err)
    return res.out.strip()


def upload(local: str, remote: str) -> None:
    current_session().upload(local, remote)


def download(remote: str, local: str) -> None:
    current_session().download(remote, local)


def on(test: dict, node: str, f: Callable[[], Any]) -> Any:
    """Run f with a session to node bound (control.clj:314-323). Uses the
    test's cached session when available."""
    sessions = test.get("sessions") or {}
    sess = sessions.get(node)
    if sess is None:
        sess = session(test, node)
        try:
            with with_session(sess):
                return f()
        finally:
            sess.disconnect()
    with with_session(sess):
        return f()


def on_nodes(test: dict, f: Callable[[dict, str], Any],
             nodes: Iterable[str] | None = None) -> dict:
    """Run (f test node) in parallel on each node with its session bound;
    returns {node: result} (control.clj:337-353)."""
    nodes = list(nodes if nodes is not None else test.get("nodes", []))

    def run(node):
        return node, on(test, node, lambda: f(test, node))

    return dict(real_pmap(run, nodes))


def on_many(test: dict, nodes: Iterable[str], f: Callable[[], Any]) -> dict:
    """Run f in parallel on each of nodes (control.clj:325-335)."""
    return dict(real_pmap(lambda n: (n, on(test, n, f)), list(nodes)))
