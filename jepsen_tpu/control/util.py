"""Remote installation / daemon-management helpers.

Re-design of `jepsen/src/jepsen/control/util.clj` (219 LoC): wget with
retries (:51-70), tarball/zip install with corruption retry (:72-140),
user management (:147-154), grepkill (:156-173), daemon start/stop via
start-stop-daemon (:176-205+), tmp dirs (:40-49).
All functions run against the currently-bound control session.
"""

from __future__ import annotations

import os.path
import secrets

from jepsen_tpu import control as c


def exists(path: str) -> bool:
    """Does a file exist on the node? (control/util.clj:17-22)"""
    try:
        c.exec_("stat", path)
        return True
    except c.RemoteError:
        return False


def tmp_dir() -> str:
    """Create and return a fresh temp directory (control/util.clj:40-49)."""
    d = f"/tmp/jepsen/{secrets.token_hex(8)}"
    c.exec_("mkdir", "-p", d)
    return d


def wget(url: str, force: bool = False, retries: int = 3) -> str:
    """Download a file to the current directory if not already present;
    returns its filename (control/util.clj:51-70)."""
    filename = os.path.basename(url)
    if force:
        c.exec_("rm", "-f", filename, may_fail=True)
    if not exists(filename):
        def fetch():
            return c.exec_("wget", "--tries", "20", "--waitretry", "60",
                           "--retry-connrefused", "--no-dns-cache",
                           "--no-cache", url)
        from jepsen_tpu.util import with_retry

        with_retry(fetch, retries=retries, exceptions=(c.RemoteError,))
    return filename


def install_archive(url: str, dest: str, force: bool = False) -> str:
    """Download a tar/zip archive and extract it to dest, retrying once on
    a corrupt archive (control/util.clj:72-140)."""
    with c.cd("/tmp"):
        name = wget(url, force=force)
        c.exec_("rm", "-rf", dest, may_fail=True)
        c.exec_("mkdir", "-p", dest)
        for attempt in (0, 1):
            try:
                if name.endswith(".zip"):
                    c.exec_("unzip", "-o", name, "-d", dest)
                else:
                    c.exec_("tar", "--extract", "--file", name,
                            "--directory", dest,
                            "--strip-components", "1")
                return dest
            except c.RemoteError:
                if attempt == 1:
                    raise
                # corrupt download: refetch once
                name = wget(url, force=True)
    return dest


def ensure_user(username: str) -> str:
    """Create a user if absent (control/util.clj:147-154)."""
    try:
        c.exec_("id", username)
    except c.RemoteError:
        c.exec_("useradd", "--create-home", username)
    return username


def grepkill(pattern: str, signal: str = "KILL") -> None:
    """Kill processes matching a pattern (control/util.clj:156-173)."""
    c.exec_(c.Lit(
        f"ps aux | grep {pattern!r} | grep -v grep | awk '{{print $2}}' "
        f"| xargs -r kill -{signal}"), may_fail=True)


def start_daemon(binary: str, *args, logfile: str, pidfile: str,
                 chdir: str | None = None, make_pidfile: bool = True,
                 background: bool = True, env: dict | None = None) -> None:
    """Start a daemon via start-stop-daemon (control/util.clj:176-205)."""
    cmd = ["start-stop-daemon", "--start"]
    if background:
        cmd += ["--background", "--no-close"]
    if make_pidfile:
        cmd += ["--make-pidfile"]
    cmd += ["--pidfile", pidfile]
    if chdir:
        cmd += ["--chdir", chdir]
    cmd += ["--oknodo", "--exec", binary, "--"]
    cmd += list(args)
    prefix = ""
    if env:
        prefix = " ".join(f"{k}={v}" for k, v in env.items()) + " "
    c.exec_(c.Lit(prefix + c.build_cmd(*cmd) + f" >> {logfile} 2>&1"))


def stop_daemon(pidfile: str, binary: str | None = None) -> None:
    """Stop a daemon by pidfile (control/util.clj:206+)."""
    if exists(pidfile):
        c.exec_("start-stop-daemon", "--stop", "--oknodo",
                "--pidfile", pidfile, "--retry", "15", may_fail=True)
        c.exec_("rm", "-f", pidfile, may_fail=True)
    elif binary:
        grepkill(binary)
