"""Fault injection: the nemesis.

Re-design of `jepsen/src/jepsen/nemesis.clj` (325 LoC): the Nemesis
protocol (nemesis.clj:9-12), partition grudge topology math
(nemesis.clj:60-157 — pure functions, property-tested), the partitioner
family, composition (nemesis.clj:159-197), clock scrambling
(nemesis.clj:199-219), SIGSTOP pauses (nemesis.clj:258-272), node
start/stop (nemesis.clj:221-256), and file truncation
(nemesis.clj:274-300).
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Iterable

from jepsen_tpu import control as c
from jepsen_tpu import net as net_ns
from jepsen_tpu.history import Op
from jepsen_tpu.util import majority, real_pmap


class Nemesis:
    def setup(self, test) -> "Nemesis":
        """Prepare to work with the cluster (nemesis.clj:10)."""
        return self

    def invoke(self, test, op: Op) -> Op:
        """Apply an op which alters the cluster (nemesis.clj:11)."""
        return op

    def teardown(self, test) -> None:
        """Clean up when work is complete (nemesis.clj:12)."""


class NoopNemesis(Nemesis):
    """Does nothing (nemesis.clj:14-19)."""


noop = NoopNemesis()


# --- grudge topology math (pure; property-tested like the reference's
# nemesis_test.clj:18-88) ----------------------------------------------------

def bisect(coll: Iterable) -> tuple[list, list]:
    """Cut a sequence in half; smaller half first (nemesis.clj:60-63)."""
    coll = list(coll)
    k = len(coll) // 2
    return coll[:k], coll[k:]


def split_one(coll: Iterable, loner=None) -> tuple[list, list]:
    """Split one node off from the rest (nemesis.clj:65-70)."""
    coll = list(coll)
    if loner is None:
        loner = random.choice(coll)
    return [loner], [x for x in coll if x != loner]


def complete_grudge(components: Iterable[Iterable]) -> dict:
    """Components (collections of nodes) -> grudge where no node can talk
    outside its component (nemesis.clj:72-84)."""
    components = [set(comp) for comp in components]
    universe = set().union(*components) if components else set()
    grudge = {}
    for comp in components:
        for node in comp:
            grudge[node] = universe - comp
    return grudge


def bridge(nodes: Iterable) -> dict:
    """Cut the network in half, but preserve one node in the middle with
    uninterrupted bidirectional connectivity to both halves
    (nemesis.clj:86-97)."""
    comps = bisect(nodes)
    bridge_node = comps[1][0]
    grudge = complete_grudge(comps)
    grudge.pop(bridge_node, None)
    return {node: others - {bridge_node}
            for node, others in grudge.items()}


def majorities_ring(nodes: Iterable) -> dict:
    """Every node sees a majority, but no node sees the *same* majority as
    any other (nemesis.clj:136-151): nodes form a random ring; each takes a
    contiguous majority window, and the window's middle node drops everyone
    outside it."""
    nodes = list(nodes)
    universe = set(nodes)
    n = len(nodes)
    m = majority(n)
    ring = nodes[:]
    random.shuffle(ring)
    grudge = {}
    for i in range(n):
        maj = [ring[(i + j) % n] for j in range(m)]
        middle = maj[len(maj) // 2]
        grudge[middle] = universe - set(maj)
    return grudge


# --- partitions -------------------------------------------------------------

def snub_nodes(test, dest, sources) -> None:
    """Drop all packets from the given nodes to dest (nemesis.clj:47-50)."""
    net = test.get("net", net_ns.noop)
    real_pmap(lambda src: net.drop(test, src, dest), list(sources or ()))


def partition(test, grudge: dict) -> None:
    """Apply a grudge: each node rejects messages from its grudge set.
    Cumulative until healed (nemesis.clj:52-58)."""
    c.on_nodes(test, lambda t, node: snub_nodes(t, node, grudge.get(node)))


class Partitioner(Nemesis):
    """:start cuts links per (grudge_fn nodes); :stop heals
    (nemesis.clj:99-117)."""

    def __init__(self, grudge_fn: Callable[[list], dict]):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        test.get("net", net_ns.noop).heal(test)
        return self

    def invoke(self, test, op):
        if op.f == "start":
            grudge = self.grudge_fn(list(test["nodes"]))
            partition(test, grudge)
            return op.replace(value=f"Cut off {grudge!r}")
        if op.f == "stop":
            test.get("net", net_ns.noop).heal(test)
            return op.replace(value="fully connected")
        raise ValueError(f"partitioner can't handle f={op.f!r}")

    def teardown(self, test):
        test.get("net", net_ns.noop).heal(test)


def partitioner(grudge_fn) -> Nemesis:
    return Partitioner(grudge_fn)


def partition_halves() -> Nemesis:
    """First half vs second half (nemesis.clj:119-124)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Nemesis:
    """Random halves (nemesis.clj:126-129)."""

    def grudge(nodes):
        nodes = nodes[:]
        random.shuffle(nodes)
        return complete_grudge(bisect(nodes))

    return Partitioner(grudge)


def partition_random_node() -> Nemesis:
    """Isolate a single random node (nemesis.clj:131-134)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Nemesis:
    """Intersecting-majorities ring partition (nemesis.clj:153-157)."""
    return Partitioner(majorities_ring)


# --- composition ------------------------------------------------------------

class Compose(Nemesis):
    """Route ops to child nemeses by f (nemesis.clj:159-197). Routers are
    either collections of fs (routed unchanged) or dicts mapping outer
    f -> inner f (rewritten, so two partitioners can coexist under
    distinct op names). Accepts a dict or an iterable of (router, nemesis)
    pairs — dict routers aren't hashable, so pairs are the general form."""

    def __init__(self, nemeses):
        self.nemeses = list(nemeses.items()) if isinstance(nemeses, dict) \
            else list(nemeses)

    def setup(self, test):
        self.nemeses = [(fs, n.setup(test) or n) for fs, n in self.nemeses]
        return self

    def invoke(self, test, op):
        for fs, nem in self.nemeses:
            if isinstance(fs, dict):
                inner = fs.get(op.f)
            elif callable(fs) and not isinstance(fs, (set, frozenset)):
                inner = fs(op.f)
            else:
                inner = op.f if op.f in fs else None
            if inner is not None:
                res = nem.invoke(test, op.replace(f=inner))
                return res.replace(f=op.f)
        raise ValueError(f"no nemesis can handle {op.f!r}")

    def teardown(self, test):
        for _, nem in self.nemeses:
            nem.teardown(test)


def compose(nemeses) -> Nemesis:
    return Compose(nemeses)


# --- clock faults (see also jepsen_tpu.nemesis_time for the precise C
# bump/strobe programs) ------------------------------------------------------

def set_time(t: float) -> None:
    """Set the bound node's time in POSIX seconds (nemesis.clj:199-202)."""
    with c.su():
        c.exec_("date", "+%s", "-s", f"@{int(t)}")


class ClockScrambler(Nemesis):
    """Randomizes node clocks within a dt-second window
    (nemesis.clj:204-219)."""

    def __init__(self, dt: float):
        self.dt = dt

    def invoke(self, test, op):
        import time as _time

        def scramble(t, node):
            set_time(_time.time() + random.uniform(-self.dt, self.dt))

        return op.replace(value=c.on_nodes(test, scramble))

    def teardown(self, test):
        import time as _time

        c.on_nodes(test, lambda t, node: set_time(_time.time()))


def clock_scrambler(dt: float) -> Nemesis:
    return ClockScrambler(dt)


# --- node start/stop, pauses, truncation ------------------------------------

class NodeStartStopper(Nemesis):
    """:start runs start_fn on targeted nodes; :stop undoes it
    (nemesis.clj:221-256)."""

    def __init__(self, targeter, start_fn, stop_fn):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.nodes: list | None = None
        self.lock = threading.Lock()

    def invoke(self, test, op):
        with self.lock:
            if op.f == "start":
                targets = self.targeter(list(test["nodes"]))
                if targets is None:
                    return op.replace(type="info", value="no-target")
                if not isinstance(targets, (list, tuple, set)):
                    targets = [targets]
                if self.nodes is not None:
                    return op.replace(
                        type="info",
                        value=f"nemesis already disrupting {self.nodes!r}")
                self.nodes = list(targets)
                value = c.on_many(
                    test, self.nodes,
                    lambda: self.start_fn(test, c.current_node()))
                return op.replace(type="info", value=value)
            if op.f == "stop":
                if self.nodes is None:
                    return op.replace(type="info", value="not-started")
                value = c.on_many(
                    test, self.nodes,
                    lambda: self.stop_fn(test, c.current_node()))
                self.nodes = None
                return op.replace(type="info", value=value)
            raise ValueError(f"node-start-stopper can't handle {op.f!r}")


def node_start_stopper(targeter, start_fn, stop_fn) -> Nemesis:
    return NodeStartStopper(targeter, start_fn, stop_fn)


def hammer_time(process: str, targeter=None) -> Nemesis:
    """Pause a process with SIGSTOP on :start, resume with SIGCONT on :stop
    (nemesis.clj:258-272)."""
    targeter = targeter or (lambda nodes: random.choice(nodes))

    def start(test, node):
        with c.su():
            c.exec_("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        with c.su():
            c.exec_("killall", "-s", "CONT", process)
        return ["resumed", process]

    return NodeStartStopper(targeter, start, stop)


class TruncateFile(Nemesis):
    """Drop the last :drop bytes from files:
    value = {node: {"file": path, "drop": bytes}} (nemesis.clj:274-300)."""

    def invoke(self, test, op):
        assert op.f == "truncate"
        plan = op.value

        def go(t, node):
            spec = plan[node]
            assert isinstance(spec["file"], str)
            assert isinstance(spec["drop"], int)
            with c.su():
                c.exec_("truncate", "-c", "-s", f"-{spec['drop']}",
                        spec["file"])

        c.on_nodes(test, go, nodes=list(plan))
        return op


def truncate_file() -> Nemesis:
    return TruncateFile()
