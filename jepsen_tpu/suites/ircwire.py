"""Minimal IRC line-protocol client for the RobustIRC suite.

The reference drives RobustIRC through an IRC client library
(robustirc/src/jepsen/robustirc.clj:213-215): writers post integers as
channel messages, a connected reader accumulates everything it sees, and
the set checker decides whether every acknowledged add survived the
nemesis. IRC is a line protocol (``COMMAND args :trailing\\r\\n``), so
the stdlib speaks it directly: NICK/USER registration, JOIN, PRIVMSG,
and PING/PONG keepalive, with a reader thread collecting channel
traffic.

Two acknowledged-write subtleties the protocol forces:

- IRC carries no per-message ack, so :meth:`IrcClient.say` confirms each
  PRIVMSG with a PING round-trip — TCP ordering means the PONG proves
  the server consumed the message — and an unconfirmed send is
  *indeterminate* (:info), never ok.
- Servers do not echo a session's own PRIVMSGs back (RFC 2812), so the
  observable set at read time is the union of channel traffic received
  and this connection's own *confirmed* sends.
"""

from __future__ import annotations

import itertools
import socket
import threading

from jepsen_tpu import client as client_ns

CHANNEL = "#jepsen"


class IrcError(Exception):
    pass


class IrcClient:
    def __init__(self, host: str, port: int = 6667, nick: str = "jepsen",
                 channel: str = CHANNEL, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.timeout = timeout
        self.channel = channel
        self.messages: list[str] = []
        self.confirmed: list[str] = []
        self.lock = threading.Lock()
        self.registered = threading.Event()
        self.joined = threading.Event()
        self.pong = threading.Event()
        self.error: str | None = None
        self.closed = False
        self._ping_n = 0
        self._sendline(f"NICK {nick}")
        self._sendline(f"USER {nick} 0 * :{nick}")
        self.reader = threading.Thread(target=self._read_loop, daemon=True)
        self.reader.start()
        if not self.registered.wait(timeout) or self.error:
            raise IrcError(self.error
                           or "registration timed out (no 001 welcome)")
        self._sendline(f"JOIN {channel}")
        if not self.joined.wait(timeout):
            raise IrcError(f"JOIN {channel} timed out")

    def _sendline(self, line: str) -> None:
        self.sock.sendall((line + "\r\n").encode())

    def _read_loop(self) -> None:
        buf = b""
        try:
            while not self.closed:
                chunk = self.sock.recv(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\r\n" in buf:
                    raw, buf = buf.split(b"\r\n", 1)
                    self._handle(raw.decode(errors="replace"))
        except OSError:
            return

    def _handle(self, line: str) -> None:
        if line.startswith(":"):
            _, _, line = line[1:].partition(" ")
        parts = line.split(" ")
        cmd = parts[0].upper() if parts else ""
        if cmd == "PING":
            token = line.partition(" ")[2]
            self._sendline(f"PONG {token}")
        elif cmd == "PONG":
            self.pong.set()
        elif cmd == "001":
            self.registered.set()
        elif cmd in ("433", "432"):      # nick in use / erroneous
            self.error = f"nick rejected ({cmd})"
            self.registered.set()
        elif cmd in ("JOIN", "366"):     # JOIN echo or end-of-NAMES
            self.joined.set()
        elif cmd == "PRIVMSG" and len(parts) >= 2 \
                and parts[1].lower() == self.channel.lower():
            text = line.partition(" :")[2]
            with self.lock:
                self.messages.append(text)

    def say(self, text: str) -> None:
        """PRIVMSG to the channel, confirmed by a PING round-trip: the
        PONG arriving proves the server consumed everything sent before
        the PING (TCP ordering). Raises IrcError on confirmation timeout
        — the caller must report the op indeterminate, not failed."""
        self.pong.clear()
        self._ping_n += 1
        self._sendline(f"PRIVMSG {self.channel} :{text}")
        self._sendline(f"PING :ack{self._ping_n}")
        if not self.pong.wait(self.timeout):
            raise IrcError(f"no PONG after PRIVMSG {text!r}")
        with self.lock:
            self.confirmed.append(text)

    def seen(self) -> list[str]:
        """Channel traffic received + this session's confirmed sends
        (servers don't echo a session's own messages back to it)."""
        with self.lock:
            return list(self.messages) + list(self.confirmed)

    def close(self) -> None:
        self.closed = True
        try:
            self._sendline("QUIT :bye")
            self.sock.close()
        except OSError:
            pass


class IrcSetClient(client_ns.Client):
    """Set workload over IRC messages (robustirc.clj:213-215): add =
    confirmed PRIVMSG of an integer, read = everything this
    (continuously connected) client has observed on the channel."""

    _nicks = itertools.count(1)      # shared: workers open concurrently

    def __init__(self, conn: IrcClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return IrcSetClient(
            IrcClient(node, nick=f"jepsen{next(self._nicks)}"))

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.conn.say(str(op.value))
                return op.replace(type="ok")
            if op.f == "read":
                vals = []
                for m in self.conn.seen():
                    try:
                        vals.append(int(m))
                    except ValueError:
                        pass
                return op.replace(type="ok", value=sorted(set(vals)))
        except (OSError, ConnectionError, IrcError) as e:
            # An unconfirmed PRIVMSG may still be in the raft log:
            # adds are indeterminate, never failed.
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
