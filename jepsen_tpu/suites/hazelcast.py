"""Hazelcast suite — the multi-workload registry
(hazelcast/src/jepsen/hazelcast.clj).

The reference's richest workload table (hazelcast.clj:364-399):
crdt-map / map (set semantics), **lock** (the Mutex-model workload whose
histories are BASELINE config #3's shape — checked linearizable on the
device mutex kernel), queue (total-queue), and three unique-id
generators. Nemesis: partition-majorities-ring on a 30s/15s start-stop
cycle (hazelcast.clj:403-427). ``--workload`` selects, exactly like the
reference's opt-spec (hazelcast.clj:433-439).

Hazelcast only speaks its Java client protocol, so wire clients are
spoken natively over the Open Client Protocol
(jepsen_tpu.suites.hazelwire).
"""

from __future__ import annotations

from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


def hazelcast_workloads() -> dict:
    """workload name -> workload map (hazelcast.clj:364-399)."""
    return {
        "crdt-map": workloads.set_workload(),
        "map": workloads.set_workload(),
        "lock": workloads.lock_workload(),
        "queue": workloads.queue_workload(),
        "atomic-ref-ids": workloads.ids_workload(),
        "atomic-long-ids": workloads.ids_workload(),
        "id-gen-ids": workloads.ids_workload(),
    }


class HazelcastDB(common.TarballDB):
    """Uberjar server upload + java daemon (hazelcast.clj:59-120: the
    reference builds a bundled server project and scps the jar)."""

    name = "hazelcast"
    dir = "/opt/hazelcast"
    binary = "java"

    def __init__(self, jar: str = "hazelcast-server.jar"):
        self.url = None
        self.jar = jar

    def post_install(self, test, node) -> None:
        os_debian.install_jdk()

    def start_args(self, test, node) -> list:
        members = ",".join(test["nodes"])
        return ["-jar", f"{self.dir}/{self.jar}", "--members", members]


def test(opts: dict | None = None) -> dict:
    """The hazelcast test map (hazelcast.clj:400-433)."""
    opts = dict(opts or {})
    from jepsen_tpu.suites import hazelwire

    name = opts.pop("workload", None) or "lock"
    table = hazelcast_workloads()
    if name not in table:
        raise ValueError(
            f"unknown workload {name!r}; one of {sorted(table)}")
    clients = {"lock": hazelwire.LockClient,
               "map": hazelwire.SetClient,
               "crdt-map": hazelwire.SetClient,
               "queue": hazelwire.QueueClient,
               "atomic-ref-ids": hazelwire.IdClient,
               "atomic-long-ids": hazelwire.IdClient,
               "id-gen-ids": hazelwire.IdClient}
    return common.suite_test(
        f"hazelcast {name}", opts,
        workload=table[name],
        db=HazelcastDB(),
        client=clients[name](),   # KeyError = workload missing a client
        nemesis=nemesis_ns.partition_majorities_ring(),
        nemesis_gen=common.standard_nemesis_gen(30, 15))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="lock",
                       choices=sorted(hazelcast_workloads()),
                       help="test workload to run (hazelcast.clj:433-439)")

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
