"""Raftis suite — Redis-over-Raft register (raftis/src/jepsen/raftis.clj).

Tarball install with the cluster string passed as daemon argv
(raftis.clj:75-96); read/write register workload (no CAS primitive —
the generator is ``mix [r w]`` against ``model/register 0``,
raftis.clj:116-124); partition-random-halves nemesis. The wire client
speaks RESP directly (:mod:`jepsen_tpu.suites.resp`) where the
reference used the carmine driver.
"""

from __future__ import annotations

from jepsen_tpu import client as client_ns
from jepsen_tpu import models
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads
from jepsen_tpu.suites.resp import RespClient, RespError

VERSION = "v2.0.4"
KEY = "jepsen"
PORT = 6379


class RaftisDB(common.TarballDB):
    """raftis.clj:76-105: daemon argv is (cluster, node, raft-port,
    data-dir, client-port)."""

    name = "raftis"
    dir = "/opt/raftis"
    binary = "raftis"

    def __init__(self, version: str = VERSION):
        self.url = (f"https://github.com/Qihoo360/floyd/releases/download/"
                    f"{version}/raftis-{version}.tar.gz")

    @property
    def logfile(self):
        return f"{self.dir}/raftis.log"

    def start_args(self, test, node) -> list:
        cluster = ",".join(f"{n}:8901" for n in test["nodes"])
        return [cluster, node, "8901", "data", str(PORT)]


class RaftisClient(client_ns.Client):
    """GET/SET register over RESP (the operations of raftis.clj:24-52)."""

    def __init__(self, conn: RespClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return RaftisClient(RespClient(node, PORT))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                v = self.conn.call("GET", KEY)
                return op.replace(type="ok",
                                  value=int(v) if v is not None else 0)
            if op.f == "write":
                self.conn.call("SET", KEY, op.value)
                return op.replace(type="ok")
        except RespError as e:
            return op.replace(type="fail", error=str(e))
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


def test(opts: dict | None = None) -> dict:
    """The raftis test map (raftis.clj:108-130): register 0, r/w mix."""
    return common.suite_test(
        "raftis", opts,
        workload=workloads.single_register(
            ops=(workloads.r, workloads.w), model=models.register(0),
            initial=0),
        db=RaftisDB(),
        client=RaftisClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
