"""Minimal RethinkDB client: V0_4 handshake + the JSON query protocol.

The reference drives RethinkDB through the official Clojure driver
(rethinkdb/src/jepsen/rethinkdb.clj, document_cas.clj); the TPU build
speaks the wire protocol from the stdlib. The V0_4 handshake is three
little-endian magics (version, auth-key length+bytes, JSON protocol),
answered by a NUL-terminated "SUCCESS". Queries are
``token:u64 length:u32 json`` frames whose payload is
``[QueryType, term, optargs]`` with ReQL terms as nested
``[TermType, args, optargs]`` arrays — only the handful of terms the
per-key register workload needs are assembled here, including the
branch-in-replace that makes CAS a single atomic server-side operation
(document_cas.clj's compare-and-set).
"""

from __future__ import annotations

import json
import socket
import struct

from jepsen_tpu import client as client_ns
from jepsen_tpu.suites.common import SocketIO

V0_4 = 0x400C2D20
PROTOCOL_JSON = 0x7E6970C7

START = 1

# ReQL term ids (ql2.proto)
T_DATUM_JSON = 157      # unused; plain JSON literals serve as datums
T_DB = 14
T_TABLE = 15
T_GET = 16
T_EQ = 17
T_GET_FIELD = 31
T_VAR = 10
T_FUNC = 69
T_MAKE_ARRAY = 2
T_BRANCH = 65
T_DEFAULT = 92
T_INSERT = 56
T_REPLACE = 55
T_DB_CREATE = 57
T_TABLE_CREATE = 60
T_DB_LIST = 59
T_TABLE_LIST = 62

SUCCESS_ATOM = 1
SUCCESS_SEQUENCE = 2


class RethinkError(Exception):
    def __init__(self, rtype, msg):
        self.rtype = rtype
        super().__init__(f"rethinkdb error {rtype}: {msg}")


class RethinkClient:
    def __init__(self, host: str, port: int = 28015, auth_key: str = "",
                 timeout: float = 10.0):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.token = 0
        key = auth_key.encode()
        self.io.send(struct.pack("<I", V0_4)
                          + struct.pack("<I", len(key)) + key
                          + struct.pack("<I", PROTOCOL_JSON))
        greeting = b""
        while not greeting.endswith(b"\x00"):
            greeting += self.io.read_exact(1)
        if greeting.rstrip(b"\x00") != b"SUCCESS":
            raise RethinkError(0, greeting.rstrip(b"\x00").decode(
                errors="replace"))

    def run(self, term, db: str = "test"):
        """START a query term; returns the decoded result (atom or
        sequence). Raises RethinkError on client/compile/runtime errors.
        """
        self.token += 1
        q = json.dumps([START, term, {"db": [T_DB, [db]]}]).encode()
        self.io.send(struct.pack("<Q", self.token)
                     + struct.pack("<I", len(q)) + q)
        token, n = struct.unpack("<QI", self.io.read_exact(12))
        resp = json.loads(self.io.read_exact(n))
        t = resp.get("t")
        if t == SUCCESS_ATOM:
            return resp["r"][0]
        if t == SUCCESS_SEQUENCE:
            return resp["r"]
        raise RethinkError(t, resp.get("r"))

    def close(self) -> None:
        try:
            self.io.close()
        except OSError:
            pass


# --- term builders ---------------------------------------------------------


def table(name: str):
    return [T_TABLE, [name]]


def get(tbl, key):
    return [T_GET, [tbl, key]]


def insert(tbl, doc, conflict: str = "error"):
    return [T_INSERT, [tbl, {k: v for k, v in doc.items()}],
            {"conflict": conflict}]


def cas_replace(tbl, key, field: str, old, new_doc):
    """REPLACE with a branch function: if row[field] == old, write
    new_doc, else keep the row — one atomic server-side CAS whose
    outcome is read from the reply's replaced/unchanged counts. The
    field access is wrapped in r.default(None) so a cas against a
    not-yet-written key evaluates to a clean no-match (replaced: 0)
    instead of a runtime error on null."""
    row = [T_VAR, [1]]
    cond = [T_EQ, [[T_DEFAULT, [[T_GET_FIELD, [row, field]], None]],
                   old]]
    fn = [T_FUNC, [[T_MAKE_ARRAY, [1]],
                   [T_BRANCH, [cond, new_doc, row]]]]
    return [T_REPLACE, [get(tbl, key), fn]]


# --- the register workload client ------------------------------------------

DB_NAME = "jepsen"
TABLE_NAME = "registers"


class RegisterClient(client_ns.Client):
    """Per-key linearizable register over one document per key
    (rethinkdb/document_cas.clj): read = get, write = insert with
    conflict replace (majority-acked by default write concern), cas =
    the branch-in-replace judged by the replaced count."""

    def __init__(self, conn: RethinkClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(RethinkClient(node))

    def setup(self, test) -> None:
        conn = RethinkClient(test["nodes"][0])
        try:
            if DB_NAME not in conn.run([T_DB_LIST, []]):
                conn.run([T_DB_CREATE, [DB_NAME]])
            if TABLE_NAME not in conn.run([T_TABLE_LIST, []], db=DB_NAME):
                conn.run([T_TABLE_CREATE, [TABLE_NAME]], db=DB_NAME)
        except RethinkError:
            pass    # racing setup from another worker: already exists
        finally:
            conn.close()

    def invoke(self, test, op):
        from jepsen_tpu import independent

        k, v = op.value if independent.is_tuple(op.value) \
            else (0, op.value)

        def join(val):
            return independent.tuple_(k, val) \
                if independent.is_tuple(op.value) else val

        tbl = table(TABLE_NAME)
        try:
            if op.f == "read":
                doc = self.conn.run(get(tbl, int(k)), db=DB_NAME)
                return op.replace(
                    type="ok",
                    value=join(None if doc is None else doc.get("value")))
            if op.f == "write":
                r = self.conn.run(
                    insert(tbl, {"id": int(k), "value": int(v)},
                           conflict="replace"), db=DB_NAME)
                if isinstance(r, dict) and r.get("errors", 0):
                    # RethinkDB embeds write failures in the SUCCESS
                    # summary (e.g. lost contact with the primary) — the
                    # write may or may not have applied: indeterminate.
                    return op.replace(type="info",
                                      error=str(r.get("first_error")))
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = v
                r = self.conn.run(
                    cas_replace(tbl, int(k), "value", int(old),
                                {"id": int(k), "value": int(new)}),
                    db=DB_NAME)
                if not isinstance(r, dict) or r.get("errors", 0):
                    return op.replace(
                        type="info",
                        error=str(r.get("first_error")
                                  if isinstance(r, dict) else r))
                return op.replace(
                    type="ok" if r.get("replaced", 0) == 1 else "fail")
        except RethinkError as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
