"""Minimal ZooKeeper client over the jute wire protocol.

The reference reaches ZooKeeper through an Avout distributed atom
(zookeeper/src/jepsen/zookeeper.clj:78-104), whose substrate is exactly
four primitives: session connect, ``create``, ``getData`` (value +
version), and ``setData`` conditioned on version — the znode-version CAS.
This client speaks that protocol from the stdlib.

Jute framing: every message is a 4-byte big-endian length prefix, then
fields in network order. A session opens with ConnectRequest /
ConnectResponse; every later request is ``RequestHeader{xid, type}`` +
body, answered by ``ReplyHeader{xid, zxid, err}`` + body. Strings and
buffers are 4-byte-length-prefixed; a Stat is 68 bytes with the data
version at offset 32.
"""

from __future__ import annotations

import socket
import struct

from jepsen_tpu import client as client_ns
from jepsen_tpu.suites.common import SocketIO, WireIndeterminate

# Op codes (zookeeper.h)
OP_CREATE = 1
OP_EXISTS = 3
OP_GETDATA = 4
OP_SETDATA = 5
OP_CLOSE = -11

# Error codes
ZOK = 0
ZNONODE = -101
ZNODEEXISTS = -110
ZBADVERSION = -103

# world:anyone ACL with all permissions (perms=31)
ACL_OPEN = struct.pack(">i", 1) + struct.pack(">i", 31) \
    + struct.pack(">i", 5) + b"world" + struct.pack(">i", 6) + b"anyone"


class ZkError(Exception):
    def __init__(self, code: int, op: str):
        self.code = code
        super().__init__(f"zookeeper error {code} in {op}")

    @property
    def bad_version(self) -> bool:
        return self.code == ZBADVERSION

    @property
    def no_node(self) -> bool:
        return self.code == ZNONODE


def _s(b: bytes) -> bytes:
    """Length-prefixed string/buffer."""
    return struct.pack(">i", len(b)) + b


class ZkClient:
    def __init__(self, host: str, port: int = 2181,
                 timeout: float = 10.0, session_timeout_ms: int = 10000):
        # Reconnect factory: a connection lost mid-op marks the socket
        # dead (that op completes :info — see ZkRegisterClient.invoke);
        # the NEXT op re-dials with SocketIO's bounded backoff and
        # re-runs the session handshake below (_ensure_session).
        self._session_timeout_ms = session_timeout_ms
        self.io = SocketIO(connect=lambda: socket.create_connection(
            (host, port), timeout=timeout))
        self.xid = 0
        self._connect(session_timeout_ms)

    # --- framing -------------------------------------------------------------

    def _read_frame(self) -> bytes:
        (n,) = struct.unpack(">i", self.io.read_exact(4))
        return self.io.read_exact(n)

    def _send_frame(self, payload: bytes) -> None:
        self.io.send(struct.pack(">i", len(payload)) + payload)

    # --- session -------------------------------------------------------------

    def _connect(self, session_timeout_ms: int) -> None:
        req = (struct.pack(">iqi", 0, 0, session_timeout_ms)
               + struct.pack(">q", 0) + _s(b"\x00" * 16))
        self._send_frame(req)
        resp = self._read_frame()
        proto, timeout, session = struct.unpack_from(">iiq", resp, 0)
        if session == 0:
            raise ZkError(-112, "connect")  # session expired/refused
        self.session_id = session

    def _ensure_session(self) -> None:
        """Reconnect + fresh session handshake when the previous
        connection died (a ZK session does not survive the socket)."""
        if self.io.ensure_connected():
            self.xid = 0
            self._connect(self._session_timeout_ms)

    def _call(self, op: int, body: bytes, name: str) -> bytes:
        self._ensure_session()
        self.xid += 1
        self._send_frame(struct.pack(">ii", self.xid, op) + body)
        while True:
            resp = self._read_frame()
            xid, zxid, err = struct.unpack_from(">iqi", resp, 0)
            if xid == -1:        # watch event notification — not ours
                continue
            if err != ZOK:
                raise ZkError(err, name)
            return resp[16:]

    # --- the four Avout primitives ------------------------------------------

    def create(self, path: str, data: bytes, ephemeral: bool = False) \
            -> str:
        flags = 1 if ephemeral else 0
        body = (_s(path.encode()) + _s(data) + ACL_OPEN
                + struct.pack(">i", flags))
        out = self._call(OP_CREATE, body, "create")
        (n,) = struct.unpack_from(">i", out, 0)
        return out[4:4 + n].decode()

    def exists(self, path: str) -> bool:
        try:
            self._call(OP_EXISTS, _s(path.encode()) + b"\x00", "exists")
            return True
        except ZkError as e:
            if e.no_node:
                return False
            raise

    def get_data(self, path: str) -> tuple[bytes, int]:
        """Returns (data, version) — the CAS token pair."""
        out = self._call(OP_GETDATA, _s(path.encode()) + b"\x00",
                         "getData")
        (n,) = struct.unpack_from(">i", out, 0)
        n = max(n, 0)            # -1 encodes an empty buffer
        data = out[4:4 + n]
        (version,) = struct.unpack_from(">i", out, 4 + n + 32)
        return data, version

    def set_data(self, path: str, data: bytes, version: int = -1) -> int:
        """setData conditioned on ``version`` (-1 = unconditional);
        returns the new version. Raises ZkError(bad_version) when the
        znode moved — the zk-atom CAS failure (zookeeper.clj:78-104)."""
        out = self._call(OP_SETDATA,
                         _s(path.encode()) + _s(data)
                         + struct.pack(">i", version), "setData")
        (new_version,) = struct.unpack_from(">i", out, 32)
        return new_version

    def close(self) -> None:
        try:
            self.xid += 1
            self._send_frame(struct.pack(">ii", self.xid, OP_CLOSE))
            self.io.close()
        except OSError:
            pass


class ZkRegisterClient(client_ns.Client):
    """The zk-atom register (zookeeper.clj:78-104): one znode holds the
    value; read = getData, write = unconditional setData, cas = getData
    then version-conditioned setData. Implements the suite Client
    surface."""

    PATH = "/jepsen-register"

    def __init__(self, conn: ZkClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return ZkRegisterClient(ZkClient(node))

    def setup(self, test) -> None:
        conn = ZkClient(test["nodes"][0])
        try:
            if not conn.exists(self.PATH):
                conn.create(self.PATH, b"")
        except ZkError as e:
            if e.code != ZNODEEXISTS:
                raise
        finally:
            conn.close()

    @staticmethod
    def _decode(data: bytes):
        return int(data) if data else None

    def invoke(self, test, op):
        try:
            if op.f == "read":
                data, _ = self.conn.get_data(self.PATH)
                return op.replace(type="ok", value=self._decode(data))
            if op.f == "write":
                self.conn.set_data(self.PATH, str(op.value).encode())
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = op.value
                data, version = self.conn.get_data(self.PATH)
                if self._decode(data) != old:
                    return op.replace(type="fail")
                try:
                    self.conn.set_data(self.PATH, str(new).encode(),
                                       version=version)
                    return op.replace(type="ok")
                except ZkError as e:
                    if e.bad_version:
                        # lint: fail-ok — a BADVERSION reply is a
                        # parsed server response: the CAS was
                        # definitely rejected (transport losses raise
                        # OSError/WireIndeterminate, handled below).
                        return op.replace(type="fail")
                    raise
        except ZkError as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=str(e))
        except WireIndeterminate as e:
            # The connection died AFTER the request may have reached
            # the server (including a reconnect budget exhausted
            # mid-op): the outcome is indeterminate and must complete
            # :info, never :fail — a :fail that actually applied makes
            # the checker unsound.
            return op.replace(type="info", error=repr(e))
        except (OSError, ConnectionError) as e:
            # Pre-send failures (dial/reconnect exhausted before the
            # request went out): the op never reached the server, so
            # :fail is sound for reads; mutators stay conservative.
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
