"""Postgres-RDS suite — bank invariant against a managed cloud database
(postgres-rds/src/jepsen/postgres_rds.clj).

The "nodes-less" client pattern (SURVEY §2.3): there is no DB setup or
nemesis — the system under test is an RDS endpoint outside the cluster
(postgres_rds.clj:238-293 runs the bank checker against it). The wire
client speaks the PostgreSQL protocol directly
(:mod:`jepsen_tpu.suites.pgwire`) with the serialization-failure retry
loop; pass ``host`` / ``user`` / ``password`` / ``dbname`` in opts.
"""

from __future__ import annotations

from jepsen_tpu import client as client_ns
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads
from jepsen_tpu.suites.pgwire import PgClient, PgError

TABLE = "jepsen_accounts"


class RdsBankClient(client_ns.Client):
    """Bank transfers in SERIALIZABLE transactions over pgwire
    (postgres_rds.clj:80-230)."""

    def __init__(self, opts: dict | None = None,
                 conn: PgClient | None = None):
        self.opts = opts or {}
        self.conn = conn

    def open(self, test, node):
        o = self.opts
        conn = PgClient(o.get("host", node),
                        port=int(o.get("port", 5432)),
                        user=o.get("user", "jepsen"),
                        database=o.get("dbname", "jepsen"),
                        password=o.get("password", ""))
        return RdsBankClient(o, conn)

    def setup(self, test) -> None:
        o = self.opts
        conn = PgClient(o.get("host", test["nodes"][0]),
                        port=int(o.get("port", 5432)),
                        user=o.get("user", "jepsen"),
                        database=o.get("dbname", "jepsen"),
                        password=o.get("password", ""))
        try:
            conn.query(f"CREATE TABLE IF NOT EXISTS {TABLE} "
                       f"(id int PRIMARY KEY, balance int NOT NULL)")
            n, total = 5, 50
            for i in range(n):
                conn.query(f"INSERT INTO {TABLE} VALUES "
                           f"({i}, {total // n}) "
                           f"ON CONFLICT (id) DO NOTHING")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT id, balance FROM {TABLE} ORDER BY id")
                return op.replace(type="ok",
                                  value=[int(b) for _, b in rows])
            if op.f == "transfer":
                t = op.value
                try:
                    self.conn.txn([
                        "SET TRANSACTION ISOLATION LEVEL SERIALIZABLE",
                        f"UPDATE {TABLE} SET balance = balance - "
                        f"{t['amount']} WHERE id = {t['from']} "
                        f"AND balance >= {t['amount']}",
                        f"UPDATE {TABLE} SET balance = balance + "
                        f"{t['amount']} WHERE id = {t['to']}",
                    ])
                    return op.replace(type="ok")
                except PgError:
                    return op.replace(type="fail")
        except (OSError, ConnectionError) as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


def test(opts: dict | None = None) -> dict:
    """The postgres-rds test map (postgres_rds.clj:238-293): no DB/OS
    hooks, no nemesis — just clients and the checker. ``workload``
    picks bank (default) or txn (list-append transactions checked by
    the dependency-graph cycle checker, jepsen_tpu.txn/doc/txn.md)."""
    opts = dict(opts or {})
    name = opts.pop("workload", None) or "bank"
    if name == "txn":
        from jepsen_tpu.suites.cockroachdb import TxnClient

        o = opts
        client = TxnClient(
            port=int(o.get("port", 5432)), user=o.get("user", "jepsen"),
            database=o.get("dbname", "jepsen"),
            password=o.get("password", ""), host=o.get("host"),
            admin_database=o.get("dbname", "jepsen"))
        return common.suite_test("postgres-rds txn", opts,
                                 workload=workloads.txn_workload(),
                                 client=client)
    return common.suite_test(
        "postgres-rds", opts,
        workload=workloads.bank_workload(),
        client=RdsBankClient(opts))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="bank",
                       choices=["bank", "txn"])
        p.add_argument("--host", help="RDS endpoint hostname")
        p.add_argument("--user", default="jepsen")
        p.add_argument("--db-password", dest="password", default="")
        p.add_argument("--dbname", default="jepsen")

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
