"""In-memory fake clusters for no-cluster suite runs.

The reference keeps a fake seam at every layer so tests run with zero
infrastructure: `control/*dummy*` skips SSH (control.clj:15,274-281), the
atom-db/atom-client pair backs core_test.clj's basic-cas-test
(tests.clj:26-56), and cockroach's ``:jdbc-mode :pg-local`` swaps the
cluster for localhost (cockroach.clj:141-152). This module is that seam
for every suite workload: each fake implements one workload vocabulary
against a lock-guarded in-process structure, so any suite's test map can
run end-to-end (runner → history → checkers) by swapping its wire client
for the workload fake.

Each fake also supports *injected consistency bugs* (``faulty=...``) —
stale reads, lost enqueues, double lock grants, non-atomic transfers,
dirty reads — used by the test suite to prove the checkers actually catch
the violations they claim to (the reference proves this with hand-built
pathological histories, checker_test.clj:58-82).
"""

from __future__ import annotations

import threading

from jepsen_tpu import client as client_ns
from jepsen_tpu.history import Op


class FakeKV:
    """Linearizable per-key register store (read/write/cas).

    faulty="stale-read": reads may return the previous value, which a
    linearizability checker must eventually flag.
    """

    def __init__(self, faulty: str | None = None):
        self.data: dict = {}
        self.prev: dict = {}
        self.lock = threading.Lock()
        self.faulty = faulty
        self._n = 0

    def read(self, k):
        with self.lock:
            self._n += 1
            if self.faulty == "stale-read" and self._n % 5 == 0 \
                    and k in self.prev:
                return self.prev[k]
            return self.data.get(k)

    def write(self, k, v) -> bool:
        with self.lock:
            self.prev[k] = self.data.get(k)
            self.data[k] = v
            return True

    def cas(self, k, old, new) -> bool:
        with self.lock:
            if self.data.get(k) != old:
                return False
            self.prev[k] = self.data.get(k)
            self.data[k] = new
            return True


class FakeSetStore:
    """Grow-only set. faulty="lost-add": drops some acknowledged adds."""

    def __init__(self, faulty: str | None = None):
        self.items: set = set()
        self.lock = threading.Lock()
        self.faulty = faulty
        self._n = 0

    def add(self, v) -> bool:
        with self.lock:
            self._n += 1
            if self.faulty == "lost-add" and self._n % 7 == 0:
                return True  # acked but dropped
            self.items.add(v)
            return True

    def read(self) -> list:
        with self.lock:
            return sorted(self.items)


class FakeQueue:
    """FIFO queue. faulty="lost-enqueue": acks then drops some enqueues;
    faulty="duplicate": delivers some items twice."""

    def __init__(self, faulty: str | None = None):
        self.items: list = []
        self.lock = threading.Lock()
        self.faulty = faulty
        self._n = 0

    def enqueue(self, v) -> bool:
        with self.lock:
            self._n += 1
            if self.faulty == "lost-enqueue" and self._n % 7 == 0:
                return True
            self.items.append(v)
            return True

    def dequeue(self):
        with self.lock:
            if not self.items:
                return None
            v = self.items.pop(0)
            if self.faulty == "duplicate" and self._n % 5 == 0:
                self.items.insert(0, v)
            return v


class FakeCounter:
    """Atomic counter. faulty="lost-add": drops some increments."""

    def __init__(self, faulty: str | None = None):
        self.value = 0
        self.lock = threading.Lock()
        self.faulty = faulty
        self._n = 0

    def add(self, dt) -> bool:
        with self.lock:
            self._n += 1
            if self.faulty == "lost-add" and self._n % 7 == 0:
                return True
            self.value += dt
            return True

    def read(self):
        with self.lock:
            return self.value


class FakeLock:
    """Distributed lock. faulty="double-grant": sometimes grants the lock
    while held (the classic split-brain lock bug, which the Mutex model
    must flag as non-linearizable)."""

    def __init__(self, faulty: str | None = None):
        self.owner = None
        self.lock = threading.Lock()
        self.faulty = faulty
        self._n = 0

    def acquire(self, who) -> bool:
        with self.lock:
            self._n += 1
            if self.owner is None:
                self.owner = who
                return True
            if self.faulty == "double-grant" and self._n % 3 == 0:
                return True  # granted while held!
            return False

    def release(self, who) -> bool:
        with self.lock:
            if self.owner == who:
                self.owner = None
                return True
            return False


class FakeIdGen:
    """Unique id source. faulty="duplicate": repeats some ids."""

    def __init__(self, faulty: str | None = None):
        self.n = 0
        self.lock = threading.Lock()
        self.faulty = faulty

    def generate(self) -> int:
        with self.lock:
            self.n += 1
            if self.faulty == "duplicate" and self.n % 6 == 0:
                return self.n - 1
            return self.n


class FakeBank:
    """Account balances with transfer transactions.

    faulty="non-atomic": a reader can observe a transfer's debit without
    its credit (the snapshot-isolation read-skew anomaly the bank
    workload exists to catch)."""

    def __init__(self, n: int = 5, total: int = 50,
                 faulty: str | None = None):
        self.balances = [total // n] * n
        self.balances[0] += total - sum(self.balances)
        self.lock = threading.Lock()
        self.faulty = faulty
        self._mid = None  # mid-transfer snapshot for the faulty mode
        self._n = 0

    def read(self) -> list[int]:
        with self.lock:
            self._n += 1
            if self.faulty == "non-atomic" and self._mid is not None \
                    and self._n % 4 == 0:
                return list(self._mid)
            return list(self.balances)

    def transfer(self, frm: int, to: int, amount: int) -> bool:
        with self.lock:
            if self.balances[frm] < amount:
                return False
            self.balances[frm] -= amount
            mid = list(self.balances)  # debit applied, credit not yet
            self.balances[to] += amount
            self._mid = mid
            return True


class FakeTxnStore:
    """List-append registers executed transactionally (the txn/Elle
    workload, doc/txn.md). Healthy mode runs each transaction under one
    lock — serializable by construction.

    faulty modes:

    - ``"write-skew"`` (alias ``"si"``): snapshot-read two-phase
      execution with a rendezvous — a transaction that reads one key
      and appends another waits briefly at its phase boundary for a
      concurrent partner, then both apply against their stale
      snapshots: the classic SI write skew, a guaranteed G2-item pair
      under a concurrent workload.
    - ``"aborted-read"``: every 5th appending transaction APPLIES its
      appends, then reports failure — later reads observe values whose
      transaction aborted (G1a).
    """

    RENDEZVOUS_S = 0.05

    def __init__(self, faulty: str | None = None):
        self.lists: dict = {}
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.faulty = faulty
        self._n = 0
        self._waiting = 0

    def _apply(self, mops, snapshot=None):
        done = []
        for f, k, v in mops:
            if f == "append":
                self.lists.setdefault(k, []).append(v)
                done.append(["append", k, v])
            else:
                src = snapshot if snapshot is not None else self.lists
                done.append(["r", k, list(src.get(k, []))])
        return done

    def txn(self, mops) -> tuple[bool, list]:
        """Execute micro-ops atomically; (committed, completed mops)."""
        mops = [tuple(m) for m in mops]
        skew = self.faulty in ("write-skew", "si") \
            and any(m[0] == "r" for m in mops) \
            and any(m[0] == "append" for m in mops)
        with self.cond:
            self._n += 1
            if self.faulty == "aborted-read" \
                    and any(m[0] == "append" for m in mops) \
                    and self._n % 5 == 0:
                self._apply(mops)
                return False, mops
            if not skew:
                return True, self._apply(mops)
            # Write skew: snapshot now, rendezvous, apply appends late.
            snapshot = {k: list(v) for k, v in self.lists.items()}
            reads = self._apply([m for m in mops if m[0] == "r"],
                                snapshot)
            self._waiting += 1
            if self._waiting % 2 == 1:
                self.cond.wait(self.RENDEZVOUS_S)   # wait for a partner
            else:
                self.cond.notify()                  # release the partner
            appends = self._apply([m for m in mops if m[0] == "append"])
            out = []
            for f, _k, _v in mops:
                out.append((reads if f == "r" else appends).pop(0))
            return True, out


class FakeTable:
    """Append-only table of (id, committed) rows for the dirty-read /
    monotonic / sequential / comments workloads.

    faulty="dirty-read": readers can see rows whose transaction later
    aborted."""

    def __init__(self, faulty: str | None = None):
        self.rows: list = []          # committed ids, insertion order
        self.uncommitted: list = []   # ids written but later aborted
        self.lock = threading.Lock()
        self.faulty = faulty
        self._n = 0

    def insert(self, v, commit: bool = True) -> bool:
        with self.lock:
            if commit:
                self.rows.append(v)
            else:
                self.uncommitted.append(v)
            return commit

    def read(self) -> list:
        with self.lock:
            self._n += 1
            if self.faulty == "dirty-read" and self.uncommitted \
                    and self._n % 3 == 0:
                return list(self.rows) + [self.uncommitted[-1]]
            return list(self.rows)


# --- clients over the fakes -------------------------------------------------


class FakeClient(client_ns.Client):
    """Base: binds a shared fake store; open() shares the store across
    processes (one cluster, many connections)."""

    def __init__(self, store):
        self.store = store

    def open(self, test, node):
        return type(self)(self.store)


class KVClient(FakeClient):
    """read/write/cas over FakeKV. Values are independent-key tuples
    ``(k, v)`` or plain values keyed under None."""

    def _split(self, op):
        from jepsen_tpu import independent

        if independent.is_tuple(op.value):
            return op.value[0], op.value[1]
        return None, op.value

    def _join(self, op, k, v):
        from jepsen_tpu import independent

        if independent.is_tuple(op.value):
            return independent.tuple_(k, v)
        return v

    def invoke(self, test, op: Op) -> Op:
        k, v = self._split(op)
        if op.f == "read":
            got = self.store.read(k)
            return op.replace(type="ok", value=self._join(op, k, got))
        if op.f == "write":
            self.store.write(k, v)
            return op.replace(type="ok")
        if op.f == "cas":
            old, new = v
            ok = self.store.cas(k, old, new)
            return op.replace(type="ok" if ok else "fail")
        return op.replace(type="fail", error=f"unknown f {op.f}")


class SetClient(FakeClient):
    def invoke(self, test, op: Op) -> Op:
        if op.f == "add":
            self.store.add(op.value)
            return op.replace(type="ok")
        if op.f == "read":
            return op.replace(type="ok", value=self.store.read())
        return op.replace(type="fail", error=f"unknown f {op.f}")


class QueueClient(FakeClient):
    def invoke(self, test, op: Op) -> Op:
        if op.f == "enqueue":
            self.store.enqueue(op.value)
            return op.replace(type="ok")
        if op.f == "dequeue":
            v = self.store.dequeue()
            if v is None:
                return op.replace(type="fail")
            return op.replace(type="ok", value=v)
        if op.f == "drain":
            # Emitted by gen.drain_queue: drain everything left.
            drained = []
            while True:
                v = self.store.dequeue()
                if v is None:
                    break
                drained.append(v)
            return op.replace(type="ok", value=drained)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class CounterClient(FakeClient):
    def invoke(self, test, op: Op) -> Op:
        if op.f == "add":
            self.store.add(op.value)
            return op.replace(type="ok")
        if op.f == "read":
            return op.replace(type="ok", value=self.store.read())
        return op.replace(type="fail", error=f"unknown f {op.f}")


class LockClient(FakeClient):
    def __init__(self, store):
        super().__init__(store)
        self.me = object()

    def invoke(self, test, op: Op) -> Op:
        if op.f == "acquire":
            ok = self.store.acquire(self.me)
            return op.replace(type="ok" if ok else "fail")
        if op.f == "release":
            ok = self.store.release(self.me)
            return op.replace(type="ok" if ok else "fail")
        return op.replace(type="fail", error=f"unknown f {op.f}")


class IdGenClient(FakeClient):
    def invoke(self, test, op: Op) -> Op:
        if op.f == "generate":
            return op.replace(type="ok", value=self.store.generate())
        return op.replace(type="fail", error=f"unknown f {op.f}")


class BankClient(FakeClient):
    def invoke(self, test, op: Op) -> Op:
        if op.f == "read":
            return op.replace(type="ok", value=self.store.read())
        if op.f == "transfer":
            t = op.value
            ok = self.store.transfer(t["from"], t["to"], t["amount"])
            return op.replace(type="ok" if ok else "fail")
        return op.replace(type="fail", error=f"unknown f {op.f}")


class TableClient(FakeClient):
    def invoke(self, test, op: Op) -> Op:
        if op.f == "insert":
            commit = not op.get("abort", False)
            ok = self.store.insert(op.value, commit=commit)
            return op.replace(type="ok" if ok else "fail")
        if op.f == "read":
            return op.replace(type="ok", value=self.store.read())
        return op.replace(type="fail", error=f"unknown f {op.f}")
