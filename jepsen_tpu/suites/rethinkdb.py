"""RethinkDB suite — per-key document CAS with replica reconfiguration
(rethinkdb/src/jepsen/rethinkdb.clj + document_cas.clj).

Per-key registers via independent/checker linearizable
(document_cas.clj:146-148). Two nemeses: the standard partitioner and
the custom **primaries grudge** (rethinkdb.clj:183-249) — partitions
computed so current table primaries land in the minority, while the
test concurrently reconfigures replicas. The ReQL wire protocol needs a
driver, so the client is gated; fakes cover no-cluster runs.
"""

from __future__ import annotations

import random

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class RethinkDB(db_ns.DB, db_ns.LogFiles):
    """apt repo install + daemon with join list (rethinkdb.clj:40-120)."""

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["rethinkdb"])
            joins = "\n".join(f"join={n}:29015" for n in test["nodes"]
                              if n != node)
            config = (f"bind=all\nserver-name={node}\n"
                      f"directory=/var/lib/rethinkdb/jepsen\n{joins}\n")
            control.exec_("tee", "/etc/rethinkdb/instances.d/jepsen.conf",
                          stdin=config)
            control.exec_("service", "rethinkdb", "restart")

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "rethinkdb", "stop", may_fail=True)
            control.exec_("rm", "-rf", "/var/lib/rethinkdb/jepsen",
                          may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/rethinkdb"]


def primaries_grudge() -> nemesis_ns.Nemesis:
    """Partition so a random majority excludes likely primaries
    (rethinkdb.clj:183-249; without a live ReQL admin connection the
    primary set is approximated by a random minority)."""

    def grudge(nodes):
        nodes = list(nodes)
        random.shuffle(nodes)
        minority = nodes[:len(nodes) // 2]
        majority = nodes[len(nodes) // 2:]
        return nemesis_ns.complete_grudge([majority, minority])

    return nemesis_ns.partitioner(grudge)


def test(opts: dict | None = None) -> dict:
    """The rethinkdb test map (rethinkdb.clj:120-180). ``nemesis`` picks
    partition (default) or primaries."""
    opts = dict(opts or {})
    nem = opts.pop("nemesis", None) or "partition"
    threads_per_key = 5
    if opts.get("concurrency", 0) < threads_per_key:
        opts["concurrency"] = threads_per_key
    from jepsen_tpu.suites import rethinkwire

    nemesis = nemesis_ns.partition_random_halves() \
        if nem == "partition" else primaries_grudge()
    return common.suite_test(
        "rethinkdb", opts,
        workload=workloads.register(threads_per_key=threads_per_key),
        db=RethinkDB(),
        client=rethinkwire.RegisterClient(),
        nemesis=nemesis,
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--nemesis", default="partition",
                       choices=["partition", "primaries"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
