"""Consul suite — CAS register over the HTTP KV API
(consul/src/jepsen/consul.clj).

Consul's KV store exposes *index-based* CAS: the client reads the key's
ModifyIndex, compares the current value itself, then PUTs with
``?cas=<index>`` (consul.clj:101-110). Single shared key, linearizable
against a nil-initialized CAS register, partition nemesis.
"""

from __future__ import annotations

import base64
import json

from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

VERSION = "0.5.2"
KEY = "jepsen"


class ConsulDB(db_ns.DB, db_ns.LogFiles):
    """Binary download + agent daemon in server mode (consul.clj:21-66):
    first node bootstraps, the rest retry-join it."""

    dir = "/opt/consul"
    binary = "consul"
    logfile = "/opt/consul/consul.log"
    pidfile = "/opt/consul/consul.pid"

    def __init__(self, version: str = VERSION):
        self.url = (f"https://releases.hashicorp.com/consul/{version}/"
                    f"consul_{version}_linux_amd64.zip")

    def setup(self, test, node) -> None:
        with control.su():
            cu.install_archive(self.url, self.dir)
            args = ["agent", "-server", "-data-dir", f"{self.dir}/data",
                    "-bind", node, "-client", "0.0.0.0",
                    "-node", node]
            if node == test["nodes"][0]:
                args += ["-bootstrap-expect", "1"]
            else:
                args += ["-retry-join", test["nodes"][0]]
            cu.start_daemon(f"{self.dir}/{self.binary}", *args,
                            logfile=self.logfile, pidfile=self.pidfile,
                            chdir=self.dir)

    def teardown(self, test, node) -> None:
        with control.su():
            cu.stop_daemon(self.pidfile, binary=self.binary)
            control.exec_("rm", "-rf", self.dir, may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return [self.logfile]


class ConsulClient(client_ns.Client):
    """read / write / index-CAS over /v1/kv (consul.clj:95-146). Values
    are JSON-encoded; reads decode the base64 payload Consul returns."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return ConsulClient(node)

    @property
    def _url(self) -> str:
        return f"http://{self.node}:8500/v1/kv/{KEY}"

    def _get(self):
        """Returns (modify_index, decoded value) or (None, None)."""
        status, body = common.http_json("GET", self._url)
        if status != 200 or not body:
            return None, None
        entry = body[0]
        raw = base64.b64decode(entry["Value"]) if entry["Value"] else b""
        val = json.loads(raw) if raw else None
        return entry["ModifyIndex"], val

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                _, val = self._get()
                return op.replace(type="ok", value=val)
            if op.f == "write":
                status, _ = common.http_json(
                    "PUT", self._url, json.dumps(op.value))
                return op.replace(type="ok" if status == 200 else "info")
            if op.f == "cas":
                old, new = op.value
                index, cur = self._get()
                if index is None or cur != old:
                    return op.replace(type="fail")
                status, body = common.http_json(
                    "PUT", f"{self._url}?cas={index}", json.dumps(new))
                ok = status == 200 and body is True
                return op.replace(type="ok" if ok else "fail")
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def test(opts: dict | None = None) -> dict:
    """The consul test map (consul.clj:160-181)."""
    return common.suite_test(
        "consul", opts,
        workload=workloads.single_register(),
        db=ConsulDB(),
        client=ConsulClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
