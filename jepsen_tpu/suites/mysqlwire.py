"""Minimal MySQL client/server wire-protocol client.

The reference drives its MySQL-family suites through JDBC — galera
(galera/src/jepsen/galera.clj:40-120), percona, mysql-cluster, and TiDB
(tidb/src/tidb/sql.clj). The TPU build speaks the wire protocol directly
from the stdlib instead of vendoring a driver (sibling of
:mod:`jepsen_tpu.suites.pgwire`): the v10 initial handshake,
``mysql_native_password`` auth (with auth-switch), and the COM_QUERY text
protocol — enough for the register/bank/sets/dirty-reads workload SQL.

Protocol framing: every packet is ``len:3 (LE) seq:1 payload``; the
sequence id resets per command. A COM_QUERY response is either an OK
(0x00) / ERR (0xFF) packet or a result set: column count (length-encoded
int), column definitions, EOF, text rows (length-encoded strings, 0xFB
for NULL), EOF.
"""

from __future__ import annotations

import hashlib
import socket
import struct

from jepsen_tpu.suites.common import SocketIO

CLIENT_LONG_PASSWORD = 0x00000001
CLIENT_FOUND_ROWS = 0x00000002
CLIENT_CONNECT_WITH_DB = 0x00000008
CLIENT_PROTOCOL_41 = 0x00000200
CLIENT_TRANSACTIONS = 0x00002000
CLIENT_SECURE_CONNECTION = 0x00008000
CLIENT_PLUGIN_AUTH = 0x00080000

# Errors the JDBC suites' txn retry loops wrap (tidb/sql.clj's
# with-txn-retries): InnoDB deadlock / lock-wait, TiDB write conflicts.
RETRYABLE_CODES = {1205, 1213, 8002, 9007}


class MyError(Exception):
    """ERR packet from the server."""

    def __init__(self, code: int, sqlstate: str, message: str):
        self.code = code
        self.sqlstate = sqlstate
        self.message = message
        super().__init__(f"({code}) [{sqlstate}] {message}")

    @property
    def retryable(self) -> bool:
        return self.code in RETRYABLE_CODES


def _scramble(password: str, nonce: bytes) -> bytes:
    """mysql_native_password: SHA1(pw) XOR SHA1(nonce + SHA1(SHA1(pw)))."""
    if not password:
        return b""
    p1 = hashlib.sha1(password.encode()).digest()
    p2 = hashlib.sha1(p1).digest()
    mix = hashlib.sha1(nonce + p2).digest()
    return bytes(a ^ b for a, b in zip(p1, mix))


class MyClient:
    def __init__(self, host: str, port: int = 3306, user: str = "root",
                 password: str = "", database: str = "",
                 timeout: float = 10.0):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.seq = 0
        self.last_affected = 0   # affected_rows of the most recent OK
        self._handshake(user, password, database)

    # --- framing -------------------------------------------------------------

    def _read_packet(self) -> bytes:
        head = self.io.read_exact(4)
        n = head[0] | (head[1] << 8) | (head[2] << 16)
        self.seq = (head[3] + 1) & 0xFF
        return self.io.read_exact(n)

    def _send_packet(self, payload: bytes) -> None:
        if len(payload) >= 0xFFFFFF:
            raise MyError(0, "HY000", "packet too large")
        head = struct.pack("<I", len(payload))[:3] + bytes([self.seq])
        self.seq = (self.seq + 1) & 0xFF
        self.io.send(head + payload)

    # --- length-encoded primitives ------------------------------------------

    @staticmethod
    def _lenenc_int(b: bytes, off: int) -> tuple[int | None, int]:
        c = b[off]
        if c < 0xFB:
            return c, off + 1
        if c == 0xFB:            # NULL in text rows
            return None, off + 1
        if c == 0xFC:
            return struct.unpack_from("<H", b, off + 1)[0], off + 3
        if c == 0xFD:
            v = b[off + 1] | (b[off + 2] << 8) | (b[off + 3] << 16)
            return v, off + 4
        return struct.unpack_from("<Q", b, off + 1)[0], off + 9

    @classmethod
    def _lenenc_str(cls, b: bytes, off: int) -> tuple[str | None, int]:
        n, off = cls._lenenc_int(b, off)
        if n is None:
            return None, off
        return b[off:off + n].decode(errors="replace"), off + n

    @staticmethod
    def _err(payload: bytes) -> MyError:
        (code,) = struct.unpack_from("<H", payload, 1)
        off = 3
        state = "HY000"
        if len(payload) > 3 and payload[3:4] == b"#":
            state = payload[4:9].decode(errors="replace")
            off = 9
        return MyError(code, state, payload[off:].decode(errors="replace"))

    # --- handshake -----------------------------------------------------------

    def _handshake(self, user: str, password: str, database: str) -> None:
        greeting = self._read_packet()
        if greeting[:1] == b"\xff":
            raise self._err(greeting)
        if greeting[0] != 10:
            raise MyError(0, "08004",
                          f"unsupported protocol version {greeting[0]}")
        off = 1
        off = greeting.index(b"\x00", off) + 1      # server version
        off += 4                                     # thread id
        nonce = greeting[off:off + 8]
        off += 8 + 1                                 # auth data 1 + filler
        cap = struct.unpack_from("<H", greeting, off)[0]
        off += 2
        if len(greeting) > off:
            off += 1 + 2                             # charset + status
            cap |= struct.unpack_from("<H", greeting, off)[0] << 16
            off += 2
            auth_len = greeting[off]
            off += 1 + 10                            # auth len + reserved
            if cap & CLIENT_SECURE_CONNECTION:
                n2 = max(13, auth_len - 8) - 1       # trailing NUL
                nonce += greeting[off:off + n2]
                off += max(13, auth_len - 8)
        nonce = nonce[:20]

        # FOUND_ROWS: affected-rows must count MATCHED rows, not changed
        # ones — otherwise a cas(v, v) whose UPDATE matches but changes
        # no bytes reports 0 and the register client would fail an op
        # that actually took effect (a false linearizability violation).
        caps = (CLIENT_LONG_PASSWORD | CLIENT_FOUND_ROWS
                | CLIENT_PROTOCOL_41 | CLIENT_TRANSACTIONS
                | CLIENT_SECURE_CONNECTION | CLIENT_PLUGIN_AUTH)
        if database:
            caps |= CLIENT_CONNECT_WITH_DB
        token = _scramble(password, nonce)
        payload = struct.pack("<IIB23x", caps, 1 << 24, 33)  # utf8
        payload += user.encode() + b"\x00"
        payload += bytes([len(token)]) + token
        if database:
            payload += database.encode() + b"\x00"
        payload += b"mysql_native_password\x00"
        self._send_packet(payload)
        self._auth_result(password)

    def _auth_result(self, password: str) -> None:
        pkt = self._read_packet()
        if pkt[:1] == b"\x00":
            return
        if pkt[:1] == b"\xff":
            raise self._err(pkt)
        if pkt[:1] == b"\xfe":                      # AuthSwitchRequest
            rest = pkt[1:]
            if b"\x00" in rest:
                plugin, _, data = rest.partition(b"\x00")
            else:
                plugin, data = rest, b""
            if plugin not in (b"mysql_native_password", b""):
                raise MyError(0, "08004",
                              f"unsupported auth plugin "
                              f"{plugin.decode(errors='replace')!r} "
                              f"(only mysql_native_password)")
            self._send_packet(_scramble(password, data.rstrip(b"\x00")))
            self._auth_result(password)
            return
        raise MyError(0, "08004", f"unexpected auth packet {pkt[:1]!r}")

    # --- COM_QUERY text protocol --------------------------------------------

    def query(self, sql: str) -> list[tuple]:
        """Run one text-protocol query; returns rows as tuples of
        str|None. DDL/DML returns [] and records affected rows in
        ``last_affected``. Raises :class:`MyError` on an ERR packet (the
        response ends there, so the connection stays usable)."""
        self.seq = 0
        self._send_packet(b"\x03" + sql.encode())
        first = self._read_packet()
        if first[:1] == b"\xff":
            raise self._err(first)
        if first[:1] == b"\x00":                    # OK: no result set
            affected, off = self._lenenc_int(first, 1)
            self.last_affected = affected or 0
            return []
        ncols, _ = self._lenenc_int(first, 0)
        for _ in range(ncols):                      # column definitions
            self._read_packet()
        pkt = self._read_packet()
        if pkt[:1] == b"\xfe" and len(pkt) < 9:     # EOF after columns
            pkt = self._read_packet()
        rows: list[tuple] = []
        while True:
            if pkt[:1] == b"\xff":
                raise self._err(pkt)
            if pkt[:1] == b"\xfe" and len(pkt) < 9:  # EOF / OK terminator
                self.last_affected = 0
                return rows
            row = []
            off = 0
            for _ in range(ncols):
                v, off = self._lenenc_str(pkt, off)
                row.append(v)
            rows.append(tuple(row))
            pkt = self._read_packet()

    def txn(self, statements: list[str], max_retries: int = 5) -> list:
        """Run statements in a transaction with the deadlock/conflict
        retry loop the reference wraps around JDBC (tidb/sql.clj).
        Returns per-statement results; the last entry is the affected-row
        count of the final statement (MySQL has no RETURNING)."""
        for attempt in range(max_retries):
            try:
                self.query("BEGIN")
                out: list = []
                affected = 0
                for s in statements:
                    out.append(self.query(s))
                    affected = self.last_affected
                self.query("COMMIT")
                self.last_affected = affected
                return out
            except MyError as e:
                try:
                    self.query("ROLLBACK")
                except (MyError, ConnectionError, OSError):
                    pass
                if not e.retryable or attempt == max_retries - 1:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        try:
            self.seq = 0
            self._send_packet(b"\x01")              # COM_QUIT
            self.io.close()
        except OSError:
            pass
