"""etcd suite — the canonical register test (etcd/src/jepsen/etcd.clj).

Per-key CAS registers over etcd's HTTP KV API, checked linearizable on
the device kernel via ``independent.checker``: tarball install
(etcd.clj:51-86), 10 threads/key × 300 ops (etcd.clj:167-173),
partition-random-halves nemesis on a 5s start/stop cycle
(etcd.clj:159,173-178).

The wire client speaks etcd's v2 HTTP API directly (the reference goes
through the Verschlimmbesserung client, etcd.clj:93-143): reads are
unquorum gets, CAS uses ``prevValue``.
"""

from __future__ import annotations

import json
import urllib.parse

from jepsen_tpu import client as client_ns
from jepsen_tpu import independent
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

VERSION = "v3.1.5"


def client_url(node: str) -> str:
    return f"http://{node}:2379"


def peer_url(node: str) -> str:
    return f"http://{node}:2380"


class EtcdDB(common.TarballDB):
    """Tarball install + daemon flags (etcd.clj:51-86)."""

    name = "etcd"
    dir = "/opt/etcd"
    binary = "etcd"

    def __init__(self, version: str = VERSION):
        self.version = version
        self.url = (f"https://storage.googleapis.com/etcd/{version}/"
                    f"etcd-{version}-linux-amd64.tar.gz")

    def start_args(self, test, node) -> list:
        initial = ",".join(f"{n}={peer_url(n)}" for n in test["nodes"])
        return ["--name", node,
                "--listen-peer-urls", peer_url(node),
                "--listen-client-urls", client_url(node),
                "--advertise-client-urls", client_url(node),
                "--initial-cluster-state", "new",
                "--initial-advertise-peer-urls", peer_url(node),
                "--initial-cluster", initial,
                "--log-output", "stdout"]


class EtcdClient(client_ns.Client):
    """CAS register over the v2 keys API (the operations of
    etcd.clj:93-143: unquorum read, put, compare-and-swap)."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return EtcdClient(node)

    def _url(self, k) -> str:
        return f"{client_url(self.node)}/v2/keys/jepsen/{k}"

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value if independent.is_tuple(op.value) else (None, op.value)

        def join(val):
            return independent.tuple_(k, val) \
                if independent.is_tuple(op.value) else val

        try:
            if op.f == "read":
                status, body = common.http_json(
                    "GET", self._url(k) + "?quorum=false")
                if status == 404:
                    return op.replace(type="ok", value=join(None))
                val = json.loads(body["node"]["value"]) \
                    if status == 200 else None
                if status != 200:
                    return op.replace(type="fail", error=body)
                return op.replace(type="ok", value=join(val))
            if op.f == "write":
                form = urllib.parse.urlencode({"value": json.dumps(v)})
                status, body = common.http_json("PUT", self._url(k), form)
                if status in (200, 201):
                    return op.replace(type="ok")
                return op.replace(type="info", error=body)
            if op.f == "cas":
                old, new = v
                form = urllib.parse.urlencode(
                    {"value": json.dumps(new),
                     "prevValue": json.dumps(old)})
                status, body = common.http_json("PUT", self._url(k), form)
                if status == 200:
                    return op.replace(type="ok")
                if status in (404, 412):  # key missing / compare failed
                    return op.replace(type="fail")
                return op.replace(type="info", error=body)
        except OSError as e:
            # Reads are side-effect free: a timed-out read definitely
            # didn't happen; writes/cas are indeterminate
            # (etcd.clj:105-113 crash handling).
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def test(opts: dict | None = None) -> dict:
    """The etcd test map (etcd.clj:149-179). Concurrency is floored at
    the per-key thread-group size — the reference instead errors out of
    independent/concurrent-generator when given fewer workers."""
    opts = dict(opts or {})
    threads_per_key = 10
    if opts.get("concurrency", 0) < threads_per_key:
        opts["concurrency"] = threads_per_key
    return common.suite_test(
        "etcd", opts,
        workload=workloads.register(threads_per_key=threads_per_key),
        db=EtcdDB(),
        client=EtcdClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
