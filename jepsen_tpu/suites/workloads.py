"""Shared workload vocabulary for the DB suites.

The reference's suites speak a small set of workload dialects (SURVEY §2.3:
register / set / bank / queue / ids / counter / dirty-read / monotonic /
sequential / comments / g2). Each builder here returns a *workload map*
in the shape hazelcast.clj:364-399 established::

    {"generator": ..., "final_generator": ... (optional),
     "client": fake-client factory (no-cluster runs),
     "checker": ..., "model": ...}

Suites compose these with their own DB + wire client; the bundled fake
client makes every suite runnable with zero infrastructure (the pg-local
pattern, cockroach.clj:141-152).

Checkers that exist only in suite code in the reference (bank
`cockroach/bank.clj:112-143`, dirty reads `galera/dirty_reads.clj:77`,
monotonic `cockroach/monotonic.clj`, sequential
`cockroach/sequential.clj:141-165`, comments `cockroach/comments.clj
:87-147`) are implemented here once and shared.
"""

from __future__ import annotations

import random
import threading

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import independent
from jepsen_tpu import models
from jepsen_tpu.checker import FnChecker, timeline
from jepsen_tpu.history import Op
from jepsen_tpu.suites import fakes

VALID = "valid?"


# --- op constructors (etcd.clj:145-147) -------------------------------------

def r(test, process):
    return {"type": "invoke", "f": "read", "value": None}


def w(test, process):
    return {"type": "invoke", "f": "write", "value": random.randint(0, 4)}


def cas(test, process):
    return {"type": "invoke", "f": "cas",
            "value": (random.randint(0, 4), random.randint(0, 4))}


# --- register ----------------------------------------------------------------

def register(per_key: int = 300, threads_per_key: int = 10,
             stagger: float = 1 / 30, faulty=None) -> dict:
    """Per-key CAS register checked linearizable — the canonical workload
    (etcd.clj:149-188): independent concurrent generator over keys, each
    key a mix of r/w/cas, checker = independent(timeline + linearizable).
    """
    store = fakes.FakeKV(faulty=faulty)
    return {
        "generator": independent.concurrent_generator(
            threads_per_key, iter(range(10 ** 9)),
            lambda k: gen.limit(per_key,
                                gen.stagger(stagger,
                                            gen.mix([r, w, cas])))),
        "client": fakes.KVClient(store),
        "checker": independent.checker(checker_ns.compose({
            "timeline": timeline.checker(),
            "linear": checker_ns.linearizable(),
        })),
        "model": models.cas_register(),
    }


def single_register(n_ops: int = 300, stagger: float = 1 / 30,
                    ops=(r, w, cas), model=None, initial=None,
                    faulty=None) -> dict:
    """One global register (consul/logcabin/raftis/zookeeper shape).
    ``ops`` selects the vocabulary — raftis has no CAS primitive so its
    mix is read/write only against ``models.register`` (raftis.clj:116-121).
    ``initial`` seeds both the fake store and should match the model's
    initial value.
    """
    store = fakes.FakeKV(faulty=faulty)
    if initial is not None:
        store.data[None] = initial
    return {
        "generator": gen.limit(n_ops,
                               gen.stagger(stagger, gen.mix(list(ops)))),
        "client": fakes.KVClient(store),
        "checker": checker_ns.compose({
            "timeline": timeline.checker(),
            "linear": checker_ns.linearizable(),
        }),
        "model": model if model is not None else models.cas_register(),
    }


# --- set ---------------------------------------------------------------------

def set_workload(n: int = 100, stagger: float = 1 / 10, faulty=None) -> dict:
    """Concurrent adds then a final read (checker.clj:131-178)."""
    counter = threading.Lock()
    state = {"n": 0}

    def add(test, process):
        with counter:
            v = state["n"]
            state["n"] += 1
        return {"type": "invoke", "f": "add", "value": v}

    store = fakes.FakeSetStore(faulty=faulty)
    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.gen(add))),
        "final_generator": gen.once(
            {"type": "invoke", "f": "read", "value": None}),
        "client": fakes.SetClient(store),
        "checker": checker_ns.set_checker(),
        "model": models.set_model(),
    }


# --- txn (list-append, Elle) ------------------------------------------------

def txn_gen(keys: int = 8, mops_per_txn: tuple = (1, 4),
            read_frac: float = 0.5):
    """Elle-style list-append transactions: 1-4 micro-ops, each an
    ``["append", k, v]`` (v unique per history — traceability is what
    makes the dependency graph inferable) or an ``["r", k, None]``
    completed with the observed list (doc/txn.md)."""
    state = {"n": 0}
    lock = threading.Lock()

    def go(test, process):
        n_mops = random.randint(*mops_per_txn)
        mops = []
        for _ in range(n_mops):
            k = random.randrange(keys)
            if random.random() < read_frac:
                mops.append(["r", k, None])
            else:
                with lock:
                    state["n"] += 1
                    v = state["n"]
                mops.append(["append", k, v])
        return {"type": "invoke", "f": "txn", "value": mops}

    return gen.gen(go)


class TxnClient(fakes.FakeClient):
    """Micro-op transactions over :class:`fakes.FakeTxnStore`."""

    def invoke(self, test, op: Op) -> Op:
        if op.f != "txn":
            return op.replace(type="fail", error=f"unknown f {op.f}")
        committed, done = self.store.txn(op.value)
        if not committed:
            return op.replace(type="fail", error="aborted")
        return op.replace(type="ok", value=done)


def txn_workload(n: int = 200, keys: int = 8, stagger: float = 1 / 30,
                 consistency: str = "serializable", algorithm: str = "tpu",
                 faulty=None) -> dict:
    """List-append transactions checked for dependency-graph cycle
    anomalies (checker.txn_cycles -> jepsen_tpu.txn) — the SQL suites'
    transactional workload (cockroachdb/tidb/galera/postgres-rds)."""
    store = fakes.FakeTxnStore(faulty=faulty)
    return {
        "generator": gen.clients(gen.limit(n, gen.stagger(
            stagger, txn_gen(keys=keys)))),
        "client": TxnClient(store),
        "checker": checker_ns.txn_cycles(consistency=consistency,
                                         algorithm=algorithm),
        "model": None,
    }


# --- queue -------------------------------------------------------------------

def queue_workload(n: int = 100, stagger: float = 1 / 10,
                   faulty=None) -> dict:
    """Enqueue/dequeue checked by total-queue (disque shape,
    disque.clj:305-310): every enqueued element must be dequeued exactly
    once after the final drain."""
    store = fakes.FakeQueue(faulty=faulty)
    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.queue_gen())),
        "final_generator": gen.once(
            {"type": "invoke", "f": "drain", "value": None}),
        "client": fakes.QueueClient(store),
        "checker": checker_ns.total_queue(),
        "model": models.unordered_queue(),
    }


# --- counter -----------------------------------------------------------------

def counter_workload(n: int = 200, stagger: float = 1 / 20,
                     faulty=None) -> dict:
    """Increments + reads; reads must fall inside the possible bounds
    (checker.clj:321-374, aerospike counter shape)."""

    def add(test, process):
        return {"type": "invoke", "f": "add", "value": 1}

    store = fakes.FakeCounter(faulty=faulty)
    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.mix(
            [add, r]))),
        "client": fakes.CounterClient(store),
        "checker": checker_ns.counter(),
        "model": None,
    }


# --- lock (hazelcast.clj:379-386) -------------------------------------------

def lock_workload(n: int = 100, stagger: float = 1 / 100,
                  faulty=None) -> dict:
    """acquire/release alternation per process, checked against the Mutex
    model — runs on the device mutex kernel. clients() keeps lock ops off
    the nemesis thread, and the stagger spreads the op budget across
    processes — without it one hot thread can consume the whole limit,
    and a single-process history can never exhibit a double grant."""
    store = fakes.FakeLock(faulty=faulty)
    return {
        "generator": gen.clients(gen.limit(n, gen.stagger(
            stagger, gen.each(lambda: gen.seq(
                _cycle_ops([{"type": "invoke", "f": "acquire",
                             "value": None},
                            {"type": "invoke", "f": "release",
                             "value": None}])))))),
        "client": fakes.LockClient(store),
        "checker": checker_ns.linearizable(),
        "model": models.mutex(),
    }


def _cycle_ops(ops):
    while True:
        yield from ops


# --- unique ids (hazelcast.clj:389-399) -------------------------------------

def ids_workload(n: int = 200, stagger: float = 1 / 20, faulty=None) -> dict:
    store = fakes.FakeIdGen(faulty=faulty)
    return {
        "generator": gen.limit(n, gen.stagger(
            stagger, {"type": "invoke", "f": "generate", "value": None})),
        "client": fakes.IdGenClient(store),
        "checker": checker_ns.unique_ids(),
        "model": None,
    }


# --- bank --------------------------------------------------------------------

def bank_checker(n: int = 5, total: int = 50) -> checker_ns.Checker:
    """Every read of all balances must be non-negative and sum to the
    invariant total (cockroach/bank.clj:112-143 custom checker)."""

    def check(test, model, history, opts):
        bad = []
        for op in history:
            if op.is_ok and op.f == "read" and op.value is not None:
                bal = list(op.value)
                if len(bal) != n or sum(bal) != total \
                        or any(b < 0 for b in bal):
                    bad.append({"op": op.to_dict(), "balances": bal,
                                "sum": sum(bal)})
        return {VALID: not bad, "bad-reads": bad[:10],
                "bad-read-count": len(bad)}

    return FnChecker(check)


def bank_workload(n_accounts: int = 5, total: int = 50, n: int = 200,
                  stagger: float = 1 / 20, faulty=None) -> dict:
    """Balance transfers + full reads (cockroach/bank.clj, galera/percona
    bank shape): total must be conserved in every snapshot."""

    def transfer(test, process):
        frm, to = random.sample(range(n_accounts), 2)
        return {"type": "invoke", "f": "transfer",
                "value": {"from": frm, "to": to,
                          "amount": random.randint(1, 5)}}

    store = fakes.FakeBank(n=n_accounts, total=total, faulty=faulty)
    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.mix(
            [transfer, r]))),
        "client": fakes.BankClient(store),
        "checker": bank_checker(n=n_accounts, total=total),
        "model": None,
    }


# --- dirty reads (galera/dirty_reads.clj:77, percona, crate) ----------------

def strong_read_classification_checker() -> checker_ns.Checker:
    """The strong-read classification shared by the crate and
    elasticsearch dirty-read probes (crate/dirty_read.clj:150-198,
    elasticsearch/dirty_read.clj:106-157): a read must never observe an
    element absent from every final strong read (dirty), every
    acknowledged write must appear in some strong read (lost;
    ``some-lost`` counts writes missing from at least one node), and
    all nodes' strong reads must agree."""

    def check(test, model, history, opts):
        writes, reads, strong = set(), set(), []
        for op in history:
            if not op.is_ok:
                continue
            if op.f == "write":
                writes.add(op.value)
            elif op.f == "read":
                reads.add(op.value)
            elif op.f == "strong-read" and op.value is not None:
                strong.append(set(op.value))
        if not strong:
            return {VALID: "unknown", "error": "no strong reads"}
        on_all = set.intersection(*strong)
        on_some = set.union(*strong)
        not_on_all = on_some - on_all
        unchecked = on_some - reads
        dirty = reads - on_some
        lost = writes - on_some
        some_lost = writes - on_all
        nodes_agree = on_all == on_some
        return {VALID: nodes_agree and not dirty and not lost,
                "nodes-agree?": nodes_agree,
                "read-count": len(reads),
                "on-all-count": len(on_all),
                "on-some-count": len(on_some),
                "unchecked-count": len(unchecked),
                "not-on-all-count": len(not_on_all),
                "not-on-all": sorted(not_on_all)[:10],
                "dirty-count": len(dirty), "dirty": sorted(dirty)[:10],
                "lost-count": len(lost), "lost": sorted(lost)[:10],
                "some-lost-count": len(some_lost),
                "some-lost": sorted(some_lost)[:10],
                "strong-read-count": len(strong)}

    return FnChecker(check)


def dirty_read_checker() -> checker_ns.Checker:
    """No read may observe a row whose insert aborted (or was never
    acknowledged): reads ∩ (writes - committed-writes) must be empty."""

    def check(test, model, history, opts):
        committed = set()
        aborted = set()
        for op in history:
            if op.f == "insert":
                if op.is_ok:
                    committed.add(op.value)
                elif op.is_fail:
                    aborted.add(op.value)
        dirty = []
        for op in history:
            if op.is_ok and op.f == "read" and op.value is not None:
                seen = set(op.value)
                bad = seen & aborted
                if bad:
                    dirty.append({"op": op.to_dict(),
                                  "dirty": sorted(bad)})
        return {VALID: not dirty, "dirty-reads": dirty[:10],
                "dirty-read-count": len(dirty)}

    return FnChecker(check)


def dirty_read_workload(n: int = 200, stagger: float = 1 / 20,
                        abort_prob: float = 0.3, faulty=None) -> dict:
    state = {"n": 0}
    lock = threading.Lock()

    def insert(test, process):
        with lock:
            v = state["n"]
            state["n"] += 1
        return {"type": "invoke", "f": "insert", "value": v,
                "abort": random.random() < abort_prob}

    store = fakes.FakeTable(faulty=faulty)
    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.mix(
            [insert, r]))),
        "client": fakes.TableClient(store),
        "checker": dirty_read_checker(),
        "model": None,
    }


# --- monotonic (cockroach/monotonic.clj) ------------------------------------

def monotonic_checker() -> checker_ns.Checker:
    """Inserted values carry (val, ts) pairs; timestamp order must agree
    with value (insertion) order — the cockroach monotonic invariant."""

    def check(test, model, history, opts):
        rows = []
        for op in history:
            if op.is_ok and op.f == "insert" and op.value is not None:
                rows.append(op.value)  # (val, ts)
        rows.sort(key=lambda p: p[0])
        anomalies = [
            {"prev": list(a), "next": list(b)}
            for a, b in zip(rows, rows[1:]) if not a[1] < b[1]
        ]
        return {VALID: not anomalies, "anomalies": anomalies[:10],
                "anomaly-count": len(anomalies)}

    return FnChecker(check)


# --- sequential (cockroach/sequential.clj:141-165) --------------------------

def sequential_checker() -> checker_ns.Checker:
    """Writers write key k1 then k2 in order; a reader that observes k2
    must also observe k1 (sequential consistency across keys)."""

    def check(test, model, history, opts):
        bad = []
        for op in history:
            if op.is_ok and op.f == "read" and op.value is not None:
                # value: ordered list of keys written so far observed
                seen = list(op.value)
                expect = list(range(len(seen)))
                if seen != expect:
                    bad.append({"op": op.to_dict(), "saw": seen})
        return {VALID: not bad, "bad-reads": bad[:10]}

    return FnChecker(check)


# --- comments (cockroach/comments.clj:87-147) -------------------------------

def comments_checker() -> checker_ns.Checker:
    """Realtime visibility: if insert A was acknowledged before read R was
    invoked, R must observe A (no "time travelling" comments)."""

    def check(test, model, history, opts):
        acked: list[tuple[int, int]] = []  # (ack index, value)
        pending: dict = {}
        bad = []
        for i, op in enumerate(history):
            if op.f == "insert":
                if op.is_invoke:
                    pending[op.process] = op.value
                elif op.is_ok:
                    v = op.value if op.value is not None \
                        else pending.get(op.process)
                    acked.append((i, v))
                    pending.pop(op.process, None)
            elif op.f == "read":
                if op.is_invoke:
                    pending[(op.process, "r")] = i
                elif op.is_ok and op.value is not None:
                    inv = pending.pop((op.process, "r"), i)
                    seen = set(op.value)
                    must = {v for j, v in acked if j < inv}
                    missing = must - seen
                    if missing:
                        bad.append({"op": op.to_dict(),
                                    "missing": sorted(missing)})
        return {VALID: not bad, "bad-reads": bad[:10]}

    return FnChecker(check)


def monotonic_workload(n: int = 200, stagger: float = 1 / 20,
                       faulty=None) -> dict:
    """Sequential inserts carrying (val, ts); timestamp order must agree
    with insertion order (cockroach/monotonic.clj shape)."""
    import time as time_mod

    class Store:
        def __init__(self):
            self.lock = threading.Lock()
            self.n = 0
            self._flip = 0

        def insert(self):
            with self.lock:
                v = self.n
                self.n += 1
                ts = time_mod.monotonic_ns()
                self._flip += 1
                if faulty == "ts-skew" and self._flip % 9 == 0:
                    ts -= 10 ** 9  # timestamp regression
                return (v, ts)

    store = Store()

    class Client(fakes.FakeClient):
        def invoke(self, test, op: Op) -> Op:
            if op.f == "insert":
                return op.replace(type="ok", value=self.store.insert())
            return op.replace(type="fail", error=f"unknown f {op.f}")

    return {
        "generator": gen.limit(n, gen.stagger(
            stagger, {"type": "invoke", "f": "insert", "value": None})),
        "client": Client(store),
        "checker": monotonic_checker(),
        "model": None,
    }


def sequential_workload(n: int = 200, stagger: float = 1 / 20,
                        faulty=None) -> dict:
    """Writers append globally-sequential keys; a reader must observe a
    prefix (cockroach/sequential.clj key-order shape)."""

    class Store:
        def __init__(self):
            self.lock = threading.Lock()
            self.keys: list = []
            self._n = 0

        def write(self):
            with self.lock:
                self._n += 1
                if faulty == "skip" and self._n % 7 == 0 and self.keys:
                    # Key becomes visible out of order: skip a slot.
                    self.keys.append(len(self.keys) + 1)
                else:
                    self.keys.append(len(self.keys))
                return self.keys[-1]

        def read(self):
            with self.lock:
                return list(self.keys)

    store = Store()

    class Client(fakes.FakeClient):
        def invoke(self, test, op: Op) -> Op:
            if op.f == "write":
                return op.replace(type="ok", value=self.store.write())
            if op.f == "read":
                return op.replace(type="ok", value=self.store.read())
            return op.replace(type="fail", error=f"unknown f {op.f}")

    def write(test, process):
        return {"type": "invoke", "f": "write", "value": None}

    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.mix(
            [write, r]))),
        "client": Client(store),
        "checker": sequential_checker(),
        "model": None,
    }


def comments_workload(n: int = 200, stagger: float = 1 / 20,
                      faulty=None) -> dict:
    """Sequential inserts + reads with the realtime visibility checker
    (cockroach/comments.clj shape): an insert acked before a read began
    must be visible to it."""
    state = {"n": 0}
    lock = threading.Lock()

    class Store:
        def __init__(self):
            self.lock = threading.Lock()
            self.rows: list = []
            self.old: list = []
            self._n = 0

        def insert(self, v):
            with self.lock:
                self.old = list(self.rows)
                self.rows.append(v)

        def read(self):
            with self.lock:
                self._n += 1
                if faulty == "stale" and self._n % 4 == 0:
                    return list(self.old)
                return list(self.rows)

    store = Store()

    class Client(fakes.FakeClient):
        def invoke(self, test, op: Op) -> Op:
            if op.f == "insert":
                self.store.insert(op.value)
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(type="ok", value=self.store.read())
            return op.replace(type="fail", error=f"unknown f {op.f}")

    def insert(test, process):
        with lock:
            v = state["n"]
            state["n"] += 1
        return {"type": "invoke", "f": "insert", "value": v}

    return {
        "generator": gen.limit(n, gen.stagger(stagger, gen.mix(
            [insert, r]))),
        "client": Client(store),
        "checker": comments_checker(),
        "model": None,
    }


REGISTRY = {
    "register": register,
    "single-register": single_register,
    "txn": txn_workload,
    "set": set_workload,
    "queue": queue_workload,
    "counter": counter_workload,
    "lock": lock_workload,
    "ids": ids_workload,
    "bank": bank_workload,
    "dirty-read": dirty_read_workload,
    "monotonic": monotonic_workload,
    "sequential": sequential_workload,
    "comments": comments_workload,
}


def finalize(workload: dict, opts: dict | None = None,
             nemesis_gen=None) -> "gen.Generator":
    """Wire a workload's generator with nemesis schedule, time limit, and
    optional healing + final phase (the hazelcast-test composition,
    hazelcast.clj:403-420)."""
    opts = opts or {}
    g = workload["generator"]
    if nemesis_gen is not None:
        g = gen.nemesis(nemesis_gen, g)
    tl = opts.get("time-limit")
    if tl:
        g = gen.time_limit(tl, g)
    final = workload.get("final_generator")
    if final is not None:
        g = gen.phases(
            g,
            gen.log("Healing cluster"),
            gen.nemesis(gen.once({"type": "info", "f": "stop",
                                  "value": None})),
            gen.clients(final))
    return g
