"""Elasticsearch suite — set + dirty-read
(elasticsearch/src/jepsen/elasticsearch/{core,sets,dirty_read}.clj).

Workloads: concurrent document indexing with a final search, validated
by the set checker (core.clj:190-193), and the dirty-read probe
(dirty_read.clj:112). Nemeses: hammer-time SIGSTOP pauses (core.clj:219)
and the bridge partitioner (core.clj:259). The wire client speaks the
HTTP JSON API directly (the reference used the ES transport client).
"""

from __future__ import annotations

from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

VERSION = "5.0.0"
INDEX = "jepsen"
PORT = 9200


class ElasticsearchDB(common.TarballDB):
    """Tarball + unicast discovery config (core.clj:60-140)."""

    name = "elasticsearch"
    dir = "/opt/elasticsearch"
    binary = "bin/elasticsearch"

    def __init__(self, version: str = VERSION):
        self.version = version
        self.url = (f"https://artifacts.elastic.co/downloads/"
                    f"elasticsearch/elasticsearch-{version}.tar.gz")

    def post_install(self, test, node) -> None:
        from jepsen_tpu import os_debian

        os_debian.install_jdk()
        hosts = ", ".join(f'"{n}"' for n in test["nodes"])
        config = (f"cluster.name: jepsen\n"
                  f"node.name: {node}\n"
                  f"network.host: {node}\n"
                  f"discovery.zen.ping.unicast.hosts: [{hosts}]\n"
                  f"discovery.zen.minimum_master_nodes: "
                  f"{len(test['nodes']) // 2 + 1}\n")
        control.exec_("tee", f"{self.dir}/config/elasticsearch.yml",
                      stdin=config)

    def start_args(self, test, node) -> list:
        return ["-d", "-p", self.pidfile]


class EsSetClient(client_ns.Client):
    """add = index a doc (wait_for refresh), read = match_all search
    (sets.clj operations)."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return EsSetClient(node)

    def _base(self) -> str:
        return f"http://{self.node}:{PORT}"

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                status, body = common.http_json(
                    "PUT",
                    f"{self._base()}/{INDEX}/doc/{op.value}"
                    f"?refresh=wait_for",
                    {"value": op.value}, timeout=10)
                if status in (200, 201):
                    return op.replace(type="ok")
                return op.replace(type="info", error=body)
            if op.f == "read":
                common.http_json("POST", f"{self._base()}/{INDEX}/_refresh",
                                 timeout=30)
                status, body = common.http_json(
                    "POST", f"{self._base()}/{INDEX}/_search",
                    {"size": 10000,  # ES 5.x index.max_result_window cap
                     "query": {"match_all": {}}}, timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                hits = body["hits"]["hits"]
                total = body["hits"].get("total", len(hits))
                if isinstance(total, dict):   # ES 7+ shape
                    total = total.get("value", len(hits))
                if total > len(hits):
                    # Truncated read: acking it would misclassify the
                    # missing acknowledged writes as lost.
                    return op.replace(type="fail",
                                      error=f"truncated: {len(hits)}"
                                            f"/{total}")
                vals = sorted(h["_source"]["value"] for h in hits)
                return op.replace(type="ok", value=vals)
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


class EsDirtyReadClient(client_ns.Client):
    """Dirty-read probe client (dirty_read.clj:30-105): GET by id is
    realtime (can observe in-flight writes), ``_search`` only sees
    refreshed docs — write / read / refresh / strong-read."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return EsDirtyReadClient(node)

    def _base(self) -> str:
        return f"http://{self.node}:{PORT}"

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                status, body = common.http_json(
                    "PUT", f"{self._base()}/{INDEX}/doc/{int(op.value)}",
                    {"value": int(op.value)}, timeout=10)
                if status in (200, 201):
                    return op.replace(type="ok")
                return op.replace(type="info", error=body)
            if op.f == "read":
                status, body = common.http_json(
                    "GET", f"{self._base()}/{INDEX}/doc/{int(op.value)}",
                    timeout=10)
                if status == 200 and body.get("found", False):
                    return op.replace(type="ok")
                if status in (200, 404):
                    return op.replace(type="fail")
                return op.replace(type="fail", error=body)
            if op.f == "refresh":
                status, body = common.http_json(
                    "POST", f"{self._base()}/{INDEX}/_refresh",
                    timeout=60)
                return op.replace(type="ok" if status == 200 else "fail",
                                  error=None if status == 200 else body)
            if op.f == "strong-read":
                status, body = common.http_json(
                    "POST", f"{self._base()}/{INDEX}/_search",
                    {"size": 10000,  # ES 5.x index.max_result_window cap
                     "query": {"match_all": {}}}, timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                hits = body["hits"]["hits"]
                total = body["hits"].get("total", len(hits))
                if isinstance(total, dict):   # ES 7+ shape
                    total = total.get("value", len(hits))
                if total > len(hits):
                    # Truncated read: acking it would misclassify the
                    # missing acknowledged writes as lost.
                    return op.replace(type="fail",
                                      error=f"truncated: {len(hits)}"
                                            f"/{total}")
                vals = sorted(h["_source"]["value"] for h in hits)
                return op.replace(type="ok", value=vals)
        except OSError as e:
            t = "fail" if op.f in ("read", "strong-read") else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def dirty_read_checker():
    """The reference's dirty/lost/stale classification
    (dirty_read.clj:106-157) — the shared strong-read classifier."""
    return workloads.strong_read_classification_checker()


def dirty_read_workload(n: int = 300, writers: int = 2,
                        faulty=None) -> dict:
    """The rw-gen schedule (dirty_read.clj:159-189): writer threads
    index sequential ids, recording the in-flight write per node;
    readers probe the most recent in-flight id on their node. After the
    nemesis heals, every worker refreshes and takes a strong read."""
    import random as _random
    import threading

    from jepsen_tpu import generator as gen

    state = {"n": 0, "in_flight": {}}
    lock = threading.Lock()

    class Store:
        """Fake-mode double with ES visibility: GETs realtime, search
        sees refreshed docs only. faulty="dirty-read" makes some writes
        visible to point reads but never durable (the anomaly the
        reference hunts); faulty="lost" silently drops indexed docs."""

        def __init__(self):
            self.docs: set = set()
            self.dirty: set = set()
            self.refreshed: set = set()
            self.lock = threading.Lock()

        def write(self, v):
            with self.lock:
                if faulty == "dirty-read" and v % 7 == 3:
                    self.dirty.add(v)  # GET-visible, never durable
                    return
                if faulty == "lost" and v % 11 == 5:
                    return  # acked, never anywhere
                self.docs.add(v)

        def read(self, v):
            with self.lock:
                return v in self.docs or v in self.dirty

        def refresh(self):
            with self.lock:
                self.refreshed = set(self.docs)

        def strong_read(self):
            with self.lock:
                return sorted(self.refreshed)

    store = Store()

    class FakeClient(client_ns.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op: Op) -> Op:
            if op.f == "write":
                store.write(op.value)
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(
                    type="ok" if store.read(op.value) else "fail")
            if op.f == "refresh":
                store.refresh()
                return op.replace(type="ok")
            if op.f == "strong-read":
                return op.replace(type="ok", value=store.strong_read())
            return op.replace(type="fail")

    def rw(test, process):
        if not isinstance(process, int):
            return None          # nemesis thread asks when no nemesis gen
        nodes = test.get("nodes") or ["n1"]
        node = nodes[process % len(nodes)]
        with lock:
            if process % max(1, test.get("concurrency", 5)) < writers \
                    or not state["in_flight"]:
                v = state["n"]
                state["n"] += 1
                state["in_flight"][node] = v
                return {"type": "invoke", "f": "write", "value": v}
            v = state["in_flight"].get(
                node, _random.choice(list(state["in_flight"].values())))
            return {"type": "invoke", "f": "read", "value": v}

    return {
        "generator": gen.limit(n, gen.stagger(1 / 10, gen.gen(rw))),
        "final_generator": gen.phases(
            gen.each(lambda: gen.once(
                {"type": "invoke", "f": "refresh", "value": None})),
            gen.each(lambda: gen.once(
                {"type": "invoke", "f": "strong-read", "value": None}))),
        "client": FakeClient(),
        "checker": dirty_read_checker(),
        "model": None,
    }


def test(opts: dict | None = None) -> dict:
    """The elasticsearch test map (core.clj:170-226). ``workload``
    picks "set" (default) or "dirty-read" (dirty_read.clj:191-220);
    ``nemesis`` picks "hammer-time" (default) or "bridge"
    (core.clj:219,259)."""
    opts = dict(opts or {})
    wl_name = opts.pop("workload", None) or "set"
    nem = opts.pop("nemesis", None) or "hammer-time"
    nemesis = (nemesis_ns.hammer_time("java") if nem == "hammer-time"
               else nemesis_ns.partitioner(nemesis_ns.bridge))
    table = {"set": (lambda: workloads.set_workload(), EsSetClient()),
             "dirty-read": (lambda: dirty_read_workload(),
                            EsDirtyReadClient())}
    if wl_name not in table:
        raise ValueError(f"unknown workload {wl_name!r}")
    wl, real_client = table[wl_name]
    return common.suite_test(
        f"elasticsearch {wl_name}" if wl_name != "set"
        else "elasticsearch", opts,
        workload=wl(),
        db=ElasticsearchDB(),
        client=real_client,
        nemesis=nemesis,
        nemesis_gen=common.standard_nemesis_gen(10, 10))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="set",
                       choices=["set", "dirty-read"])
        p.add_argument("--nemesis", default="hammer-time",
                       choices=["hammer-time", "bridge"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
