"""Elasticsearch suite — set + dirty-read
(elasticsearch/src/jepsen/elasticsearch/{core,sets,dirty_read}.clj).

Workloads: concurrent document indexing with a final search, validated
by the set checker (core.clj:190-193), and the dirty-read probe
(dirty_read.clj:112). Nemeses: hammer-time SIGSTOP pauses (core.clj:219)
and the bridge partitioner (core.clj:259). The wire client speaks the
HTTP JSON API directly (the reference used the ES transport client).
"""

from __future__ import annotations

from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

VERSION = "5.0.0"
INDEX = "jepsen"
PORT = 9200


class ElasticsearchDB(common.TarballDB):
    """Tarball + unicast discovery config (core.clj:60-140)."""

    name = "elasticsearch"
    dir = "/opt/elasticsearch"
    binary = "bin/elasticsearch"

    def __init__(self, version: str = VERSION):
        self.version = version
        self.url = (f"https://artifacts.elastic.co/downloads/"
                    f"elasticsearch/elasticsearch-{version}.tar.gz")

    def post_install(self, test, node) -> None:
        from jepsen_tpu import os_debian

        os_debian.install_jdk()
        hosts = ", ".join(f'"{n}"' for n in test["nodes"])
        config = (f"cluster.name: jepsen\n"
                  f"node.name: {node}\n"
                  f"network.host: {node}\n"
                  f"discovery.zen.ping.unicast.hosts: [{hosts}]\n"
                  f"discovery.zen.minimum_master_nodes: "
                  f"{len(test['nodes']) // 2 + 1}\n")
        control.exec_("tee", f"{self.dir}/config/elasticsearch.yml",
                      stdin=config)

    def start_args(self, test, node) -> list:
        return ["-d", "-p", self.pidfile]


class EsSetClient(client_ns.Client):
    """add = index a doc (wait_for refresh), read = match_all search
    (sets.clj operations)."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return EsSetClient(node)

    def _base(self) -> str:
        return f"http://{self.node}:{PORT}"

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                status, body = common.http_json(
                    "PUT",
                    f"{self._base()}/{INDEX}/doc/{op.value}"
                    f"?refresh=wait_for",
                    {"value": op.value}, timeout=10)
                if status in (200, 201):
                    return op.replace(type="ok")
                return op.replace(type="info", error=body)
            if op.f == "read":
                common.http_json("POST", f"{self._base()}/{INDEX}/_refresh",
                                 timeout=30)
                status, body = common.http_json(
                    "POST", f"{self._base()}/{INDEX}/_search",
                    {"size": 10 ** 6,
                     "query": {"match_all": {}}}, timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                vals = sorted(h["_source"]["value"]
                              for h in body["hits"]["hits"])
                return op.replace(type="ok", value=vals)
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def test(opts: dict | None = None) -> dict:
    """The elasticsearch set test map (core.clj:170-226). ``nemesis``
    opt picks "hammer-time" (default) or "bridge" (core.clj:219,259)."""
    opts = dict(opts or {})
    nem = opts.pop("nemesis", None) or "hammer-time"
    nemesis = (nemesis_ns.hammer_time("java") if nem == "hammer-time"
               else nemesis_ns.partitioner(nemesis_ns.bridge))
    return common.suite_test(
        "elasticsearch", opts,
        workload=workloads.set_workload(),
        db=ElasticsearchDB(),
        client=EsSetClient(),
        nemesis=nemesis,
        nemesis_gen=common.standard_nemesis_gen(10, 10))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--nemesis", default="hammer-time",
                       choices=["hammer-time", "bridge"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
