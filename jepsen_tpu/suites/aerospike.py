"""Aerospike suite — CAS register + counter
(aerospike/src/aerospike/core.clj).

Workloads: CAS register checked linearizable (core.clj:530-533) and the
counter (checker/counter, core.clj:556-557). Nemeses:
partition-random-halves (core.clj:533) and node kill/restart via
node-start-stopper (core.clj:488). The reference also ships the repo's
only formal artifact, a TLA+ model (aerospike/spec/aerospike.tla); its
counterpart here is ``spec/cas_register.tla`` at the repo root.

Aerospike speaks a proprietary binary protocol (reference uses the Java
client), so the wire client is gated; no-cluster runs use the fakes.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class AerospikeDB(db_ns.DB, db_ns.LogFiles):
    """Package install + cluster config (core.clj:60-200)."""

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["aerospike-server-community",
                               "aerospike-tools"])
            mesh = "\n".join(
                f"    mesh-seed-address-port {n} 3002"
                for n in test["nodes"])
            config = f"""service {{
  paxos-single-replica-limit 1
  proto-fd-max 15000
}}
network {{
  service {{ address any; port 3000 }}
  heartbeat {{
    mode mesh
    port 3002
{mesh}
    interval 150
    timeout 10
  }}
  fabric {{ port 3001 }}
  info {{ port 3003 }}
}}
namespace jepsen {{
  replication-factor 3
  memory-size 512M
  storage-engine memory
}}
"""
            control.exec_("tee", "/etc/aerospike/aerospike.conf",
                          stdin=config)
            control.exec_("service", "aerospike", "restart")

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "aerospike", "stop", may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/aerospike/aerospike.log"]


def kill_nemesis() -> nemesis_ns.Nemesis:
    """Node kill/restart via start-stopper (core.clj:488): the nemesis
    :start op kills asd on a random node, :stop restarts it."""
    import random

    def kill(test, node):
        control.exec_("killall", "-9", "asd", may_fail=True)
        return ["killed", "asd"]

    def restart(test, node):
        control.exec_("service", "aerospike", "restart", may_fail=True)
        return ["restarted", "asd"]

    return nemesis_ns.node_start_stopper(
        lambda nodes: [random.choice(nodes)], kill, restart)


def test(opts: dict | None = None) -> dict:
    """The aerospike test map (core.clj:500-560). ``workload`` picks
    cas-register (default) or counter; ``nemesis`` partition or kill."""
    opts = dict(opts or {})
    name = opts.pop("workload", None) or "cas-register"
    nem = opts.pop("nemesis", None) or "partition"
    from jepsen_tpu.suites import aerowire

    if name == "cas-register":
        wl = workloads.single_register()
        client = aerowire.RegisterClient()
    else:
        wl = workloads.counter_workload()
        client = aerowire.CounterClient()
    nemesis = nemesis_ns.partition_random_halves() \
        if nem == "partition" else kill_nemesis()
    return common.suite_test(
        f"aerospike {name}", opts,
        workload=wl,
        db=AerospikeDB(),
        client=client,
        nemesis=nemesis,
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="cas-register",
                       choices=["cas-register", "counter"])
        p.add_argument("--nemesis", default="partition",
                       choices=["partition", "kill"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
