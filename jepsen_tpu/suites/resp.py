"""Minimal RESP (REdis Serialization Protocol) client.

Two reference suites speak this wire protocol: disque (the AddJob/GetJob
queue tested by disque.clj via the jedisque Java client) and raftis
(Redis-over-Raft, raftis.clj via carmine). The protocol is simple enough
that a stdlib socket client is the honest TPU-build equivalent of those
driver dependencies — no vendored packages.

RESP2 framing: requests are arrays of bulk strings; replies are simple
strings (+), errors (-), integers (:), bulk strings ($), or arrays (*).
"""

from __future__ import annotations

import socket


class RespError(Exception):
    """Server-reported error reply (the ``-ERR ...`` line)."""


class RespClient:
    def __init__(self, host: str, port: int, timeout: float = 5.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""

    # --- framing -------------------------------------------------------------

    def _read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed mid-reply")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n + 2:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed mid-bulk")
            self.buf += chunk
        data, self.buf = self.buf[:n], self.buf[n + 2:]  # strip CRLF
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None
            return self._read_exact(n).decode(errors="replace")
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None
            return [self._read_reply() for _ in range(n)]
        raise RespError(f"unknown reply type {line!r}")

    # --- public --------------------------------------------------------------

    def call(self, *args):
        """Issue one command (e.g. ``call("SET", "k", "1")``) and return
        the parsed reply. Raises :class:`RespError` on error replies."""
        parts = [b"*%d\r\n" % len(args)]
        for a in args:
            b = a if isinstance(a, bytes) else str(a).encode()
            parts.append(b"$%d\r\n%s\r\n" % (len(b), b))
        self.sock.sendall(b"".join(parts))
        return self._read_reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass
