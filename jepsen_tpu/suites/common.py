"""Shared scaffolding for the DB suites.

Every reference suite repeats the same skeleton (etcd.clj:51-86 is the
cleanest instance): a DB that installs a tarball / package and runs a
daemon, a wire client, a test-map constructor merging ``noop_test`` with
workload + nemesis + checker, and a ``-main`` built from
``cli/single-test-cmd`` + ``serve-cmd``. This module carries the shared
parts so each suite is mostly declaration.

Wire clients use real protocols where the Python stdlib can speak them
(HTTP/JSON, RESP, the PostgreSQL wire protocol); drivers that would need
external packages are *gated*: the client raises
:class:`DriverUnavailable` at open time with instructions, and every
suite can instead run against its in-memory workload fake
(``fake=True``), the pg-local pattern of cockroach.clj:141-152.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import os_debian
from jepsen_tpu.control import util as cu
from jepsen_tpu.tests_support import noop_test


class DriverUnavailable(Exception):
    """Raised at client open time when a suite's wire protocol needs a
    driver that is not vendored (e.g. AMQP, the Mongo wire protocol).
    Runs against real clusters need that driver; no-cluster runs use the
    workload fake instead (``fake=True``)."""


class TarballDB(db_ns.DB, db_ns.LogFiles):
    """DB installed from a release archive and run as a daemon — the etcd
    template (etcd.clj:51-86): install tarball, start daemon with
    per-node flags, teardown = stop + rm -rf.

    Subclasses define :meth:`start_args` (daemon argv) and may override
    :meth:`post_install` / :meth:`await_ready`.
    """

    name = "db"
    url: str | None = None          # release archive URL
    dir = "/opt/jepsen/db"
    binary = "db"

    @property
    def logfile(self):
        return f"{self.dir}/{self.name}.log"

    @property
    def pidfile(self):
        return f"{self.dir}/{self.name}.pid"

    def start_args(self, test, node) -> list:
        raise NotImplementedError

    def post_install(self, test, node) -> None:
        pass

    def await_ready(self, test, node) -> None:
        pass

    def setup(self, test, node) -> None:
        with control.su():
            if self.url:
                cu.install_archive(self.url, self.dir)
            self.post_install(test, node)
            cu.start_daemon(f"{self.dir}/{self.binary}",
                            *self.start_args(test, node),
                            logfile=self.logfile, pidfile=self.pidfile,
                            chdir=self.dir)
        self.await_ready(test, node)

    def teardown(self, test, node) -> None:
        with control.su():
            cu.stop_daemon(self.pidfile, binary=self.binary)
            control.exec_("rm", "-rf", self.dir, may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return [self.logfile]

    # start/stop used by kill/restart nemeses (node_start_stopper)
    def start(self, test, node) -> None:
        with control.su():
            cu.start_daemon(f"{self.dir}/{self.binary}",
                            *self.start_args(test, node),
                            logfile=self.logfile, pidfile=self.pidfile,
                            chdir=self.dir)

    def stop(self, test, node) -> None:
        with control.su():
            cu.stop_daemon(self.pidfile, binary=self.binary)


def http_json(method: str, url: str, body=None, timeout: float = 5.0,
              headers=None) -> tuple[int, dict | list | str | None]:
    """Tiny HTTP/JSON helper for the suites whose DB speaks HTTP (etcd,
    consul, elasticsearch, crate, chronos). Returns (status, parsed)."""
    data = None
    hdrs = dict(headers or {})
    if body is not None:
        if isinstance(body, (dict, list)):
            data = json.dumps(body).encode()
            hdrs.setdefault("Content-Type", "application/json")
        elif isinstance(body, str):
            data = body.encode()
            hdrs.setdefault("Content-Type",
                            "application/x-www-form-urlencoded")
        else:
            data = body
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            raw = resp.read().decode()
            status = resp.status
    except urllib.error.HTTPError as e:
        raw = e.read().decode()
        status = e.code
    try:
        return status, json.loads(raw) if raw else None
    except json.JSONDecodeError:
        return status, raw


class WireIndeterminate(ConnectionError):
    """The connection died mid-exchange — after the request may have
    reached the server. The op's outcome is INDETERMINATE: clients
    must complete it ``:info``, never ``:fail`` (an op recorded
    ``:fail`` is excluded from the search, so a ``:fail`` that
    actually applied makes the checker unsound — SURVEY.md and the
    reference's client contract, etcd.clj:112-125)."""


class ReconnectExhausted(ConnectionError):
    """The bounded reconnect budget ran out before a connection was
    re-established. Raised BEFORE any request is sent, so the op
    never reached the server — clients may complete it ``:fail``."""


class SocketIO:
    """Buffered exact-read over a stream socket — the framing loop every
    wire client needs (one shared copy instead of one per protocol) —
    plus BOUNDED RECONNECT with exponential backoff: constructed with a
    ``connect`` factory, a dead connection is re-established at the
    next op (never mid-exchange: silently re-sending a request that
    may already have applied could double-apply a mutator). A send or
    read that fails mid-exchange marks the connection dead and raises
    :class:`WireIndeterminate`; the NEXT op's :meth:`ensure_connected`
    runs the retry/backoff ladder (protocols with a session handshake
    re-run it via the True return — see suites.zkwire).

    ``JEPSEN_TPU_WIRE_RETRIES`` / ``JEPSEN_TPU_WIRE_BACKOFF_S``
    override the per-instance defaults (doc/env.md)."""

    def __init__(self, sock=None, *, connect=None, retries=None,
                 backoff=None):
        from jepsen_tpu.util import env_float, env_int

        self.sock = sock
        self._connect = connect
        self.retries = retries if retries is not None else \
            env_int("JEPSEN_TPU_WIRE_RETRIES", 4)
        self.backoff = backoff if backoff is not None else \
            env_float("JEPSEN_TPU_WIRE_BACKOFF_S", 0.05)
        self.reconnects = 0
        self.buf = b""
        if self.sock is None and connect is not None:
            self.ensure_connected()

    def ensure_connected(self) -> bool:
        """Connect (or reconnect) if the connection is dead; bounded
        retries with exponential backoff. Returns True when a FRESH
        socket was established (the caller re-runs any session
        handshake), False when the existing connection stands. Raises
        :class:`ReconnectExhausted` when the budget runs out."""
        import time

        if self.sock is not None:
            return False
        if self._connect is None:
            raise ReconnectExhausted(
                "connection closed and no reconnect factory")
        delay = self.backoff
        last: Exception | None = None
        for attempt in range(self.retries + 1):
            try:
                self.sock = self._connect()
                self.buf = b""
                self.reconnects += 1
                return True
            except OSError as e:
                last = e
                if attempt < self.retries:
                    time.sleep(delay)
                    delay *= 2
        raise ReconnectExhausted(
            f"reconnect budget ({self.retries + 1} attempts) "
            f"exhausted: {last!r}")

    def mark_dead(self) -> None:
        if self.sock is not None:
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None
        self.buf = b""

    def read_exact(self, n: int) -> bytes:
        if self.sock is None:
            # Marked dead by an earlier op. Reconnect is the CLIENT's
            # per-op job (ensure_connected + its session handshake);
            # raising a ConnectionError subclass here keeps factory-
            # less legacy clients on their pre-reconnect behavior
            # (suites catch ConnectionError, not AttributeError).
            raise ReconnectExhausted(
                "connection closed (reconnect via ensure_connected)")
        try:
            while len(self.buf) < n:
                chunk = self.sock.recv(65536)
                if not chunk:
                    raise ConnectionError("connection closed")
                self.buf += chunk
        except (ConnectionError, OSError) as e:
            # Mid-exchange death: a request is in flight, so the op
            # outcome is indeterminate. The connection is marked dead
            # so the NEXT op reconnects.
            self.mark_dead()
            raise WireIndeterminate(
                f"connection lost awaiting reply: {e!r}") from e
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def send(self, data: bytes) -> None:
        if self.sock is None:
            # See read_exact: never silently re-dial here — a raw
            # reconnect would skip the protocol's session handshake.
            raise ReconnectExhausted(
                "connection closed (reconnect via ensure_connected)")
        try:
            self.sock.sendall(data)
        except (ConnectionError, OSError) as e:
            # A partial sendall may still have delivered the request:
            # indeterminate, same as a lost reply.
            self.mark_dead()
            raise WireIndeterminate(
                f"connection lost sending request: {e!r}") from e

    def close(self) -> None:
        if self.sock is not None:
            self.sock.close()


class GatedClient(client_ns.Client):
    """Client for a wire protocol whose driver isn't vendored: fails
    loudly at open() with the reason, rather than silently faking."""

    def __init__(self, reason: str):
        self.reason = reason

    def open(self, test, node):
        raise DriverUnavailable(self.reason)

    def invoke(self, test, op):
        raise DriverUnavailable(self.reason)


def suite_test(name: str, opts: dict | None = None, *,
               workload: dict, nemesis=None, nemesis_gen=None,
               db=None, client=None, os=None, extra=None) -> dict:
    """Assemble a suite test map: noop_test <- suite components <- opts
    (the merge order of etcd.clj:149-179).

    ``workload`` is a workload map (jepsen_tpu.suites.workloads). With
    ``opts={"fake": True}`` (or no client given) the workload's fake
    client is used, making the test runnable with the dummy transport.
    """
    from jepsen_tpu import checker as checker_ns
    from jepsen_tpu.suites import workloads as wl

    opts = dict(opts or {})
    fake = opts.pop("fake", client is None)

    checker = checker_ns.compose({
        "perf": checker_ns.perf(),
        "workload": workload["checker"],
    })

    test = noop_test(
        name=name,
        client=workload["client"] if fake else client,
        model=workload.get("model"),
        checker=checker,
        generator=wl.finalize(workload, opts, nemesis_gen=nemesis_gen),
    )
    if not fake:
        # Real-cluster components; omitted keys fall back to core's noops.
        for key, v in (("os", os or os_debian.os), ("db", db),
                       ("nemesis", nemesis)):
            if v is not None:
                test[key] = v
    if extra:
        test.update(extra)
    test.update(opts)
    if fake:
        # No-cluster run: the dummy transport records control commands
        # instead of SSHing (control.clj:15 *dummy*), regardless of any
        # --transport flag that rode in through opts.
        test["transport"] = "dummy"
        test["nemesis"] = None
    return test


def standard_nemesis_gen(start_sleep: float = 5.0, stop_sleep: float = 5.0):
    """The ubiquitous start/stop fault schedule (etcd.clj:173-178)."""
    from jepsen_tpu import generator as gen

    def cycle():
        while True:
            yield gen.sleep(start_sleep)
            yield {"type": "info", "f": "start", "value": None}
            yield gen.sleep(stop_sleep)
            yield {"type": "info", "f": "stop", "value": None}

    return gen.seq(cycle())
