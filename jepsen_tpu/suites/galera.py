"""Galera (MariaDB cluster) suite — bank set + dirty reads
(galera/src/jepsen/galera.clj + galera/dirty_reads.clj).

Workloads: the bank-style invariant set (galera.clj:256-258) and the
dirty-reads probe (dirty_reads.clj:77): readers must never observe rows
from aborted transactions. Nemesis: partition-random-halves
(galera.clj:195). DB install provisions mariadb-server with a wsrep
cluster address over all nodes (galera.clj:40-150). The client speaks
the MySQL wire protocol natively (jepsen_tpu.suites.mysqlwire) where the
reference uses JDBC.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class GaleraDB(db_ns.DB, db_ns.LogFiles):
    """mariadb + wsrep cluster config (galera.clj:40-150)."""

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["mariadb-server", "galera-3", "rsync"])
            cluster = ",".join(test["nodes"])
            config = f"""[mysqld]
bind-address=0.0.0.0
wsrep_on=ON
wsrep_provider=/usr/lib/galera/libgalera_smm.so
wsrep_cluster_address=gcomm://{cluster}
wsrep_cluster_name=jepsen
wsrep_node_address={node}
wsrep_sst_method=rsync
binlog_format=ROW
default_storage_engine=InnoDB
innodb_autoinc_lock_mode=2
"""
            control.exec_("tee", "/etc/mysql/conf.d/galera.cnf",
                          stdin=config)
            if node == test["nodes"][0]:
                control.exec_("galera_new_cluster", may_fail=True)
            else:
                control.exec_("service", "mysql", "restart")

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "mysql", "stop", may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/mysql/error.log"]


def test(opts: dict | None = None) -> dict:
    """The galera test map (galera.clj:240-270). ``workload`` picks
    bank (default) or dirty-reads."""
    from jepsen_tpu.suites import mysql_clients

    opts = dict(opts or {})
    name = opts.pop("workload", None) or "bank"
    wl, client = mysql_clients.bank_or_dirty_reads(name)
    return common.suite_test(
        f"galera {name}", opts,
        workload=wl,
        db=GaleraDB(),
        client=client,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="bank",
                       choices=["bank", "dirty-reads", "txn"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
