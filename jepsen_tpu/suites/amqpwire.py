"""Minimal AMQP 0-9-1 client for the RabbitMQ suite.

The reference drives RabbitMQ through Langohr
(rabbitmq/src/jepsen/rabbitmq.clj:100-170); the TPU build speaks AMQP
0-9-1 from the stdlib: protocol header, PLAIN authentication over the
Connection.Start/Tune/Open negotiation, one channel, ``queue.declare``,
``basic.publish`` (method + content-header + body frames), and
synchronous ``basic.get`` — the enqueue/dequeue/drain surface the
total-queue workload needs.

Framing: ``type:1 channel:2 size:4 payload frame-end:0xCE`` (all
big-endian); method payloads are ``class:2 method:2 args``. Only the
argument shapes these six methods use are implemented; field tables are
sent empty and skipped on receipt.
"""

from __future__ import annotations

import socket
import struct

from jepsen_tpu import client as client_ns
from jepsen_tpu.suites.common import SocketIO

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE


class AmqpError(Exception):
    pass


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


class AmqpClient:
    def __init__(self, host: str, port: int = 5672, user: str = "guest",
                 password: str = "guest", vhost: str = "/",
                 timeout: float = 10.0):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.io.send(b"AMQP\x00\x00\x09\x01")
        self._negotiate(user, password, vhost)
        self._channel_open()

    # --- framing -------------------------------------------------------------

    def _read_frame(self) -> tuple[int, int, bytes]:
        t, ch, size = struct.unpack(">BHI", self.io.read_exact(7))
        payload = self.io.read_exact(size)
        if self.io.read_exact(1) != bytes([FRAME_END]):
            raise AmqpError("bad frame end")
        return t, ch, payload

    def _send_frame(self, t: int, ch: int, payload: bytes) -> None:
        self.io.send(struct.pack(">BHI", t, ch, len(payload))
                     + payload + bytes([FRAME_END]))

    def _send_method(self, ch: int, class_id: int, method_id: int,
                     args: bytes) -> None:
        self._send_frame(FRAME_METHOD, ch,
                         struct.pack(">HH", class_id, method_id) + args)

    def _expect_method(self, class_id: int, method_id: int) -> bytes:
        """Read frames until the given method arrives; heartbeats are
        answered, Connection.Close / Channel.Close raise."""
        while True:
            t, ch, payload = self._read_frame()
            if t == FRAME_HEARTBEAT:
                self._send_frame(FRAME_HEARTBEAT, 0, b"")
                continue
            if t != FRAME_METHOD:
                raise AmqpError(f"unexpected frame type {t}")
            cid, mid = struct.unpack_from(">HH", payload, 0)
            if (cid, mid) == (10, 50) or (cid, mid) == (20, 40):
                code, = struct.unpack_from(">H", payload, 4)
                raise AmqpError(f"server closed ({cid}.{mid}) code {code}")
            if (cid, mid) == (class_id, method_id):
                return payload[4:]

    # --- connection negotiation ----------------------------------------------

    def _negotiate(self, user: str, password: str, vhost: str) -> None:
        self._expect_method(10, 10)                   # Connection.Start
        plain = _longstr(f"\x00{user}\x00{password}".encode())
        args = (b"\x00\x00\x00\x00"                   # empty client props
                + _shortstr("PLAIN") + plain + _shortstr("en_US"))
        self._send_method(0, 10, 11, args)            # Start-Ok
        tune = self._expect_method(10, 30)            # Tune
        channel_max, frame_max, heartbeat = struct.unpack_from(
            ">HIH", tune, 0)
        self.frame_max = frame_max or (1 << 20)
        self._send_method(0, 10, 31, struct.pack(     # Tune-Ok
            ">HIH", channel_max, self.frame_max, 0))
        self._send_method(0, 10, 40,                  # Open
                          _shortstr(vhost) + _shortstr("") + b"\x00")
        self._expect_method(10, 41)                   # Open-Ok

    def _channel_open(self) -> None:
        self._send_method(1, 20, 10, _shortstr(""))   # Channel.Open
        self._expect_method(20, 11)

    # --- the queue surface ---------------------------------------------------

    def confirm_select(self) -> None:
        """Enable publisher confirms (the reference's Langohr client
        publishes confirmed): every publish then blocks on basic.ack, so
        an \"ok\" enqueue really is in the broker."""
        self._send_method(1, 85, 10, b"\x00")         # Confirm.Select
        self._expect_method(85, 11)
        self.confirms = True

    def queue_declare(self, queue: str, durable: bool = True) -> None:
        bits = 0x02 if durable else 0
        args = (struct.pack(">H", 0) + _shortstr(queue) + bytes([bits])
                + b"\x00\x00\x00\x00")                # empty arguments
        self._send_method(1, 50, 10, args)
        self._expect_method(50, 11)                   # Declare-Ok

    confirms = False

    def publish(self, queue: str, body: bytes,
                persistent: bool = True) -> None:
        args = (struct.pack(">H", 0) + _shortstr("")  # default exchange
                + _shortstr(queue) + b"\x00")
        self._send_method(1, 60, 40, args)            # Basic.Publish
        # Content header: class, weight, body size, flags, delivery-mode
        props = struct.pack(">HHQH", 60, 0, len(body), 0x1000) \
            + bytes([2 if persistent else 1])
        self._send_frame(FRAME_HEADER, 1, props)
        self._send_frame(FRAME_BODY, 1, body)
        if self.confirms:
            while True:                               # await Ack/Nack
                t, _, payload = self._read_frame()
                if t == FRAME_HEARTBEAT:
                    self._send_frame(FRAME_HEARTBEAT, 0, b"")
                    continue
                cid, mid = struct.unpack_from(">HH", payload, 0)
                if (cid, mid) == (60, 80):            # Basic.Ack
                    return
                if (cid, mid) == (60, 120):           # Basic.Nack
                    raise AmqpError("broker nacked publish")
                if (cid, mid) in ((10, 50), (20, 40)):
                    raise AmqpError(f"server closed ({cid}.{mid})")

    def get(self, queue: str, auto_ack: bool = True) \
            -> tuple[int, bytes] | None:
        """Synchronous Basic.Get in MANUAL-ack mode: the broker keeps the
        message as an unacked delivery until we ack it, so a connection
        death between delivery and ack redelivers instead of silently
        destroying data (the reference consumes with manual acks for
        exactly this). ``auto_ack=True`` acks once the body is fully
        read; ``auto_ack=False`` leaves the delivery held (the
        message-holding mutex). Returns (delivery_tag, body) or None."""
        args = struct.pack(">H", 0) + _shortstr(queue) + b"\x00"
        self._send_method(1, 60, 70, args)
        while True:
            t, ch, payload = self._read_frame()
            if t == FRAME_HEARTBEAT:
                self._send_frame(FRAME_HEARTBEAT, 0, b"")
                continue
            if t != FRAME_METHOD:
                raise AmqpError(f"unexpected frame type {t}")
            cid, mid = struct.unpack_from(">HH", payload, 0)
            if (cid, mid) == (60, 72):                # Get-Empty
                return None
            if (cid, mid) == (60, 71):                # Get-Ok
                (tag,) = struct.unpack_from(">Q", payload, 4)
                break
            if mid in (40, 50):
                raise AmqpError(f"server closed ({cid}.{mid})")
        t, _, header = self._read_frame()
        if t != FRAME_HEADER:
            raise AmqpError("expected content header")
        (size,) = struct.unpack_from(">Q", header, 4)
        body = b""
        while len(body) < size:
            t, _, part = self._read_frame()
            if t != FRAME_BODY:
                raise AmqpError("expected content body")
            body += part
        if auto_ack:
            self.ack(tag)
        return tag, body

    def ack(self, delivery_tag: int) -> None:
        self._send_method(1, 60, 80,                  # Basic.Ack
                          struct.pack(">QB", delivery_tag, 0))

    def reject(self, delivery_tag: int, requeue: bool = True) -> None:
        self._send_method(1, 60, 90,                  # Basic.Reject
                          struct.pack(">QB", delivery_tag,
                                      1 if requeue else 0))

    def close(self) -> None:
        try:
            self._send_method(0, 10, 50,              # Connection.Close
                              struct.pack(">HHH", 200, 0, 0) + b"\x00")
            self.io.close()
        except OSError:
            pass


class QueueClient(client_ns.Client):
    """Enqueue/dequeue/drain over one AMQP queue (rabbitmq.clj:100-170):
    confirmed persistent publishes, manually-acked synchronous gets."""

    QUEUE = "jepsen.queue"
    DRAIN_RETRIES = 10

    def __init__(self, conn: AmqpClient | None = None, node=None):
        self.conn = conn
        self.node = node

    def _connect(self, node):
        c = AmqpClient(node)
        c.queue_declare(self.QUEUE)
        c.confirm_select()
        return c

    def open(self, test, node):
        return QueueClient(self._connect(node), node)

    def setup(self, test) -> None:
        # Purge leftovers from a previous run against the same durable
        # queue, or run 2 would "unexpectedly" dequeue run 1's values.
        conn = self._connect(test["nodes"][0])
        try:
            while conn.get(self.QUEUE) is not None:
                pass
        finally:
            conn.close()

    def invoke(self, test, op):
        try:
            if op.f == "enqueue":
                self.conn.publish(self.QUEUE, str(op.value).encode())
                return op.replace(type="ok")
            if op.f == "dequeue":
                r = self.conn.get(self.QUEUE)
                if r is None:
                    return op.replace(type="fail")
                return op.replace(type="ok", value=int(r[1]))
            if op.f == "drain":
                return self._drain(op)
        except (AmqpError, OSError, ConnectionError) as e:
            # Indeterminate: an unconfirmed publish may still land, and
            # an unacked delivery is redelivered rather than lost.
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def _drain(self, op):
        """Drain with reconnect-retry: the total-queue checker cannot
        interpret a crashed drain (checker.clj raises on it), so every
        failure — get OR reconnect — consumes retry budget, the dead
        connection is closed first (the broker then requeues its unacked
        delivery instead of hiding it from the new connection), and only
        exhaustion propagates."""
        import time

        drained = []
        attempts = 0
        while True:
            try:
                r = self.conn.get(self.QUEUE)
                if r is None:
                    return op.replace(type="ok", value=drained)
                drained.append(int(r[1]))
            except (AmqpError, OSError, ConnectionError) as e:
                attempts += 1
                if attempts > self.DRAIN_RETRIES:
                    # The values drained so far are ACKED — permanently
                    # consumed — so they must be reported as dequeued or
                    # the checker counts them lost. Completing ok with
                    # the partial list (plus an error note for the
                    # reader) is the only shape the total-queue checker
                    # can digest; messages genuinely still enqueued will
                    # show as lost, which is the honest upper bound.
                    return op.replace(type="ok", value=drained,
                                      error=f"partial drain: {e!r}")
                try:
                    self.conn.close()
                except Exception:
                    pass
                time.sleep(0.5)
                try:
                    self.conn = self._connect(self.node)
                except (AmqpError, OSError, ConnectionError):
                    continue        # reconnect failure burns budget too

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class MutexClient(client_ns.Client):
    """The message-holding semaphore mutex (rabbitmq.clj:263): one token
    message circulates; acquire = get the token WITHOUT acking (the held
    unacked delivery IS the lock), release = basic.reject with requeue.
    A holder's death closes its channel and the broker requeues the
    token automatically — a crashed holder cannot destroy the lock."""

    QUEUE = "jepsen.mutex"

    def __init__(self, conn: AmqpClient | None = None):
        self.conn = conn
        self.held_tag: int | None = None

    def open(self, test, node):
        c = AmqpClient(node)
        c.queue_declare(self.QUEUE)
        c.confirm_select()
        return MutexClient(c)

    def setup(self, test) -> None:
        conn = AmqpClient(test["nodes"][0])
        try:
            conn.queue_declare(self.QUEUE)
            while conn.get(self.QUEUE) is not None:
                pass                     # drain stale tokens from reruns
            conn.confirm_select()
            conn.publish(self.QUEUE, b"token")
        finally:
            conn.close()

    def invoke(self, test, op):
        try:
            if op.f == "acquire":
                if self.held_tag is not None:
                    return op.replace(type="fail", error="already held")
                r = self.conn.get(self.QUEUE, auto_ack=False)
                if r is None:
                    return op.replace(type="fail")
                self.held_tag = r[0]
                return op.replace(type="ok")
            if op.f == "release":
                if self.held_tag is None:
                    return op.replace(type="fail", error="not held")
                self.conn.reject(self.held_tag, requeue=True)
                self.held_tag = None
                return op.replace(type="ok")
        except (AmqpError, OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
