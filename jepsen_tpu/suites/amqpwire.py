"""Minimal AMQP 0-9-1 client for the RabbitMQ suite.

The reference drives RabbitMQ through Langohr
(rabbitmq/src/jepsen/rabbitmq.clj:100-170); the TPU build speaks AMQP
0-9-1 from the stdlib: protocol header, PLAIN authentication over the
Connection.Start/Tune/Open negotiation, one channel, ``queue.declare``,
``basic.publish`` (method + content-header + body frames), and
synchronous ``basic.get`` — the enqueue/dequeue/drain surface the
total-queue workload needs.

Framing: ``type:1 channel:2 size:4 payload frame-end:0xCE`` (all
big-endian); method payloads are ``class:2 method:2 args``. Only the
argument shapes these six methods use are implemented; field tables are
sent empty and skipped on receipt.
"""

from __future__ import annotations

import socket
import struct

from jepsen_tpu import client as client_ns

FRAME_METHOD = 1
FRAME_HEADER = 2
FRAME_BODY = 3
FRAME_HEARTBEAT = 8
FRAME_END = 0xCE


class AmqpError(Exception):
    pass


def _shortstr(s: str) -> bytes:
    b = s.encode()
    return bytes([len(b)]) + b


def _longstr(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


class AmqpClient:
    def __init__(self, host: str, port: int = 5672, user: str = "guest",
                 password: str = "guest", vhost: str = "/",
                 timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.buf = b""
        self.sock.sendall(b"AMQP\x00\x00\x09\x01")
        self._negotiate(user, password, vhost)
        self._channel_open()

    # --- framing -------------------------------------------------------------

    def _read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def _read_frame(self) -> tuple[int, int, bytes]:
        t, ch, size = struct.unpack(">BHI", self._read_exact(7))
        payload = self._read_exact(size)
        if self._read_exact(1) != bytes([FRAME_END]):
            raise AmqpError("bad frame end")
        return t, ch, payload

    def _send_frame(self, t: int, ch: int, payload: bytes) -> None:
        self.sock.sendall(struct.pack(">BHI", t, ch, len(payload))
                          + payload + bytes([FRAME_END]))

    def _send_method(self, ch: int, class_id: int, method_id: int,
                     args: bytes) -> None:
        self._send_frame(FRAME_METHOD, ch,
                         struct.pack(">HH", class_id, method_id) + args)

    def _expect_method(self, class_id: int, method_id: int) -> bytes:
        """Read frames until the given method arrives; heartbeats are
        answered, Connection.Close / Channel.Close raise."""
        while True:
            t, ch, payload = self._read_frame()
            if t == FRAME_HEARTBEAT:
                self._send_frame(FRAME_HEARTBEAT, 0, b"")
                continue
            if t != FRAME_METHOD:
                raise AmqpError(f"unexpected frame type {t}")
            cid, mid = struct.unpack_from(">HH", payload, 0)
            if (cid, mid) == (10, 50) or (cid, mid) == (20, 40):
                code, = struct.unpack_from(">H", payload, 4)
                raise AmqpError(f"server closed ({cid}.{mid}) code {code}")
            if (cid, mid) == (class_id, method_id):
                return payload[4:]

    # --- connection negotiation ----------------------------------------------

    def _negotiate(self, user: str, password: str, vhost: str) -> None:
        self._expect_method(10, 10)                   # Connection.Start
        plain = _longstr(f"\x00{user}\x00{password}".encode())
        args = (b"\x00\x00\x00\x00"                   # empty client props
                + _shortstr("PLAIN") + plain + _shortstr("en_US"))
        self._send_method(0, 10, 11, args)            # Start-Ok
        tune = self._expect_method(10, 30)            # Tune
        channel_max, frame_max, heartbeat = struct.unpack_from(
            ">HIH", tune, 0)
        self.frame_max = frame_max or (1 << 20)
        self._send_method(0, 10, 31, struct.pack(     # Tune-Ok
            ">HIH", channel_max, self.frame_max, 0))
        self._send_method(0, 10, 40,                  # Open
                          _shortstr(vhost) + _shortstr("") + b"\x00")
        self._expect_method(10, 41)                   # Open-Ok

    def _channel_open(self) -> None:
        self._send_method(1, 20, 10, _shortstr(""))   # Channel.Open
        self._expect_method(20, 11)

    # --- the queue surface ---------------------------------------------------

    def confirm_select(self) -> None:
        """Enable publisher confirms (the reference's Langohr client
        publishes confirmed): every publish then blocks on basic.ack, so
        an \"ok\" enqueue really is in the broker."""
        self._send_method(1, 85, 10, b"\x00")         # Confirm.Select
        self._expect_method(85, 11)
        self.confirms = True

    def queue_declare(self, queue: str, durable: bool = True) -> None:
        bits = 0x02 if durable else 0
        args = (struct.pack(">H", 0) + _shortstr(queue) + bytes([bits])
                + b"\x00\x00\x00\x00")                # empty arguments
        self._send_method(1, 50, 10, args)
        self._expect_method(50, 11)                   # Declare-Ok

    confirms = False

    def publish(self, queue: str, body: bytes,
                persistent: bool = True) -> None:
        args = (struct.pack(">H", 0) + _shortstr("")  # default exchange
                + _shortstr(queue) + b"\x00")
        self._send_method(1, 60, 40, args)            # Basic.Publish
        # Content header: class, weight, body size, flags, delivery-mode
        props = struct.pack(">HHQH", 60, 0, len(body), 0x1000) \
            + bytes([2 if persistent else 1])
        self._send_frame(FRAME_HEADER, 1, props)
        self._send_frame(FRAME_BODY, 1, body)
        if self.confirms:
            while True:                               # await Ack/Nack
                t, _, payload = self._read_frame()
                if t == FRAME_HEARTBEAT:
                    self._send_frame(FRAME_HEARTBEAT, 0, b"")
                    continue
                cid, mid = struct.unpack_from(">HH", payload, 0)
                if (cid, mid) == (60, 80):            # Basic.Ack
                    return
                if (cid, mid) == (60, 120):           # Basic.Nack
                    raise AmqpError("broker nacked publish")
                if (cid, mid) in ((10, 50), (20, 40)):
                    raise AmqpError(f"server closed ({cid}.{mid})")

    def get(self, queue: str) -> bytes | None:
        """Synchronous Basic.Get with auto-ack; None when empty."""
        args = struct.pack(">H", 0) + _shortstr(queue) + b"\x01"  # no-ack
        self._send_method(1, 60, 70, args)
        while True:
            t, ch, payload = self._read_frame()
            if t == FRAME_HEARTBEAT:
                self._send_frame(FRAME_HEARTBEAT, 0, b"")
                continue
            if t != FRAME_METHOD:
                raise AmqpError(f"unexpected frame type {t}")
            cid, mid = struct.unpack_from(">HH", payload, 0)
            if (cid, mid) == (60, 72):                # Get-Empty
                return None
            if (cid, mid) == (60, 71):                # Get-Ok
                break
            if mid in (40, 50):
                raise AmqpError(f"server closed ({cid}.{mid})")
        t, _, header = self._read_frame()
        if t != FRAME_HEADER:
            raise AmqpError("expected content header")
        (size,) = struct.unpack_from(">Q", header, 4)
        body = b""
        while len(body) < size:
            t, _, part = self._read_frame()
            if t != FRAME_BODY:
                raise AmqpError("expected content body")
            body += part
        return body

    def close(self) -> None:
        try:
            self._send_method(0, 10, 50,              # Connection.Close
                              struct.pack(">HHH", 200, 0, 0) + b"\x00")
            self.sock.close()
        except OSError:
            pass


class QueueClient(client_ns.Client):
    """Enqueue/dequeue/drain over one AMQP queue (rabbitmq.clj:100-170):
    publish persistent messages, consume with synchronous basic.get."""

    QUEUE = "jepsen.queue"

    def __init__(self, conn: AmqpClient | None = None):
        self.conn = conn

    def open(self, test, node):
        c = AmqpClient(node)
        c.queue_declare(self.QUEUE)
        c.confirm_select()
        return QueueClient(c)

    def invoke(self, test, op):
        try:
            if op.f == "enqueue":
                self.conn.publish(self.QUEUE, str(op.value).encode())
                return op.replace(type="ok")
            if op.f == "dequeue":
                body = self.conn.get(self.QUEUE)
                if body is None:
                    return op.replace(type="fail")
                return op.replace(type="ok", value=int(body))
            if op.f == "drain":
                drained = []
                while True:
                    body = self.conn.get(self.QUEUE)
                    if body is None:
                        break
                    drained.append(int(body))
                return op.replace(type="ok", value=drained)
        except (AmqpError, OSError, ConnectionError) as e:
            # All indeterminate: an unconfirmed publish may still land,
            # and a no-ack get may have consumed a message the broker
            # already removed — neither may claim "no effect".
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class MutexClient(client_ns.Client):
    """The message-holding semaphore mutex (rabbitmq.clj:263): one token
    message circulates; acquire = consume it (hold), release = publish
    it back. A successful get IS the lock acquisition."""

    QUEUE = "jepsen.mutex"

    def __init__(self, conn: AmqpClient | None = None):
        self.conn = conn
        self.holding = False

    def open(self, test, node):
        c = AmqpClient(node)
        c.queue_declare(self.QUEUE)
        c.confirm_select()
        return MutexClient(c)

    def setup(self, test) -> None:
        conn = AmqpClient(test["nodes"][0])
        try:
            conn.queue_declare(self.QUEUE)
            while conn.get(self.QUEUE) is not None:
                pass                     # drain stale tokens from reruns
            conn.publish(self.QUEUE, b"token")
        finally:
            conn.close()

    def invoke(self, test, op):
        try:
            if op.f == "acquire":
                if self.holding:
                    return op.replace(type="fail", error="already held")
                body = self.conn.get(self.QUEUE)
                if body is None:
                    return op.replace(type="fail")
                self.holding = True
                return op.replace(type="ok")
            if op.f == "release":
                if not self.holding:
                    return op.replace(type="fail", error="not held")
                self.conn.publish(self.QUEUE, b"token")
                self.holding = False
                return op.replace(type="ok")
        except (AmqpError, OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
