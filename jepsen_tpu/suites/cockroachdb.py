"""CockroachDB suite — the multi-workload, multi-nemesis runner
(cockroachdb/src/jepsen/cockroach/*.clj, the reference's richest suite).

Registries mirror cockroach/runner.clj:25-57: a **test registry**
(bank, bank-multitable, comments, register, monotonic, sets, sequential,
g2) crossed with a **nemesis registry** (none, parts, majring, clock
skews at five magnitudes, strobe-skews, split, start-stop-2,
start-kill-2), composable pairwise the way runner.clj:94-110 builds a
cartesian product of --nemesis × --nemesis2.

The wire client speaks the PostgreSQL protocol
(:mod:`jepsen_tpu.suites.pgwire`) with cockroach/client.clj's
serialization-retry semantics; register and bank run real SQL, the rest
run no-cluster against their workload fakes (the reference's
``--jdbc-mode pg-local`` seam, cockroach.clj:141-152).
"""

from __future__ import annotations

import random

from jepsen_tpu import adya
from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import independent
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import nemesis_time
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads
from jepsen_tpu.suites.pgwire import PgClient, PgError

VERSION = "v1.0"
PORT = 26257


PCAP_LOG = "/opt/cockroach/trace.pcap"
TCPDUMP_PIDFILE = "/var/run/jepsen-tcpdump.pid"


def control_addr() -> str:
    """The control node's address as seen from a DB node, recovered from
    the SSH session's environment (auto.clj:56-66)."""
    import re

    out = control.exec_("env", may_fail=False)
    m = re.search(r"SSH_CLIENT=(\S+)", out)
    if not m:
        raise RuntimeError(f"no SSH_CLIENT in node env: {out[:200]!r}")
    return m.group(1)


class CockroachDB(common.TarballDB):
    """Tarball install + cockroach start --join (cockroach/auto.clj).

    With ``tcpdump=True`` the node also runs a packet capture of its
    control-node <-> db-port traffic for the length of the test
    (auto.clj:67-75); the pcap rides home with the log files."""

    name = "cockroach"
    dir = "/opt/cockroach"
    binary = "cockroach"

    def __init__(self, version: str = VERSION, tcpdump: bool = False):
        self.url = (f"https://binaries.cockroachdb.com/"
                    f"cockroach-{version}.linux-amd64.tgz")
        self.tcpdump = tcpdump

    def start_args(self, test, node) -> list:
        join = ",".join(f"{n}:26258" for n in test["nodes"])
        return ["start", "--insecure", "--background",
                f"--advertise-host={node}",
                f"--port={PORT}", "--http-port=8081",
                f"--join={join}",
                f"--store=path={self.dir}/data"]

    def packet_capture(self, node) -> None:
        """Start tcpdump on control-node traffic (auto.clj:67-75)."""
        from jepsen_tpu.control import util as cu

        addr = control_addr()
        with control.su():
            cu.start_daemon(
                "/usr/sbin/tcpdump",
                "-w", PCAP_LOG, "host", addr, "and", "port", str(PORT),
                logfile="/dev/null", pidfile=TCPDUMP_PIDFILE)

    def stop_packet_capture(self) -> None:
        from jepsen_tpu.control import util as cu

        with control.su():
            cu.stop_daemon(TCPDUMP_PIDFILE, binary="tcpdump")

    def setup(self, test, node) -> None:
        super().setup(test, node)
        if self.tcpdump:
            self.packet_capture(node)

    def teardown(self, test, node) -> None:
        if self.tcpdump:
            self.stop_packet_capture()
        super().teardown(test, node)

    def log_files(self, test, node) -> list[str]:
        files = super().log_files(test, node)
        if self.tcpdump:
            files = files + [PCAP_LOG]
        return files


# --- SQL clients over pgwire -------------------------------------------------


class RegisterClient(client_ns.Client):
    """Per-key register via SQL upserts (cockroach/register.clj:82):
    read = SELECT, write = UPSERT, cas = conditional UPDATE in a txn."""

    TABLE = "jepsen_registers"

    def __init__(self, conn: PgClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(PgClient(node, port=PORT, user="root",
                                       database="jepsen"))

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            conn.query(f"CREATE TABLE IF NOT EXISTS jepsen.{self.TABLE} "
                       f"(id INT PRIMARY KEY, val INT)")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value if independent.is_tuple(op.value) \
            else (0, op.value)

        def join(val):
            return independent.tuple_(k, val) \
                if independent.is_tuple(op.value) else val

        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT val FROM {self.TABLE} WHERE id = {int(k)}")
                val = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                return op.replace(type="ok", value=join(val))
            if op.f == "write":
                self.conn.query(f"UPSERT INTO {self.TABLE} (id, val) "
                                f"VALUES ({int(k)}, {int(v)})")
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = v
                rows = self.conn.txn([
                    f"UPDATE {self.TABLE} SET val = {int(new)} "
                    f"WHERE id = {int(k)} AND val = {int(old)} "
                    f"RETURNING id"])
                return op.replace(type="ok" if rows[-1] else "fail")
        except PgError as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


class BankClient(client_ns.Client):
    """Bank transfers in explicit transactions (cockroach/bank.clj)."""

    TABLE = "jepsen_accounts"

    def __init__(self, conn: PgClient | None = None, n: int = 5,
                 total: int = 50):
        self.conn = conn
        self.n = n
        self.total = total

    def open(self, test, node):
        return BankClient(PgClient(node, port=PORT, user="root",
                                   database="jepsen"),
                          self.n, self.total)

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            conn.query(f"CREATE TABLE IF NOT EXISTS jepsen.{self.TABLE} "
                       f"(id INT PRIMARY KEY, balance INT NOT NULL)")
            for i in range(self.n):
                conn.query(f"INSERT INTO jepsen.{self.TABLE} VALUES "
                           f"({i}, {self.total // self.n}) "
                           f"ON CONFLICT (id) DO NOTHING")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT balance FROM {self.TABLE} ORDER BY id")
                return op.replace(type="ok",
                                  value=[int(r[0]) for r in rows])
            if op.f == "transfer":
                t = op.value
                amt = int(t["amount"])
                try:
                    # Read-check-update in one txn (bank.clj:112-143):
                    # the credit must not run when the debit would go
                    # negative — a guarded-debit + unconditional-credit
                    # pair would mint money on a failed guard.
                    for attempt in range(5):
                        try:
                            self.conn.query("BEGIN")
                            try:
                                rows = self.conn.query(
                                    f"SELECT balance FROM {self.TABLE} "
                                    f"WHERE id = {int(t['from'])}")
                                if not rows or int(rows[0][0]) < amt:
                                    self.conn.query("ROLLBACK")
                                    return op.replace(type="fail",
                                                      error="negative")
                                self.conn.query(
                                    f"UPDATE {self.TABLE} SET balance = "
                                    f"balance - {amt} "
                                    f"WHERE id = {int(t['from'])}")
                                self.conn.query(
                                    f"UPDATE {self.TABLE} SET balance = "
                                    f"balance + {amt} "
                                    f"WHERE id = {int(t['to'])}")
                                self.conn.query("COMMIT")
                            except PgError:
                                try:
                                    self.conn.query("ROLLBACK")
                                except (PgError, OSError):
                                    pass
                                raise
                            return op.replace(type="ok")
                        except PgError as e:
                            if e.ambiguous:
                                # COMMIT outcome unknown: may have
                                # applied (client.clj:183-230).
                                return op.replace(type="info",
                                                  error=str(e))
                            if not (e.retryable and attempt < 4):
                                raise
                except PgError:
                    return op.replace(type="fail")
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class MultiBankClient(client_ns.Client):
    """Bank with one table per account (bank.clj:168-249): transfers
    read both single-row tables, reject a negative result, and update
    both inside one transaction; reads select every table in one txn."""

    def __init__(self, conn: PgClient | None = None, n: int = 5,
                 total: int = 50):
        self.conn = conn
        self.n = n
        self.total = total

    def _table(self, i) -> str:
        return f"jepsen_accounts{int(i)}"

    def open(self, test, node):
        return MultiBankClient(PgClient(node, port=PORT, user="root",
                                        database="jepsen"),
                               self.n, self.total)

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            for i in range(self.n):
                t = self._table(i)
                conn.query(f"CREATE TABLE IF NOT EXISTS jepsen.{t} "
                           f"(balance INT NOT NULL)")
                rows = conn.query(f"SELECT count(*) FROM jepsen.{t}")
                if not rows or int(rows[0][0]) == 0:
                    conn.query(f"INSERT INTO jepsen.{t} VALUES "
                               f"({self.total // self.n})")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                stmts = [f"SELECT balance FROM {self._table(i)}"
                         for i in range(self.n)]
                rows = self.conn.txn(stmts)
                return op.replace(
                    type="ok",
                    value=[int(r[0][0]) for r in rows])
            if op.f == "transfer":
                t = op.value
                src, dst = self._table(t["from"]), self._table(t["to"])
                amt = int(t["amount"])
                # Read-check-update inside one transaction
                # (bank.clj:193-225): the credit must not happen when
                # the debit would go negative. Serialization aborts
                # (40001) retry like PgClient.txn's with-txn-retry —
                # without it, contention on single-row tables would
                # degenerate the workload to mostly-failed transfers.
                for attempt in range(5):
                    try:
                        self.conn.query("BEGIN")
                        try:
                            rows = self.conn.query(
                                f"SELECT balance FROM {src}")
                            if not rows or int(rows[0][0]) < amt:
                                self.conn.query("ROLLBACK")
                                return op.replace(type="fail",
                                                  error="negative")
                            self.conn.query(
                                f"UPDATE {src} SET balance = "
                                f"balance - {amt}")
                            self.conn.query(
                                f"UPDATE {dst} SET balance = "
                                f"balance + {amt}")
                            self.conn.query("COMMIT")
                        except PgError:
                            try:
                                self.conn.query("ROLLBACK")
                            except (PgError, OSError):
                                pass
                            raise
                        return op.replace(type="ok")
                    except PgError as e:
                        if getattr(e, "ambiguous", False):
                            # COMMIT outcome unknown: the transfer may
                            # have applied (client.clj:183-230).
                            return op.replace(type="info", error=str(e))
                        if not (getattr(e, "retryable", False)
                                and attempt < 4):
                            return op.replace(type="fail")
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class MonotonicClient(client_ns.Client):
    """Real monotonic client (monotonic.clj:60-140): each insert runs
    one txn that reads max(val) over the tables, reads the cluster's
    logical timestamp, and inserts (max+1, sts, node, process, tb);
    the op completes with (val, sts) so the checker can compare
    insertion order against timestamp order."""

    PREFIX = "jepsen_mono"

    def __init__(self, conn: PgClient | None = None, tables: int = 1,
                 node_num: int = 0):
        self.conn = conn
        self.tables = tables
        self.node_num = node_num

    def _table(self, i) -> str:
        return f"{self.PREFIX}{int(i)}"

    def open(self, test, node):
        return MonotonicClient(
            PgClient(node, port=PORT, user="root", database="jepsen"),
            self.tables, list(test["nodes"]).index(node)
            if node in test.get("nodes", []) else 0)

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            for i in range(self.tables):
                conn.query(
                    f"CREATE TABLE IF NOT EXISTS jepsen.{self._table(i)} "
                    f"(val INT PRIMARY KEY, sts DECIMAL, node INT, "
                    f"process INT, tb INT)")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        from decimal import Decimal

        try:
            if op.f == "insert":
                for attempt in range(5):
                    try:
                        self.conn.query("BEGIN")
                        try:
                            cur_max = 0
                            for i in range(self.tables):
                                rows = self.conn.query(
                                    f"SELECT max(val) FROM "
                                    f"{self._table(i)}")
                                if rows and rows[0][0] is not None:
                                    cur_max = max(cur_max,
                                                  int(rows[0][0]))
                            ts_rows = self.conn.query(
                                "SELECT cluster_logical_timestamp()")
                            sts = int(Decimal(ts_rows[0][0]) * 10 ** 10)
                            t = self._table(random.randrange(self.tables))
                            self.conn.query(
                                f"INSERT INTO {t} (val, sts, node, "
                                f"process, tb) VALUES ({cur_max + 1}, "
                                f"{sts}, {self.node_num}, "
                                f"{int(op.process or 0)}, 0)")
                            self.conn.query("COMMIT")
                        except PgError:
                            try:
                                self.conn.query("ROLLBACK")
                            except (PgError, OSError):
                                pass
                            raise
                        return op.replace(type="ok",
                                          value=(cur_max + 1, sts))
                    except PgError as e:
                        if e.ambiguous:
                            return op.replace(type="info", error=str(e))
                        if not (e.retryable and attempt < 4):
                            return op.replace(type="fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class CrdbSetsClient(client_ns.Client):
    """Real sets client (sets.clj:60-127): add = INSERT into one table,
    final read = full SELECT."""

    TABLE = "jepsen_set"

    def __init__(self, conn: PgClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return CrdbSetsClient(PgClient(node, port=PORT, user="root",
                                       database="jepsen"))

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            conn.query(f"CREATE TABLE IF NOT EXISTS jepsen.{self.TABLE} "
                       f"(val INT PRIMARY KEY)")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.txn([f"INSERT INTO {self.TABLE} (val) "
                               f"VALUES ({int(op.value)})"])
                return op.replace(type="ok")
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT val FROM {self.TABLE}")
                return op.replace(type="ok",
                                  value=sorted(int(r[0]) for r in rows))
        except PgError as e:
            if op.f == "read":
                return op.replace(type="fail", error=str(e))
            return op.replace(
                type="info" if e.ambiguous else "fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class SequentialClient(client_ns.Client):
    """Real sequential client (sequential.clj:51-105, adapted to the
    workload's single global key sequence): write = one txn reading
    max(key) and inserting max+1 (serializability keeps the sequence
    gap-free; anomalies surface as non-prefix reads), read = ordered
    SELECT of all keys."""

    TABLE = "jepsen_seq"

    def __init__(self, conn: PgClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return SequentialClient(PgClient(node, port=PORT, user="root",
                                         database="jepsen"))

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            conn.query(f"CREATE TABLE IF NOT EXISTS jepsen.{self.TABLE} "
                       f"(key INT PRIMARY KEY)")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "write":
                for attempt in range(5):
                    try:
                        self.conn.query("BEGIN")
                        try:
                            rows = self.conn.query(
                                f"SELECT max(key) FROM {self.TABLE}")
                            nxt = (int(rows[0][0]) + 1
                                   if rows and rows[0][0] is not None
                                   else 0)
                            self.conn.query(
                                f"INSERT INTO {self.TABLE} (key) "
                                f"VALUES ({nxt})")
                            self.conn.query("COMMIT")
                        except PgError:
                            try:
                                self.conn.query("ROLLBACK")
                            except (PgError, OSError):
                                pass
                            raise
                        return op.replace(type="ok", value=nxt)
                    except PgError as e:
                        if e.ambiguous:
                            return op.replace(type="info", error=str(e))
                        if not (e.retryable and attempt < 4):
                            return op.replace(type="fail", error=str(e))
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT key FROM {self.TABLE} ORDER BY key")
                return op.replace(type="ok",
                                  value=[int(r[0]) for r in rows])
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class CommentsClient(client_ns.Client):
    """Real comments client (comments.clj:42-86): inserts shard over
    ``tables`` by id hash; reads run one txn selecting every table, so
    an insert acked before the read began must be visible."""

    PREFIX = "jepsen_comments"

    def __init__(self, conn: PgClient | None = None, tables: int = 2):
        self.conn = conn
        self.tables = tables

    def _table(self, i) -> str:
        return f"{self.PREFIX}{int(i)}"

    def open(self, test, node):
        return CommentsClient(PgClient(node, port=PORT, user="root",
                                       database="jepsen"), self.tables)

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            for i in range(self.tables):
                conn.query(
                    f"CREATE TABLE IF NOT EXISTS jepsen.{self._table(i)} "
                    f"(id INT PRIMARY KEY)")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "insert":
                v = int(op.value)
                t = self._table(v % self.tables)
                self.conn.query(f"INSERT INTO {t} (id) VALUES ({v})")
                return op.replace(type="ok")
            if op.f == "read":
                stmts = [f"SELECT id FROM {self._table(i)}"
                         for i in range(self.tables)]
                per_table = self.conn.txn(stmts)
                vals = sorted(int(r[0]) for rows in per_table
                              for r in rows)
                return op.replace(type="ok", value=vals)
        except PgError as e:
            if op.f == "read":
                return op.replace(type="fail", error=str(e))
            return op.replace(
                type="info" if e.ambiguous else "fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class G2Client(client_ns.Client):
    """Real G2 anti-dependency client (adya.clj:24-83): each insert
    transaction checks BOTH tables for a committed row of its key
    (value % 3 = 0 predicate reads) and inserts into its own side only
    when none exists — under serializability at most one of the paired
    inserts may commit."""

    def __init__(self, conn: PgClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return G2Client(PgClient(node, port=PORT, user="root",
                                 database="jepsen"))

    def setup(self, test) -> None:
        conn = PgClient(test["nodes"][0], port=PORT, user="root",
                        database="system")
        try:
            conn.query("CREATE DATABASE IF NOT EXISTS jepsen")
            for t in ("jepsen_g2_a", "jepsen_g2_b"):
                conn.query(f"CREATE TABLE IF NOT EXISTS jepsen.{t} "
                           f"(id INT PRIMARY KEY, key INT, value INT)")
        finally:
            conn.close()

    def invoke(self, test, op: Op) -> Op:
        from jepsen_tpu import independent

        v = op.value
        k, payload = (v[0], v[1]) if independent.is_tuple(v) \
            else (0, v)
        side = int(payload["id"])
        try:
            if op.f == "insert":
                try:
                    self.conn.query("BEGIN")
                    try:
                        hits = []
                        for t in ("jepsen_g2_a", "jepsen_g2_b"):
                            hits += self.conn.query(
                                f"SELECT id FROM {t} WHERE key = "
                                f"{int(k)} AND value % 3 = 0")
                        if hits:
                            self.conn.query("ROLLBACK")
                            return op.replace(type="fail",
                                              error="too-late")
                        t = "jepsen_g2_a" if side == 0 else "jepsen_g2_b"
                        self.conn.query(
                            f"INSERT INTO {t} (id, key, value) VALUES "
                            f"({int(k)}, {int(k)}, 30)")
                        self.conn.query("COMMIT")
                    except PgError:
                        try:
                            self.conn.query("ROLLBACK")
                        except (PgError, OSError):
                            pass
                        raise
                    return op.replace(type="ok")
                except PgError as e:
                    if e.ambiguous:
                        return op.replace(type="info", error=str(e))
                    # serialization aborts mean NOT applied — exactly
                    # the G2-prevention the workload hopes to see.
                    return op.replace(type="fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class TxnClient(client_ns.Client):
    """List-append transactions over pgwire (the Elle workload,
    doc/txn.md): each op's value is a micro-op list executed inside one
    BEGIN/COMMIT — append = INSERT .. ON CONFLICT DO UPDATE concat,
    read = SELECT, the observed list parsed back into the completion.
    An ambiguous COMMIT completes ``:info`` (the txn may have applied —
    checker soundness depends on it); serialization aborts retry then
    fail (definitely not applied). Shared by cockroachdb and
    postgres-rds (construct with the RDS conn parameters)."""

    TABLE = "jepsen_txn"

    def __init__(self, conn: PgClient | None = None, port: int = PORT,
                 user: str = "root", database: str = "jepsen",
                 password: str = "", host: str | None = None,
                 admin_database: str = "system"):
        self.conn = conn
        self.port, self.user, self.database = port, user, database
        self.password, self.host = password, host
        self.admin_database = admin_database

    def _connect(self, node):
        return PgClient(self.host or node, port=self.port,
                        user=self.user, database=self.database,
                        password=self.password)

    def open(self, test, node):
        c = TxnClient(self._connect(node), port=self.port,
                      user=self.user, database=self.database,
                      password=self.password, host=self.host,
                      admin_database=self.admin_database)
        return c

    def _setup_stmts(self) -> list[str]:
        """Dialect-aware DDL: CockroachDB (admin db "system") takes the
        db-qualified STRING form; stock PostgreSQL (the RDS path) has
        neither `CREATE DATABASE IF NOT EXISTS`, db-qualified names
        (they parse as schemas), nor a STRING type — unqualified TEXT.
        The per-op SQL in _mop is common to both dialects."""
        if self.admin_database == "system":
            return ["CREATE DATABASE IF NOT EXISTS jepsen",
                    f"CREATE TABLE IF NOT EXISTS "
                    f"{self.database}.{self.TABLE} "
                    f"(k INT PRIMARY KEY, vals STRING)"]
        return [f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
                f"(k INT PRIMARY KEY, vals TEXT)"]

    def setup(self, test) -> None:
        conn = PgClient(self.host or test["nodes"][0], port=self.port,
                        user=self.user, database=self.admin_database,
                        password=self.password)
        try:
            for stmt in self._setup_stmts():
                conn.query(stmt)
        finally:
            conn.close()

    def _mop(self, f, k, v):
        if f == "append":
            self.conn.query(
                f"INSERT INTO {self.TABLE} (k, vals) VALUES "
                f"({int(k)}, '{int(v)}') ON CONFLICT (k) DO UPDATE "
                f"SET vals = concat({self.TABLE}.vals, ',{int(v)}')")
            return ["append", k, v]
        rows = self.conn.query(
            f"SELECT vals FROM {self.TABLE} WHERE k = {int(k)}")
        obs = [] if not rows or rows[0][0] in (None, "") \
            else [int(x) for x in str(rows[0][0]).split(",")]
        return ["r", k, obs]

    def invoke(self, test, op: Op) -> Op:
        if op.f != "txn":
            return op.replace(type="fail", error=f"unknown f {op.f}")
        try:
            for attempt in range(5):
                try:
                    self.conn.query("BEGIN")
                    # The workload asserts serializability, so demand
                    # it: stock Postgres (the RDS path) defaults to
                    # READ COMMITTED, where healthy write skew would be
                    # convicted as G2 (the RdsBankClient precedent);
                    # CockroachDB accepts the statement as a no-op.
                    self.conn.query(
                        "SET TRANSACTION ISOLATION LEVEL SERIALIZABLE")
                    try:
                        done = [self._mop(*m) for m in op.value]
                        self.conn.query("COMMIT")
                    except PgError:
                        try:
                            self.conn.query("ROLLBACK")
                        except (PgError, OSError):
                            pass
                        raise
                    return op.replace(type="ok", value=done)
                except PgError as e:
                    if e.ambiguous:
                        # COMMIT outcome unknown: the txn may have
                        # applied (client.clj:183-230) — never "fail".
                        return op.replace(type="info", error=str(e))
                    if not (e.retryable and attempt < 4):
                        return op.replace(type="fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error="retries exhausted")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


# --- nemesis registry (cockroach/nemesis.clj) -------------------------------


def _skew(name: str, dt_s: float, slow_dt_s: float | None = None) -> dict:
    """Clock-bump nemesis at one magnitude (nemesis.clj:233-272): :start
    bumps randomly-selected nodes by dt seconds, :stop resets clocks.
    Wrapped in :class:`Restarting` like the reference's bump-time
    (nemesis.clj:237), and — for the big/huge magnitudes — additionally
    in :class:`Slowing` (nemesis.clj:269-272)."""

    class Skew(nemesis_ns.Nemesis):
        def invoke(self, test, op):
            from jepsen_tpu.control import on_many

            if op.f == "start":
                def bump():
                    if random.random() < 0.5:
                        nemesis_time.bump_time(dt_s * 1000)
                        return dt_s
                    return 0

                vals = on_many(test, test["nodes"], bump)
                return op.replace(type="info", value=vals)
            if op.f == "stop":
                on_many(test, test["nodes"],
                        lambda: nemesis_time.reset_time())
                return op.replace(type="info", value="clocks-reset")
            return op.replace(type="info")

    nem: nemesis_ns.Nemesis = Restarting(Skew())
    if slow_dt_s is not None:
        nem = Slowing(nem, slow_dt_s)
    return {"name": name, "nemesis": nem, "clocks": True,
            "gen": common.standard_nemesis_gen(5, 5)}


def _strobe() -> dict:
    """strobe-skews (nemesis.clj:202-230): oscillate the clock 200ms
    ahead/back every 10ms for 10s on :start."""

    class Strobe(nemesis_ns.Nemesis):
        def invoke(self, test, op):
            from jepsen_tpu.control import on_many

            if op.f == "start":
                on_many(test, test["nodes"],
                        lambda: nemesis_time.strobe_time(200, 10, 10))
                return op.replace(type="info", value="strobed")
            if op.f == "stop":
                on_many(test, test["nodes"],
                        lambda: nemesis_time.reset_time())
                return op.replace(type="info", value="clocks-reset")
            return op.replace(type="info")

    return {"name": "strobe-skews", "nemesis": Restarting(Strobe()),
            "clocks": True, "gen": common.standard_nemesis_gen(0, 0)}


class Slowing(nemesis_ns.Nemesis):
    """Wraps a nemesis: before the underlying nemesis starts, slow the
    network by ``dt`` seconds of mean delay; when it resolves, restore
    network speed (cockroach/nemesis.clj:153-176)."""

    def __init__(self, nem: nemesis_ns.Nemesis, dt_s: float):
        self.nem = nem
        self.dt_s = dt_s

    def _net(self, test):
        from jepsen_tpu import net as net_ns

        return test.get("net") or net_ns.noop

    def setup(self, test):
        self._net(test).fast(test)
        self.nem = self.nem.setup(test) or self.nem
        return self

    def invoke(self, test, op):
        if op.f == "start":
            self._net(test).slow(test, mean_ms=self.dt_s * 1000,
                                 sigma_ms=1)
            return self.nem.invoke(test, op)
        if op.f == "stop":
            try:
                return self.nem.invoke(test, op)
            finally:
                self._net(test).fast(test)
        return self.nem.invoke(test, op)

    def teardown(self, test):
        self._net(test).fast(test)
        self.nem.teardown(test)


class Restarting(nemesis_ns.Nemesis):
    """Wraps a nemesis: after the underlying nemesis completes :stop,
    restart the cockroach daemon on every node — skews/strobes can stop
    it (cockroach/nemesis.clj:178-200; used by bump-time :237 and
    strobe-time :207)."""

    def __init__(self, nem: nemesis_ns.Nemesis, db=None):
        self.nem = nem
        self.db = db or CockroachDB()

    def setup(self, test):
        self.nem = self.nem.setup(test) or self.nem
        return self

    def invoke(self, test, op):
        from jepsen_tpu.control import on_nodes

        op2 = self.nem.invoke(test, op)
        if op.f == "stop":
            def restart(test_, node):
                try:
                    self.db.start(test_, node)
                    return "started"
                except Exception as e:  # noqa: BLE001 - per-node status
                    return str(e)

            stat = on_nodes(test, restart)
            return op2.replace(value=[op2.value, stat])
        return op2

    def teardown(self, test):
        self.nem.teardown(test)


def _startstop(n: int) -> dict:
    """SIGSTOP n random cockroach processes (runner.clj startstop)."""
    return {"name": f"start-stop-{n}",
            "nemesis": nemesis_ns.hammer_time(
                "cockroach",
                lambda nodes: random.sample(list(nodes),
                                            min(n, len(nodes)))),
            "clocks": False,
            "gen": common.standard_nemesis_gen(5, 5)}


def _startkill(n: int) -> dict:
    """kill -9 + restart n random nodes (runner.clj startkill)."""
    db = CockroachDB()

    def kill(test, node):
        control.exec_("killall", "-9", "cockroach", may_fail=True)
        return ["killed", "cockroach"]

    def restart(test, node):
        db.start(test, node)
        return ["restarted", "cockroach"]

    return {"name": f"start-kill-{n}",
            "nemesis": nemesis_ns.node_start_stopper(
                lambda nodes: random.sample(list(nodes),
                                            min(n, len(nodes))),
                kill, restart),
            "clocks": False,
            "gen": common.standard_nemesis_gen(5, 5)}


def _split() -> dict:
    """Range-split nemesis (nemesis.clj:274-317): SPLIT AT below the
    most recently written register key."""

    class Split(nemesis_ns.Nemesis):
        def invoke(self, test, op):
            keyrange = test.get("keyrange")
            if not keyrange:
                return op.replace(type="info", value="no-keyrange")
            k = max(keyrange)
            try:
                conn = PgClient(random.choice(test["nodes"]), port=PORT,
                                user="root", database="jepsen")
                try:
                    conn.query(f"ALTER TABLE {RegisterClient.TABLE} "
                               f"SPLIT AT VALUES ({int(k)})")
                finally:
                    conn.close()
                return op.replace(type="info", value=["split", k])
            except (PgError, OSError, ConnectionError) as e:
                return op.replace(type="info", value=repr(e))

    def delay_gen():
        from jepsen_tpu import generator as gen

        return gen.delay(2, {"type": "info", "f": "split", "value": None})

    return {"name": "splits", "nemesis": Split(), "clocks": False,
            "gen": delay_gen()}


def nemeses() -> dict:
    """name -> nemesis map (runner.clj:42-57)."""
    return {
        "none": {"name": "blank", "nemesis": nemesis_ns.noop,
                 "clocks": False, "gen": None},
        "parts": {"name": "parts",
                  "nemesis": nemesis_ns.partition_random_halves(),
                  "clocks": False,
                  "gen": common.standard_nemesis_gen(5, 5)},
        "majority-ring": {"name": "majring",
                          "nemesis":
                          nemesis_ns.partition_majorities_ring(),
                          "clocks": False,
                          "gen": common.standard_nemesis_gen(5, 5)},
        "small-skews": _skew("small-skews", 0.100),
        "subcritical-skews": _skew("subcritical-skews", 0.200),
        "critical-skews": _skew("critical-skews", 0.250),
        "big-skews": _skew("big-skews", 0.5, slow_dt_s=0.5),
        "huge-skews": _skew("huge-skews", 5, slow_dt_s=5),
        "strobe-skews": _strobe(),
        "split": _split(),
        "start-stop-2": _startstop(2),
        "start-kill-2": _startkill(2),
    }


def combine_nemeses(a: dict, b: dict) -> dict:
    """Compose two registry entries (runner.clj:94-110 nemesis product):
    composed client, concatenated schedules, OR'd clock flag."""
    from jepsen_tpu import generator as gen

    gens = [g for g in (a.get("gen"), b.get("gen")) if g is not None]
    return {"name": f"{a['name']}+{b['name']}",
            "nemesis": nemesis_ns.compose([a["nemesis"], b["nemesis"]]),
            "clocks": a["clocks"] or b["clocks"],
            "gen": gen.mix(gens) if len(gens) > 1 else
            (gens[0] if gens else None)}


def tests_registry() -> dict:
    """name -> workload factory (runner.clj:25-34)."""
    return {
        "bank": lambda: workloads.bank_workload(),
        "bank-multitable": lambda: workloads.bank_workload(),
        "comments": lambda: workloads.comments_workload(),
        "register": lambda: workloads.register(threads_per_key=5),
        "monotonic": lambda: workloads.monotonic_workload(),
        "monotonic-multitable": lambda: workloads.monotonic_workload(),
        "sets": lambda: workloads.set_workload(),
        "sequential": lambda: workloads.sequential_workload(),
        "g2": lambda: adya.workload(),
        "txn": lambda: workloads.txn_workload(),
    }


def test(opts: dict | None = None) -> dict:
    """The cockroach test map (cockroach.clj:136-164 basic-test +
    runner.clj test-cmd): ``workload``, ``nemesis``, ``nemesis2``."""
    opts = dict(opts or {})
    wname = opts.pop("workload", None) or "register"
    n1 = opts.pop("nemesis", None) or "none"
    n2 = opts.pop("nemesis2", None)
    table = tests_registry()
    if wname not in table:
        raise ValueError(f"unknown workload {wname!r}; "
                         f"one of {sorted(table)}")
    reg = nemeses()
    nem = reg[n1] if n2 is None else combine_nemeses(reg[n1], reg[n2])
    if wname == "register" and opts.get("concurrency", 0) < 5:
        opts["concurrency"] = 5
    client_factories = {
        "register": RegisterClient,
        "bank": BankClient,
        "bank-multitable": MultiBankClient,
        "monotonic": MonotonicClient,
        "monotonic-multitable": lambda: MonotonicClient(tables=2),
        "sets": CrdbSetsClient,
        "sequential": SequentialClient,
        "comments": CommentsClient,
        "g2": G2Client,
        "txn": TxnClient,
    }
    client = client_factories.get(wname)
    os_name = opts.pop("os", "ubuntu")
    if os_name == "ubuntu":
        from jepsen_tpu import os_ubuntu

        os_obj = os_ubuntu.os
    elif os_name == "debian":
        from jepsen_tpu import os_debian

        os_obj = os_debian.os
    else:
        raise ValueError(f"unknown os {os_name!r}; 'ubuntu' or 'debian'")
    return common.suite_test(
        f"cockroachdb {wname} {nem['name']}", opts,
        workload=table[wname](),
        db=CockroachDB(tcpdump=bool(opts.pop("tcpdump", False))),
        client=client() if client else None,
        os=os_obj,
        nemesis=nem["nemesis"],
        nemesis_gen=nem["gen"])


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="register",
                       choices=sorted(tests_registry()))
        p.add_argument("--nemesis", default="none",
                       choices=sorted(nemeses()))
        p.add_argument("--nemesis2", default=None,
                       choices=sorted(nemeses()))
        p.add_argument("--os", default="ubuntu",
                       choices=["ubuntu", "debian"],
                       help="node OS provisioning (os/ubuntu.clj is the "
                            "reference's cockroach default)")
        p.add_argument("--tcpdump", action="store_true",
                       help="capture control<->db packets per node "
                            "(auto.clj:67-75)")

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
