"""RobustIRC suite — set over IRC messages
(robustirc/src/jepsen/robustirc.clj).

Clients post integers as IRC messages to a channel; the final read
collects the channel log and the set checker verifies every
acknowledged add survived (robustirc.clj:213-215). Nemesis:
partition-random-halves (robustirc.clj:192). DB install downloads the
robustirc binary and bootstraps the network (robustirc.clj:30-120).

The reference uses an IRC client library; the TPU build speaks the IRC
line protocol natively (:mod:`jepsen_tpu.suites.ircwire`).
"""

from __future__ import annotations

from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.suites import common, workloads


class RobustIrcDB(common.TarballDB):
    """Binary download + network bootstrap (robustirc.clj:30-120)."""

    name = "robustirc"
    dir = "/opt/robustirc"
    binary = "robustirc"

    def __init__(self):
        self.url = None  # release binary fetched in post_install

    def post_install(self, test, node) -> None:
        from jepsen_tpu.control import util as cu

        cu.wget("https://github.com/robustirc/robustirc/releases/"
                "latest/download/robustirc_linux_amd64")

    def start_args(self, test, node) -> list:
        args = ["-network_name=jepsen", f"-peer_addr={node}:13001"]
        if node != test["nodes"][0]:
            args.append(f"-join={test['nodes'][0]}:13001")
        else:
            args.append("-singlenode")
        return args


def test(opts: dict | None = None) -> dict:
    """The robustirc test map (robustirc.clj:180-220)."""
    from jepsen_tpu.suites.ircwire import IrcSetClient

    return common.suite_test(
        "robustirc", opts,
        workload=workloads.set_workload(),
        db=RobustIrcDB(),
        client=IrcSetClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
