"""RabbitMQ suite — queue + distributed mutex
(rabbitmq/src/jepsen/rabbitmq.clj).

Two workloads: the job queue checked by total-queue
(rabbitmq.clj:100-170), and the **message-holding semaphore mutex**
(rabbitmq.clj:263) — a lock built from a 1-message queue, checked
linearizable against the Mutex model (device mutex kernel). DB install
is the Debian rabbitmq-server package with a generated clustering
config (rabbitmq.clj:38-98).

The AMQP wire protocol needs a driver (the reference uses Langohr), so
the client speaks AMQP 0-9-1 natively (jepsen_tpu.suites.amqpwire).
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class RabbitDB(db_ns.DB, db_ns.LogFiles):
    """Package install + erlang cookie + cluster config
    (rabbitmq.clj:38-98)."""

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["rabbitmq-server"])
            cluster = ", ".join(f"'rabbit@{n}'" for n in test["nodes"])
            config = (f"[{{rabbit, [{{cluster_nodes, {{[{cluster}], "
                      f"disc}}}}]}}].")
            control.exec_("tee", "/etc/rabbitmq/rabbitmq.config",
                          stdin=config)
            control.exec_("tee", "/var/lib/rabbitmq/.erlang.cookie",
                          stdin="jepsen-rabbitmq")
            control.exec_("chown", "rabbitmq:rabbitmq",
                          "/var/lib/rabbitmq/.erlang.cookie")
            control.exec_("chmod", "600",
                          "/var/lib/rabbitmq/.erlang.cookie")
            control.exec_("service", "rabbitmq-server", "restart")

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "rabbitmq-server", "stop",
                          may_fail=True)
            control.exec_("rm", "-rf", "/var/lib/rabbitmq/mnesia",
                          may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return [f"/var/log/rabbitmq/rabbit@{node}.log"]


def test(opts: dict | None = None) -> dict:
    """The rabbitmq test map (rabbitmq.clj:282-320). ``workload`` is
    "queue" (default) or "mutex"."""
    opts = dict(opts or {})
    from jepsen_tpu.suites import amqpwire

    name = opts.pop("workload", None) or "queue"
    if name == "queue":
        wl = workloads.queue_workload()
        client = amqpwire.QueueClient()
    else:
        wl = workloads.lock_workload()
        client = amqpwire.MutexClient()
    return common.suite_test(
        f"rabbitmq {name}", opts,
        workload=wl,
        db=RabbitDB(),
        client=client,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="queue",
                       choices=["queue", "mutex"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
