"""Minimal PostgreSQL wire-protocol (v3) client.

The reference's SQL suites reach CockroachDB and Postgres-RDS through
JDBC (cockroachdb/src/jepsen/cockroach/client.clj). The TPU build speaks
the wire protocol directly from the stdlib instead of vendoring a
driver: startup, trust/cleartext/md5 auth, and the simple-query flow —
enough for the bank/register/sets/monotonic workload SQL.

Protocol framing: every backend message is ``type:1 len:4 payload``;
StartupMessage has no type byte. Simple query sends ``Q`` and reads
RowDescription / DataRow / CommandComplete / ErrorResponse until
ReadyForQuery.
"""

from __future__ import annotations

import hashlib
import socket
import struct

from jepsen_tpu.suites.common import SocketIO


class PgError(Exception):
    """ErrorResponse from the server; carries the severity/code/message
    fields keyed by their protocol tags."""

    def __init__(self, fields: dict):
        self.fields = fields
        super().__init__(fields.get("M", "postgres error"))

    @property
    def code(self) -> str:
        return self.fields.get("C", "")

    @property
    def retryable(self) -> bool:
        # 40001 serialization_failure / 40P01 deadlock — the txn retry
        # loop of cockroach/client.clj wraps exactly these.
        return self.code in ("40001", "40P01", "CR000")

    @property
    def ambiguous(self) -> bool:
        """The statement (typically COMMIT) may or may not have applied:
        40003 statement_completion_unknown, XXA00 CockroachDB ambiguous
        result. Clients must complete mutating ops as :info on these —
        never :fail — matching the reference's exception->op defaulting
        to :info for non-idempotent ops (cockroach/client.clj:183-230)."""
        return self.code in ("40003", "XXA00")


class PgClient:
    def __init__(self, host: str, port: int = 5432, user: str = "root",
                 database: str = "postgres", password: str = "",
                 timeout: float = 10.0):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.user = user
        self.password = password
        self._startup(user, database)

    # --- low-level framing ---------------------------------------------------

    def _send(self, type_byte: bytes, payload: bytes) -> None:
        msg = type_byte + struct.pack("!I", len(payload) + 4) + payload
        self.io.send(msg)

    def _read_msg(self) -> tuple[bytes, bytes]:
        head = self.io.read_exact(5)
        t = head[:1]
        (n,) = struct.unpack("!I", head[1:])
        return t, self.io.read_exact(n - 4)

    @staticmethod
    def _cstr(b: bytes) -> str:
        return b.split(b"\x00", 1)[0].decode()

    @staticmethod
    def _error_fields(payload: bytes) -> dict:
        fields = {}
        for part in payload.split(b"\x00"):
            if part:
                fields[chr(part[0])] = part[1:].decode(errors="replace")
        return fields

    # --- startup / auth ------------------------------------------------------

    def _startup(self, user: str, database: str) -> None:
        params = (f"user\x00{user}\x00database\x00{database}\x00\x00"
                  .encode())
        payload = struct.pack("!I", 196608) + params  # protocol 3.0
        self.io.send(struct.pack("!I", len(payload) + 4) + payload)
        while True:
            t, body = self._read_msg()
            if t == b"R":
                (kind,) = struct.unpack("!I", body[:4])
                if kind == 0:            # AuthenticationOk
                    continue
                if kind == 3:            # cleartext password
                    self._send(b"p", self.password.encode() + b"\x00")
                    continue
                if kind == 5:            # md5
                    salt = body[4:8]
                    inner = hashlib.md5(
                        (self.password + self.user).encode()).hexdigest()
                    outer = hashlib.md5(
                        inner.encode() + salt).hexdigest()
                    self._send(b"p", b"md5" + outer.encode() + b"\x00")
                    continue
                raise PgError({"M": f"unsupported auth method {kind}"})
            if t == b"E":
                raise PgError(self._error_fields(body))
            if t == b"Z":                # ReadyForQuery
                return
            # ParameterStatus (S), BackendKeyData (K), NoticeResponse (N)
            if t not in (b"S", b"K", b"N"):
                raise PgError({"M": f"unexpected startup message {t!r}"})

    # --- simple query --------------------------------------------------------

    def query(self, sql: str) -> list[tuple]:
        """Run one simple-protocol query; returns rows as tuples of
        str|None. DDL/DML with no result set returns []. Raises
        :class:`PgError` on ErrorResponse (after draining to
        ReadyForQuery, so the connection stays usable)."""
        self._send(b"Q", sql.encode() + b"\x00")
        rows: list[tuple] = []
        err: PgError | None = None
        while True:
            t, body = self._read_msg()
            if t == b"D":
                (ncol,) = struct.unpack("!H", body[:2])
                off = 2
                row = []
                for _ in range(ncol):
                    (ln,) = struct.unpack("!i", body[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(tuple(row))
            elif t == b"E":
                err = PgError(self._error_fields(body))
            elif t == b"Z":
                if err is not None:
                    raise err
                return rows
            # T RowDescription, C CommandComplete, N notice, I empty — skip

    def txn(self, statements: list[str], max_retries: int = 5) -> list:
        """Run statements in a transaction with the serialization-failure
        retry loop of cockroach/client.clj's with-txn-retry."""
        for attempt in range(max_retries):
            try:
                self.query("BEGIN")
                out = [self.query(s) for s in statements]
                self.query("COMMIT")
                return out
            except PgError as e:
                try:
                    self.query("ROLLBACK")
                except (PgError, ConnectionError):
                    pass
                if not e.retryable or attempt == max_retries - 1:
                    raise
        raise AssertionError("unreachable")

    def close(self) -> None:
        try:
            self._send(b"X", b"")
            self.io.close()
        except OSError:
            pass
