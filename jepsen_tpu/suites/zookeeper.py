"""ZooKeeper suite — CAS register over a zk-atom
(zookeeper/src/jepsen/zookeeper.clj).

DB install goes through Debian packages + per-node ``myid`` and a
generated ``zoo.cfg`` server list, restarted via the service manager
(zookeeper.clj:40-71). The workload is the canonical r/w/cas register
checked linearizable (zookeeper.clj:78-129).

The reference's client is an Avout distributed atom over the ZooKeeper
jute wire protocol (zookeeper.clj:78-104); the TPU build speaks jute
natively (:mod:`jepsen_tpu.suites.zkwire`): session connect, create,
getData, and the znode-version-conditioned setData that is the zk-atom
CAS.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads

ZOO_CFG = """tickTime=2000
initLimit=10
syncLimit=5
dataDir=/var/lib/zookeeper
clientPort=2181
"""


def node_id(test, node) -> int:
    """Node name -> myid (zookeeper.clj:19-31)."""
    return test["nodes"].index(node)


def zoo_cfg_servers(test) -> str:
    """server.N lines for zoo.cfg (zookeeper.clj:33-39)."""
    return "\n".join(f"server.{i}={n}:2888:3888"
                     for i, n in enumerate(test["nodes"]))


class ZookeeperDB(db_ns.DB, db_ns.LogFiles):
    """Package install + myid/zoo.cfg + service restart
    (zookeeper.clj:41-71)."""

    def __init__(self, version: str = "3.4.5+dfsg-2"):
        self.version = version

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install([f"zookeeper={self.version}",
                               f"zookeeper-bin={self.version}",
                               f"zookeeperd={self.version}"])
            control.exec_("mkdir", "-p", "/etc/zookeeper/conf")
            control.exec_("tee", "/etc/zookeeper/conf/myid",
                          stdin=str(node_id(test, node)))
            control.exec_("tee", "/etc/zookeeper/conf/zoo.cfg",
                          stdin=ZOO_CFG + "\n" + zoo_cfg_servers(test))
            control.exec_("service", "zookeeper", "restart")

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "zookeeper", "stop", may_fail=True)
            control.exec_("bash", "-c",
                          "rm -rf /var/lib/zookeeper/version-* "
                          "/var/log/zookeeper/*", may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/zookeeper/zookeeper.log"]


def test(opts: dict | None = None) -> dict:
    """The zookeeper test map (zookeeper.clj:110-129)."""
    from jepsen_tpu.suites.zkwire import ZkRegisterClient

    return common.suite_test(
        "zookeeper", opts,
        workload=workloads.single_register(),
        db=ZookeeperDB(),
        client=ZkRegisterClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
