"""Minimal Hazelcast Open Client Protocol (1.x) client.

The reference drives Hazelcast through its Java client
(hazelcast/src/jepsen/hazelcast.clj:364-399, plus a bundled server
uberjar); the TPU build speaks the 3.x-era Open Client Protocol from
the stdlib: the ``CB2`` protocol preamble, the 22-byte little-endian
client-message header (frameLength, version, flags, messageType,
correlationId, partitionId, dataOffset), string/nullable-string
parameter encoding, and the handful of codecs the suite's workloads
need — authentication, lock lock/tryLock/unlock, map put/get/values,
queue offer/poll, and atomic-long incrementAndGet.

Codec message-type ids follow the published protocol definitions for
Hazelcast 3.x (hazelcast-client-protocol, protocol version 1.x);
they're listed next to each method so a mismatch against a specific
server build is one constant away from fixed. Payload values travel as
Hazelcast serialization-format integers/strings (the suite only needs
ints and strings).
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading

from jepsen_tpu import client as client_ns
from jepsen_tpu.suites.common import SocketIO

VERSION = 1
FLAGS_BEGIN_END = 0xC0
HEADER = 22                      # bytes up to and including dataOffset

# message types (hazelcast-client-protocol 1.x definitions)
AUTH = 0x0002
AUTH_RESPONSE = 0x0107

LOCK_LOCK = 0x0705
LOCK_UNLOCK = 0x0706
LOCK_TRYLOCK = 0x0708

MAP_PUT = 0x0101
MAP_GET = 0x0102
MAP_VALUES = 0x012A

QUEUE_OFFER = 0x0301
QUEUE_POLL = 0x0304

ATOMIC_LONG_INC_GET = 0x0A05

BOOL_RESPONSE = 0x0065
LONG_RESPONSE = 0x0067
DATA_RESPONSE = 0x0069
LIST_DATA_RESPONSE = 0x006A
ERROR_RESPONSE = 0x006D

# Hazelcast serialization type ids (big-endian payload after a 4-byte
# partition hash): int = -7, long = -8, string = -11.
SER_STRING = -11
SER_LONG = -8


class HazelcastError(Exception):
    pass


def _s(v: str) -> bytes:
    b = v.encode()
    return struct.pack("<i", len(b)) + b


def _nullable(v: str | None) -> bytes:
    if v is None:
        return b"\x01"
    return b"\x00" + _s(v)


def _data_long(v: int) -> bytes:
    """Hazelcast Data blob for a long: partition-hash(4) + type id (BE)
    + 8-byte BE value, wrapped in the <i length prefix."""
    blob = struct.pack(">iiq", 0, SER_LONG, v)
    return struct.pack("<i", len(blob)) + blob


def _parse_data_long(blob: bytes) -> int | None:
    if len(blob) < 8:
        return None
    tid = struct.unpack_from(">i", blob, 4)[0]
    if tid == SER_LONG:
        return struct.unpack_from(">q", blob, 8)[0]
    return None


class HazelcastClient:
    def __init__(self, host: str, port: int = 5701,
                 timeout: float = 10.0, group: str = "dev",
                 password: str = "dev-pass"):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.corr = itertools.count(1)
        self.lock = threading.Lock()
        self.thread_id = threading.get_ident() & 0x7FFFFFFF
        self.io.send(b"CB2")
        self._authenticate(group, password)

    # --- framing -------------------------------------------------------------

    def _send(self, msg_type: int, payload: bytes,
              partition: int = -1) -> int:
        corr = next(self.corr)
        frame = struct.pack("<iBBHqiH", HEADER + len(payload), VERSION,
                            FLAGS_BEGIN_END, msg_type, corr, partition,
                            HEADER) + payload
        self.io.send(frame)
        return corr

    def _recv(self) -> tuple[int, int, bytes]:
        head = self.io.read_exact(HEADER)
        length, _ver, _flags, mtype, corr, _part, off = struct.unpack(
            "<iBBHqiH", head)
        body = self.io.read_exact(length - HEADER)
        return mtype, corr, body[off - HEADER:]

    def _call(self, msg_type: int, payload: bytes,
              partition: int = -1) -> tuple[int, bytes]:
        with self.lock:
            corr = self._send(msg_type, payload, partition)
            while True:
                mtype, rcorr, body = self._recv()
                if rcorr != corr:
                    continue              # stale event/response
                if mtype == ERROR_RESPONSE:
                    raise HazelcastError(f"server error for 0x{msg_type:04x}")
                return mtype, body

    # --- authentication ------------------------------------------------------

    def _authenticate(self, group: str, password: str) -> None:
        payload = (_s(group) + _s(password) + _nullable(None)
                   + _nullable(None) + b"\x01" + _s("PYH")
                   + bytes([1]) + _s("3.12"))
        mtype, body = self._call(AUTH, payload)
        if mtype != AUTH_RESPONSE or (body and body[0] != 0):
            raise HazelcastError(
                f"authentication failed (type 0x{mtype:04x}, "
                f"status {body[0] if body else '?'})")

    # --- lock service (hazelcast.clj:379-386's ILock) ------------------------

    def try_lock(self, name: str, lease_ms: int = -1,
                 timeout_ms: int = 0) -> bool:
        payload = (_s(name) + struct.pack("<q", self.thread_id)
                   + struct.pack("<q", lease_ms)
                   + struct.pack("<q", timeout_ms)
                   + struct.pack("<q", 0))      # reference id (3.7+)
        mtype, body = self._call(LOCK_TRYLOCK, payload)
        return bool(body and body[0])

    def unlock(self, name: str) -> None:
        payload = (_s(name) + struct.pack("<q", self.thread_id)
                   + struct.pack("<q", 0))
        self._call(LOCK_UNLOCK, payload)

    # --- map service (set semantics via keys) --------------------------------

    def map_put(self, name: str, key: int, value: int) -> None:
        payload = (_s(name) + _data_long(key) + _data_long(value)
                   + struct.pack("<q", self.thread_id)
                   + struct.pack("<q", -1))     # ttl
        self._call(MAP_PUT, payload)

    def map_get(self, name: str, key: int) -> int | None:
        payload = (_s(name) + _data_long(key)
                   + struct.pack("<q", self.thread_id))
        mtype, body = self._call(MAP_GET, payload)
        if not body or body[0] == 1:            # null data
            return None
        (n,) = struct.unpack_from("<i", body, 1)
        return _parse_data_long(body[5:5 + n])

    def map_values(self, name: str) -> list[int]:
        mtype, body = self._call(MAP_VALUES, _s(name))
        (count,) = struct.unpack_from("<i", body, 0)
        out = []
        off = 4
        for _ in range(count):
            (n,) = struct.unpack_from("<i", body, off)
            v = _parse_data_long(body[off + 4:off + 4 + n])
            if v is not None:
                out.append(v)
            off += 4 + n
        return out

    # --- queue service --------------------------------------------------------

    def queue_offer(self, name: str, value: int,
                    timeout_ms: int = 0) -> bool:
        payload = (_s(name) + _data_long(value)
                   + struct.pack("<q", timeout_ms))
        mtype, body = self._call(QUEUE_OFFER, payload)
        return bool(body and body[0])

    def queue_poll(self, name: str, timeout_ms: int = 0) -> int | None:
        payload = _s(name) + struct.pack("<q", timeout_ms)
        mtype, body = self._call(QUEUE_POLL, payload)
        if not body or body[0] == 1:
            return None
        (n,) = struct.unpack_from("<i", body, 1)
        return _parse_data_long(body[5:5 + n])

    # --- atomic long (unique ids) --------------------------------------------

    def atomic_increment(self, name: str) -> int:
        mtype, body = self._call(ATOMIC_LONG_INC_GET, _s(name))
        (v,) = struct.unpack_from("<q", body, 0)
        return v

    def close(self) -> None:
        try:
            self.io.close()
        except OSError:
            pass


# --- workload clients --------------------------------------------------------


class LockClient(client_ns.Client):
    """The ILock mutex (hazelcast.clj:379-386): acquire = tryLock with
    no wait, release = unlock. Checked against the Mutex model on the
    device mutex kernel."""

    NAME = "jepsen-lock"

    def __init__(self, conn: HazelcastClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return LockClient(HazelcastClient(node))

    def invoke(self, test, op):
        try:
            if op.f == "acquire":
                ok = self.conn.try_lock(self.NAME)
                return op.replace(type="ok" if ok else "fail")
            if op.f == "release":
                try:
                    self.conn.unlock(self.NAME)
                    return op.replace(type="ok")
                except HazelcastError:
                    # lint: fail-ok — HazelcastError is raised only on
                    # a parsed ERROR_RESPONSE frame (the server
                    # processed the unlock and rejected it: not held);
                    # transport losses raise OSError, handled below.
                    return op.replace(type="fail", error="not held")
        except HazelcastError as e:
            # A server-side rejection is definite: the op did not
            # happen — HazelcastError only ever comes from a parsed
            # ERROR_RESPONSE frame (_call), never from socket loss
            # (OSError/ConnectionError, handled below as :info).
            # lint: fail-ok
            return op.replace(type="fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class SetClient(client_ns.Client):
    """Set semantics over an IMap's keys (hazelcast.clj's map/crdt-map
    workloads): add = put(v, v), read = values()."""

    NAME = "jepsen-map"

    def __init__(self, conn: HazelcastClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return SetClient(HazelcastClient(node))

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.conn.map_put(self.NAME, int(op.value), int(op.value))
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(
                    type="ok", value=sorted(self.conn.map_values(self.NAME)))
        except (HazelcastError, OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class QueueClient(client_ns.Client):
    """IQueue enqueue/dequeue/drain (hazelcast.clj:387-388)."""

    NAME = "jepsen-queue"

    def __init__(self, conn: HazelcastClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return QueueClient(HazelcastClient(node))

    def invoke(self, test, op):
        try:
            if op.f == "enqueue":
                ok = self.conn.queue_offer(self.NAME, int(op.value))
                return op.replace(type="ok" if ok else "fail")
            if op.f == "dequeue":
                v = self.conn.queue_poll(self.NAME)
                if v is None:
                    return op.replace(type="fail")
                return op.replace(type="ok", value=v)
            if op.f == "drain":
                drained = []
                while True:
                    v = self.conn.queue_poll(self.NAME)
                    if v is None:
                        return op.replace(type="ok", value=drained)
                    drained.append(v)
        except (HazelcastError, OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class IdClient(client_ns.Client):
    """Unique ids from an IAtomicLong (hazelcast.clj:389-399)."""

    NAME = "jepsen-ids"

    def __init__(self, conn: HazelcastClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return IdClient(HazelcastClient(node))

    def invoke(self, test, op):
        try:
            if op.f == "generate":
                return op.replace(type="ok",
                                  value=self.conn.atomic_increment(
                                      self.NAME))
        except (HazelcastError, OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
