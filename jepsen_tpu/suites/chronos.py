"""Chronos suite — job-scheduler runs-vs-targets checking
(chronos/src/jepsen/chronos.clj + chronos/checker.clj).

A scheduled job with (start, interval, count, epsilon, duration) induces
*target* windows in which a run must begin: window i is
``[start + i*interval, start + i*interval + epsilon + forgiveness]``,
truncated to targets that must have begun by the final read
(checker.clj:30-46). The history's runs satisfy the schedule iff every
target can be assigned a *distinct* run starting inside its window.

The reference solves this with the loco/Choco CSP solver
(checker.clj:22-23,116-176); the assignment problem is exactly maximum
bipartite matching, solved here directly with augmenting paths — no
solver dependency, O(targets × runs) per augment.

The real cluster needs Mesos + Chronos (mesosphere.clj provisions
both); the wire client posts jobs over Chronos's HTTP API. No-cluster
runs use a fake scheduler that executes jobs in-process with jitter.
"""

from __future__ import annotations

import random
import threading
import time

from jepsen_tpu import checker as checker_ns
from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.checker import FnChecker
from jepsen_tpu.control import util as cu
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common

EPSILON_FORGIVENESS = 5  # seconds of extra grace (checker.clj:26-28)


def job_targets(read_time: float, job: dict) -> list[tuple[float, float]]:
    """Target windows that must have begun by read_time
    (checker.clj:30-46): cutoff is epsilon+duration before the read."""
    finish = read_time - job["epsilon"] - job["duration"]
    out = []
    t = job["start"]
    for _ in range(job["count"]):
        if t >= finish:
            break
        out.append((t, t + job["epsilon"] + EPSILON_FORGIVENESS))
        t += job["interval"]
    return out


def match_targets(targets: list[tuple[float, float]],
                  runs: list[float]) -> dict | None:
    """Assign each target a distinct run starting inside its window —
    maximum bipartite matching via augmenting paths. Returns
    {target index: run index} covering all targets, or None."""
    match_of_run: dict[int, int] = {}

    def augment(ti: int, seen: set[int]) -> bool:
        lo, hi = targets[ti]
        for ri, r in enumerate(runs):
            if ri in seen or not (lo <= r <= hi):
                continue
            seen.add(ri)
            if ri not in match_of_run or \
                    augment(match_of_run[ri], seen):
                match_of_run[ri] = ti
                return True
        return False

    for ti in range(len(targets)):
        if not augment(ti, set()):
            return None
    return {ti: ri for ri, ti in match_of_run.items()}


def job_solution(read_time: float, job: dict, runs: list[float]) -> dict:
    """The per-job verdict (checker.clj:116-176 job-solution shape)."""
    targets = job_targets(read_time, job)
    sol = match_targets(targets, sorted(runs))
    if sol is None:
        return {"valid?": False, "job": job, "targets": targets,
                "runs": sorted(runs), "solution": None}
    used = set(sol.values())
    extra = [r for i, r in enumerate(sorted(runs)) if i not in used]
    return {"valid?": True, "job": job, "solution": sol, "extra": extra}


def checker() -> checker_ns.Checker:
    """History checker: add-job invocations define the schedule; the
    final read carries {job name: [run start times]}
    (chronos/checker.clj:179-226)."""

    def check(test, model, history, opts):
        jobs: dict = {}
        read = None
        read_time = None
        for op in history:
            if op.f == "add-job" and op.is_ok:
                jobs[op.value["name"]] = op.value
            elif op.f == "read" and op.is_ok:
                read = op.value
                read_time = op.value.get("time") \
                    if isinstance(op.value, dict) else None
        if read is None:
            return {"valid?": "unknown", "error": "no final read"}
        runs_by_job = read.get("runs", {}) \
            if isinstance(read, dict) else {}
        if read_time is None:
            read_time = time.time()
        sols = {name: job_solution(read_time, job,
                                   runs_by_job.get(name, []))
                for name, job in jobs.items()}
        bad = {n: s for n, s in sols.items() if not s["valid?"]}
        return {"valid?": not bad, "job-count": len(jobs),
                "bad-jobs": {n: {"targets": s["targets"],
                                 "runs": s["runs"]}
                             for n, s in list(bad.items())[:5]}}

    return FnChecker(check)


class FakeScheduler:
    """In-process job scheduler: runs each job's occurrences on time with
    bounded jitter (within epsilon), recording start times."""

    def __init__(self, drop_prob: float = 0.0):
        self.jobs: dict = {}
        self.runs: dict = {}
        self.lock = threading.Lock()
        self.threads: list[threading.Thread] = []
        self.drop_prob = drop_prob

    def add(self, job: dict) -> None:
        with self.lock:
            self.jobs[job["name"]] = job
            self.runs.setdefault(job["name"], [])

        def run_job():
            t = job["start"]
            for _ in range(job["count"]):
                delay = t - time.time()
                if delay > 0:
                    time.sleep(delay)
                jitter = random.uniform(0, max(job["epsilon"] - 1, 0))
                if jitter:
                    time.sleep(min(jitter, 2))
                if random.random() >= self.drop_prob:
                    with self.lock:
                        self.runs[job["name"]].append(time.time())
                t += job["interval"]

        th = threading.Thread(target=run_job, daemon=True)
        th.start()
        self.threads.append(th)

    def read(self) -> dict:
        with self.lock:
            return {"time": time.time(),
                    "runs": {k: list(v) for k, v in self.runs.items()}}


class FakeChronosClient(client_ns.Client):
    def __init__(self, sched: FakeScheduler):
        self.sched = sched

    def open(self, test, node):
        return FakeChronosClient(self.sched)

    def invoke(self, test, op: Op) -> Op:
        if op.f == "add-job":
            self.sched.add(op.value)
            return op.replace(type="ok")
        if op.f == "read":
            return op.replace(type="ok", value=self.sched.read())
        return op.replace(type="fail", error=f"unknown f {op.f}")


# --- cluster provisioning (mesosphere.clj + chronos.clj db layers) ----------

MASTER_COUNT = 3                       # mesosphere.clj:17
MASTER_PIDFILE = "/var/run/mesos/master.pid"
AGENT_PIDFILE = "/var/run/mesos/slave.pid"
MASTER_DIR = "/var/lib/mesos/master"
AGENT_DIR = "/var/lib/mesos/slave"
MESOS_LOG_DIR = "/var/log/mesos"
JOB_DIR = "/tmp/chronos-test"


def zk_uri(test) -> str:
    """zk://n1:2181,...,n5:2181/mesos (mesosphere.clj:38-46)."""
    hosts = ",".join(f"{n}:2181" for n in test["nodes"])
    return f"zk://{hosts}/mesos"


def masters(test) -> list:
    """The first MASTER_COUNT nodes (sorted) run mesos-master; the rest
    run agents (mesosphere.clj:60-68)."""
    return sorted(test["nodes"])[:MASTER_COUNT]


class MesosDB(db_ns.DB, db_ns.LogFiles):
    """ZooKeeper + Mesos master/agent bring-up (mesosphere.clj:26-159:
    repo + package install, /etc/mesos/zk + quorum config, masters on
    the first three sorted nodes via start-stop-daemon, agents on the
    rest)."""

    def __init__(self, version: str = "1.11.0"):
        self.version = version
        from jepsen_tpu.suites.zookeeper import ZookeeperDB

        self.zk = ZookeeperDB()

    def setup(self, test, node) -> None:
        self.zk.setup(test, node)
        with control.su():
            os_debian.add_repo(
                "mesosphere",
                "deb http://repos.mesosphere.io/debian wheezy main",
                keyserver="keyserver.ubuntu.com", key="E56151BF")
            os_debian.install([f"mesos={self.version}"])
            control.exec_("mkdir", "-p", "/var/run/mesos", MASTER_DIR,
                          AGENT_DIR, MESOS_LOG_DIR)
            control.exec_("tee", "/etc/mesos/zk", stdin=zk_uri(test))
            control.exec_("tee", "/etc/mesos-master/quorum",
                          stdin=str(MASTER_COUNT // 2 + 1))
            if node in masters(test):
                cu.start_daemon(
                    "/usr/sbin/mesos-master",
                    f"--hostname={node}",
                    f"--log_dir={MESOS_LOG_DIR}",
                    f"--quorum={MASTER_COUNT // 2 + 1}",
                    "--registry_fetch_timeout=120secs",
                    "--registry_store_timeout=5secs",
                    f"--work_dir={MASTER_DIR}",
                    "--offer_timeout=30secs",
                    f"--zk={zk_uri(test)}",
                    logfile=f"{MESOS_LOG_DIR}/master.stdout",
                    pidfile=MASTER_PIDFILE, chdir=MASTER_DIR,
                    env={"GLOG_v": "1"})
            else:
                cu.start_daemon(
                    "/usr/sbin/mesos-slave",
                    f"--hostname={node}",
                    f"--log_dir={MESOS_LOG_DIR}",
                    "--recovery_timeout=30secs",
                    f"--work_dir={AGENT_DIR}",
                    f"--master={zk_uri(test)}",
                    logfile=f"{MESOS_LOG_DIR}/slave.stdout",
                    pidfile=AGENT_PIDFILE, chdir=AGENT_DIR)

    def teardown(self, test, node) -> None:
        with control.su():
            cu.grepkill("mesos-slave")
            cu.grepkill("mesos-master")
            control.exec_("rm", "-rf", MASTER_PIDFILE, AGENT_PIDFILE,
                          may_fail=True)
            control.exec_(control.Lit(
                f"rm -rf {MASTER_DIR}/* {AGENT_DIR}/* "
                f"{MESOS_LOG_DIR}/*"), may_fail=True)
        self.zk.teardown(test, node)

    def log_files(self, test, node) -> list[str]:
        return self.zk.log_files(test, node) + [
            f"{MESOS_LOG_DIR}/master.stdout",
            f"{MESOS_LOG_DIR}/slave.stdout"]


class ChronosDB(db_ns.DB, db_ns.LogFiles):
    """Chronos on top of Mesos (chronos.clj:57-83: package install,
    schedule-horizon config, service start; teardown stops the service
    and clears the job dir)."""

    def __init__(self, mesos_version: str = "1.11.0",
                 chronos_version: str = "3.0.2"):
        self.version = chronos_version
        self.mesos = MesosDB(mesos_version)

    def setup(self, test, node) -> None:
        self.mesos.setup(test, node)
        with control.su():
            os_debian.install([f"chronos={self.version}"])
            control.exec_("mkdir", "-p", "/etc/chronos/conf", JOB_DIR)
            # Lower the scheduler horizon or frequent jobs are skipped
            # (chronos.clj:40-45).
            control.exec_("tee", "/etc/chronos/conf/schedule_horizon",
                          stdin="1")
            control.exec_("service", "chronos", "start", may_fail=True)

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "chronos", "stop", may_fail=True)
            cu.grepkill("/usr/bin/chronos")
            control.exec_("rm", "-rf", JOB_DIR, may_fail=True)
        self.mesos.teardown(test, node)

    def log_files(self, test, node) -> list[str]:
        return self.mesos.log_files(test, node) + ["/var/log/messages"]


class ChronosClient(client_ns.Client):
    """Job submission over Chronos's HTTP API (chronos.clj:120-170);
    reading runs back requires the reference's remote run-log scrape."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return ChronosClient(node)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add-job":
                j = op.value
                body = {"name": j["name"],
                        "schedule": (f"R{j['count']}/"
                                     f"{j['start']}/PT{j['interval']}S"),
                        "epsilon": f"PT{j['epsilon']}S",
                        "command": f"echo run >> /tmp/chronos-{j['name']}"}
                status, _ = common.http_json(
                    "POST",
                    f"http://{self.node}:4400/scheduler/iso8601", body)
                return op.replace(
                    type="ok" if status in (200, 204) else "info")
        except OSError as e:
            return op.replace(type="info", error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def workload(n_jobs: int = 10, horizon: float = 10.0) -> dict:
    """Job-submission generator + final read (chronos.clj:180-260):
    random (interval, count, epsilon, duration) per job starting shortly
    after submission."""
    state = {"n": 0}
    lock = threading.Lock()

    def add_job(test, process):
        with lock:
            state["n"] += 1
            i = state["n"]
        if i > n_jobs:
            return None
        return {"type": "invoke", "f": "add-job",
                "value": {"name": f"job-{i}",
                          "start": time.time() + random.uniform(1, 3),
                          "interval": random.randint(2, 5),
                          "count": random.randint(1, 3),
                          "epsilon": random.randint(1, 2),
                          "duration": 0}}

    sched = FakeScheduler()
    return {
        "generator": gen.stagger(0.5, gen.gen(add_job)),
        # Let scheduled runs play out, then one read collects them.
        "final_generator": gen.then(
            gen.singlethreaded(gen.once({"type": "invoke", "f": "read",
                                         "value": None})),
            gen.sleep(horizon)),
        "client": FakeChronosClient(sched),
        "checker": checker(),
        "model": None,
    }


def test(opts: dict | None = None) -> dict:
    """The chronos test map (chronos.clj:240-280): a real-cluster run
    provisions ZooKeeper + Mesos masters/agents + Chronos via
    ChronosDB; ``--fake`` runs the in-process scheduler instead."""
    return common.suite_test(
        "chronos", opts,
        workload=workload(),
        db=ChronosDB(),
        client=ChronosClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(30, 30))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
