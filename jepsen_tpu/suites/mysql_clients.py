"""Workload clients for the MySQL-family suites (galera, percona,
mysql-cluster, tidb) over the native wire client
(:mod:`jepsen_tpu.suites.mysqlwire`).

These mirror the JDBC clients of the reference — galera.clj:40-120's
bank, galera/dirty_reads.clj:30-60's reader/writer pair,
tidb/{register,bank,sets}.clj — in MySQL dialect: no UPSERT (INSERT ...
ON DUPLICATE KEY UPDATE), no RETURNING (conditional CAS checks the OK
packet's affected-rows count), explicit BEGIN/COMMIT transactions with
the deadlock/write-conflict retry loop in MyClient.txn.
"""

from __future__ import annotations

from jepsen_tpu import client as client_ns
from jepsen_tpu import independent
from jepsen_tpu.history import Op
from jepsen_tpu.suites.mysqlwire import MyClient, MyError

PORT = 3306
DB = "jepsen"


def _fail_or_info(op: Op, e: Exception) -> Op:
    """Reads can safely fail (definitely didn't happen); writes whose
    fate is unknown crash the process (core.clj:185-217 semantics)."""
    definite = isinstance(e, MyError)
    return op.replace(
        type="fail" if (op.f == "read" or definite) else "info",
        error=str(e) if definite else repr(e))


class _SqlClient(client_ns.Client):
    """Shared open/close/setup plumbing: connect to the node's mysqld
    (``port`` varies: 3306 for mysqld/mariadb, 4000 for tidb-server),
    create the jepsen database + the client's table on first setup."""

    CREATE: tuple = ()       # DDL statements, run once against node 0

    def __init__(self, conn: MyClient | None = None, port: int = PORT,
                 **kw):
        self.conn = conn
        self.port = port
        self.kw = kw

    def _connect(self, node, database=DB):
        return MyClient(node, port=self.port, user="root",
                        database=database)

    def open(self, test, node):
        return type(self)(conn=self._connect(node), port=self.port,
                          **self.kw)

    def setup(self, test) -> None:
        conn = MyClient(test["nodes"][0], port=self.port, user="root")
        try:
            conn.query(f"CREATE DATABASE IF NOT EXISTS {DB}")
            for ddl in self.CREATE:
                conn.query(ddl.format(db=DB))
            self.populate(conn)
        finally:
            conn.close()

    def populate(self, conn: MyClient) -> None:
        pass

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class RegisterClient(_SqlClient):
    """Per-key linearizable register (tidb/register.clj:30-74): read =
    SELECT, write = INSERT .. ON DUPLICATE KEY UPDATE, cas = conditional
    UPDATE in a txn judged by affected-rows."""

    TABLE = f"{DB}.jepsen_registers"
    CREATE = (f"CREATE TABLE IF NOT EXISTS {TABLE} "
              f"(id INT PRIMARY KEY, val INT)",)

    def invoke(self, test, op: Op) -> Op:
        k, v = op.value if independent.is_tuple(op.value) else (0, op.value)

        def join(val):
            return independent.tuple_(k, val) \
                if independent.is_tuple(op.value) else val

        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT val FROM {self.TABLE} WHERE id = {int(k)}")
                val = int(rows[0][0]) if rows and rows[0][0] is not None \
                    else None
                return op.replace(type="ok", value=join(val))
            if op.f == "write":
                self.conn.query(
                    f"INSERT INTO {self.TABLE} (id, val) VALUES "
                    f"({int(k)}, {int(v)}) "
                    f"ON DUPLICATE KEY UPDATE val = {int(v)}")
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = v
                self.conn.txn([
                    f"UPDATE {self.TABLE} SET val = {int(new)} "
                    f"WHERE id = {int(k)} AND val = {int(old)}"])
                return op.replace(
                    type="ok" if self.conn.last_affected == 1 else "fail")
        except (MyError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class BankClient(_SqlClient):
    """Balance transfers in explicit transactions (galera.clj bank,
    tidb/bank.clj): the conditional debit must not overdraw."""

    TABLE = f"{DB}.jepsen_accounts"
    CREATE = (f"CREATE TABLE IF NOT EXISTS {TABLE} "
              f"(id INT PRIMARY KEY, balance INT NOT NULL)",)

    def __init__(self, conn=None, port: int = PORT, n: int = 5,
                 total: int = 50):
        super().__init__(conn=conn, port=port, n=n, total=total)
        self.n = n
        self.total = total

    def populate(self, conn: MyClient) -> None:
        for i in range(self.n):
            conn.query(f"INSERT IGNORE INTO {self.TABLE} VALUES "
                       f"({i}, {self.total // self.n})")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT balance FROM {self.TABLE} ORDER BY id")
                return op.replace(type="ok",
                                  value=[int(r[0]) for r in rows])
            if op.f == "transfer":
                t = op.value
                for attempt in range(5):
                    try:
                        self.conn.query("BEGIN")
                        self.conn.query(
                            f"UPDATE {self.TABLE} SET balance = balance - "
                            f"{t['amount']} WHERE id = {t['from']} AND "
                            f"balance >= {t['amount']}")
                        if self.conn.last_affected != 1:
                            self.conn.query("ROLLBACK")
                            return op.replace(type="fail",
                                              error="insufficient funds")
                        self.conn.query(
                            f"UPDATE {self.TABLE} SET balance = balance + "
                            f"{t['amount']} WHERE id = {t['to']}")
                        self.conn.query("COMMIT")
                        return op.replace(type="ok")
                    except MyError as e:
                        try:
                            self.conn.query("ROLLBACK")
                        except (MyError, ConnectionError, OSError):
                            pass
                        if not e.retryable or attempt == 4:
                            return op.replace(type="fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class TableClient(_SqlClient):
    """Dirty-reads probe (galera/dirty_reads.clj:30-77): inserts commit
    or deliberately abort; readers must never observe aborted rows."""

    TABLE = f"{DB}.jepsen_rows"
    CREATE = (f"CREATE TABLE IF NOT EXISTS {TABLE} "
              f"(id INT PRIMARY KEY)",)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "insert":
                abort = op.get("abort", False)
                self.conn.query("BEGIN")
                try:
                    self.conn.query(
                        f"INSERT INTO {self.TABLE} VALUES ({int(op.value)})")
                finally:
                    self.conn.query("ROLLBACK" if abort else "COMMIT")
                return op.replace(type="fail" if abort else "ok")
            if op.f == "read":
                rows = self.conn.query(f"SELECT id FROM {self.TABLE}")
                return op.replace(type="ok",
                                  value=[int(r[0]) for r in rows])
        except (MyError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class SetClient(_SqlClient):
    """Concurrent adds + final read (tidb/sets.clj:53-55)."""

    TABLE = f"{DB}.jepsen_sets"
    CREATE = (f"CREATE TABLE IF NOT EXISTS {TABLE} "
              f"(val INT PRIMARY KEY)",)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.query(
                    f"INSERT INTO {self.TABLE} VALUES ({int(op.value)})")
                return op.replace(type="ok")
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT val FROM {self.TABLE} ORDER BY val")
                return op.replace(type="ok",
                                  value=[int(r[0]) for r in rows])
        except (MyError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class CounterClient(_SqlClient):
    """Increments + reads against a single row (mysql-cluster's
    simple-test shape over ndb)."""

    TABLE = f"{DB}.jepsen_counter"
    CREATE = (f"CREATE TABLE IF NOT EXISTS {TABLE} "
              f"(id INT PRIMARY KEY, val INT NOT NULL)",)

    def populate(self, conn: MyClient) -> None:
        conn.query(f"INSERT IGNORE INTO {self.TABLE} VALUES (0, 0)")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                self.conn.query(f"UPDATE {self.TABLE} "
                                f"SET val = val + {int(op.value)} "
                                f"WHERE id = 0")
                return op.replace(type="ok")
            if op.f == "read":
                rows = self.conn.query(
                    f"SELECT val FROM {self.TABLE} WHERE id = 0")
                return op.replace(type="ok", value=int(rows[0][0]))
        except (MyError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class TxnAppendClient(_SqlClient):
    """List-append transactions in MySQL dialect (the Elle workload,
    doc/txn.md; tidb + galera): micro-ops inside one BEGIN/COMMIT —
    append = INSERT .. ON DUPLICATE KEY UPDATE CONCAT, read = SELECT.
    These stores claim snapshot isolation at best (TiDB rejects
    ``SET ... SERIALIZABLE`` outright; Galera/InnoDB runs REPEATABLE
    READ), so the suites register ``txn_workload(consistency=
    "snapshot-isolation")`` — asserting serializability here would
    convict healthy write skew the store never promised to prevent.
    Errors raised by the COMMIT itself (or a dropped connection after
    writes) complete ``:info`` — the txn may have applied; statement
    errors inside the txn roll back and fail definitely."""

    TABLE = f"{DB}.jepsen_txn"
    CREATE = (f"CREATE TABLE IF NOT EXISTS {TABLE} "
              f"(k INT PRIMARY KEY, vals TEXT)",)

    def _mop(self, f, k, v):
        if f == "append":
            self.conn.query(
                f"INSERT INTO {self.TABLE} (k, vals) VALUES "
                f"({int(k)}, '{int(v)}') ON DUPLICATE KEY UPDATE "
                f"vals = CONCAT(vals, ',{int(v)}')")
            return ["append", k, v]
        rows = self.conn.query(
            f"SELECT vals FROM {self.TABLE} WHERE k = {int(k)}")
        obs = [] if not rows or rows[0][0] in (None, "") \
            else [int(x) for x in str(rows[0][0]).split(",")]
        return ["r", k, obs]

    def invoke(self, test, op: Op) -> Op:
        if op.f != "txn":
            return op.replace(type="fail", error=f"unknown f {op.f}")
        try:
            self.conn.query("BEGIN")
            try:
                done = [self._mop(*m) for m in op.value]
            except MyError as e:
                try:
                    self.conn.query("ROLLBACK")
                except (MyError, OSError):
                    pass
                return op.replace(type="fail", error=str(e))
            try:
                self.conn.query("COMMIT")
            except (MyError, OSError, ConnectionError) as e:
                # The commit's fate is unknown: it may have applied.
                return op.replace(type="info", error=repr(e))
            return op.replace(type="ok", value=done)
        except MyError as e:
            # Only BEGIN can land here (statements and COMMIT have
            # their own handlers above): nothing applied — fail.
            return op.replace(type="fail", error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="info", error=repr(e))


def bank_or_dirty_reads(name: str, port: int = PORT):
    """(workload, client) for the galera/percona workload registry: the
    shared bank/dirty-reads/txn mapping both suites expose."""
    from jepsen_tpu.suites import workloads

    if name == "bank":
        return workloads.bank_workload(), BankClient(port=port)
    if name == "txn":
        return (workloads.txn_workload(consistency="snapshot-isolation"),
                TxnAppendClient(port=port))
    return workloads.dirty_read_workload(), TableClient(port=port)
