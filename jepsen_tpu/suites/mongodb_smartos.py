"""MongoDB-on-SmartOS suite — document CAS + bank transfer
(mongodb-smartos/src/jepsen/mongodb_smartos/{core,document_cas,transfer}.clj).

The one suite that runs on SmartOS (os/smartos.clj pkgin provisioning —
core.clj:60-150 installs mongod via pkgin and drives it through svcadm).
Workloads: per-document CAS register checked linearizable
(document_cas.clj, core.clj:390-392 — the reference defines a custom
knossos Model inline at core.clj:34,198-205; here the stock
cas-register device kernel covers it) and the bank transfer
(transfer.clj). The Mongo wire protocol (OP_MSG + BSON) is spoken from
scratch by jepsen_tpu.suites.mongowire.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_smartos
from jepsen_tpu.suites import common, workloads


class MongoSmartosDB(db_ns.DB, db_ns.LogFiles):
    """pkgin install + replica-set init via svcadm
    (mongodb_smartos/core.clj:60-200)."""

    def setup(self, test, node) -> None:
        with control.su():
            control.exec_("pkgin", "-y", "install", "mongodb",
                          may_fail=True)
            config = (f"replication:\n  replSetName: jepsen\n"
                      f"net:\n  bindIp: {node}\n")
            control.exec_("tee", "/opt/local/etc/mongod.conf",
                          stdin=config)
            control.exec_("svcadm", "enable", "mongodb", may_fail=True)
        if node == test["nodes"][0]:
            self._initiate(test)

    def _initiate(self, test) -> None:
        """replSetInitiate from the harness over the wire client
        (core.clj's replica-set bring-up), retried until mongod answers.
        AlreadyInitialized (code 23) makes re-runs idempotent."""
        import time

        from jepsen_tpu.suites.mongowire import MongoClient, MongoError

        members = [{"_id": i, "host": f"{n}:27017"}
                   for i, n in enumerate(test["nodes"])]
        deadline = time.time() + 60
        while True:
            try:
                conn = MongoClient(test["nodes"][0], follow_primary=False)
                try:
                    conn.command("admin", {"replSetInitiate": {
                        "_id": "jepsen", "members": members}})
                finally:
                    conn.close()
                return
            except MongoError as e:
                if e.code == 23:        # AlreadyInitialized
                    return
                if time.time() > deadline:
                    raise
            except (OSError, ConnectionError):
                if time.time() > deadline:
                    raise
            time.sleep(1)

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("svcadm", "disable", "mongodb", may_fail=True)
            control.exec_("rm", "-rf", "/var/mongodb", may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/mongodb/mongod.log"]


def test(opts: dict | None = None) -> dict:
    """The mongodb-smartos test map (core.clj:360-400). ``workload``
    picks document-cas (default) or transfer."""
    opts = dict(opts or {})
    name = opts.pop("workload", None) or "document-cas"
    from jepsen_tpu.suites import mongowire

    if name == "document-cas":
        wl = workloads.register()
        client = mongowire.DocumentCasClient()
        threads_per_key = 10
        if opts.get("concurrency", 0) < threads_per_key:
            opts["concurrency"] = threads_per_key
    else:
        # One source of truth for the bank shape: the client seeds the
        # same accounts/total the workload's checker validates.
        n_accounts, total = 5, 50
        wl = workloads.bank_workload(n_accounts=n_accounts, total=total)
        client = mongowire.BankClient(n=n_accounts, total=total)
    return common.suite_test(
        f"mongodb-smartos {name}", opts,
        workload=wl,
        db=MongoSmartosDB(),
        client=client,
        os=os_smartos.os,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="document-cas",
                       choices=["document-cas", "transfer"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
