"""Minimal MongoDB wire-protocol client with a built-in BSON codec.

The reference drives MongoDB through the official driver
(mongodb-smartos/src/jepsen/mongodb_smartos/core.clj, document_cas.clj);
the TPU build speaks the wire protocol from the stdlib. Commands run as
BSON documents over OP_QUERY against ``$cmd`` (MongoDB 2.6-5.0, the
reference's era) or OP_MSG (3.6+), selected by the handshake's
``maxWireVersion`` — so both the old SmartOS mongod and a modern one
work.

BSON subset: double, string, document, array, bool, null, int32, int64,
ObjectId (opaque 12 bytes), binary (opaque) — everything the
document-CAS / bank / insert workloads touch. Unknown element types
raise rather than silently mis-parse.
"""

from __future__ import annotations

import socket
import struct
import threading

from jepsen_tpu import client as client_ns
from jepsen_tpu.suites.common import SocketIO

OP_QUERY = 2004
OP_REPLY = 1
OP_MSG = 2013


class MongoError(Exception):
    """A definite server-reported command error: the op did not happen."""

    def __init__(self, doc: dict):
        self.doc = doc
        super().__init__(doc.get("errmsg") or doc.get("$err")
                         or f"mongo error {doc.get('code')}")

    @property
    def code(self):
        return self.doc.get("code")


class MongoIndeterminate(MongoError):
    """The command may or may not have applied: reply unparsable, or the
    server acknowledged the write but reported a write-concern failure
    (the write can still be rolled back on primary step-down). Ops
    hitting this must complete :info, never :fail."""


# --- BSON ---------------------------------------------------------------


def _enc_elem(key: str, v) -> bytes:
    k = key.encode() + b"\x00"
    if isinstance(v, bool):
        return b"\x08" + k + (b"\x01" if v else b"\x00")
    if isinstance(v, int):
        if -(2 ** 31) <= v < 2 ** 31:
            return b"\x10" + k + struct.pack("<i", v)
        return b"\x12" + k + struct.pack("<q", v)
    if isinstance(v, float):
        return b"\x01" + k + struct.pack("<d", v)
    if isinstance(v, str):
        b = v.encode()
        return b"\x02" + k + struct.pack("<i", len(b) + 1) + b + b"\x00"
    if v is None:
        return b"\x0a" + k
    if isinstance(v, dict):
        return b"\x03" + k + bson_encode(v)
    if isinstance(v, (list, tuple)):
        doc = {str(i): x for i, x in enumerate(v)}
        return b"\x04" + k + bson_encode(doc)
    if isinstance(v, bytes) and len(v) == 12:      # ObjectId passthrough
        return b"\x07" + k + v
    raise TypeError(f"cannot BSON-encode {type(v).__name__}: {v!r}")


def bson_encode(doc: dict) -> bytes:
    body = b"".join(_enc_elem(k, v) for k, v in doc.items())
    return struct.pack("<i", len(body) + 5) + body + b"\x00"


def _dec_elem(b: bytes, off: int):
    t = b[off]
    off += 1
    end = b.index(b"\x00", off)
    key = b[off:end].decode()
    off = end + 1
    if t == 0x01:
        return key, struct.unpack_from("<d", b, off)[0], off + 8
    if t == 0x02:
        (n,) = struct.unpack_from("<i", b, off)
        return key, b[off + 4:off + 3 + n].decode(), off + 4 + n
    if t in (0x03, 0x04):
        (n,) = struct.unpack_from("<i", b, off)
        doc = bson_decode(b[off:off + n])
        if t == 0x04:
            doc = [doc[str(i)] for i in range(len(doc))]
        return key, doc, off + n
    if t == 0x05:                                  # binary: opaque
        (n,) = struct.unpack_from("<i", b, off)
        return key, b[off + 5:off + 5 + n], off + 5 + n
    if t == 0x07:
        return key, b[off:off + 12], off + 12
    if t == 0x08:
        return key, b[off] != 0, off + 1
    if t == 0x09 or t == 0x12:                     # datetime / int64
        return key, struct.unpack_from("<q", b, off)[0], off + 8
    if t == 0x0A:
        return key, None, off
    if t == 0x10:
        return key, struct.unpack_from("<i", b, off)[0], off + 4
    if t == 0x11:                                  # timestamp
        return key, struct.unpack_from("<Q", b, off)[0], off + 8
    raise ValueError(f"unsupported BSON element type 0x{t:02x} at {off}")


def bson_decode(b: bytes) -> dict:
    (n,) = struct.unpack_from("<i", b, 0)
    out: dict = {}
    off = 4
    while off < n - 1:
        key, v, off = _dec_elem(b, off)
        out[key] = v
    return out


# --- wire client ---------------------------------------------------------


class MongoClient:
    def __init__(self, host: str, port: int = 27017,
                 timeout: float = 10.0, follow_primary: bool = True):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.req_id = 0
        self.lock = threading.Lock()
        hello = self._command_query("admin", {"ismaster": 1})
        self.use_msg = hello.get("maxWireVersion", 0) >= 6
        # Replica-set primary routing: writes against a secondary fail
        # NotMaster, so follow the hello response's primary pointer (the
        # driver behavior the reference's client gets from mongo-java).
        primary = hello.get("primary")
        if follow_primary and primary and not hello.get("ismaster", True):
            phost, _, pport = primary.partition(":")
            if (phost, int(pport or port)) != (host, port):
                self.io.close()
                self.io = SocketIO(socket.create_connection(
                    (phost, int(pport or port)), timeout=timeout))
                hello = self._command_query("admin", {"ismaster": 1})
                self.use_msg = hello.get("maxWireVersion", 0) >= 6

    def _send(self, opcode: int, payload: bytes) -> int:
        self.req_id += 1
        head = struct.pack("<iiii", len(payload) + 16, self.req_id, 0,
                           opcode)
        self.io.send(head + payload)
        return self.req_id

    def _recv(self) -> tuple[int, bytes]:
        head = self.io.read_exact(16)
        length, _, _, opcode = struct.unpack("<iiii", head)
        return opcode, self.io.read_exact(length - 16)

    def _command_query(self, db: str, cmd: dict) -> dict:
        """Command via OP_QUERY on <db>.$cmd (wire versions < 6)."""
        payload = (struct.pack("<i", 0) + f"{db}.$cmd\x00".encode()
                   + struct.pack("<ii", 0, -1) + bson_encode(cmd))
        self._send(OP_QUERY, payload)
        opcode, body = self._recv()
        if opcode != OP_REPLY:
            # The command was sent: an unparsable reply is indeterminate.
            raise MongoIndeterminate(
                {"errmsg": f"unexpected opcode {opcode}"})
        # flags i32, cursorId i64, startingFrom i32, numberReturned i32
        (num,) = struct.unpack_from("<i", body, 16)
        if num < 1:
            raise MongoIndeterminate({"errmsg": "empty reply"})
        doc = bson_decode(body[20:])
        return self._check(doc)

    def _command_msg(self, db: str, cmd: dict) -> dict:
        """Command via OP_MSG (wire versions >= 6)."""
        body = dict(cmd)
        body["$db"] = db
        payload = struct.pack("<I", 0) + b"\x00" + bson_encode(body)
        self._send(OP_MSG, payload)
        opcode, resp = self._recv()
        if opcode != OP_MSG:
            raise MongoIndeterminate(
                {"errmsg": f"unexpected opcode {opcode}"})
        if resp[4:5] != b"\x00":
            raise MongoIndeterminate({"errmsg": "unexpected OP_MSG section"})
        return self._check(bson_decode(resp[5:]))

    @staticmethod
    def _check(doc: dict) -> dict:
        if doc.get("ok") not in (1, 1.0, True):
            raise MongoError(doc)
        errs = doc.get("writeErrors")
        if errs:
            raise MongoError(errs[0])
        if doc.get("writeConcernError"):
            # Acknowledged but under-replicated: may roll back later.
            raise MongoIndeterminate(doc["writeConcernError"])
        return doc

    def command(self, db: str, cmd: dict) -> dict:
        with self.lock:
            if self.use_msg:
                return self._command_msg(db, cmd)
            return self._command_query(db, cmd)

    # --- the operations the workloads use --------------------------------

    def find_one(self, db: str, coll: str, query: dict) -> dict | None:
        r = self.command(db, {"find": coll, "filter": query, "limit": 1,
                              "singleBatch": True})
        batch = r.get("cursor", {}).get("firstBatch", [])
        return batch[0] if batch else None

    def find_all(self, db: str, coll: str, query: dict | None = None) \
            -> list[dict]:
        r = self.command(db, {"find": coll, "filter": query or {},
                              "singleBatch": True, "batchSize": 10 ** 6})
        return r.get("cursor", {}).get("firstBatch", [])

    def insert(self, db: str, coll: str, doc: dict, majority=True) -> None:
        cmd = {"insert": coll, "documents": [doc]}
        if majority:
            cmd["writeConcern"] = {"w": "majority"}
        self.command(db, cmd)

    def upsert(self, db: str, coll: str, query: dict, update: dict,
               majority=True) -> None:
        cmd = {"update": coll,
               "updates": [{"q": query, "u": update, "upsert": True}]}
        if majority:
            cmd["writeConcern"] = {"w": "majority"}
        self.command(db, cmd)

    def find_and_modify(self, db: str, coll: str, query: dict,
                        update: dict, majority=True) -> dict | None:
        """Atomic conditional update returning the PRE-image (None if the
        query matched nothing) — the document-CAS primitive
        (document_cas.clj)."""
        cmd = {"findAndModify": coll, "query": query, "update": update}
        if majority:
            cmd["writeConcern"] = {"w": "majority"}
        r = self.command(db, cmd)
        return r.get("value")

    def close(self) -> None:
        try:
            self.io.close()
        except OSError:
            pass


# --- workload clients -----------------------------------------------------

DB = "jepsen"


def _fail_or_info(op, e: Exception):
    definite = isinstance(e, MongoError) \
        and not isinstance(e, MongoIndeterminate)
    return op.replace(
        type="fail" if (op.f == "read" or definite) else "info",
        error=str(e) if isinstance(e, MongoError) else repr(e))


class _MongoSuiteClient(client_ns.Client):
    """Shared plumbing (jepsen_tpu.client.Client surface)."""

    COLL = "jepsen"

    def __init__(self, conn: MongoClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return type(self)(MongoClient(node))

    def setup(self, test) -> None:
        pass

    def teardown(self, test) -> None:
        pass

    def invoke(self, test, op):
        raise NotImplementedError

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class DocumentCasClient(_MongoSuiteClient):
    """Per-key register over one document per key
    (mongodb_smartos/document_cas.clj): read = find, write = upsert with
    majority write concern, cas = findAndModify conditioned on the
    current value (atomic within one document)."""

    COLL = "registers"

    def invoke(self, test, op):
        from jepsen_tpu import independent

        k, v = op.value if independent.is_tuple(op.value) \
            else (0, op.value)

        def join(val):
            return independent.tuple_(k, val) \
                if independent.is_tuple(op.value) else val

        try:
            if op.f == "read":
                doc = self.conn.find_one(DB, self.COLL, {"_id": int(k)})
                return op.replace(
                    type="ok",
                    value=join(None if doc is None else doc.get("value")))
            if op.f == "write":
                self.conn.upsert(DB, self.COLL, {"_id": int(k)},
                                 {"$set": {"value": int(v)}})
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = v
                pre = self.conn.find_and_modify(
                    DB, self.COLL, {"_id": int(k), "value": int(old)},
                    {"$set": {"value": int(new)}})
                return op.replace(type="ok" if pre is not None else "fail")
        except (MongoError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class BankClient(_MongoSuiteClient):
    """Balance transfers (mongodb_smartos/transfer.clj shape): the debit
    is an atomic conditional findAndModify; debit and credit are NOT
    one transaction (the reference era predates multi-document txns) —
    exactly the anomaly surface the bank checker probes."""

    COLL = "accounts"

    def __init__(self, conn=None, n: int = 5, total: int = 50):
        super().__init__(conn)
        self.n = n
        self.total = total

    def open(self, test, node):
        return BankClient(MongoClient(node), self.n, self.total)

    def setup(self, test) -> None:
        conn = MongoClient(test["nodes"][0])
        try:
            for i in range(self.n):
                if conn.find_one(DB, self.COLL, {"_id": i}) is None:
                    conn.insert(DB, self.COLL,
                                {"_id": i,
                                 "balance": self.total // self.n})
        finally:
            conn.close()

    def invoke(self, test, op):
        try:
            if op.f == "read":
                docs = self.conn.find_all(DB, self.COLL)
                docs.sort(key=lambda d: d["_id"])
                return op.replace(type="ok",
                                  value=[int(d["balance"]) for d in docs])
            if op.f == "transfer":
                t = op.value
                pre = self.conn.find_and_modify(
                    DB, self.COLL,
                    {"_id": t["from"], "balance": {"$gte": t["amount"]}},
                    {"$inc": {"balance": -t["amount"]}})
                if pre is None:
                    return op.replace(type="fail",
                                      error="insufficient funds")
                try:
                    self.conn.find_and_modify(
                        DB, self.COLL, {"_id": t["to"]},
                        {"$inc": {"balance": t["amount"]}})
                except (MongoError, OSError, ConnectionError) as e:
                    # The debit already applied: half-applied transfers
                    # are indeterminate, never "fail" (= no effect).
                    return op.replace(type="info",
                                      error=f"credit leg: {e!r}")
                return op.replace(type="ok")
        except (MongoError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")


class TableClient(_MongoSuiteClient):
    """Insert/read rows (mongodb_rocks perf harness shape)."""

    COLL = "rows"

    def invoke(self, test, op):
        try:
            if op.f == "insert":
                self.conn.insert(DB, self.COLL, {"_id": int(op.value)})
                return op.replace(type="ok")
            if op.f == "read":
                docs = self.conn.find_all(DB, self.COLL)
                return op.replace(
                    type="ok", value=sorted(int(d["_id"]) for d in docs))
        except (MongoError, OSError, ConnectionError) as e:
            return _fail_or_info(op, e)
        return op.replace(type="fail", error=f"unknown f {op.f}")
