"""LogCabin suite — CAS register via the TreeOps CLI
(logcabin/src/jepsen/logcabin.clj).

LogCabin is the Raft reference implementation; its test drives a CAS
register through the ``TreeOps`` binary executed *on the node over the
control plane* (logcabin.clj:163-204) — reads/writes pipe through
``echo -n value | TreeOps -c <servers> write <path>``, CAS adds the
``-p path:oldvalue`` condition. This is the one suite whose client IS
the SSH layer, so it exercises the control plane end to end.
"""

from __future__ import annotations

import json

from jepsen_tpu import client as client_ns
from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.control import RemoteError
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

TREEOPS = "/root/TreeOps"
PATH = "/jepsen"
OP_TIMEOUT = 3


def server_addrs(test) -> str:
    return ",".join(f"{n}:5254" for n in test["nodes"])


class LogCabinDB(db_ns.DB, db_ns.LogFiles):
    """Build-from-source install + daemon bootstrap: first node
    bootstraps the Raft config, all run logcabind (logcabin.clj:36-140)."""

    dir = "/root/logcabin"
    storage = "/root/storage"
    logfile = "/root/logcabin.log"
    pidfile = "/root/logcabin.pid"

    def _config(self, test, node) -> str:
        sid = test["nodes"].index(node) + 1
        return (f"serverId = {sid}\n"
                f"listenAddresses = {node}:5254\n"
                f"storagePath = {self.storage}\n")

    def setup(self, test, node) -> None:
        with control.su():
            control.exec_("tee", "/root/logcabin.conf",
                          stdin=self._config(test, node))
            if node == test["nodes"][0]:
                control.exec_(f"{self.dir}/build/LogCabin",
                              "--config", "/root/logcabin.conf",
                              "--bootstrap", may_fail=True)
            from jepsen_tpu.control import util as cu

            cu.start_daemon(f"{self.dir}/build/LogCabin",
                            "--config", "/root/logcabin.conf",
                            logfile=self.logfile, pidfile=self.pidfile,
                            chdir="/root")

    def teardown(self, test, node) -> None:
        from jepsen_tpu.control import util as cu

        with control.su():
            cu.stop_daemon(self.pidfile, binary="LogCabin")
            control.exec_("rm", "-rf", self.storage, may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return [self.logfile]


class LogCabinClient(client_ns.Client):
    """read/write/cas through TreeOps over the control plane
    (logcabin.clj:163-246): CAS failure is reported by message match."""

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return LogCabinClient(node)

    def _treeops(self, test, *args, stdin=None) -> str:
        def go():
            with control.su(), control.cd("/root"):
                return control.exec_(TREEOPS, "-c", server_addrs(test),
                                     "-q", "-t", OP_TIMEOUT, *args,
                                     stdin=stdin)
        return control.on(test, self.node, go)

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                raw = self._treeops(test, "read", PATH)
                return op.replace(type="ok",
                                  value=json.loads(raw) if raw else None)
            if op.f == "write":
                self._treeops(test, "write", PATH,
                              stdin=json.dumps(op.value))
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = op.value
                try:
                    self._treeops(test, "-p", f"{PATH}:{json.dumps(old)}",
                                  "write", PATH, stdin=json.dumps(new))
                    return op.replace(type="ok")
                except RemoteError as e:
                    if "CONDITION_NOT_MET" in str(e):
                        return op.replace(type="fail")
                    raise
        except RemoteError as e:
            if "timeout" in str(e).lower():
                t = "fail" if op.f == "read" else "info"
                return op.replace(type=t, error="timed-out")
            raise
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def test(opts: dict | None = None) -> dict:
    """The logcabin test map (logcabin.clj:253-282)."""
    return common.suite_test(
        "logcabin", opts,
        workload=workloads.single_register(),
        db=LogCabinDB(),
        client=LogCabinClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
