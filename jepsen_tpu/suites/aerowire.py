"""Minimal Aerospike wire-protocol client.

The reference drives Aerospike through the official Java client
(aerospike/src/aerospike/core.clj:330-480); the TPU build speaks the
binary data protocol from the stdlib: the 8-byte proto header
(version 2, type 3, 48-bit length), the 22-byte message header with
info/result/generation words, fields (namespace, set, RIPEMD160 key
digest), and bin operations (read-all, write, add). CAS is a write with
an expected generation (info2 GENERATION bit, result code 3 on
mismatch) — the same read-version-then-conditional-write shape the
reference's check-and-set uses (core.clj:408-430).
"""

from __future__ import annotations

import hashlib
import socket
import struct

from jepsen_tpu import client as client_ns
from jepsen_tpu.suites.common import SocketIO

# info bits
INFO1_READ = 1
INFO1_GET_ALL = 2
INFO2_WRITE = 1
INFO2_GENERATION = 2

# ops
OP_READ = 1
OP_WRITE = 2
OP_INCR = 5

# bin types
BIN_INT = 1
BIN_STR = 3

# fields
FIELD_NAMESPACE = 0
FIELD_SET = 1
FIELD_DIGEST = 4

RC_OK = 0
RC_NOT_FOUND = 2
RC_GENERATION = 3


class AerospikeError(Exception):
    def __init__(self, code: int):
        self.code = code
        super().__init__(f"aerospike result code {code}")

    @property
    def not_found(self):
        return self.code == RC_NOT_FOUND

    @property
    def generation_mismatch(self):
        return self.code == RC_GENERATION


# --- RIPEMD-160 ------------------------------------------------------------
#
# OpenSSL 3 ships ripemd160 in the (often disabled) legacy provider, so
# hashlib may not have it; the pure-Python implementation below is the
# fallback. Every Aerospike client computes this digest client-side.

def _rmd160_py(msg: bytes) -> bytes:
    # Standard RIPEMD-160 (ISO/IEC 10118-3), 32-bit word little-endian.
    r1 = [0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15,
          7, 4, 13, 1, 10, 6, 15, 3, 12, 0, 9, 5, 2, 14, 11, 8,
          3, 10, 14, 4, 9, 15, 8, 1, 2, 7, 0, 6, 13, 11, 5, 12,
          1, 9, 11, 10, 0, 8, 12, 4, 13, 3, 7, 15, 14, 5, 6, 2,
          4, 0, 5, 9, 7, 12, 2, 10, 14, 1, 3, 8, 11, 6, 15, 13]
    r2 = [5, 14, 7, 0, 9, 2, 11, 4, 13, 6, 15, 8, 1, 10, 3, 12,
          6, 11, 3, 7, 0, 13, 5, 10, 14, 15, 8, 12, 4, 9, 1, 2,
          15, 5, 1, 3, 7, 14, 6, 9, 11, 8, 12, 2, 10, 0, 4, 13,
          8, 6, 4, 1, 3, 11, 15, 0, 5, 12, 2, 13, 9, 7, 10, 14,
          12, 15, 10, 4, 1, 5, 8, 7, 6, 2, 13, 14, 0, 3, 9, 11]
    s1 = [11, 14, 15, 12, 5, 8, 7, 9, 11, 13, 14, 15, 6, 7, 9, 8,
          7, 6, 8, 13, 11, 9, 7, 15, 7, 12, 15, 9, 11, 7, 13, 12,
          11, 13, 6, 7, 14, 9, 13, 15, 14, 8, 13, 6, 5, 12, 7, 5,
          11, 12, 14, 15, 14, 15, 9, 8, 9, 14, 5, 6, 8, 6, 5, 12,
          9, 15, 5, 11, 6, 8, 13, 12, 5, 12, 13, 14, 11, 8, 5, 6]
    s2 = [8, 9, 9, 11, 13, 15, 15, 5, 7, 7, 8, 11, 14, 14, 12, 6,
          9, 13, 15, 7, 12, 8, 9, 11, 7, 7, 12, 7, 6, 15, 13, 11,
          9, 7, 15, 11, 8, 6, 6, 14, 12, 13, 5, 14, 13, 13, 7, 5,
          15, 5, 8, 11, 14, 14, 6, 14, 6, 9, 12, 9, 12, 5, 15, 8,
          8, 5, 12, 9, 12, 5, 14, 6, 8, 13, 6, 5, 15, 13, 11, 11]
    k1 = [0, 0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xA953FD4E]
    k2 = [0x50A28BE6, 0x5C4DD124, 0x6D703EF3, 0x7A6D76E9, 0]

    def f(j, x, y, z):
        if j < 16:
            return x ^ y ^ z
        if j < 32:
            return (x & y) | (~x & z)
        if j < 48:
            return (x | ~y) ^ z
        if j < 64:
            return (x & z) | (y & ~z)
        return x ^ (y | ~z)

    def rol(x, n):
        x &= 0xFFFFFFFF
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    h = [0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476, 0xC3D2E1F0]
    padded = msg + b"\x80" + b"\x00" * ((55 - len(msg)) % 64) \
        + struct.pack("<Q", 8 * len(msg))
    for off in range(0, len(padded), 64):
        x = struct.unpack("<16I", padded[off:off + 64])
        a1, b1, c1, d1, e1 = h
        a2, b2, c2, d2, e2 = h
        for j in range(80):
            a1 = rol(a1 + f(j, b1, c1, d1) + x[r1[j]] + k1[j // 16],
                     s1[j]) + e1 & 0xFFFFFFFF
            a1, b1, c1, d1, e1 = e1, a1, b1, rol(c1, 10), d1
            a2 = rol(a2 + f(79 - j, b2, c2, d2) + x[r2[j]]
                     + k2[j // 16], s2[j]) + e2 & 0xFFFFFFFF
            a2, b2, c2, d2, e2 = e2, a2, b2, rol(c2, 10), d2
        t = (h[1] + c1 + d2) & 0xFFFFFFFF
        h = [t, (h[2] + d1 + e2) & 0xFFFFFFFF,
             (h[3] + e1 + a2) & 0xFFFFFFFF,
             (h[4] + a1 + b2) & 0xFFFFFFFF,
             (h[0] + b1 + c2) & 0xFFFFFFFF]
    return struct.pack("<5I", *h)


def _rmd160(data: bytes) -> bytes:
    try:
        h = hashlib.new("ripemd160")
    except (ValueError, TypeError):
        return _rmd160_py(data)
    h.update(data)
    return h.digest()


def digest(set_name: str, key) -> bytes:
    """RIPEMD160 over set + key-type + key bytes (the client-side record
    digest every Aerospike client computes)."""
    if isinstance(key, int):
        kt, kb = 1, struct.pack(">q", key)
    else:
        kt, kb = 3, str(key).encode()
    return _rmd160(set_name.encode() + bytes([kt]) + kb)


def _field(ftype: int, data: bytes) -> bytes:
    return struct.pack(">I", len(data) + 1) + bytes([ftype]) + data


def _bin_value(v) -> tuple[int, bytes]:
    if isinstance(v, int):
        return BIN_INT, struct.pack(">q", v)
    return BIN_STR, str(v).encode()


def _op(op: int, name: str, v=None) -> bytes:
    btype, data = (0, b"") if v is None else _bin_value(v)
    nb = name.encode()
    return (struct.pack(">I", 4 + len(nb) + len(data))
            + bytes([op, btype, 0, len(nb)]) + nb + data)


class AerospikeClient:
    def __init__(self, host: str, port: int = 3000,
                 namespace: str = "test", set_name: str = "jepsen",
                 timeout: float = 10.0):
        self.io = SocketIO(
            socket.create_connection((host, port), timeout=timeout))
        self.ns = namespace
        self.set = set_name

    def _call(self, info1: int, info2: int, key, ops: list[bytes],
              generation: int = 0) -> tuple[int, int, dict]:
        """One request/response. Returns (result_code, generation,
        bins)."""
        fields = [_field(FIELD_NAMESPACE, self.ns.encode()),
                  _field(FIELD_SET, self.set.encode()),
                  _field(FIELD_DIGEST, digest(self.set, key))]
        msg = (bytes([22, info1, info2, 0, 0, 0])
               + struct.pack(">IIIHH", generation, 0, 1000,
                             len(fields), len(ops))
               + b"".join(fields) + b"".join(ops))
        proto = (2 << 56) | (3 << 48) | len(msg)
        self.io.send(struct.pack(">Q", proto) + msg)

        (head,) = struct.unpack(">Q", self.io.read_exact(8))
        body = self.io.read_exact(head & ((1 << 48) - 1))
        rc = body[5]
        gen = struct.unpack_from(">I", body, 6)[0]
        n_fields, n_ops = struct.unpack_from(">HH", body, 18)
        off = body[0]                       # header size
        for _ in range(n_fields):
            (sz,) = struct.unpack_from(">I", body, off)
            off += 4 + sz
        bins: dict = {}
        for _ in range(n_ops):
            (sz,) = struct.unpack_from(">I", body, off)
            btype = body[off + 5]
            name_len = body[off + 7]
            name = body[off + 8:off + 8 + name_len].decode()
            data = body[off + 8 + name_len:off + 4 + sz]
            if btype == BIN_INT:
                bins[name] = struct.unpack(">q", data)[0]
            else:
                bins[name] = data.decode(errors="replace")
            off += 4 + sz
        return rc, gen, bins

    def get(self, key) -> tuple[dict, int] | None:
        """(bins, generation), or None when the record doesn't exist."""
        rc, gen, bins = self._call(INFO1_READ | INFO1_GET_ALL, 0, key, [])
        if rc == RC_NOT_FOUND:
            return None
        if rc != RC_OK:
            raise AerospikeError(rc)
        return bins, gen

    def put(self, key, bins: dict, expect_gen: int | None = None) -> None:
        """Write bins; with ``expect_gen`` the write only applies when
        the record generation matches (the CAS primitive; result code 3
        = lost the race)."""
        info2 = INFO2_WRITE
        gen = 0
        if expect_gen is not None:
            info2 |= INFO2_GENERATION
            gen = expect_gen
        ops = [_op(OP_WRITE, k, v) for k, v in bins.items()]
        rc, _, _ = self._call(0, info2, key, ops, generation=gen)
        if rc != RC_OK:
            raise AerospikeError(rc)

    def incr(self, key, bin_name: str, delta: int) -> None:
        rc, _, _ = self._call(0, INFO2_WRITE, key,
                              [_op(OP_INCR, bin_name, delta)])
        if rc != RC_OK:
            raise AerospikeError(rc)

    def close(self) -> None:
        try:
            self.io.close()
        except OSError:
            pass


# --- workload clients -------------------------------------------------------


class RegisterClient(client_ns.Client):
    """CAS register over one record (aerospike core.clj:395-430): read
    returns (value, generation); cas re-reads and writes conditioned on
    the generation — atomic server-side."""

    KEY = "register"
    BIN = "value"

    def __init__(self, conn: AerospikeClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return RegisterClient(AerospikeClient(node))

    def invoke(self, test, op):
        try:
            if op.f == "read":
                r = self.conn.get(self.KEY)
                return op.replace(type="ok",
                                  value=None if r is None
                                  else r[0].get(self.BIN))
            if op.f == "write":
                self.conn.put(self.KEY, {self.BIN: int(op.value)})
                return op.replace(type="ok")
            if op.f == "cas":
                old, new = op.value
                r = self.conn.get(self.KEY)
                if r is None or r[0].get(self.BIN) != old:
                    return op.replace(type="fail")
                try:
                    self.conn.put(self.KEY, {self.BIN: int(new)},
                                  expect_gen=r[1])
                    return op.replace(type="ok")
                except AerospikeError as e:
                    if e.generation_mismatch:
                        # lint: fail-ok — a generation-mismatch result
                        # code is a parsed server response: the
                        # conditional put definitely did not apply
                        # (socket losses raise OSError, handled below).
                        return op.replace(type="fail")
                    raise
        except AerospikeError as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=str(e))
        except (OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


class CounterClient(client_ns.Client):
    """Increment-only counter (aerospike core.clj:540-557): add = the
    server-side INCR op, read = get."""

    KEY = "counter"
    BIN = "count"

    def __init__(self, conn: AerospikeClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return CounterClient(AerospikeClient(node))

    def invoke(self, test, op):
        try:
            if op.f == "add":
                self.conn.incr(self.KEY, self.BIN, int(op.value))
                return op.replace(type="ok")
            if op.f == "read":
                r = self.conn.get(self.KEY)
                return op.replace(type="ok",
                                  value=0 if r is None
                                  else r[0].get(self.BIN, 0))
        except (AerospikeError, OSError, ConnectionError) as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()
