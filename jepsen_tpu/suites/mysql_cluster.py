"""MySQL Cluster (NDB) suite — infrastructure-only bring-up
(mysql-cluster/src/jepsen/mysql_cluster.clj).

The reference suite is a `simple-test` (:223-227) whose substance is the
three-daemon NDB orchestration (:188-216): management daemon (ndb_mgmd)
on the first node, data nodes (ndbd) on the rest, mysqld on all —
verifying the harness can sequence a heterogeneous cluster. No workload
checker beyond unbridled optimism; the fake path exercises the runner.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class NdbCluster(db_ns.DB, db_ns.LogFiles):
    """Three-daemon orchestration (mysql_cluster.clj:188-216): mgmd on
    node 1, ndbd on the others, mysqld everywhere."""

    def _config_ini(self, test) -> str:
        mgm = test["nodes"][0]
        sections = [f"[ndb_mgmd]\nhostname={mgm}\ndatadir=/var/lib/ndb"]
        for n in test["nodes"][1:]:
            sections.append(f"[ndbd]\nhostname={n}\ndatadir=/var/lib/ndb")
        sections.append("[mysqld]\n" * len(test["nodes"]))
        return "[ndbd default]\nNoOfReplicas=2\n\n" + "\n\n".join(sections)

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["mysql-cluster-community-server"])
            control.exec_("mkdir", "-p", "/var/lib/ndb")
            if node == test["nodes"][0]:
                control.exec_("tee", "/var/lib/ndb/config.ini",
                              stdin=self._config_ini(test))
                control.exec_("ndb_mgmd", "-f", "/var/lib/ndb/config.ini",
                              "--initial", may_fail=True)
            else:
                control.exec_("ndbd",
                              f"--ndb-connectstring={test['nodes'][0]}",
                              may_fail=True)
            control.exec_("service", "mysql", "restart", may_fail=True)

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "mysql", "stop", may_fail=True)
            control.exec_("pkill", "-9", "ndbd", may_fail=True)
            control.exec_("pkill", "-9", "ndb_mgmd", may_fail=True)
            control.exec_("rm", "-rf", "/var/lib/ndb", may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/lib/ndb/ndb_1_cluster.log"]


def test(opts: dict | None = None) -> dict:
    """The simple-test map (mysql_cluster.clj:223-227): cluster cycles
    up and down; generator is a light read load."""
    from jepsen_tpu.suites import mysql_clients

    return common.suite_test(
        "mysql-cluster", opts,
        workload=workloads.counter_workload(n=50),
        db=NdbCluster(),
        client=mysql_clients.CounterClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(10, 10))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
