"""Percona XtraDB cluster suite — bank + dirty reads
(percona/src/jepsen/percona.clj + percona/dirty_reads.clj).

Same workload dialects as galera (bank invariant, percona.clj:77 custom
checker; dirty reads, percona.clj:319) over Percona's XtraDB cluster
packages. Nemesis: partition-random-halves (percona.clj:212). MySQL
wire protocol gated as in the galera suite.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class PerconaDB(db_ns.DB, db_ns.LogFiles):
    """percona-xtradb-cluster install + wsrep config
    (percona.clj:40-180)."""

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["percona-xtradb-cluster-57"])
            cluster = ",".join(test["nodes"])
            config = f"""[mysqld]
wsrep_provider=/usr/lib/galera3/libgalera_smm.so
wsrep_cluster_address=gcomm://{cluster}
wsrep_node_address={node}
wsrep_cluster_name=jepsen
wsrep_sst_method=rsync
pxc_strict_mode=ENFORCING
binlog_format=ROW
default_storage_engine=InnoDB
innodb_autoinc_lock_mode=2
"""
            control.exec_("tee", "/etc/mysql/percona-xtradb-cluster.conf.d/"
                          "jepsen.cnf", stdin=config)
            if node == test["nodes"][0]:
                control.exec_("service", "mysql", "bootstrap-pxc",
                              may_fail=True)
            else:
                control.exec_("service", "mysql", "restart")

    def teardown(self, test, node) -> None:
        with control.su():
            control.exec_("service", "mysql", "stop", may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/mysqld.log"]


def test(opts: dict | None = None) -> dict:
    """The percona test map (percona.clj:200-240)."""
    from jepsen_tpu.suites import mysql_clients

    opts = dict(opts or {})
    name = opts.pop("workload", None) or "bank"
    wl, client = mysql_clients.bank_or_dirty_reads(name)
    return common.suite_test(
        f"percona {name}", opts,
        workload=wl,
        db=PerconaDB(),
        client=client,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="bank",
                       choices=["bank", "dirty-reads"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
