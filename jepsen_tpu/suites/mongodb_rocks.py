"""MongoDB-RocksDB suite — perf-only harness
(mongodb-rocks/src/jepsen/mongodb_rocks.clj).

The reference's one performance-focused suite (:163): generate document
insert load, no safety checker beyond the perf graphs. DB install swaps
mongod's storage engine to RocksDB. Non-fake runs drive the real wire
client (jepsen_tpu.suites.mongowire.TableClient — OP_MSG + from-scratch
BSON; fake-server-tested in tests/test_mongowire.py); ``--fake`` runs
keep the workload fake and still exercise the latency/rate graph
pipeline.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import os_debian
from jepsen_tpu.suites import common, workloads


class MongoRocksDB(db_ns.DB, db_ns.LogFiles):
    """mongod with --storageEngine rocksdb (mongodb_rocks.clj:40-120)."""

    def setup(self, test, node) -> None:
        with control.su():
            os_debian.install(["mongodb-org-server"])
            control.exec_("mkdir", "-p", "/var/lib/mongodb-rocks")
            from jepsen_tpu.control import util as cu

            cu.start_daemon("/usr/bin/mongod",
                            "--storageEngine", "rocksdb",
                            "--dbpath", "/var/lib/mongodb-rocks",
                            "--bind_ip", node,
                            logfile="/var/log/mongod-rocks.log",
                            pidfile="/var/run/mongod-rocks.pid",
                            chdir="/var/lib/mongodb-rocks")

    def teardown(self, test, node) -> None:
        from jepsen_tpu.control import util as cu

        with control.su():
            cu.stop_daemon("/var/run/mongod-rocks.pid", binary="mongod")
            control.exec_("rm", "-rf", "/var/lib/mongodb-rocks",
                          may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return ["/var/log/mongod-rocks.log"]


def test(opts: dict | None = None) -> dict:
    """The perf test map (mongodb_rocks.clj:140-170): insert-heavy load,
    perf graphs as the only analysis."""
    from jepsen_tpu.suites import mongowire

    return common.suite_test(
        "mongodb-rocks", opts,
        workload=workloads.dirty_read_workload(abort_prob=0.0),
        db=MongoRocksDB(),
        client=mongowire.TableClient())


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
