"""DB test suites — the L13 layer of the reference (SURVEY §2.3-2.8).

One module per reference suite (24 sibling Leiningen projects in the
reference repo). Each module exposes:

- ``test(opts) -> dict`` — the test-map constructor (etcd.clj:149-179
  shape), runnable no-cluster with ``opts={"fake": True}`` via the
  workload fakes;
- ``main(argv)`` — the CLI entry (cli/single-test-cmd + serve-cmd,
  etcd.clj:182-188).

``SUITES`` maps suite name → module path for the umbrella CLI
(``python -m jepsen_tpu.cli suite <name> ...``) and the test matrix.
"""

from __future__ import annotations

import importlib

SUITES = {
    "aerospike": "jepsen_tpu.suites.aerospike",
    "chronos": "jepsen_tpu.suites.chronos",
    "cockroachdb": "jepsen_tpu.suites.cockroachdb",
    "consul": "jepsen_tpu.suites.consul",
    "crate": "jepsen_tpu.suites.crate",
    "disque": "jepsen_tpu.suites.disque",
    "elasticsearch": "jepsen_tpu.suites.elasticsearch",
    "etcd": "jepsen_tpu.suites.etcd",
    "galera": "jepsen_tpu.suites.galera",
    "hazelcast": "jepsen_tpu.suites.hazelcast",
    "logcabin": "jepsen_tpu.suites.logcabin",
    "mongodb-rocks": "jepsen_tpu.suites.mongodb_rocks",
    "mongodb-smartos": "jepsen_tpu.suites.mongodb_smartos",
    "mysql-cluster": "jepsen_tpu.suites.mysql_cluster",
    "percona": "jepsen_tpu.suites.percona",
    "postgres-rds": "jepsen_tpu.suites.postgres_rds",
    "rabbitmq": "jepsen_tpu.suites.rabbitmq",
    "raftis": "jepsen_tpu.suites.raftis",
    "rethinkdb": "jepsen_tpu.suites.rethinkdb",
    "robustirc": "jepsen_tpu.suites.robustirc",
    "tidb": "jepsen_tpu.suites.tidb",
    "zookeeper": "jepsen_tpu.suites.zookeeper",
}


def load(name: str):
    """Import a suite module by registry name."""
    if name not in SUITES:
        raise KeyError(
            f"unknown suite {name!r}; one of {sorted(SUITES)}")
    return importlib.import_module(SUITES[name])
