"""TiDB suite — register / bank / sets over a three-component cluster
(tidb/src/tidb/{core,db,sql,bank,register,sets,nemesis,basic}.clj).

The DB layer sequences the three-daemon bring-up (pd → tikv → tidb,
tidb/db.clj): placement drivers first on all nodes, then the KV stores,
then the SQL layer. Workloads: per-key register checked linearizable
(register.clj:68-74), the bank invariant (bank.clj), and sets
(sets.clj:53-55). TiDB fronts MySQL's wire protocol, spoken from
scratch by jepsen_tpu.suites.mysql_clients (mysqlwire handshake +
text-protocol queries); fakes cover no-cluster runs.
"""

from __future__ import annotations

from jepsen_tpu import control
from jepsen_tpu import db as db_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.control import util as cu
from jepsen_tpu.suites import common, workloads

VERSION = "v2.0.4"


class TiDBCluster(db_ns.DB, db_ns.LogFiles):
    """pd → tikv → tidb ordered bring-up (tidb/db.clj, 223 LoC in the
    reference). All three daemons run on every node; tidb-server waits
    for the stores."""

    dir = "/opt/tidb"

    def __init__(self, version: str = VERSION):
        self.version = version
        self.url = (f"https://download.pingcap.org/"
                    f"tidb-{version}-linux-amd64.tar.gz")

    def _pd_args(self, test, node) -> list:
        initial = ",".join(f"{n}=http://{n}:2380" for n in test["nodes"])
        return ["--name", node,
                "--client-urls", f"http://{node}:2379",
                "--peer-urls", f"http://{node}:2380",
                "--initial-cluster", initial,
                "--data-dir", f"{self.dir}/pd"]

    def setup(self, test, node) -> None:
        pds = ",".join(f"{n}:2379" for n in test["nodes"])
        with control.su():
            cu.install_archive(self.url, self.dir)
            cu.start_daemon(f"{self.dir}/bin/pd-server",
                            *self._pd_args(test, node),
                            logfile=f"{self.dir}/pd.log",
                            pidfile=f"{self.dir}/pd.pid", chdir=self.dir)
            cu.start_daemon(f"{self.dir}/bin/tikv-server",
                            "--pd", pds,
                            "--addr", f"{node}:20160",
                            "--data-dir", f"{self.dir}/tikv",
                            logfile=f"{self.dir}/tikv.log",
                            pidfile=f"{self.dir}/tikv.pid",
                            chdir=self.dir)
            cu.start_daemon(f"{self.dir}/bin/tidb-server",
                            "--store", "tikv",
                            "--path", pds,
                            logfile=f"{self.dir}/tidb.log",
                            pidfile=f"{self.dir}/tidb.pid",
                            chdir=self.dir)

    def teardown(self, test, node) -> None:
        with control.su():
            for name in ("tidb", "tikv", "pd"):
                cu.stop_daemon(f"{self.dir}/{name}.pid",
                               binary=f"{name}-server")
            control.exec_("rm", "-rf", self.dir, may_fail=True)

    def log_files(self, test, node) -> list[str]:
        return [f"{self.dir}/{n}.log" for n in ("pd", "tikv", "tidb")]


def test(opts: dict | None = None) -> dict:
    """The tidb test map (tidb/basic.clj + runner registry). ``workload``
    picks register (default) / bank / sets."""
    from jepsen_tpu.suites import mysql_clients

    opts = dict(opts or {})
    name = opts.pop("workload", None) or "register"
    if name == "register":
        threads_per_key = 5
        if opts.get("concurrency", 0) < threads_per_key:
            opts["concurrency"] = threads_per_key
        wl = workloads.register(threads_per_key=threads_per_key)
        client = mysql_clients.RegisterClient(port=4000)
    elif name == "bank":
        wl = workloads.bank_workload()
        client = mysql_clients.BankClient(port=4000)
    elif name == "txn":
        # List-append transactions checked by the dependency-graph
        # cycle checker (jepsen_tpu.txn, doc/txn.md). TiDB claims
        # snapshot isolation, not serializability (see TxnAppendClient).
        wl = workloads.txn_workload(consistency="snapshot-isolation")
        client = mysql_clients.TxnAppendClient(port=4000)
    else:
        wl = workloads.set_workload()
        client = mysql_clients.SetClient(port=4000)
    # TiDB listens on 4000; the wire protocol is MySQL's.
    return common.suite_test(
        f"tidb {name}", opts,
        workload=wl,
        db=TiDBCluster(),
        client=client,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="register",
                       choices=["register", "bank", "sets", "txn"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
