"""Disque suite — distributed job queue (disque/src/jepsen/disque.clj).

Enqueue/dequeue/ack of jobs checked by total-queue against the
unordered-queue model (disque.clj:305-310): every acknowledged enqueue
must eventually be dequeued exactly once after the final drain. Faults:
partition-random-halves (disque.clj:321) and node kill/restart
(disque.clj:268). The wire client speaks Disque's RESP dialect
(ADDJOB/GETJOB/ACKJOB) via :mod:`jepsen_tpu.suites.resp` where the
reference used jedisque.
"""

from __future__ import annotations

from jepsen_tpu import client as client_ns
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads
from jepsen_tpu.suites.resp import RespClient, RespError

VERSION = "2b04ba0a61069b4945bad2b16c90b49a30c80f33"
QUEUE = "jepsen"
PORT = 7711


class DisqueDB(common.TarballDB):
    """Source build + daemon (disque.clj:40-108): every node joins the
    cluster via CLUSTER MEET after start."""

    name = "disque"
    dir = "/opt/disque"
    binary = "disque-server"

    def __init__(self, version: str = VERSION):
        self.url = f"https://github.com/antirez/disque/archive/{version}.tar.gz"

    def start_args(self, test, node) -> list:
        return ["--port", str(PORT), "--appendonly", "yes",
                "--cluster-enabled", "yes"]

    def await_ready(self, test, node) -> None:
        # CLUSTER MEET fan-in from the first node (disque.clj:88-99).
        if node == test["nodes"][0]:
            try:
                c = RespClient(node, PORT, timeout=10)
                for peer in test["nodes"][1:]:
                    c.call("CLUSTER", "MEET", peer, str(PORT))
                c.close()
            except (OSError, RespError):
                pass


class DisqueClient(client_ns.Client):
    """ADDJOB / GETJOB+ACKJOB over RESP (disque.clj:126-180)."""

    def __init__(self, conn: RespClient | None = None):
        self.conn = conn

    def open(self, test, node):
        return DisqueClient(RespClient(node, PORT))

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "enqueue":
                self.conn.call("ADDJOB", QUEUE, str(op.value), "0",
                               "RETRY", "1")
                return op.replace(type="ok")
            if op.f in ("dequeue", "drain"):
                drained = []
                while True:
                    got = self.conn.call("GETJOB", "NOHANG", "FROM", QUEUE)
                    if not got:
                        break
                    _, job_id, body = got[0]
                    self.conn.call("ACKJOB", job_id)
                    drained.append(int(body))
                    if op.f == "dequeue":
                        return op.replace(type="ok", value=drained[0])
                if op.f == "drain":
                    return op.replace(type="ok", value=drained)
                return op.replace(type="fail")
        except RespError as e:
            return op.replace(type="fail", error=str(e))
        except OSError as e:
            t = "fail" if op.f in ("dequeue",) else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")

    def close(self, test) -> None:
        if self.conn is not None:
            self.conn.close()


def test(opts: dict | None = None) -> dict:
    """The disque test map (disque.clj:290-330)."""
    return common.suite_test(
        "disque", opts,
        workload=workloads.queue_workload(),
        db=DisqueDB(),
        client=DisqueClient(),
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(5, 5))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    cli.main(cli.suite_commands(test), argv)


if __name__ == "__main__":
    main()
