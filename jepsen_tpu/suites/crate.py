"""CrateDB suite — dirty-read / lost-updates / version-divergence / set
(crate/src/jepsen/crate/{core,dirty_read,lost_updates,version_divergence}.clj).

Crate speaks SQL over HTTP (``/_sql``), so the wire client is a real
stdlib HTTP client (the reference used the ES transport client).
Workloads: the independent-keyed set (core.clj:117-121), the
dirty-read probe (dirty_read.clj), and lost-updates (lost_updates.clj:141
— concurrent updates to one row must all survive in the final value).
"""

from __future__ import annotations

import threading

from jepsen_tpu import client as client_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.checker import FnChecker
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

PORT = 4200


class CrateDB(common.TarballDB):
    """Tarball + unicast discovery (core.clj:40-100)."""

    name = "crate"
    dir = "/opt/crate"
    binary = "bin/crate"

    def __init__(self, version: str = "0.57.5"):
        self.url = (f"https://cdn.crate.io/downloads/releases/"
                    f"crate-{version}.tar.gz")

    def post_install(self, test, node) -> None:
        from jepsen_tpu import control, os_debian

        os_debian.install_jdk()
        hosts = ", ".join(f'"{n}:4300"' for n in test["nodes"])
        config = (f"cluster.name: jepsen\nnode.name: {node}\n"
                  f"network.host: {node}\n"
                  f"discovery.zen.ping.unicast.hosts: [{hosts}]\n")
        control.exec_("tee", f"{self.dir}/config/crate.yml", stdin=config)

    def start_args(self, test, node) -> list:
        return ["-d", "-p", self.pidfile]


def sql(node: str, stmt: str, args=None, timeout: float = 10.0):
    """POST /_sql (the HTTP endpoint the reference's transport client
    wraps). Returns (status, body dict with "rows")."""
    body: dict = {"stmt": stmt}
    if args is not None:
        body["args"] = args
    return common.http_json("POST", f"http://{node}:{PORT}/_sql", body,
                            timeout=timeout)


class CrateSetClient(client_ns.Client):
    """add = INSERT, read = SELECT with refresh (core.clj:117-121)."""

    TABLE = "jepsen_set"

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return CrateSetClient(node)

    def setup(self, test) -> None:
        sql(test["nodes"][0],
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            f"(id integer PRIMARY KEY)")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                status, body = sql(self.node,
                                   f"INSERT INTO {self.TABLE} (id) "
                                   f"VALUES (?)", [op.value])
                return op.replace(
                    type="ok" if status == 200 else "info",
                    error=None if status == 200 else body)
            if op.f == "read":
                sql(self.node, f"REFRESH TABLE {self.TABLE}", timeout=30)
                status, body = sql(self.node,
                                   f"SELECT id FROM {self.TABLE} "
                                   f"LIMIT 1000000", timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                return op.replace(
                    type="ok", value=sorted(r[0] for r in body["rows"]))
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def lost_updates_checker() -> FnChecker:
    """Every acknowledged update must appear in the final value
    (lost_updates.clj:141): value is a collected list per key."""

    def check(test, model, history, opts):
        acked = set()
        final = None
        for op in history:
            if op.f == "update" and op.is_ok:
                acked.add(op.value)
            elif op.f == "read" and op.is_ok and op.value is not None:
                final = set(op.value)
        if final is None:
            return {"valid?": "unknown", "error": "no final read"}
        lost = acked - final
        return {"valid?": not lost, "lost": sorted(lost)[:10],
                "lost-count": len(lost), "acked-count": len(acked)}

    return FnChecker(check)


def lost_updates_workload(n: int = 100, faulty=None) -> dict:
    """Concurrent list-append updates to one row; the final read must
    contain every acknowledged update (lost_updates.clj)."""
    state = {"n": 0}
    lock = threading.Lock()

    class Store:
        def __init__(self):
            self.vals: list = []
            self.lock = threading.Lock()
            self._n = 0

        def update(self, v):
            with self.lock:
                self._n += 1
                if faulty == "lost-update" and self._n % 7 == 0:
                    return True
                self.vals.append(v)
                return True

        def read(self):
            with self.lock:
                return sorted(self.vals)

    store = Store()

    class Client(client_ns.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op: Op) -> Op:
            if op.f == "update":
                store.update(op.value)
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(type="ok", value=store.read())
            return op.replace(type="fail")

    def update(test, process):
        with lock:
            v = state["n"]
            state["n"] += 1
        return {"type": "invoke", "f": "update", "value": v}

    return {
        "generator": gen.limit(n, gen.stagger(1 / 20, gen.gen(update))),
        "final_generator": gen.once({"type": "invoke", "f": "read",
                                     "value": None}),
        "client": Client(),
        "checker": lost_updates_checker(),
        "model": None,
    }


def test(opts: dict | None = None) -> dict:
    """The crate test map (core.clj:100-140 + runner.clj). ``workload``
    picks set (default) / dirty-read / lost-updates."""
    opts = dict(opts or {})
    name = opts.pop("workload", None) or "set"
    table = {"set": lambda: workloads.set_workload(),
             "dirty-read": lambda: workloads.dirty_read_workload(),
             "lost-updates": lambda: lost_updates_workload()}
    if name not in table:
        raise ValueError(f"unknown workload {name!r}")
    return common.suite_test(
        f"crate {name}", opts,
        workload=table[name](),
        db=CrateDB(),
        client=CrateSetClient() if name == "set" else None,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(10, 10))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="set",
                       choices=["set", "dirty-read", "lost-updates"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
