"""CrateDB suite — dirty-read / lost-updates / version-divergence / set
(crate/src/jepsen/crate/{core,dirty_read,lost_updates,version_divergence}.clj).

Crate speaks SQL over HTTP (``/_sql``), so the wire client is a real
stdlib HTTP client (the reference used the ES transport client).
Workloads: the independent-keyed set (core.clj:117-121), the
dirty-read probe (dirty_read.clj), and lost-updates (lost_updates.clj:141
— concurrent updates to one row must all survive in the final value).
"""

from __future__ import annotations

import threading

from jepsen_tpu import client as client_ns
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nemesis_ns
from jepsen_tpu.checker import FnChecker
from jepsen_tpu.history import Op
from jepsen_tpu.suites import common, workloads

PORT = 4200


class CrateDB(common.TarballDB):
    """Tarball + unicast discovery (core.clj:40-100)."""

    name = "crate"
    dir = "/opt/crate"
    binary = "bin/crate"

    def __init__(self, version: str = "0.57.5"):
        self.url = (f"https://cdn.crate.io/downloads/releases/"
                    f"crate-{version}.tar.gz")

    def post_install(self, test, node) -> None:
        from jepsen_tpu import control, os_debian

        os_debian.install_jdk()
        hosts = ", ".join(f'"{n}:4300"' for n in test["nodes"])
        config = (f"cluster.name: jepsen\nnode.name: {node}\n"
                  f"network.host: {node}\n"
                  f"discovery.zen.ping.unicast.hosts: [{hosts}]\n")
        control.exec_("tee", f"{self.dir}/config/crate.yml", stdin=config)

    def start_args(self, test, node) -> list:
        return ["-d", "-p", self.pidfile]


def sql(node: str, stmt: str, args=None, timeout: float = 10.0):
    """POST /_sql (the HTTP endpoint the reference's transport client
    wraps). Returns (status, body dict with "rows")."""
    body: dict = {"stmt": stmt}
    if args is not None:
        body["args"] = args
    return common.http_json("POST", f"http://{node}:{PORT}/_sql", body,
                            timeout=timeout)


class CrateSetClient(client_ns.Client):
    """add = INSERT, read = SELECT with refresh (core.clj:117-121)."""

    TABLE = "jepsen_set"

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return CrateSetClient(node)

    def setup(self, test) -> None:
        sql(test["nodes"][0],
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            f"(id integer PRIMARY KEY)")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "add":
                status, body = sql(self.node,
                                   f"INSERT INTO {self.TABLE} (id) "
                                   f"VALUES (?)", [op.value])
                return op.replace(
                    type="ok" if status == 200 else "info",
                    error=None if status == 200 else body)
            if op.f == "read":
                sql(self.node, f"REFRESH TABLE {self.TABLE}", timeout=30)
                status, body = sql(self.node,
                                   f"SELECT id FROM {self.TABLE} "
                                   f"LIMIT 1000000", timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                return op.replace(
                    type="ok", value=sorted(r[0] for r in body["rows"]))
        except OSError as e:
            t = "fail" if op.f == "read" else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


class CrateLostUpdatesClient(client_ns.Client):
    """Real lost-updates client over ``/_sql``: read-modify-write of a
    JSON element list guarded by CrateDB's ``_version`` optimistic CAS
    (lost_updates.clj:32-99)."""

    TABLE = "jepsen_sets"
    KEY = 0

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return CrateLostUpdatesClient(node)

    def setup(self, test) -> None:
        sql(test["nodes"][0],
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            f"(id integer PRIMARY KEY, elements string)")

    def invoke(self, test, op: Op) -> Op:
        import json as _json

        try:
            if op.f == "read":
                status, body = sql(
                    self.node, f"REFRESH TABLE {self.TABLE}", timeout=30)
                if status != 200:
                    # A stale (unrefreshed) read could report acknowledged
                    # updates as lost — never ack it.
                    return op.replace(type="fail", error=body)
                status, body = sql(
                    self.node,
                    f"SELECT elements FROM {self.TABLE} WHERE id = ?",
                    [self.KEY], timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                rows = body.get("rows") or []
                els = _json.loads(rows[0][0]) if rows else []
                return op.replace(type="ok", value=sorted(els))
            if op.f == "update":
                status, body = sql(
                    self.node,
                    f"SELECT elements, \"_version\" FROM {self.TABLE} "
                    f"WHERE id = ?", [self.KEY])
                if status != 200:
                    return op.replace(type="info", error=body)
                rows = body.get("rows") or []
                if rows:
                    els = _json.loads(rows[0][0])
                    els.append(op.value)
                    status, body = sql(
                        self.node,
                        f"UPDATE {self.TABLE} SET elements = ? "
                        f"WHERE id = ? AND \"_version\" = ?",
                        [_json.dumps(els), self.KEY, rows[0][1]])
                    if status != 200:
                        return op.replace(type="info", error=body)
                    n = body.get("rowcount", 0)
                    # rowcount 0 = version conflict: definitely not
                    # applied (lost_updates.clj:85-87).
                    return op.replace(type="ok" if n == 1 else "fail")
                status, body = sql(
                    self.node,
                    f"INSERT INTO {self.TABLE} (id, elements) "
                    f"VALUES (?, ?)",
                    [self.KEY, _json.dumps([op.value])])
                if status == 200:
                    return op.replace(type="ok")
                if "Duplicate" in str(body):
                    return op.replace(type="fail", error="duplicate")
                return op.replace(type="info", error=body)
        except OSError as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


class CrateVersionDivergenceClient(client_ns.Client):
    """Real version-divergence client (version_divergence.clj:29-88):
    upsert unique values into one row, read back (value, _version) —
    each observed _version must name a single value."""

    TABLE = "jepsen_registers"

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return CrateVersionDivergenceClient(node)

    def setup(self, test) -> None:
        sql(test["nodes"][0],
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            f"(id integer PRIMARY KEY, value integer)")

    def invoke(self, test, op: Op) -> Op:
        from jepsen_tpu import independent

        tup = independent.is_tuple(op.value)
        k, v = op.value if tup else (0, op.value)

        def join(val):
            return independent.tuple_(k, val) if tup else val

        try:
            if op.f == "read":
                status, body = sql(
                    self.node,
                    f"SELECT value, \"_version\" FROM {self.TABLE} "
                    f"WHERE id = ?", [int(k)])
                if status != 200:
                    return op.replace(type="fail", error=body)
                rows = body.get("rows") or []
                val = list(rows[0]) if rows else None
                return op.replace(type="ok", value=join(val))
            if op.f == "write":
                status, body = sql(
                    self.node,
                    f"INSERT INTO {self.TABLE} (id, value) VALUES (?, ?) "
                    f"ON DUPLICATE KEY UPDATE value = VALUES(value)",
                    [int(k), int(v)])
                if status == 200:
                    return op.replace(type="ok")
                return op.replace(type="info", error=body)
        except OSError as e:
            return op.replace(type="fail" if op.f == "read" else "info",
                              error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


class CrateDirtyReadClient(client_ns.Client):
    """Real dirty-read client (dirty_read.clj:30-88): point reads by
    primary key are realtime in CrateDB (can observe unreplicated
    writes); table scans only see refreshed rows — the asymmetry the
    workload probes."""

    TABLE = "jepsen_dirty_read"

    def __init__(self, node: str | None = None):
        self.node = node

    def open(self, test, node):
        return CrateDirtyReadClient(node)

    def setup(self, test) -> None:
        sql(test["nodes"][0],
            f"CREATE TABLE IF NOT EXISTS {self.TABLE} "
            f"(id integer PRIMARY KEY)")

    def invoke(self, test, op: Op) -> Op:
        try:
            if op.f == "read":
                status, body = sql(
                    self.node,
                    f"SELECT id FROM {self.TABLE} WHERE id = ?",
                    [int(op.value)])
                if status != 200:
                    return op.replace(type="fail", error=body)
                found = bool(body.get("rows"))
                return op.replace(type="ok" if found else "fail")
            if op.f == "refresh":
                status, body = sql(self.node,
                                   f"REFRESH TABLE {self.TABLE}",
                                   timeout=60)
                return op.replace(type="ok" if status == 200 else "fail",
                                  error=None if status == 200 else body)
            if op.f == "strong-read":
                status, body = sql(
                    self.node,
                    f"SELECT id FROM {self.TABLE} LIMIT 1000000",
                    timeout=30)
                if status != 200:
                    return op.replace(type="fail", error=body)
                return op.replace(
                    type="ok",
                    value=sorted(r[0] for r in body["rows"]))
            if op.f == "write":
                status, body = sql(
                    self.node,
                    f"INSERT INTO {self.TABLE} (id) VALUES (?)",
                    [int(op.value)])
                if status == 200:
                    return op.replace(type="ok")
                return op.replace(type="info", error=body)
        except OSError as e:
            t = "fail" if op.f in ("read", "strong-read") else "info"
            return op.replace(type=t, error=repr(e))
        return op.replace(type="fail", error=f"unknown f {op.f}")


def multiversion_checker() -> FnChecker:
    """Each observed ``_version`` of a row must name a single value
    (version_divergence.clj:91-106). Read values are ``[value,
    version]`` pairs (optionally independent-keyed)."""

    def check(test, model, history, opts):
        from collections import defaultdict

        from jepsen_tpu import independent

        seen = defaultdict(set)          # (key, version) -> values
        for op in history:
            if not (op.is_ok and op.f == "read") or op.value is None:
                continue
            k, payload = (op.value if independent.is_tuple(op.value)
                          else (0, op.value))
            if payload is None:
                continue
            val, version = payload
            seen[(k, version)].add(val)
        multis = {f"{k}@v{ver}": sorted(vs)
                  for (k, ver), vs in seen.items() if len(vs) > 1}
        return {"valid?": not multis, "multis": multis,
                "versions-seen": len(seen)}

    return FnChecker(check)


def crate_dirty_read_checker():
    """The reference's dirty-read classification (dirty_read.clj:150-198)
    — the shared strong-read classifier (also used by the elasticsearch
    probe, whose reference checker is the same code)."""
    return workloads.strong_read_classification_checker()


def lost_updates_checker() -> FnChecker:
    """Every acknowledged update must appear in the final value
    (lost_updates.clj:141): value is a collected list per key."""

    def check(test, model, history, opts):
        acked = set()
        final = None
        for op in history:
            if op.f == "update" and op.is_ok:
                acked.add(op.value)
            elif op.f == "read" and op.is_ok and op.value is not None:
                final = set(op.value)
        if final is None:
            return {"valid?": "unknown", "error": "no final read"}
        lost = acked - final
        return {"valid?": not lost, "lost": sorted(lost)[:10],
                "lost-count": len(lost), "acked-count": len(acked)}

    return FnChecker(check)


def lost_updates_workload(n: int = 100, faulty=None) -> dict:
    """Concurrent list-append updates to one row; the final read must
    contain every acknowledged update (lost_updates.clj)."""
    state = {"n": 0}
    lock = threading.Lock()

    class Store:
        def __init__(self):
            self.vals: list = []
            self.lock = threading.Lock()
            self._n = 0

        def update(self, v):
            with self.lock:
                self._n += 1
                if faulty == "lost-update" and self._n % 7 == 0:
                    return True
                self.vals.append(v)
                return True

        def read(self):
            with self.lock:
                return sorted(self.vals)

    store = Store()

    class Client(client_ns.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op: Op) -> Op:
            if op.f == "update":
                store.update(op.value)
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(type="ok", value=store.read())
            return op.replace(type="fail")

    def update(test, process):
        with lock:
            v = state["n"]
            state["n"] += 1
        return {"type": "invoke", "f": "update", "value": v}

    return {
        "generator": gen.limit(n, gen.stagger(1 / 20, gen.gen(update))),
        "final_generator": gen.once({"type": "invoke", "f": "read",
                                     "value": None}),
        "client": Client(),
        "checker": lost_updates_checker(),
        "model": None,
    }


def version_divergence_workload(n: int = 200, faulty=None) -> dict:
    """Unique-int upserts + (value, _version) reads under partitions
    (version_divergence.clj:108-136). The fake-mode client is an
    in-process versioned row store; real runs drive
    :class:`CrateVersionDivergenceClient`."""
    state = {"n": 0}
    lock = threading.Lock()

    class Store:
        def __init__(self):
            self.row = None            # (value, version)
            self.lock = threading.Lock()
            self._writes = 0

        def write(self, v):
            with self.lock:
                self._writes += 1
                ver = (self.row[1] + 1) if self.row else 1
                if faulty == "divergence" and self._writes % 5 == 0 \
                        and self.row is not None:
                    ver = self.row[1]  # same version, new value
                self.row = (v, ver)

        def read(self):
            with self.lock:
                return list(self.row) if self.row else None

    store = Store()

    class FakeClient(client_ns.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op: Op) -> Op:
            if op.f == "write":
                store.write(op.value)
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(type="ok", value=store.read())
            return op.replace(type="fail")

    def write(test, process):
        with lock:
            v = state["n"]
            state["n"] += 1
        return {"type": "invoke", "f": "write", "value": v}

    r = {"type": "invoke", "f": "read", "value": None}
    return {
        "generator": gen.limit(n, gen.stagger(
            1 / 20, gen.mix([gen.gen(write), r]))),
        "client": FakeClient(),
        "checker": multiversion_checker(),
        "model": None,
    }


def crate_dirty_read_workload(n: int = 200, faulty=None) -> dict:
    """The crate dirty-read probe (dirty_read.clj:188-257): writers add
    sequential ids, readers probe recently written ids, and after the
    nemesis heals every worker takes a strong read (preceded by a
    refresh)."""
    state = {"n": 0, "in_flight": []}
    lock = threading.Lock()

    class Store:
        """Fake-mode double with CrateDB's visibility split: point reads
        are realtime, scans see only refreshed rows."""

        def __init__(self):
            self.rows: set = set()
            self.refreshed: set = set()
            self.lock = threading.Lock()

        def write(self, v):
            with self.lock:
                self.rows.add(v)

        def read(self, v):
            with self.lock:
                if faulty == "dirty-read" and v not in self.rows \
                        and v % 13 == 0:
                    return True
                return v in self.rows

        def refresh(self):
            with self.lock:
                self.refreshed = set(self.rows)

        def strong_read(self):
            with self.lock:
                return sorted(self.refreshed)

    store = Store()

    class FakeClient(client_ns.Client):
        def open(self, test, node):
            return self

        def invoke(self, test, op: Op) -> Op:
            if op.f == "write":
                store.write(op.value)
                return op.replace(type="ok")
            if op.f == "read":
                return op.replace(
                    type="ok" if store.read(op.value) else "fail")
            if op.f == "refresh":
                store.refresh()
                return op.replace(type="ok")
            if op.f == "strong-read":
                return op.replace(type="ok", value=store.strong_read())
            return op.replace(type="fail")

    def rw(test, process):
        import random as _random

        with lock:
            if not state["in_flight"] or _random.random() < 0.5:
                v = state["n"]
                state["n"] += 1
                state["in_flight"].append(v)
                del state["in_flight"][:-10]
                return {"type": "invoke", "f": "write", "value": v}
            v = _random.choice(state["in_flight"])
            return {"type": "invoke", "f": "read", "value": v}

    return {
        "generator": gen.limit(n, gen.stagger(1 / 50, gen.gen(rw))),
        "final_generator": gen.phases(
            gen.singlethreaded(gen.once(
                {"type": "invoke", "f": "refresh", "value": None})),
            gen.each(lambda: gen.once(
                {"type": "invoke", "f": "strong-read", "value": None}))),
        "client": FakeClient(),
        "checker": crate_dirty_read_checker(),
        "model": None,
    }


def test(opts: dict | None = None) -> dict:
    """The crate test map (core.clj:100-140 + runner.clj). ``workload``
    picks set (default) / dirty-read / lost-updates /
    version-divergence — all four drive real CrateDB SQL over ``/_sql``
    on non-fake runs."""
    opts = dict(opts or {})
    name = opts.pop("workload", None) or "set"
    table = {
        "set": (lambda: workloads.set_workload(), CrateSetClient()),
        "dirty-read": (lambda: crate_dirty_read_workload(),
                       CrateDirtyReadClient()),
        "lost-updates": (lambda: lost_updates_workload(),
                         CrateLostUpdatesClient()),
        "version-divergence": (lambda: version_divergence_workload(),
                               CrateVersionDivergenceClient()),
    }
    if name not in table:
        raise ValueError(f"unknown workload {name!r}")
    wl, real_client = table[name]
    return common.suite_test(
        f"crate {name}", opts,
        workload=wl(),
        db=CrateDB(),
        client=real_client,
        nemesis=nemesis_ns.partition_random_halves(),
        nemesis_gen=common.standard_nemesis_gen(10, 10))


def main(argv=None) -> None:
    from jepsen_tpu import cli

    def opt_spec(p):
        p.add_argument("--workload", default="set",
                       choices=["set", "dirty-read", "lost-updates",
                                "version-divergence"])

    cli.main(cli.suite_commands(test, opt_spec=opt_spec), argv)


if __name__ == "__main__":
    main()
