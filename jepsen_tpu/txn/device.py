"""Device SCC engine for the transaction dependency-graph checker.

Decides cycle structure of a packed dependency graph
(:mod:`jepsen_tpu.txn.pack`) on device, as three sequential fixpoint
loops over the flat edge arrays inside ONE jitted program
(:func:`_scc_program`), per edge *tier* (``ww`` for G0, ``ww+wr`` for
G1c, the full graph for G-single/G2-item):

1. **Trim**: repeatedly drop nodes with zero in- or out-degree among
   live edges. The fixpoint (the *core*) is nonempty iff a cycle
   exists — a DAG always trims to nothing — so the tier's cycle
   verdict is decided entirely on device.
2. **Forward min-label**: ``lab[v]`` converges to the smallest core
   ancestor of ``v`` (including itself) — min-scatter over the edge
   adjacency to fixpoint (Orzan-style coloring).
3. **Backward flag**: within each label region, flag the nodes that
   reach the region's root. Flagged nodes of region ``r`` are EXACTLY
   the SCC containing ``r`` (mutual reachability: ``lab[v] == r``
   means r reaches v, the flag means v reaches r, and any such path
   stays inside the region — a smaller-id detour would have relabeled
   the root).

The host then groups flagged nodes by label into SCCs and runs the
oracle's Tarjan only on the *residue* (core nodes whose region root
lies outside their SCC — typically empty); classification and the
canonical witness cycle are shared with :mod:`jepsen_tpu.txn.oracle`
(:func:`oracle.check_graph`), so verdict and witness are bit-identical
to the CPU spec by construction wherever the SCC decompositions agree
— and the decompositions are what the parity fuzz exercises.

Fault discipline (CLAUDE.md lore as machine state):

- Every device loop carries an IN-PROGRAM iteration ceiling
  (``JEPSEN_TPU_TXN_IT_MAX``, auto ``n + 8``): a nonterminating orbit
  becomes an honest ``overflow: budget`` instead of a runtime-watchdog
  kill that presents like a kernel fault.
- Every tier dispatch runs under :func:`supervise.run_guarded`
  (site ``txn-scc``): wedges retry under the watchdog deadline, faults
  and exhausted wedges land in the quarantine ledger keyed by the
  traced shape (rows = node bucket, cap = edge bucket), and a
  quarantined shape routes straight to the host fallback rung in
  future runs.
- The fallback ladder per tier: device program -> host Tarjan
  (bounded by ``JEPSEN_TPU_TXN_CPU_MAX`` edges) -> honest
  ``valid? "unknown"``.

Array shapes are bucketed to powers of two (nodes >= 256, edges >=
512) so XLA compiles one program per bucket, shared by all three tiers
(the tier only changes the live-edge mask, which is data).
"""

from __future__ import annotations

import os
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu import util
from jepsen_tpu.lin import supervise
from jepsen_tpu.obs import metrics as obs_metrics
from jepsen_tpu.obs import trace as obs_trace
from jepsen_tpu.txn import oracle
from jepsen_tpu.txn.pack import PackedTxnHistory

MIN_NODE_PAD = 256
MIN_EDGE_PAD = 512

# Edge tiers, in classification order. Each anomaly needs the tiers
# listed (classify's coverage sets need wwr whenever full runs).
TIER_TYPES = {"ww": (oracle.WW,),
              "wwr": (oracle.WW, oracle.WR),
              "full": (oracle.WW, oracle.WR, oracle.RW)}
# G-single/G2-item need only the full tier: the classifier consumes a
# wwr decomposition solely for G1c's own loop (the strongest-
# explanation skip is populated by witnesses actually reported there),
# so dispatching wwr for an rw-classes-only request is dead device work
# and an avoidable wedge/fault path.
ANOMALY_TIERS = {"G0": ("ww",), "G1c": ("wwr",),
                 "G-single": ("full",), "G2-item": ("full",)}


def it_max_for(n: int) -> int:
    """In-program iteration ceiling. Every phase converges in at most
    n+1 rounds (each trim round kills a node or stops; a label/flag
    round extends the fixed set or stops), so the auto ceiling is a
    true upper bound, not a tuning knob; override for triage only."""
    env = util.env_int("JEPSEN_TPU_TXN_IT_MAX", 0)
    return env if env > 0 else n + 8


def cpu_max_edges() -> int:
    """Largest graph the host-Tarjan fallback rung accepts; past it a
    wedged/faulted/overflowed tier reports an honest unknown."""
    return util.env_int("JEPSEN_TPU_TXN_CPU_MAX", 2_000_000)


def stats_path() -> str | None:
    """Snapshot file for the web anomaly panel (``web.py /txn``)."""
    return os.environ.get("JEPSEN_TPU_TXN_STATS",
                          os.path.join(".jax_cache", "txn_stats.json"))


def _bucket(n: int, floor: int) -> int:
    return max(floor, 1 << max(0, (max(1, n) - 1).bit_length()))


@partial(jax.jit, static_argnames=("n_pad",))
def _scc_program(src, dst, live, n, it_max, *, n_pad):
    """Trim -> forward min-label -> backward flag, one device program.

    src/dst: i32[e_pad] (padded edges point at node 0 with live=False);
    live: bool[e_pad]; n: i32 live node count; it_max: i32 ceiling.
    Returns (alive bool[n_pad], lab i32[n_pad], flag bool[n_pad],
    iters i32[3], overflow bool[3]).
    """
    iota = lax.iota(jnp.int32, n_pad)
    node_ok = iota < n
    big = jnp.int32(n_pad)

    def edges_alive(alive):
        return live & alive[src] & alive[dst]

    # Phase 1: trim to the cycle core. (Isolated nodes fall out on the
    # first round: zero degree on both sides.)
    def trim_body(c):
        alive, _, it = c
        ea = edges_alive(alive).astype(jnp.int32)
        indeg = jnp.zeros(n_pad, jnp.int32).at[dst].add(ea)
        outdeg = jnp.zeros(n_pad, jnp.int32).at[src].add(ea)
        new = alive & (indeg > 0) & (outdeg > 0)
        return new, jnp.any(new != alive), it + jnp.int32(1)

    alive, trim_ch, trim_it = lax.while_loop(
        lambda c: c[1] & (c[2] < it_max), trim_body,
        (node_ok, jnp.bool_(True), jnp.int32(0)))

    ea = edges_alive(alive)

    # Phase 2: forward min-label fixpoint over the core.
    def lab_body(c):
        lab, _, it = c
        contrib = jnp.full(n_pad, big).at[dst].min(
            jnp.where(ea, lab[src], big))
        new = jnp.where(alive, jnp.minimum(lab, contrib), big)
        return new, jnp.any(new != lab), it + jnp.int32(1)

    lab, lab_ch, lab_it = lax.while_loop(
        lambda c: c[1] & (c[2] < it_max), lab_body,
        (jnp.where(alive, iota, big), jnp.bool_(True), jnp.int32(0)))

    # Phase 3: backward reach-the-root flags within label regions.
    # (int32 flags: scatter-max over bools is backend-dependent.)
    same = ea & (lab[src] == lab[dst])

    def flag_body(c):
        flag, _, it = c
        prop = jnp.zeros(n_pad, jnp.int32).at[src].max(
            jnp.where(same, flag[dst], 0))
        new = jnp.maximum(flag, jnp.where(alive, prop, 0))
        return new, jnp.any(new != flag), it + jnp.int32(1)

    flag0 = (alive & (lab == iota)).astype(jnp.int32)
    flag, flag_ch, flag_it = lax.while_loop(
        lambda c: c[1] & (c[2] < it_max), flag_body,
        (flag0, jnp.bool_(True), jnp.int32(0)))

    iters = jnp.stack([trim_it, lab_it, flag_it])
    overflow = jnp.stack([trim_ch, lab_ch, flag_ch])
    return alive, lab, flag.astype(jnp.bool_), iters, overflow


def _tier_device_sccs(pt: PackedTxnHistory, tier: str, stats: dict,
                      rt: bool):
    """One tier on device: dispatch the SCC program under the watchdog,
    decode SCCs from (alive, lab, flag), Tarjan the residue on host.
    Returns (sccs, tier_stats) or raises _TierFallback with the reason.

    ``rt`` is the REQUESTED realtime flag, not ``pt.realtime`` (whether
    rt edges were packed): a realtime-packed history checked as plain
    serializable must exclude rt edges from every tier or its SCC
    decompositions diverge from the shared classifier's cycle types.
    """
    types = set(TIER_TYPES[tier]) | ({oracle.RT} if rt else set())
    mask = np.isin(pt.edge_typ, list(types))
    src_h = pt.edge_src[mask]
    dst_h = pt.edge_dst[mask]
    e_all = len(src_h)
    if e_all == 0 or pt.n == 0:
        return [], {"edges": 0, "core": 0, "device": False}

    # Backward-edge window (exact): node ids follow invocation order,
    # so a healthy serializable history's edges all point FORWARD
    # (src < dst) — a topological order exists and the tier is
    # trivially acyclic. Any cycle must contain a backward edge, and
    # every node of every cycle lies inside
    # [min backward dst, max backward src] (the forward sub-paths
    # between a cycle's backward edges ascend monotonically, so they
    # never leave the span). Restricting the program to that window
    # makes healthy 100k-op histories a host-side no-op and keeps the
    # trim's layer-peeling local to the anomalous region.
    bw = src_h > dst_h
    if not bw.any():
        return [], {"edges": int(e_all), "core": 0, "device": False,
                    "short_circuit": "forward-order"}
    lo = int(dst_h[bw].min())
    hi = int(src_h[bw].max())
    inwin = (src_h >= lo) & (src_h <= hi) & (dst_h >= lo) & (dst_h <= hi)
    src_h = (src_h[inwin] - lo).astype(np.int32)
    dst_h = (dst_h[inwin] - lo).astype(np.int32)
    e = len(src_h)
    n = hi - lo + 1

    n_pad = _bucket(n, MIN_NODE_PAD)
    e_pad = _bucket(e, MIN_EDGE_PAD)
    key = supervise.shape_key("txn-scc", cap=e_pad, window=0,
                              kernel=f"txn-{tier}", rows=n_pad)
    if supervise.quarantined(key) is not None:
        util.stat_bump(stats, "quarantine_skips")
        raise _TierFallback(tier, "quarantined", key)

    src_d = jnp.asarray(np.pad(src_h.astype(np.int32), (0, e_pad - e)))
    dst_d = jnp.asarray(np.pad(dst_h.astype(np.int32), (0, e_pad - e)))
    live_d = jnp.asarray(np.arange(e_pad) < e)
    it_max = it_max_for(n)

    def prog():
        return _scc_program(src_d, dst_d, live_d, jnp.int32(n),
                            jnp.int32(it_max), n_pad=n_pad)

    def thunk():
        # Materialize on host inside the supervised worker: a wedged
        # fetch is a wedged dispatch, not a wedged caller.
        return tuple(np.asarray(x) for x in prog())

    outcome, value = supervise.run_guarded("txn-scc", key, thunk,
                                           stats=stats,
                                           traceable=prog)
    util.progress_tick()
    if outcome != "ok":
        raise _TierFallback(tier, outcome, key)
    alive, lab, flag, iters, overflow = value
    if bool(overflow.any()):
        # The ceiling fired with changes pending: an honest budget
        # overflow, never a silently-partial decomposition.
        util.stat_bump(stats, "overflows")
        raise _TierFallback(tier, "overflow: budget", key)

    alive = alive[:n]
    lab = lab[:n]
    flag = flag[:n]
    core_idx = np.nonzero(alive)[0]
    # Flagged nodes of region r form exactly the SCC containing r
    # (window coordinates; +lo restores graph node ids).
    sccs: dict[int, list[int]] = {}
    for v in np.nonzero(alive & flag)[0]:
        sccs.setdefault(int(lab[v]), []).append(int(v) + lo)
    device_sccs = [sorted(c) for c in sccs.values() if len(c) > 1]
    # Residue: core nodes whose region root lies outside their SCC —
    # the peel Tarjan, restricted to residue-internal edges.
    residue = alive & ~flag
    res_sccs: list[list[int]] = []
    if residue.any():
        rset = np.nonzero(residue)[0]
        remap = -np.ones(n, np.int64)
        remap[rset] = np.arange(len(rset))
        em = residue[src_h] & residue[dst_h]
        res = oracle.tarjan(len(rset), remap[src_h[em]], remap[dst_h[em]])
        res_sccs = [sorted(int(rset[v]) + lo for v in c) for c in res]
    all_sccs = sorted(device_sccs + res_sccs, key=lambda c: c[0])
    tier_stats = {"edges": int(e_all), "window": [lo, hi],
                  "window_edges": int(e), "core": int(len(core_idx)),
                  "device_sccs": len(device_sccs),
                  "residue": int(residue.sum()),
                  "residue_sccs": len(res_sccs),
                  "iterations": [int(x) for x in iters],
                  "n_pad": n_pad, "e_pad": e_pad, "device": True}
    return all_sccs, tier_stats


class _TierFallback(Exception):
    def __init__(self, tier: str, reason: str, key: str):
        self.tier, self.reason, self.key = tier, reason, key
        super().__init__(f"tier {tier}: {reason}")


def _tier_host_sccs(pt: PackedTxnHistory, tier: str, rt: bool):
    types = set(TIER_TYPES[tier]) | ({oracle.RT} if rt else set())
    mask = np.isin(pt.edge_typ, list(types))
    return oracle.tarjan(pt.n, pt.edge_src[mask], pt.edge_dst[mask])


def _write_snapshot(snap: dict) -> None:
    path = stats_path()
    if not path:
        return
    try:
        util.write_json_atomic(path, snap, default=str)
    except Exception:  # noqa: BLE001 - snapshots are observability
        pass


def check_packed(pt: PackedTxnHistory, anomalies=None,
                 consistency: str = "serializable",
                 realtime: bool | None = None,
                 snapshot: bool = True) -> dict:
    """Decide transactional consistency of a packed history on device.

    Runs the SCC program once per needed edge tier (shared compiled
    shape; the tier is data), hands the decompositions to the oracle's
    shared classifier, and reports the oracle-identical verdict +
    witness. Tier failures walk the fallback ladder (module
    docstring); only a graph past the host bound reports unknown.
    """
    requested, rt = oracle.resolve_anomalies(anomalies, consistency,
                                             realtime)
    if rt and not pt.realtime:
        return {"valid?": "unknown", "analyzer": "txn-tpu",
                "error": "history packed without realtime edges; "
                         "re-pack with realtime=True"}
    tiers: list[str] = []
    for a in requested:
        for t in ANOMALY_TIERS.get(a, ()):
            if t not in tiers:
                tiers.append(t)

    stats: dict = {"tiers": {}}
    # Flight recorder: the txn stats dict as a live registry view, one
    # span per edge tier (the txn-scc dispatch span inside it comes
    # from supervise.run_guarded).
    obs_metrics.REGISTRY.view("txn", stats)
    t0 = time.time()
    sccs_by_tier: dict = {}
    fallbacks: dict = {}
    for tier in tiers:
        _tier0 = time.monotonic()
        try:
            sccs, ts = _tier_device_sccs(pt, tier, stats, rt)
            sccs_by_tier[tier] = sccs
            stats["tiers"][tier] = ts
        except _TierFallback as f:
            fallbacks[tier] = f.reason
            if pt.n_edges > cpu_max_edges():
                out = {"valid?": "unknown", "analyzer": "txn-tpu",
                       "error": f"tier {tier} {f.reason} and graph "
                                f"({pt.n_edges} edges) exceeds the "
                                f"host fallback bound "
                                f"(JEPSEN_TPU_TXN_CPU_MAX)",
                       "overflow": f.reason, "stats": stats}
                if snapshot:
                    _write_snapshot({"verdict": "unknown",
                                     "error": out["error"],
                                     "stats": stats})
                return out
            util.stat_bump(stats, "cpu_tiers")
            sccs_by_tier[tier] = _tier_host_sccs(pt, tier, rt)
            stats["tiers"][tier] = {"edges": None, "device": False,
                                    "fallback": f.reason}
        obs_trace.complete("txn-tier", _tier0,
                           time.monotonic() - _tier0, tier=tier,
                           fallback=fallbacks.get(tier))
        util.progress_tick()

    out = oracle.check_graph(pt.graph, requested, realtime=rt,
                             sccs_by_tier=sccs_by_tier)
    out["analyzer"] = "txn-tpu"
    out["consistency"] = consistency
    if fallbacks:
        out["fallbacks"] = fallbacks
    stats["seconds"] = round(time.time() - t0, 3)
    stats["edges"] = pt.n_edges
    stats["txns"] = pt.n
    out["device-stats"] = stats
    if snapshot:
        _write_snapshot({
            "verdict": out["valid?"],
            "consistency": consistency,
            "anomaly_types": out.get("anomaly-types", []),
            "anomaly_counts": {k: len(v) for k, v in
                               out.get("anomalies", {}).items()},
            "edge_counts": pt.graph.stats.get("edge_counts", {}),
            "graph": pt.graph.stats,
            "device": stats,
            "fallbacks": fallbacks,
            "updated": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime())})
    return out
