"""Host-side packing for the transaction dependency-graph checker.

Converts a list-append history (vector of ``txn`` ops whose values are
micro-op lists, :mod:`jepsen_tpu.txn.oracle`) into the dense int-array
form the device SCC engine consumes — the :mod:`jepsen_tpu.lin.prepare`
role for the transactional workload family, following its conventions:

- **Pairing / indeterminacy**: ``fail`` txns are dropped (their appends
  kept only to convict G1a reads); ``info`` txns stay with their
  invocation micro-ops and contribute writes only when observed
  (recoverable-write rule) — the ``:info``-completion contract of the
  wire suites (an op that may have applied must constrain, not be
  assumed away).
- ``edge_src/edge_dst/edge_typ`` — the inferred dependency edges
  (``oracle.WR/WW/RW/RT``), deduplicated, sorted by (src, dst, typ):
  the flat arrays the device SCC program's scatter formulation consumes
  directly (it builds no CSR — degree counts and label propagation are
  ``at[].add/min/max`` scatters over these).

**Fast edge inference** (:func:`infer_fast`, ISSUE 14 satellite): the
device path's wall clock at the 100k-op scale is INFERENCE-bound —
``oracle.infer``'s per-read per-ELEMENT Python loop is
O(total observed elements), quadratic-ish in history length for
list-append reads that observe their key's whole growing list
(~1 s per 1k txns measured). :func:`infer_fast` replaces exactly that
loop with numpy over the per-key version-order columns: each read's
prefix check is one vectorized compare against the key's longest
observed order, and the per-element anomaly/writer lookups collapse
to per-key precomputed position arrays + ``searchsorted`` counts.
Reads that fail the prefix check (the ``incompatible-order`` anomaly,
rare by construction) and non-numeric value domains take the oracle's
literal per-element path, so the output — edge set, anomaly
witnesses (order included), and stats — is BYTE-IDENTICAL to
``oracle.infer``, which stays the parity spec (``algorithm="cpu"``
runs it end to end; equality is fuzzed in tests/test_txn_oracle.py).

The graph inference semantics live in :mod:`jepsen_tpu.txn.oracle` —
pack is a codec plus a faithful vectorization of the oracle's
inference, never a second set of rules.
"""

from __future__ import annotations

import hashlib
import time
from collections import defaultdict
from dataclasses import dataclass

import numpy as np

from jepsen_tpu.txn import oracle
from jepsen_tpu.txn.oracle import RT, RW, WR, WW

# Pack-wall accounting (bench's txn artifacts + the service's
# pack-seconds counter read this; the pack-txn trace span carries the
# per-call attribution). Mirrors lin/prepare's _pack_stats convention.
_pack_stats = {"pack_s": 0.0, "pack_calls": 0}


def pack_stats() -> dict:
    """Snapshot of cumulative txn packing wall this process (seconds)."""
    return dict(_pack_stats)


def reset_pack_stats() -> None:
    for k in _pack_stats:
        _pack_stats[k] = 0.0 if k.endswith("_s") else 0


@dataclass
class PackedTxnHistory:
    """Dense arrays driving the device SCC search; module docstring."""

    graph: oracle.TxnGraph
    n: int                       # transactions (graph nodes)
    edge_src: np.ndarray         # i32[E]
    edge_dst: np.ndarray         # i32[E]
    edge_typ: np.ndarray         # i8[E]
    realtime: bool = False

    @property
    def n_edges(self) -> int:
        return int(len(self.edge_src))

    def fingerprint(self) -> str:
        """Identity of the packed graph (the supervise checkpoint /
        ledger convention: same shape+content -> same key)."""
        h = hashlib.sha256()
        h.update(f"txn|{self.n}|{self.n_edges}|{self.realtime}".encode())
        for a in (self.edge_src, self.edge_dst, self.edge_typ):
            arr = np.ascontiguousarray(np.asarray(a))
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        return h.hexdigest()


class _KeyInfo:
    """Per-key precomputation over the longest observed order (module
    docstring): writer per position, anomaly positions/entries, and
    duplicate positions — everything the oracle's per-element read
    loop looks up, hoisted so a prefix-verified read costs
    O(log) searchsorted counts instead of O(len(obs)) Python.

    When the key's order, written values, and failed values are all
    lossless ints (the dtype gate below), the construction itself is
    vectorized (ISSUE 16 tentpole c): the per-position writer lookup
    becomes one searchsorted join against the key's write column, the
    duplicate scan one stable sort, and the G1a/garbage split one more
    join against the failed column — the last linear-Python pass over
    version orders. Witness entries still carry the ORIGINAL history
    objects (JSON-safe over the wire), materialized per anomaly entry
    only."""

    __slots__ = ("arr", "fast", "warr", "warr_a", "g1a_pos", "g1a_ent",
                 "never_pos", "never_ent", "dup_pos", "dup_ent")

    def __init__(self, k, order, writer, failed, kw=None, kf=None):
        # Lossless-int gate: np.asarray infers the dtype, so a float
        # (1.5), bool, mixed, or bignum order comes back non-"iu" and
        # the key's reads take the oracle's literal path — fromiter
        # with a forced int64 would silently TRUNCATE 1.5 -> 1 and
        # mask exactly the corrupt reads the checker exists to catch.
        arr = np.asarray(order)
        if arr.dtype.kind in "iu" and kw is not None and kf is not None:
            wv = kw[0] if kw else ()
            fv = kf[0] if kf else ()
            va = np.asarray(wv) if len(wv) else np.zeros(0, np.int64)
            fa = np.asarray(fv) if len(fv) else np.zeros(0, np.int64)
            # The dict paths compare with Python ==, so the write and
            # failed columns must be lossless ints too (True == 1,
            # 1.0 == 1: a "b"/"f"/"O" column falls back to the spec
            # loop rather than risk a dtype-coerced false join).
            if va.dtype.kind in "iu" and fa.dtype.kind in "iu":
                self._init_vec(k, order, arr, kw, kf, failed)
                return
        if arr.dtype.kind in "iu":
            self.arr = arr.astype(np.int64)
            self.fast = True
        else:
            self.arr = None
            self.fast = False
        self.warr = [writer.get((k, v)) for v in order]
        self.warr_a = None
        g1a_pos: list = []
        g1a_ent: list = []
        never_pos: list = []
        never_ent: list = []
        dup_pos: list = []
        dup_ent: list = []
        seen: set = set()
        for p, v in enumerate(order):
            if v in seen:
                dup_pos.append(p)
                dup_ent.append(v)
            seen.add(v)
            if (k, v) not in writer:
                if (k, v) in failed:
                    g1a_pos.append(p)
                    g1a_ent.append((v, failed[(k, v)]))
                else:
                    never_pos.append(p)
                    never_ent.append(v)
        self.g1a_pos = np.asarray(g1a_pos, np.int64)
        self.g1a_ent = g1a_ent
        self.never_pos = np.asarray(never_pos, np.int64)
        self.never_ent = never_ent
        self.dup_pos = np.asarray(dup_pos, np.int64)
        self.dup_ent = dup_ent

    def _init_vec(self, k, order, arr, kw, kf, failed):
        self.fast = True
        oa = arr.astype(np.int64)
        self.arr = oa
        self.warr = None
        m = len(oa)
        wid = np.full(m, -1, np.int64)
        if kw[0]:
            va = np.asarray(kw[0]).astype(np.int64)
            ia = np.asarray(kw[1], np.int64)
            sv = np.argsort(va, kind="stable")
            svals = va[sv]
            sids = ia[sv]
            pos = np.searchsorted(svals, oa)
            inb = pos < len(svals)
            hit = np.zeros(m, bool)
            hit[inb] = svals[pos[inb]] == oa[inb]
            wid[hit] = sids[pos[hit]]
        self.warr_a = wid
        # Duplicates: for equal values the stable sort keeps position
        # order, so all but the first of each run are the dups.
        so = np.argsort(oa, kind="stable")
        svo = oa[so]
        dm = np.zeros(m, bool)
        dm[1:] = svo[1:] == svo[:-1]
        dup_p = np.sort(so[dm])
        # Unwritten positions split into failed (G1a) vs never-written.
        miss = np.flatnonzero(wid < 0)
        g1a_m = np.zeros(len(miss), bool)
        if kf[0] and len(miss):
            fva = np.asarray(kf[0]).astype(np.int64)
            sfv = np.sort(fva)
            ov = oa[miss]
            fpos = np.searchsorted(sfv, ov)
            finb = fpos < len(sfv)
            g1a_m[finb] = sfv[fpos[finb]] == ov[finb]
        g1a_p = miss[g1a_m]
        never_p = miss[~g1a_m]
        self.g1a_pos = g1a_p
        self.never_pos = never_p
        self.dup_pos = dup_p
        self.g1a_ent = [(order[p], failed[(k, order[p])])
                        for p in g1a_p.tolist()]
        self.never_ent = [order[p] for p in never_p.tolist()]
        self.dup_ent = [order[p] for p in dup_p.tolist()]

    def wid(self, p):
        """Writer txn id at order position p, or None (the oracle's
        ``writer.get`` contract), from whichever column form exists."""
        if self.warr_a is not None:
            w = int(self.warr_a[p])
            return None if w < 0 else w
        return self.warr[p]


def infer_fast(history=None, nodes=None, failed=None,
               realtime: bool = False) -> oracle.TxnGraph:
    """Numpy-vectorized twin of :func:`oracle.infer` (module
    docstring): identical edge set, anomaly witnesses, and stats —
    fuzzed in tests/test_txn_oracle.py — with the per-read
    per-element Python loop replaced by one vectorized prefix compare
    plus per-key precomputed anomaly columns. Reads that are not a
    prefix of their key's longest order (or whose values defeat the
    int columns) run the oracle's literal per-element path, so exotic
    histories degrade to spec behaviour, never to different
    answers."""
    from jepsen_tpu.txn.oracle import EDGE_NAMES, MAX_WITNESSES

    if nodes is None:
        nodes, failed = oracle.pair_txns(history)
    failed = failed or {}
    n = len(nodes)

    # --- append pass (verbatim oracle.infer) ----------------------
    writer: dict = {}
    dupes_w: list = []          # append-duplicate witnesses (full —
    dup_count = 0               # bounded by the append count)
    appends_per_key: dict = defaultdict(int)
    per_key_w: dict = defaultdict(lambda: ([], []))  # k -> (vals, ids)
    for t in nodes:
        for f, k, v in t.mops:
            if f != "append":
                continue
            appends_per_key[k] += 1
            if (k, v) in writer and writer[(k, v)] != t.idx:
                dupes_w.append({"key": k, "value": v,
                                "txns": [writer[(k, v)], t.idx]})
                dup_count += 1
            else:
                if (k, v) not in writer:       # first-occurrence column
                    kw = per_key_w[k]
                    kw[0].append(v)
                    kw[1].append(t.idx)
                writer[(k, v)] = t.idx
    failed_by_key: dict = defaultdict(lambda: ([], []))
    for (fk, fv), fidx in failed.items():
        kf = failed_by_key[fk]
        kf[0].append(fv)
        kf[1].append(fidx)

    longest: dict = {}
    reads: list = []
    for t in nodes:
        if not t.ok:
            continue
        for f, k, v in t.mops:
            if f != "r" or v is None:
                continue
            obs = tuple(v)
            reads.append((t.idx, k, obs))
            if len(obs) > len(longest.get(k, ())):
                longest[k] = obs

    es: list = []
    ed: list = []
    et: list = []

    def edge(a, b, ty):
        if a != b:
            es.append(a)
            ed.append(b)
            et.append(ty)

    # --- unobserved committed appends + ww (verbatim) -------------
    unobserved: dict = defaultdict(list)
    ok_txn = {t.idx for t in nodes if t.ok}
    observed_vals = {k: set(order) for k, order in longest.items()}
    for (k, v), w in writer.items():
        if w in ok_txn and v not in observed_vals.get(k, ()):
            unobserved[k].append(w)

    # The version-order WW join, vectorized (tentpole c): per key the
    # writer lookups are one searchsorted join (_KeyInfo.warr_a) and
    # the chain edges one pairwise pass over the present writers —
    # identical to the per-element loop, which non-int keys still run.
    observed = 0
    keyinfo: dict = {}
    for k, order in longest.items():
        ki = keyinfo[k] = _KeyInfo(k, order, writer, failed,
                                   per_key_w.get(k, ((), ())),
                                   failed_by_key.get(k, ((), ())))
        if ki.warr_a is not None:
            idx = np.flatnonzero(ki.warr_a >= 0)
            observed += len(idx)
            if len(idx):
                a = ki.warr_a[idx]
                keep = a[:-1] != a[1:]
                es.extend(a[:-1][keep].tolist())
                ed.extend(a[1:][keep].tolist())
                et.extend([WW] * int(keep.sum()))
                prev = int(a[-1])
                for w in unobserved.get(k, ()):
                    edge(prev, w, WW)
            continue
        prev = None
        for v in order:
            w = writer.get((k, v))
            if w is not None:
                observed += 1
                if prev is not None:
                    edge(prev, w, WW)
                prev = w
        if prev is not None:
            for w in unobserved.get(k, ()):
                edge(prev, w, WW)

    # --- per-read pass: vectorized prefix path --------------------
    incompatible: list = []
    g1a_w: list = []
    never_w: list = []
    g1a_count = never_count = 0

    def take_witnesses(out, pos, ent, limit, make):
        if len(out) >= MAX_WITNESSES:
            return
        for p, e in zip(pos, ent):
            if p >= limit or len(out) >= MAX_WITNESSES:
                break
            out.append(make(e))

    for i, k, obs in reads:
        order = longest.get(k, ())
        L = len(obs)
        ki = keyinfo.get(k)
        if ki is None:
            ki = keyinfo[k] = _KeyInfo(k, order, writer, failed,
                                       per_key_w.get(k, ((), ())),
                                       failed_by_key.get(k, ((), ())))
        fast = False
        if L == 0:
            fast = True
        elif ki.fast:
            # Same lossless-int gate as _KeyInfo: dtype inference,
            # never a forced cast (a float/bool/bignum element must
            # fail to the literal path, not truncate into a false
            # prefix match).
            obs_arr = np.asarray(obs)
            fast = obs_arr.dtype.kind in "iu" \
                and bool(np.array_equal(obs_arr.astype(np.int64),
                                        ki.arr[:L]))
        if fast:
            # obs is a verified prefix of the longest order: every
            # element-level lookup collapses to the precomputed
            # per-key columns.
            c = int(np.searchsorted(ki.g1a_pos, L))
            if c:
                g1a_count += c
                take_witnesses(
                    g1a_w, ki.g1a_pos, ki.g1a_ent, L,
                    lambda e, k=k, i=i: {
                        "key": k, "value": e[0], "txn": i,
                        "failed-op-index": e[1]})
            c = int(np.searchsorted(ki.never_pos, L))
            if c:
                never_count += c
                take_witnesses(
                    never_w, ki.never_pos, ki.never_ent, L,
                    lambda e, k=k, i=i: {"key": k, "value": e,
                                         "txn": i})
            c = int(np.searchsorted(ki.dup_pos, L))
            if c:
                dup_count += c
                take_witnesses(
                    dupes_w, ki.dup_pos, ki.dup_ent, L,
                    lambda e, k=k, i=i: {"key": k, "value": e,
                                         "txns": [i],
                                         "kind": "read-duplicate"})
            if L:
                w = ki.wid(L - 1)
                if w is not None:
                    edge(w, i, WR)
            if L < len(order):
                nxt = ki.wid(L)
                if nxt is not None:
                    edge(i, nxt, RW)
            else:               # obs == order (verified prefix, full)
                for w in unobserved.get(k, ()):
                    edge(i, w, RW)
            continue
        # --- the oracle's literal per-element path (mismatching or
        # non-numeric reads — the incompatible-order anomaly class).
        if obs != order[:L]:
            incompatible.append(
                {"key": k, "txn": i, "observed": list(obs),
                 "longest": list(order)})
        seen: set = set()
        for v in obs:
            if v in seen:
                dup_count += 1
                if len(dupes_w) < MAX_WITNESSES:
                    dupes_w.append({"key": k, "value": v, "txns": [i],
                                    "kind": "read-duplicate"})
            seen.add(v)
            if (k, v) not in writer:
                if (k, v) in failed:
                    g1a_count += 1
                    if len(g1a_w) < MAX_WITNESSES:
                        g1a_w.append(
                            {"key": k, "value": v, "txn": i,
                             "failed-op-index": failed[(k, v)]})
                else:
                    never_count += 1
                    if len(never_w) < MAX_WITNESSES:
                        never_w.append({"key": k, "value": v,
                                        "txn": i})
        if obs:
            w = writer.get((k, obs[-1]))
            if w is not None:
                edge(w, i, WR)
        if L < len(order):
            nxt = writer.get((k, order[L]))
            if nxt is not None:
                edge(i, nxt, RW)
        elif obs == order:
            for w in unobserved.get(k, ()):
                edge(i, w, RW)

    if realtime:
        for a, b in oracle._realtime_edges(nodes):
            edge(a, b, RT)

    if es:
        e = np.unique(np.stack([np.asarray(es, np.int64),
                                np.asarray(ed, np.int64),
                                np.asarray(et, np.int64)], axis=1),
                      axis=0)
        src, dst, typ = (e[:, 0].astype(np.int32),
                         e[:, 1].astype(np.int32),
                         e[:, 2].astype(np.int8))
    else:
        src = np.zeros(0, np.int32)
        dst = np.zeros(0, np.int32)
        typ = np.zeros(0, np.int8)

    anomalies = {}
    if g1a_count:
        anomalies["G1a"] = g1a_w[:MAX_WITNESSES]
    if never_count:
        anomalies["garbage-read"] = never_w[:MAX_WITNESSES]
    if dup_count:
        anomalies["duplicate-elements"] = dupes_w[:MAX_WITNESSES]
    if incompatible:
        anomalies["incompatible-order"] = incompatible[:MAX_WITNESSES]
    counts = {EDGE_NAMES[t]: int((typ == t).sum())
              for t in (WR, WW, RW, RT)}
    stats = {"txns": n, "ok_txns": sum(1 for t in nodes if t.ok),
             "info_txns": sum(1 for t in nodes if not t.ok),
             "keys": len(appends_per_key), "reads": len(reads),
             "appends": sum(appends_per_key.values()),
             "observed_appends": observed,
             "edges": int(len(src)), "edge_counts": counts,
             "g1a": g1a_count, "garbage": never_count,
             "duplicates": dup_count,
             "incompatible": len(incompatible)}
    return oracle.TxnGraph(n=n, src=src, dst=dst, typ=typ, txns=nodes,
                           anomalies=anomalies, stats=stats)


def pack(history=None, graph: oracle.TxnGraph | None = None,
         realtime: bool = False) -> PackedTxnHistory:
    """Pack a list-append history (or a pre-inferred graph) for the
    device checker. Inference runs through :func:`infer_fast` (the
    oracle-identical vectorization); ``algorithm="cpu"`` checks keep
    running ``oracle.infer`` end to end, so the parity leg never
    shares this code."""
    from jepsen_tpu.obs import trace as obs_trace

    t0 = time.perf_counter()
    with obs_trace.span("pack-txn",
                        prepacked=graph is not None) as sp:
        if graph is None:
            graph = infer_fast(history, realtime=realtime)

        src, dst, typ = graph.src, graph.dst, graph.typ
        order = np.lexsort((typ, dst, src)) if len(src) else \
            np.zeros(0, np.int64)
        out = PackedTxnHistory(
            graph=graph, n=graph.n,
            edge_src=src[order].astype(np.int32),
            edge_dst=dst[order].astype(np.int32),
            edge_typ=typ[order].astype(np.int8),
            realtime=realtime)
        sp.note(txns=out.n, edges=out.n_edges)
    _pack_stats["pack_s"] += time.perf_counter() - t0
    _pack_stats["pack_calls"] += 1
    return out
