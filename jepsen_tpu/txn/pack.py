"""Host-side packing for the transaction dependency-graph checker.

Converts a list-append history (vector of ``txn`` ops whose values are
micro-op lists, :mod:`jepsen_tpu.txn.oracle`) into the dense int-array
form the device SCC engine consumes — the :mod:`jepsen_tpu.lin.prepare`
role for the transactional workload family, following its conventions:

- **Pairing / indeterminacy**: ``fail`` txns are dropped (their appends
  kept only to convict G1a reads); ``info`` txns stay with their
  invocation micro-ops and contribute writes only when observed
  (recoverable-write rule) — the ``:info``-completion contract of the
  wire suites (an op that may have applied must constrain, not be
  assumed away).
- ``edge_src/edge_dst/edge_typ`` — the inferred dependency edges
  (``oracle.WR/WW/RW/RT``), deduplicated, sorted by (src, dst, typ):
  the flat arrays the device SCC program's scatter formulation consumes
  directly (it builds no CSR — degree counts and label propagation are
  ``at[].add/min/max`` scatters over these).

The graph inference itself lives in :mod:`jepsen_tpu.txn.oracle` — pack
is a codec around the oracle's graph, never a second implementation.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass

import numpy as np

from jepsen_tpu.txn import oracle


@dataclass
class PackedTxnHistory:
    """Dense arrays driving the device SCC search; module docstring."""

    graph: oracle.TxnGraph
    n: int                       # transactions (graph nodes)
    edge_src: np.ndarray         # i32[E]
    edge_dst: np.ndarray         # i32[E]
    edge_typ: np.ndarray         # i8[E]
    realtime: bool = False

    @property
    def n_edges(self) -> int:
        return int(len(self.edge_src))

    def fingerprint(self) -> str:
        """Identity of the packed graph (the supervise checkpoint /
        ledger convention: same shape+content -> same key)."""
        h = hashlib.sha256()
        h.update(f"txn|{self.n}|{self.n_edges}|{self.realtime}".encode())
        for a in (self.edge_src, self.edge_dst, self.edge_typ):
            arr = np.ascontiguousarray(np.asarray(a))
            h.update(str(arr.shape).encode())
            h.update(arr.tobytes())
        return h.hexdigest()


def pack(history=None, graph: oracle.TxnGraph | None = None,
         realtime: bool = False) -> PackedTxnHistory:
    """Pack a list-append history (or a pre-inferred graph) for the
    device checker."""
    if graph is None:
        graph = oracle.infer(history, realtime=realtime)

    src, dst, typ = graph.src, graph.dst, graph.typ
    order = np.lexsort((typ, dst, src)) if len(src) else \
        np.zeros(0, np.int64)
    return PackedTxnHistory(
        graph=graph, n=graph.n,
        edge_src=src[order].astype(np.int32),
        edge_dst=dst[order].astype(np.int32),
        edge_typ=typ[order].astype(np.int8),
        realtime=realtime)
